"""Fleet router: the data plane above N ServingEngine replicas.

One client-facing request (:class:`FleetRequest`) maps to one-or-more
per-replica ``ServingRequest`` *attempts*: the router picks a replica via
its :mod:`policy <.policies>`, submits, and streams delivered tokens into
the fleet-level record.  When a replica dies (scripted kill, health
tracker, or an injected ``device_loss`` at the ``router.dispatch`` fault
site), its in-flight requests are re-queued and re-dispatched onto
survivors with ``resume_tokens`` — the per-replica recompute-on-resume
contract, lifted across replicas — so a failed-over request's final token
output is IDENTICAL to an unperturbed run's.

The router is driver-agnostic: :class:`~.sim.FleetSimulator` drives it
deterministically on a shared ``VirtualClock`` (tests, ``--dryrun``
benches); a real deployment would run the same ``dispatch_pending`` /
``poll`` surface from a wall-clock loop with replicas ticking in threads.

Fleet request lifecycle::

    PENDING → DISPATCHED → DONE
       ▲          │ (replica died: failover, tokens preserved)
       └──────────┘
    PENDING | DISPATCHED → TIMED_OUT     (deadline)
    PENDING → REJECTED                   (structurally infeasible everywhere)

Terminal states are reached exactly once — ``_finish`` enforces it — which
is the property the fleet chaos/property tests pin: no request lost,
duplicated, or served twice through any kill/recover/drain schedule.
"""

import dataclasses
import enum
import itertools
import math
from typing import Dict, List, Optional, Sequence, Tuple

from ...resilience import fault_injection as _fi
from ...telemetry.spans import emit_attempt_spans
from ...telemetry.trace import NULL_TRACER
from ...utils.logging import logger
from ..metrics import percentile_summary
from ..request import RequestState, ServingRequest
from ..kvtier import HostKVHandle
from ..kvtransfer import SnapshotAborted
from .health import FleetHealthView, LeaseConfig, LeaseState, ReplicaState
from .policies import RoutingPolicy
from .pool import ReplicaPool, ReplicaRole
from .tenancy import TenantRegistry, order_key as _tenant_order_key


class FleetState(enum.Enum):
    PENDING = "pending"        # in the router queue (new, or displaced)
    DISPATCHED = "dispatched"  # live on a replica
    DONE = "done"
    TIMED_OUT = "timed_out"
    REJECTED = "rejected"

    @property
    def terminal(self) -> bool:
        return self in (FleetState.DONE, FleetState.TIMED_OUT, FleetState.REJECTED)


#: validated fleet transitions (dslint state-machine table; rendered into
#: docs/STATE_MACHINES.md).  PENDING -> DONE covers the one legitimate
#: shortcut: a failover victim displaced with its output already complete
#: is closed out at its next dispatch attempt without re-serving a token.
_FLEET_ALLOWED = {
    FleetState.PENDING: {FleetState.DISPATCHED, FleetState.DONE,
                         FleetState.TIMED_OUT, FleetState.REJECTED},
    FleetState.DISPATCHED: {FleetState.PENDING, FleetState.DONE,
                            FleetState.TIMED_OUT},
    FleetState.DONE: set(),
    FleetState.TIMED_OUT: set(),
    FleetState.REJECTED: set(),
}


@dataclasses.dataclass
class FleetRequest:
    """One client request as the FLEET sees it.  ``tokens`` accumulates
    across replica attempts (stream deliveries + failover resumes) and is
    the client-visible output; per-attempt ``ServingRequest`` objects are
    bookkeeping."""
    fid: int
    prompt: List[int]
    max_new_tokens: int
    arrival_ts: float
    deadline: Optional[float] = None
    priority: float = 0.0
    state: FleetState = FleetState.PENDING
    tokens: List[int] = dataclasses.field(default_factory=list)
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    failovers: int = 0
    affinity_hits: int = 0
    migrations: int = 0          # KV handoffs between replicas (kvtransfer)
    reject_reason: Optional[str] = None
    #: when to retry a REJECTED request (clock-seconds from rejection) —
    #: set on transient rejections (overload shed, tenant-admission
    #: fault); None on structural rejections, where retrying cannot help
    #: (replica-level queue_full never rejects at the FLEET level — the
    #: request just stays pending for the next dispatch round)
    retry_after: Optional[float] = None
    #: QoS: the submitting tenant and its weighted-fair stride pass
    tenant: str = "default"
    _wfq: float = 0.0
    #: agentic-session identity (serving/sessions): set when this request
    #: is one TURN of a multi-turn session — the ``session_affinity``
    #: routing policy keys its sticky replica map on it, so turn N+1 lands
    #: where turn N left its warm transcript pages
    session_id: Optional[object] = None
    #: True when a brownout rung capped this request's max_new_tokens
    brownout_capped: bool = False
    #: host-staged KV carried between attempts: set when a migration's
    #: export completed (or harvested from a dead replica — failover
    #: reuse), consumed by the next dispatch's KV-import fast path
    _kv_snapshot: Optional[object] = None
    #: (replica rid, dispatch ts) per attempt
    dispatches: List[Tuple[int, float]] = dataclasses.field(default_factory=list)
    history: List[Tuple[FleetState, float]] = dataclasses.field(default_factory=list)
    _current: Optional[Tuple[int, ServingRequest, int]] = None  # (rid, sr, generation)
    #: telemetry context when the router traces: {"trace_id", "root_id",
    #: "attempts": [per-attempt dicts], "last_dead": span id of the most
    #: recently displaced attempt (the next attempt links to it)}
    trace: Optional[dict] = None

    def __post_init__(self):
        self.prompt = list(self.prompt)
        self.history.append((self.state, self.arrival_ts))

    def to(self, state: FleetState, ts: float) -> None:
        """The ONLY sanctioned way to move a fleet request: validates the
        hop against ``_FLEET_ALLOWED`` (an illegal one is a router bug and
        raises — the exactly-once-terminal property the chaos suite pins
        is enforced here, not merely asserted at ``_finish``) and appends
        the auditable history entry in the same step."""
        if state not in _FLEET_ALLOWED[self.state]:
            raise ValueError(f"fleet request {self.fid}: illegal transition "
                             f"{self.state.value} -> {state.value}")
        self.state = state
        self.history.append((state, ts))

    @property
    def ttft(self) -> Optional[float]:
        return None if self.first_token_ts is None else self.first_token_ts - self.arrival_ts

    @property
    def tpot(self) -> Optional[float]:
        if self.first_token_ts is None or self.finish_ts is None or len(self.tokens) < 2:
            return None
        return (self.finish_ts - self.first_token_ts) / (len(self.tokens) - 1)

    @property
    def e2e(self) -> Optional[float]:
        return None if self.finish_ts is None else self.finish_ts - self.arrival_ts

    @property
    def met_deadline(self) -> bool:
        if self.state is not FleetState.DONE:
            return False
        return self.deadline is None or self.finish_ts <= self.deadline


#: retry-after stamped on a TRANSIENT tenant-admission fault when no
#: overload episode is in progress: a bookkeeping blip, not backpressure —
#: retry soon (an active brownout substitutes the ladder's own hint)
TENANT_FAULT_RETRY_S = 1.0

#: retry-after stamped on a KV-quota rejection: pages free as the
#: tenant's own requests complete, so the hint is a serving-timescale
#: backoff, not the overload ladder's episode-scale one
KV_QUOTA_RETRY_S = 2.0


@dataclasses.dataclass
class _DirFeed:
    """Router-side receiver state for ONE replica's sequence-numbered
    prefix-publish stream (docs/SERVING.md "Control-plane transport").

    In-order messages apply immediately; out-of-order ones buffer inside a
    small reorder window; a gap that outlives the window (or the timeout)
    is a LOST publish — detected, not absorbed: ``prefix/publish_gap``
    fires and the router pulls a targeted full-digest resync from the
    replica.  ``expect = None`` means the stream is broken (gap declared,
    or the replica's lease expired) and every delivery is dropped until a
    resync snapshot re-anchors the sequence at its barrier."""
    expect: Optional[int] = 1          # next seq to apply; None = broken
    buffer: Dict[int, Tuple[str, int]] = dataclasses.field(default_factory=dict)
    gap_since: Optional[float] = None
    resync_since: Optional[float] = None   # outstanding resync request ts


class LifecycleCmdState(enum.Enum):
    """Router-side delivery state of ONE transported lifecycle command
    (docs/SERVING.md "Closed-loop control")."""
    PENDING = "pending"   # recorded; the first send has not left yet
    SENT = "sent"         # on the wire, awaiting the replica's ack
    ACKED = "acked"       # the replica reported an outcome (applied/rejected)
    ABORTED = "aborted"   # overtaken: the target's lease epoch advanced
    #                       mid-flight — retrying would carry a pre-fencing
    #                       decision into the post-fence world


#: validated lifecycle-command transitions (dslint state-machine table;
#: rendered into docs/STATE_MACHINES.md).  PENDING -> ABORTED covers a
#: command whose target's epoch advanced before its first send ever left
#: (an injected send fault kept it queued across the expiry).
_LIFECYCLE_ALLOWED = {
    LifecycleCmdState.PENDING: {LifecycleCmdState.SENT,
                                LifecycleCmdState.ABORTED},
    LifecycleCmdState.SENT: {LifecycleCmdState.ACKED,
                             LifecycleCmdState.ABORTED},
    LifecycleCmdState.ACKED: set(),
    LifecycleCmdState.ABORTED: set(),
}


@dataclasses.dataclass
class _LifecycleCmd:
    """One transported lifecycle command: typed op + monotone seq (the
    replica-side dedup key) + the target's lease epoch at dispatch (the
    fencing token — a zombie replica, or a command that outlived a lease
    expiry, can never act on or double-apply stale intent).  Delivery is
    stop-and-wait with ack/retry, the same discipline as fences and
    migration chunks."""
    seq: int
    rid: int
    op: str
    payload: dict
    epoch: int                      # lease epoch of rid when issued
    issued_ts: float
    state: LifecycleCmdState = LifecycleCmdState.PENDING
    sent_ts: Optional[float] = None
    status: Optional[str] = None    # the replica's ack outcome

    def to(self, state: LifecycleCmdState) -> None:
        if state not in _LIFECYCLE_ALLOWED[self.state]:
            raise ValueError(f"lifecycle cmd {self.seq}: illegal transition "
                             f"{self.state.value} -> {state.value}")
        self.state = state


class Router:
    """Cache-affinity, health-aware request router over a ReplicaPool."""

    def __init__(self, pool: ReplicaPool, policy: RoutingPolicy, monitor=None,
                 tracer=None, migration_chunk_pages: int = 4,
                 migration_chunk_cost: float = 0.0,
                 prefill_handoff: bool = False,
                 tenants: Optional[TenantRegistry] = None,
                 overload=None, prefix_import_cost: float = 0.0,
                 transport=None, lease_config: Optional[LeaseConfig] = None,
                 warmup_chains: int = 4, recorder=None, slo=None):
        self.pool = pool
        self.policy = policy
        self.monitor = monitor
        # fleet flight recorder (telemetry/flight_recorder.py): the
        # bounded always-on control-plane ring.  Attaching it here fans it
        # out to every producer — transport message spans, lease-state
        # intervals, overload-rung occupancy, autoscaler instants, and
        # (via the pool tracer's retention sink) the request phase spans —
        # and the router drives the crash-scoped dumps: replica death,
        # lease expiry, a completed fencing episode.  None = off, zero
        # overhead, byte-identical pre-r18 behavior.
        self.recorder = recorder
        if recorder is not None:
            # replica frontends record their side of control episodes
            # (ctrl/fence) directly — tracer or no tracer; pool.recorder
            # makes recover()/restart() replacements inherit it
            pool.recorder = recorder
            for rid in pool.rids:
                pool.replica(rid).serve.recorder = recorder
        if recorder is not None and transport is not None \
                and transport.recorder is None:
            transport.recorder = recorder
        # control-plane transport (docs/SERVING.md "Control-plane
        # transport"): with one attached, the router stops observing
        # replicas perfectly — health is heartbeat leases with fencing,
        # load_stats are last-known-good + age, prefix publishes are a
        # seq-numbered feed with gap-resync, migration chunks flow
        # ack/retry.  None (default) keeps every pre-r16 direct path.
        self.transport = transport
        if pool.transport is not transport:
            # BOTH directions are misconfigurations: a router-only
            # transport would read a fabric nobody heartbeats into, and a
            # pool-only one would send every heartbeat/publish into a
            # fabric nobody drains — silent 100% cold routing plus an
            # unboundedly growing in-flight queue, not an error anyone
            # would see
            raise ValueError(
                "the Router's ControlTransport must be the ReplicaPool's: "
                "pass the SAME transport= to both ReplicaPool(...) and "
                "Router(...) (or to neither) so replicas heartbeat and "
                "publish into the fabric the router reads")
        self.lease: Optional[FleetHealthView] = None
        #: fid -> assembling router-side migration snapshot
        #: {"next": chunk idx expected, "snap": KVSnapshot}
        self._mig_rx: Dict[int, dict] = {}
        #: rid -> [(fid, displaced ServingRequest)] at the last lease
        #: expiry — audited at fence time for late (fenced) completions
        self._lease_displaced: Dict[int, list] = {}
        self._dir_feeds: Dict[int, _DirFeed] = {}
        #: out-of-order publishes buffered before a gap is declared lost
        self.dir_reorder_window = 4
        #: clock time a seq gap may wait for the missing message before the
        #: router declares it lost and pulls a resync
        self.dir_gap_timeout = 2.0
        #: clock time before an unanswered resync request is re-sent
        self.dir_resync_retry = 4.0
        #: min clock time between retransmits of an unacked migration chunk
        self.mig_retry = 1.0
        #: min clock time between retransmits of an unacked lifecycle command
        self.lifecycle_retry = 1.0
        # transported lifecycle commands (docs/SERVING.md "Closed-loop
        # control"): with a transport attached, every control-plane
        # mutation of a replica — autoscaler recover/drain/park/restart,
        # role changes, migration completion — crosses the same lossy
        # fabric as everything else as a typed, seq-numbered, epoch-fenced
        # ``lifecycle_cmd`` with stop-and-wait ack/retry; without one,
        # ``lifecycle_command`` degenerates to the pre-r21 direct calls
        self._lifecycle_seq = itertools.count(1)
        #: cmd seq -> _LifecycleCmd (the full auditable command log)
        self._lifecycle: Dict[int, _LifecycleCmd] = {}
        #: hottest directory chains pre-imported onto a recovering replica
        self.warmup_chains = int(warmup_chains)
        if transport is not None:
            self.lease = FleetHealthView(
                pool.rids, config=lease_config, clock=pool.clock,
                emit=lambda name, value: self._emit(
                    [(name, value, self._next_event_step())]),
                recorder=recorder)
            self._dir_feeds = {rid: _DirFeed() for rid in pool.rids}
        # fleet prefix directory (docs/SERVING.md "Prefix directory"): a
        # directory-routing policy carries the directory it reads; the
        # POOL must carry the same one, or no replica would ever publish
        # into it and every dispatch would read an empty table — silent
        # 100% cold routing, not an error anyone would see
        self.directory = getattr(policy, "directory", None)
        if self.directory is not None \
                and pool.prefix_directory is not self.directory:
            raise ValueError(
                "the routing policy's PrefixDirectory must be the "
                "ReplicaPool's: pass prefix_directory= to ReplicaPool(...) "
                "so replicas publish their digests into the table the "
                "policy routes on (the pool re-wires it across "
                "recover()/restart() engine swaps)")
        # per-page clock charge of a hot-prefix import (d2h on the donor's
        # view + h2d on the target's, max-combined with their step costs —
        # overlapped staging, not a stall), mirroring migration_chunk_cost
        self.prefix_import_cost = float(prefix_import_cost)
        # multi-tenant QoS (docs/SERVING.md "Overload control plane"):
        # weighted-fair ordering + per-tenant outstanding bounds come from
        # the registry; with no registry every request rides the implicit
        # "default" tenant and ordering degenerates to the pre-tenancy
        # (priority, arrival, fid) FCFS — zero behavioral change
        self.tenants = tenants if tenants is not None else TenantRegistry()
        #: per-tenant terminal accounting, keyed by tenant name
        self.tenant_stats: Dict[str, dict] = {}
        # graceful-degradation ladder (fleet/autoscale.py): consulted at
        # admission (shed/cap) and dispatch (spec off, migration pause)
        self.overload = overload
        if overload is not None:
            overload.bind(lambda name, value: self._emit(
                [(name, value, self._next_event_step())]))
            if recorder is not None and overload.recorder is None:
                overload.recorder = recorder
        # SLO burn-rate monitor (telemetry/slo.py): observes every DONE
        # request's TTFT against its tenant's ttft_slo, ticked once per
        # fleet round from export_replica_gauges; None = off
        self.slo = slo
        if slo is not None:
            if slo.clock is None:
                slo.clock = pool.clock
            slo.bind(emit=lambda name, value: self._emit(
                [(name, value, self._next_event_step())]),
                metrics=pool.metrics, recorder=recorder)
        #: DONE-request TTFTs in completion order — the autoscaler's EWMA
        #: input (appended in _finish; never truncated mid-run)
        self.ttft_log: List[float] = []
        # prefill/decode disaggregation (docs/SERVING.md "Disaggregated
        # serving"): policies that declare ``migrates = True`` turn on the
        # two-phase dispatch — requests that reach DECODE on a PREFILL-role
        # replica are paused, their KV exported chunk-by-chunk (overlapping
        # the source's other work), and resumed on a decode replica via the
        # KV-import fast path.  ``migration_chunk_cost`` > 0 charges each
        # export chunk's d2h staging on the source replica's clock view
        # (max-combined with its step cost — the overlap, not a stall).
        self.migrate = bool(getattr(policy, "migrates", False))
        self.migration_chunk_pages = int(migration_chunk_pages)
        self.migration_chunk_cost = float(migration_chunk_cost)
        # prefill_handoff=True migrates at the LATE-PREFILL boundary too
        # (DistServe semantics: the decode replica runs the final chunk +
        # first-token sampling, so the staging pause lands in TTFT);
        # False (default) migrates only after the first decode token —
        # prompt processing finishes at full speed on the prefill replica
        self.prefill_handoff = bool(prefill_handoff)
        #: fid -> in-flight export record {"rid", "sr", "generation",
        #: "exporter", "started_ts"}
        self._migrations: Dict[int, dict] = {}
        # one trace per CLIENT request: the trace_id allocated at submit
        # propagates through every per-replica attempt and survives
        # failover (the resumed attempt links to the dead replica's span).
        # The tracer MUST be the pool's: a router-only tracer would emit
        # attempt spans whose phase children the (untraced) replica
        # frontends never produce — a half-instrumented trace that fails
        # trace_report's tiling invariant by construction.
        if tracer is not None and getattr(tracer, "enabled", True) \
                and tracer is not pool.tracer:
            # a DISABLED tracer (NULL_TRACER) is the documented way to say
            # "tracing off" and is equivalent to None, not a mismatch
            raise ValueError(
                "Router tracer must be the ReplicaPool's: pass tracer= to "
                "ReplicaPool(...) so the replica frontends emit the phase "
                "spans (the pool propagates it to every attached engine, "
                "including recover()/restart() replacements)")
        self.tracer = pool.tracer if pool.tracer is not None else NULL_TRACER
        if recorder is not None and self.tracer.enabled \
                and self.tracer.recorder is None:
            # retention sink: request/phase spans mirror into the bounded
            # ring as they finish, so a crash dump shows the recent
            # requests NEXT TO the control-plane timeline that hurt them
            self.tracer.recorder = recorder
        self.clock = pool.clock
        self._fids = itertools.count()
        self._pending: List[FleetRequest] = []
        self._dispatched: Dict[int, FleetRequest] = {}
        self.requests: List[FleetRequest] = []       # every request ever submitted
        self._t0 = self.clock.now()
        self._events_step = 0
        # failover bookkeeping: one record per replica death, closed when
        # every displaced request has been re-dispatched (or terminated)
        self.kill_records: List[dict] = []
        self.stats = {
            "submitted": 0, "dispatches": 0, "failovers": 0,
            "affinity_hits": 0, "affinity_misses": 0,
            "dispatch_faults": 0, "saturated_dispatches": 0,
            "migrations_started": 0, "migration_chunks": 0,
            "migrations_completed": 0, "migration_fallbacks": 0,
            "migration_failover_reuse": 0,
            "prefix_imports": 0, "prefix_import_pages": 0,
            "prefix_import_fallbacks": 0, "prefix_imports_paused": 0,
            "prefix_imports_noop": 0,
            "shed": 0, "brownout_capped": 0, "tenant_admission_faults": 0,
            "tenant_deferrals": 0,
            "lease_expirations": 0, "fenced_replicas": 0,
            "fenced_completions": 0, "fenced_requests": 0,
            "publish_gaps": 0, "dir_resyncs": 0,
            "warmup_imports": 0, "warmup_fallbacks": 0,
            "partition_dispatch_skips": 0,
            "kv_quota_rejects": 0,
            "lifecycle_cmds": 0, "lifecycle_applied": 0,
            "lifecycle_acked": 0, "lifecycle_stale_acks": 0,
            "lifecycle_aborted": 0, "lifecycle_send_faults": 0,
            "session_sticky_hits": 0, "session_failovers": 0,
            "session_parks": 0, "session_resumes": 0,
        }
        self.recovery_times: List[float] = []
        # arrival-rate telemetry (ROADMAP's predictive-scale-up input):
        # submissions counted at submit(), folded into a rate EWMA + its
        # derivative once per fleet round by export_replica_gauges —
        # deterministic under VirtualClock like every gauge here.  The
        # fold is TIME-constant based (alpha = 1 - exp(-dt/tau)), not
        # per-sample: round lengths vary, and in sparse traffic a single
        # arrival inside a short round reads as a huge instantaneous rate
        # — a fixed per-sample alpha would let that noise (times the
        # forecast horizon, via the slope) conjure phantom demand
        self.arrival_rate_tau = 2.5
        self._arrival_count = 0
        self._arr_last: Optional[Tuple[float, int, Optional[float],
                                       float]] = None
        #: (rate EWMA, slope) as of the last fold — kept unrounded; the
        #: gauges round at export, the predictive autoscaler reads it raw
        self._arr_rate: Tuple[float, float] = (0.0, 0.0)
        #: tenants that ever carried a kv/tenant_pages gauge — a tenant
        #: whose pages drop to zero must READ zero, not freeze its last
        #: non-zero sample forever
        self._kv_tenants_seen: set = set()

    # -------------------------------------------------------------- submit

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               deadline: Optional[float] = None, arrival_ts: Optional[float] = None,
               priority: float = 0.0, tenant: str = "default",
               session: Optional[object] = None) -> FleetRequest:
        now = self.clock.now() if arrival_ts is None else float(arrival_ts)
        self._arrival_count += 1   # demand signal: sheds/rejects included
        spec = self.tenants.spec(tenant)
        max_new_tokens = int(max_new_tokens)
        capped = False
        if self.overload is not None and self.overload.token_cap_active \
                and spec.best_effort:
            # brownout rung 1: best-effort output budgets shrink.  Greedy
            # decode makes the capped output an exact PREFIX of the uncapped
            # one, so degradation never changes a token, only truncates.
            cap = self.overload.config.token_cap
            if max_new_tokens > cap:
                max_new_tokens, capped = cap, True
        fr = FleetRequest(fid=next(self._fids), prompt=list(prompt),
                          max_new_tokens=max_new_tokens, arrival_ts=now,
                          deadline=deadline, priority=priority, tenant=tenant,
                          session_id=session)
        if self.tracer.enabled:
            # reserve the root span id now: attempt/phase children parent
            # to it long before the root's extent (terminal ts) is known
            fr.trace = {"trace_id": self.tracer.new_trace_id(),
                        "root_id": self.tracer.reserve_span_id(),
                        "attempts": [], "last_dead": None}
        self.requests.append(fr)
        self.stats["submitted"] += 1
        self._taccount(tenant)["submitted"] += 1
        try:
            # chaos site: per-tenant admission bookkeeping is a control-
            # plane edge of its own (quota lookups, accounting stores)
            _fi.check("admission.tenant")
        except _fi.InjectedCrash:
            raise  # simulated death of THIS driver process
        except OSError as e:
            # transient tenant-admission fault: the client sees a REJECTED
            # request with a reason and a retry-after hint, never a crash
            self.stats["tenant_admission_faults"] += 1
            fr.reject_reason = "tenant_admission_fault"
            fr.retry_after = self.overload.config.retry_after \
                if (self.overload is not None and self.overload.rung >= 1) \
                else TENANT_FAULT_RETRY_S
            logger.warning(f"admission.tenant transient fault for "
                           f"fid={fr.fid}: {e}")
            self._finish(fr, FleetState.REJECTED, now)
            return fr
        if self.overload is not None and self.overload.shed(spec):
            # brownout rung 4: best-effort admissions are shed outright —
            # an explicit REJECTED with a retry-after beats queueing work
            # the fleet cannot serve inside anyone's deadline
            self.stats["shed"] += 1
            self._taccount(tenant)["shed"] += 1
            self.overload.record_shed()
            fr.reject_reason = "shed_overload"
            fr.retry_after = self.overload.config.retry_after
            self._emit([("fleet/overload_shed", float(self.overload.rung),
                         self._next_event_step())])
            self._finish(fr, FleetState.REJECTED, now)
            return fr
        if spec.kv_page_quota > 0:
            # per-tenant KV quota (docs/SERVING.md "Closed-loop control"):
            # admission charges the request's PROJECTED page need against
            # the tenant's exactly-once fleet-wide tally — one tenant's
            # long-context burst cannot occupy every arena's pages.  A
            # rejection is transient (pages free as the tenant's own work
            # completes), so it carries a retry-after hint.
            need = self._kv_page_need(len(fr.prompt), max_new_tokens)
            if need is not None and \
                    self.tenant_kv_pages().get(tenant, 0) + need \
                    > spec.kv_page_quota:
                self.stats["kv_quota_rejects"] += 1
                fr.reject_reason = "kv_quota"
                fr.retry_after = KV_QUOTA_RETRY_S
                self._emit([("fleet/kv_quota_reject", float(need),
                             self._next_event_step())])
                self._finish(fr, FleetState.REJECTED, now)
                return fr
        if capped:
            # flagged/counted only for requests that will actually be
            # SERVED with the truncated budget — a shed/fault-rejected
            # request never ran and must not inflate the brownout receipt
            fr.brownout_capped = True
            self.stats["brownout_capped"] += 1
            self._taccount(tenant)["brownout_capped"] += 1
        if not self._pending and not self._dispatched:
            # fully idle fleet: no backlog to arbitrate — reset the stride
            # state so the next busy period starts fair for everyone
            self.tenants.reset_passes()
        # the WFQ virtual-time floor tracks ALL outstanding work, not just
        # the queue: under steady uncontended load the queue drains between
        # arrivals, and a pending-only floor (stuck at 0) would let passes
        # earned while nobody waited become permanent scheduling debt —
        # and let a newly-joining tenant jump ahead of every incumbent
        floor = min((r._wfq for r in self._pending), default=None)
        if floor is None:
            floor = min((r._wfq for r in self._dispatched.values()),
                        default=0.0)
        fr._wfq = self.tenants.next_pass(tenant, floor=floor)
        self._pending.append(fr)
        return fr

    def _kv_page_need(self, prompt_len: int, max_new_tokens: int) -> Optional[int]:
        """Projected KV page demand of one request at full generation —
        the admission-time charge against a tenant's ``kv_page_quota``.
        Reads the arena geometry off the first live engine (every replica
        shares it); None when no engine is attached to read from, in
        which case the quota cannot be metered and admission proceeds."""
        for rid in self.pool.rids:
            rep = self.pool.replica(rid)
            if rep.serve is not None:
                ps = rep.serve.engine.kv.page_size
                return -(-(prompt_len + max_new_tokens) // ps)
        return None

    def _taccount(self, tenant: str) -> dict:
        t = self.tenant_stats.get(tenant)
        if t is None:
            t = self.tenant_stats[tenant] = {
                "submitted": 0, "completed": 0, "deadline_met": 0,
                "timed_out": 0, "rejected": 0, "shed": 0,
                "brownout_capped": 0, "failovers": 0, "dispatches": 0,
                "tokens": 0}
        return t

    # ------------------------------------------------------------ dispatch

    def _candidates(self):
        if self.transport is not None:
            # partition-tolerant view: dispatchability comes from the
            # heartbeat LEASE (a replica the router cannot hear from takes
            # no new work), and the load snapshot is LAST-KNOWN-GOOD from
            # its newest heartbeat, annotated with its age — stale routing
            # signals place work suboptimally (slower), never wrongly
            out = []
            for rid in self.pool.rids:
                if not self.lease.dispatchable(rid):
                    continue
                rep = self.pool.replica(rid)
                if rep.serve is None:
                    continue
                stats, age = self.lease.stats(rid)
                if stats is None:
                    continue   # never heard from it: nothing to go on yet
                out.append((rid, rep, {**stats, "age": round(age, 9)}))
            return out
        out = []
        for rid in self.pool.rids:
            if not self.pool.health.dispatchable(rid):
                continue
            rep = self.pool.replica(rid)
            if rep.serve is None:
                continue
            out.append((rid, rep, rep.serve.load_stats()))
        return out

    def dispatch_pending(self, now: Optional[float] = None) -> int:
        """Try to place every pending request on a replica (FCFS by
        arrival).  Returns how many dispatched.  Saturation (per-replica
        ``queue_full`` rejection) leaves a request pending for the next
        round; a structural rejection (infeasible on this engine geometry —
        identical across replicas) is terminal."""
        now = self.clock.now() if now is None else now
        if self.migrate:
            # pre-charge each in-flight export's next chunk on its source's
            # clock view HERE — the dispatch phase runs before this round's
            # replica ticks advance the clock, so the staging cost lands
            # INSIDE the MIGRATING window (the phase span the telemetry
            # materializes per migrated request has real width) and is
            # max-combined with the source's own step cost: overlapped,
            # not serial.  The chunks themselves are pumped in poll().
            self._precharge_migrations()
        # priority class (lower = more urgent) first, then WEIGHTED-FAIR
        # order within the class (tenancy.py stride pass; single-tenant
        # fleets degenerate to pure FCFS), then FCFS tie-break — the fleet
        # queue must honor both the priority submit() accepts and the
        # tenant weights, or one heavy tenant's burst starves everyone
        # exactly when every replica is saturated; anti-starvation aging
        # applies per replica once dispatched
        self._pending.sort(key=lambda r: _tenant_order_key(
            r.priority, r._wfq, r.arrival_ts, r.fid))
        # expire FIRST, for every pending request — expiry must not depend
        # on dispatchable capacity existing (with all replicas dead, expired
        # work still has to reach TIMED_OUT or the driver would stall on a
        # deadline that already passed)
        for fr in list(self._pending):
            if fr.deadline is not None and now > fr.deadline:
                self._pending.remove(fr)
                self._finish(fr, FleetState.TIMED_OUT, now)
        placed = 0
        # one candidate snapshot per round, refreshed incrementally: a full
        # rebuild (load_stats on every replica) per pending request would be
        # O(pending x replicas) per round for state that only changes where
        # a request just landed (or a replica just died)
        candidates = self._candidates()
        # per-tenant concurrency bound: a tenant at max_outstanding keeps
        # its requests PENDING (deferred, not rejected) until completions
        # free slots — the cap is what stops one tenant's burst from
        # occupying every replica's batch at once
        outstanding_by_tenant: Dict[str, int] = {}
        for d in self._dispatched.values():
            outstanding_by_tenant[d.tenant] = \
                outstanding_by_tenant.get(d.tenant, 0) + 1
        for fr in list(self._pending):
            if not candidates:
                break
            tspec = self.tenants.spec(fr.tenant)
            if tspec.max_outstanding > 0 and \
                    outstanding_by_tenant.get(fr.tenant, 0) >= tspec.max_outstanding:
                self.stats["tenant_deferrals"] += 1
                continue
            rid, info = self.policy.select(fr, candidates)
            if rid is None:
                continue
            try:
                _fi.check("router.dispatch")
            except _fi.DeviceLossError as e:
                # the dispatch found its target's device gone — the fleet
                # treats that exactly like a scripted kill of that replica
                self.on_replica_dead(rid, now, reason=str(e))
                self.stats["dispatch_faults"] += 1
                candidates = self._candidates()
                continue   # fr stays pending
            except OSError as e:
                # transient dispatch-path failure (RPC hiccup): the request
                # stays pending and the next round retries
                self.stats["dispatch_faults"] += 1
                logger.warning(f"router.dispatch transient fault for fid={fr.fid}: {e}")
                continue
            if info.get("prefix_import") is not None:
                # cluster-wide warmth: adopt the hot prefix's KV onto the
                # cold target before the dispatch (docs/SERVING.md "Prefix
                # directory").  A replica death during staging is handled
                # like a dispatch-time device loss: refresh candidates,
                # and retry the request next round if its target died.
                if self._prefix_import(fr, rid, info, now) == "dead":
                    candidates = self._candidates()
                    if not any(c[0] == rid for c in candidates):
                        continue   # fr stays pending
            if self._dispatch_to(fr, rid, info, now):
                placed += 1
                outstanding_by_tenant[fr.tenant] = \
                    outstanding_by_tenant.get(fr.tenant, 0) + 1
                if self.transport is None:
                    candidates = [(r, rp, rp.serve.load_stats() if r == rid else st)
                                  for r, rp, st in candidates]
                else:
                    # no fresh probe exists under the transport: fold the
                    # router's OWN dispatch into its stale estimate (the
                    # one state change it knows about without a heartbeat)
                    candidates = [
                        (r, rp,
                         {**st, "queue_depth": st["queue_depth"] + 1,
                          "outstanding_tokens": st["outstanding_tokens"]
                          + max(0, fr.max_new_tokens - len(fr.tokens))}
                         if r == rid else st)
                        for r, rp, st in candidates]
        return placed

    def _dispatch_to(self, fr: FleetRequest, rid: int, info: dict, now: float) -> bool:
        rep = self.pool.replica(rid)
        if len(fr.tokens) >= fr.max_new_tokens:
            # a victim displaced with its output already complete (killed in
            # the same tick it finished): nothing to resume — close it out
            self._pending.remove(fr)
            fr.finish_ts = fr.finish_ts if fr.finish_ts is not None else now
            self._finish(fr, FleetState.DONE, now)
            return False
        if self.transport is not None and \
                not self.transport.connected("router", rid, now):
            # the dispatch RPC vanished into a partition the lease has not
            # yet diagnosed: to the router it is a timeout — the request
            # stays pending and the replica goes SUSPECT before this can
            # loop for long (the same shape as a transient dispatch fault)
            self.stats["partition_dispatch_skips"] += 1
            self.stats["dispatch_faults"] += 1
            return False
        att = None
        if fr.trace is not None:
            # the attempt span id is reserved BEFORE submit so the replica
            # frontend can parent this attempt's phase spans to it; the
            # span itself is materialized when the attempt ends
            att = {"rid": rid, "span_id": self.tracer.reserve_span_id(),
                   "dispatch_ts": now, "generation": rep.generation,
                   "resumed_from": fr.trace["last_dead"],
                   "resume_tokens": len(fr.tokens), "end_ts": None}
        # brownout rung 2: speculative decoding off for NEW dispatches —
        # verify dispatches are k+1-wide model work the overloaded fleet
        # can spend on plain decode instead; greedy parity means outputs
        # do not change, only the speed strategy does
        spec_flag = False if (self.overload is not None
                              and self.overload.spec_disabled) else None
        sr = rep.serve.submit(
            fr.prompt, max_new_tokens=fr.max_new_tokens, deadline=fr.deadline,
            arrival_ts=fr.arrival_ts, priority=fr.priority,
            # under the transport, token deliveries are observed by POLL
            # re-sync (sequence = len(tokens)) instead of push callbacks —
            # a stream delivered into a partition would either vanish or
            # double; the re-sync is idempotent by construction
            stream=None if self.transport is not None
            else self._make_stream(fr, rep.generation),
            resume_tokens=list(fr.tokens) or None,
            trace_id=fr.trace["trace_id"] if fr.trace is not None else None,
            parent_span_id=att["span_id"] if att is not None else None,
            spec=spec_flag,
            kv_snapshot=fr._kv_snapshot)
        if sr.state is RequestState.REJECTED:
            if sr.reject_reason == "queue_full":
                self.stats["saturated_dispatches"] += 1
                return False            # transient: stays pending (the
                # snapshot, if any, stays on fr for the retry)
            self._pending.remove(fr)
            fr.reject_reason = sr.reject_reason
            if att is not None:
                fr.trace["attempts"].append(att)
                self._close_attempt(fr, "rejected", now)
            self._finish(fr, FleetState.REJECTED, now)
            return False
        self._pending.remove(fr)
        # the ServingRequest owns the snapshot now (consumed — or rejected
        # into the recompute fallback — at its admission on the replica)
        fr._kv_snapshot = None
        if att is not None:
            fr.trace["attempts"].append(att)
            fr.trace["last_dead"] = None
        fr._current = (rid, sr, rep.generation)
        fr.dispatches.append((rid, now))
        fr.to(FleetState.DISPATCHED, now)
        self._dispatched[fr.fid] = fr
        self.stats["dispatches"] += 1
        self._taccount(fr.tenant)["dispatches"] += 1
        if "affinity_hit" in info:
            key = "affinity_hits" if info["affinity_hit"] else "affinity_misses"
            self.stats[key] += 1
            if info["affinity_hit"]:
                fr.affinity_hits += 1
        if info.get("session_sticky"):
            self.stats["session_sticky_hits"] += 1
        if info.get("session_failover"):
            # the session's sticky replica was gone/saturated and the turn
            # re-homed — distinct from fr.failovers (mid-attempt displacement)
            self.stats["session_failovers"] += 1
        self._emit([("fleet/dispatch", float(rid), self._next_event_step())])
        return True

    # -------------------------------------------------------- prefix import

    def _prefix_import(self, fr: FleetRequest, rid: int, info: dict,
                       now: float) -> str:
        """Hot-prefix KV import ahead of a cold dispatch: export the
        directory-promised prefix pages once from the warmest donor
        (host-staged, crc-tagged — the PR-8 ``kvtransfer`` path) and adopt
        them into ``rid``'s prefix cache, so the request prefills warm on
        the replica load balancing picked.  Returns ``"ok"``,
        ``"fallback"`` (any ordinary rejection: the dispatch proceeds cold
        and the prefill recomputes — slower, never wrong) or ``"dead"`` (a
        replica died mid-staging; the caller refreshes its candidates).
        The fleet-level accounting lands on ``stats["prefix_*"]`` and the
        ``fleet/prefix_import[_fallback]`` events."""
        from ..kvtransfer import SnapshotError, export_prefix
        from ...resilience.fault_injection import DeviceLossError
        plan = info.pop("prefix_import")
        if self.overload is not None and self.overload.migrations_paused:
            # brownout rung 3 shares one switch with migration: no NEW
            # staging under overload — the h2d/d2h bandwidth (and the
            # target's pages) go to serving (docs/SERVING.md ladder table)
            self.stats["prefix_imports_paused"] += 1
            return "fallback"
        donor_rid = plan["donor"]
        donor = self.pool.replica(donor_rid)
        target = self.pool.replica(rid)
        if donor.serve is None or target.serve is None:
            return self._prefix_import_fallback(fr, "replica gone before staging")
        tspec = self.tenants.spec(fr.tenant)
        if tspec.kv_page_quota > 0 and \
                self.tenant_kv_pages().get(fr.tenant, 0) \
                + plan["donor_depth"] > tspec.kv_page_quota:
            # the import charges the IMPORTING tenant's quota: adopting
            # remote pages it has no budget for would launder arena
            # occupancy through the prefix cache.  Checked BEFORE the d2h
            # export against the directory's promised depth, so a
            # quota-bound tenant costs no staging bandwidth — the dispatch
            # proceeds cold instead (slower, never wrong).
            self.stats["kv_quota_rejects"] += 1
            self._emit([("fleet/kv_quota_reject", float(plan["donor_depth"]),
                         self._next_event_step())])
            return self._prefix_import_fallback(
                fr, f"tenant {fr.tenant!r} kv quota "
                f"({tspec.kv_page_quota} pages)")
        tokens = list(fr.prompt) + list(fr.tokens)
        try:
            snapshot = export_prefix(donor.serve.engine, tokens,
                                     source=f"replica{donor_rid}")
        except _fi.InjectedCrash:
            raise  # simulated death of THIS driver process
        except DeviceLossError as e:
            # the d2h staging found the DONOR device gone: replica death,
            # ordinary failover path; the target is untouched
            self.on_replica_dead(donor_rid, now, reason=str(e))
            self._prefix_import_fallback(fr, f"donor died mid-export: {e}")
            return "dead"
        except (SnapshotError, OSError) as e:
            return self._prefix_import_fallback(fr, f"export fault: {e}")
        if snapshot is None:
            # evict-after-publish staleness: the donor no longer holds what
            # it published — recompute owns the request (the retraction
            # that should have fixed the directory was lost or raced)
            return self._prefix_import_fallback(fr, "donor cold (stale directory)")
        try:
            n_imported = target.serve.import_prefix(snapshot)
        except _fi.InjectedCrash:
            raise
        except DeviceLossError as e:
            # the h2d scatter found the TARGET device gone — the caller
            # must re-pick a replica for this request
            self.on_replica_dead(rid, now, reason=str(e))
            self._prefix_import_fallback(fr, f"target died mid-import: {e}")
            return "dead"
        except (SnapshotError, OSError) as e:
            # torn staging (crc verify), geometry drift, no page room, a
            # transient import fault: cold dispatch + recompute
            return self._prefix_import_fallback(fr, f"import rejected: {e}")
        if n_imported == 0:
            # directory stale-COLD about the TARGET (a dropped publish):
            # it already held the whole chain, nothing was installed — the
            # request lands warm, but no import is counted or charged
            self.stats["prefix_imports_noop"] += 1
            info["affinity_hit"] = True
            info["warm_pages"] = snapshot.n_pages
            return "ok"
        if self.prefix_import_cost > 0:
            # charge the staging on both clock views, max-combined with
            # each side's own step cost (overlap, not a stall) — the same
            # accounting stance as migration chunk pre-charges.  The donor
            # staged the WHOLE snapshot d2h; the target scattered only the
            # pages it was missing.
            donor.clock.on_step(self.prefix_import_cost * snapshot.n_pages)
            target.clock.on_step(self.prefix_import_cost * n_imported)
        self.stats["prefix_imports"] += 1
        self.stats["prefix_import_pages"] += n_imported
        # the request now LANDS warm: the hit label reports where it landed
        info["affinity_hit"] = True
        info["warm_pages"] = snapshot.n_pages
        info["prefix_imported"] = True
        # (the per-replica "prefix/import" counter is incremented by the
        # target frontend's import_prefix — one registry, counted once)
        self._emit([("fleet/prefix_import", float(rid),
                     self._next_event_step())])
        return "ok"

    def _prefix_import_fallback(self, fr: FleetRequest, reason: str) -> str:
        self.stats["prefix_import_fallbacks"] += 1
        logger.warning(f"fleet: prefix import for fid={fr.fid} fell back "
                       f"({reason})")
        if self.pool.metrics is not None:
            self.pool.metrics.counter("prefix/import_fallback").inc()
        self._emit([("fleet/prefix_import_fallback", 1.0,
                     self._next_event_step())])
        return "fallback"

    def _make_stream(self, fr: FleetRequest, generation: int):
        def on_tokens(sr: ServingRequest, toks: List[int], ts: float) -> None:
            cur = fr._current
            if cur is None or cur[1] is not sr or cur[2] != generation:
                return  # stale attempt (replica since failed over) — drop
            if fr.first_token_ts is None and toks:
                fr.first_token_ts = ts
            fr.tokens.extend(toks)
        return on_tokens

    # ----------------------------------------------------- session parking

    def _current_attempt(self, fr: FleetRequest):
        """``(replica, sr)`` when ``fr``'s current attempt is live on a
        healthy replica of the generation it was dispatched to; None when
        the request has no attempt (pending/terminal) or the replica died
        or restarted since — callers degrade gracefully (a park that can't
        happen just means the stall holds its device pages; a resume that
        can't happen means failover already re-queued the request)."""
        cur = fr._current
        if cur is None:
            return None
        rid, sr, gen = cur
        rep = self.pool.replica(rid)
        if rep.serve is None or rep.generation != gen:
            return None
        return rep, sr

    def request_decoding(self, fr: FleetRequest) -> bool:
        """True when ``fr``'s current attempt is actively DECODING on a
        live replica — the only window :meth:`park_request` can use.  A
        session coordinator polls this after a failover re-dispatch to
        re-park a request whose tool stall the death interrupted."""
        live = self._current_attempt(fr)
        return live is not None and live[1].state is RequestState.DECODE

    def park_request(self, fr: FleetRequest, phase: str = "tool_stall") -> bool:
        """Park ``fr``'s in-flight attempt through its replica's host KV
        tier (serving/sessions tool stall): partial generation demoted
        host-side, device pages freed, the fleet request stays DISPATCHED
        (the attempt is PARKED, not displaced).  False when the attempt
        isn't in a parkable window — the stall then simply rides out
        on-device, slower for neighbors but never wrong."""
        live = self._current_attempt(fr)
        if live is None:
            return False
        rep, sr = live
        if not rep.serve.park(sr.uid, phase=phase):
            return False
        self.stats["session_parks"] += 1
        self._emit([("fleet/session_park", 1.0, self._next_event_step())])
        return True

    def prefetch_resume_request(self, fr: FleetRequest) -> bool:
        """Prefetch hint for a parked attempt's h2d promotion (the session
        coordinator calls this ``prefetch_lead_s`` ahead of the tool
        result's ETA, so the transfer hides under intervening steps)."""
        live = self._current_attempt(fr)
        if live is None:
            return False
        rep, sr = live
        return rep.serve.prefetch_resume(sr.uid)

    def resume_request(self, fr: FleetRequest) -> bool:
        """Resume ``fr``'s parked attempt in place (tool result arrived):
        re-enqueued on the SAME replica, admission promotes the staged KV
        back (or recomputes on any host-tier miss).  False when the
        attempt is gone — replica death displaced it and the normal
        failover path owns it now."""
        live = self._current_attempt(fr)
        if live is None:
            return False
        rep, sr = live
        if sr.state is not RequestState.PARKED:
            return False
        if not rep.serve.resume(sr.uid):
            return False
        self.stats["session_resumes"] += 1
        self._emit([("fleet/session_resume", 1.0, self._next_event_step())])
        return True

    # ---------------------------------------------------------------- poll

    def poll(self, now: Optional[float] = None) -> None:
        """Fold per-replica terminal states up into fleet terminal states.
        Under a migrating policy this is also the migration pump: one
        export chunk per in-flight migration per round, completions handed
        off to a decode replica."""
        now = self.clock.now() if now is None else now
        if self.migrate:
            # pump BEFORE starting new exports: a fresh export's first
            # chunk waits for the next poll, after its pre-charged staging
            # cost has landed on the clock — so even a single-chunk
            # migration's MIGRATING window spans a real clock advance
            self._pump_migrations(now)
            self._start_migrations(now)
        for fr in list(self._dispatched.values()):
            rid, sr, _gen = fr._current
            if self.transport is not None:
                if not self.transport.connected(rid, "router", now):
                    # partitioned: the router cannot observe this attempt —
                    # its tokens and terminal state wait for the heal (or
                    # for the lease to expire and re-home the request)
                    continue
                self._sync_tokens(fr, sr, now)
            if sr.state is RequestState.DONE:  # dslint-ok(state-machine): poll folds only replica-TERMINAL outcomes; every other state means the attempt is still live and stays dispatched (MIGRATED/EVICTED are resolved by the migration pump and the replica's own requeue)
                del self._dispatched[fr.fid]
                fr._current = None
                fr.finish_ts = sr.finish_ts if sr.finish_ts is not None else now
                self._close_attempt(fr, "done", fr.finish_ts)
                self._finish(fr, FleetState.DONE, now)
            elif sr.state is RequestState.TIMED_OUT:
                del self._dispatched[fr.fid]
                fr._current = None
                # close at the REPLICA-side timeout instant, not poll-time
                # now (the shared clock advanced by a round in between):
                # the root span must end where the phase spans do or the
                # trace_report tiling invariant breaks by one round
                t_out = sr.history[-1][1]
                self._close_attempt(fr, "timed_out", t_out)
                self._finish(fr, FleetState.TIMED_OUT, t_out)

    # ------------------------------------------------------- control plane

    def _sync_tokens(self, fr: FleetRequest, sr: ServingRequest,
                     now: float) -> None:
        """Sequence-numbered token sync: ``sr.tokens`` always EXTENDS the
        resume seed the router dispatched with, so ``len(tokens)`` is the
        stream's sequence number and catch-up after a healed partition is
        one idempotent list copy — no delivery can be lost or applied
        twice.  First-token time is the router's OBSERVATION instant (the
        client cannot see a token before the router does)."""
        toks = sr.tokens
        have = len(fr.tokens)
        if len(toks) > have:
            if fr.first_token_ts is None:
                fr.first_token_ts = now
            # append only the unseen suffix: a full-list rebuild per poll
            # round would be O(T^2) over a T-token generation
            fr.tokens.extend(int(t) for t in toks[have:])

    def transport_poll(self, now: Optional[float] = None) -> None:
        """One control-plane round: drain due message deliveries, sweep the
        heartbeat leases (expiry re-homes work and bumps fencing epochs),
        (re)send unacked fences, and fire gap-timeout directory resyncs.
        The fleet driver calls this once per round, before dispatch; no-op
        without a transport."""
        if self.transport is None:
            return
        now = self.clock.now() if now is None else now
        for msg in self.transport.deliver(now):
            self._on_message(msg, now)
        # generation fencing: a replica that died and came back INSIDE its
        # lease window renews the lease, but its heartbeat's bumped engine
        # generation betrays the restart — attempts dispatched to the old
        # generation died with it and must re-home now, not at an expiry
        # that will never come
        for fr in list(self._dispatched.values()):
            if fr._current is None:
                continue
            rid, _sr, gen = fr._current
            g = self.lease.generation(rid)
            if g is not None and g > gen:
                self._requeue_attempt(fr, now, "replica_restarted")
                self._emit([("fleet/failover_requeued", 1.0,
                             self._next_event_step())])
        if self.lease.config.adaptive:
            # feed the adaptive-lease loop its link-quality inputs before
            # the expiry sweep, so a lossy fabric widens the band BEFORE
            # it can false-fence (docs/SERVING.md "Closed-loop control")
            for rid in self.pool.rids:
                feed = self._dir_feeds.get(rid)
                age = 0.0 if feed is None or feed.gap_since is None \
                    else max(0.0, now - feed.gap_since)
                self.lease.note_link_quality(
                    rid, self.transport.link_loss_ewma("router", rid),
                    age, now)
        for rid in self.lease.tick(now):
            self.on_lease_expired(rid, now)
        for rid in self.lease.fence_pending(now):
            self._send_fence(rid, now)
        self._sweep_lifecycle(now)
        for rid, feed in self._dir_feeds.items():
            self._check_dir_feed(rid, feed, now)

    def _on_message(self, msg, now: float) -> None:
        """Route one delivered message to its handler.  ``dst == "router"``
        is the router's inbox; an integer dst is a replica's (the replicas
        are in-process, so their inbox handling lives here too)."""
        kind, p = msg.kind, msg.payload
        if msg.dst == "router":
            if kind == "heartbeat":
                # a "zombie" verdict flips the view to FENCING; the fence
                # itself goes out in this same transport_poll round via the
                # fence_pending sweep (and retries on its timer)
                self.lease.observe_heartbeat(
                    msg.src, msg.seq, p["state"], p["stats"], msg.send_ts, now,
                    generation=p.get("generation"))
            elif kind == "dir_publish":
                self._on_dir_publish(msg.src, msg.seq, p, now)
            elif kind == "dir_resync":
                self._on_dir_resync(msg.src, p, now)
            elif kind == "fence_ack":
                self._on_fence_ack(msg.src, p, now)
            elif kind == "mig_chunk":
                self._on_mig_chunk(msg.src, p, now)
            elif kind == "lifecycle_ack":
                self._on_lifecycle_ack(msg.src, p, now)
            return
        rid = msg.dst
        if kind == "fence":
            # replica-side fence execution: cancel ALL in-flight work (every
            # dispatch on this replica predates the epoch bump) and ack.
            # Idempotent per epoch — a duplicated/late fence copy delivered
            # after the rejoin must NOT cancel re-dispatched work.
            counts = self.pool.fence_replica(rid, epoch=p["epoch"])
            n = counts["queued"] + counts["active"]
            if n:
                self.stats["fenced_requests"] += n
                self._emit([("fleet/fenced_request", float(n),
                             self._next_event_step())])
            self.transport.send("fence_ack", rid, "router",
                                {"epoch": p["epoch"], **counts})
        elif kind == "dir_resync_req":
            snap = self.pool.dir_snapshot(rid)
            if snap is not None:   # dead replicas answer nothing; retry finds
                self.transport.send("dir_resync", rid, "router", snap)
        elif kind == "mig_ack":
            m = self._migrations.get(p["fid"])
            if m is not None and m["rid"] == rid:
                ch = m["chan"]
                if p["next"] > ch["base"]:
                    ch["base"] = p["next"]
                    ch["sent_idx"], ch["sent_ts"] = None, None
        elif kind == "lifecycle_cmd":
            self._apply_lifecycle(rid, p, now)

    # -------------------------------------------------- lease expiry + fence

    def on_lease_expired(self, rid: int, now: float) -> None:
        """Fleet-declared death of ``rid``: the router has not heard a
        heartbeat for a full lease window.  Unlike :meth:`on_replica_dead`
        this does NOT touch the replica's engine — the replica may be a
        perfectly healthy zombie on the far side of a partition.  Its
        in-flight fleet requests are re-homed (tokens preserved up to the
        last connected sync; recompute-on-resume keeps outputs
        byte-identical), its dispatch epoch was bumped by the lease sweep
        (fencing every outstanding attempt), and its directory entries and
        publish feed are invalidated pending a post-rejoin resync."""
        self.stats["lease_expirations"] += 1
        displaced = []
        victims = []
        for fr in list(self._dispatched.values()):
            if fr._current is None or fr._current[0] != rid:
                continue
            sr = self._requeue_attempt(fr, now, "lease_expired")
            displaced.append((fr.fid, sr))
            victims.append(fr)
        #: audited when the fence completes: any of these that reached DONE
        #: on the zombie is a LATE COMPLETION the fencing discarded
        self._lease_displaced[rid] = displaced
        # surviving export records anchored on the lease-dead source are
        # unpumpable (and its chunks unackable) — drop them
        for fid in [f for f, m in self._migrations.items() if m["rid"] == rid]:
            self._migrations.pop(fid)
            self._mig_rx.pop(fid, None)
        if self.directory is not None:
            self.directory.purge(rid)
        feed = self._dir_feeds.get(rid)
        if feed is not None:
            feed.expect = None
            feed.buffer.clear()
            feed.gap_since = feed.resync_since = None
        record = {"rid": rid, "ts": now, "reason": "lease expired",
                  "victims": {fr.fid for fr in victims},
                  "n_victims": len(victims), "recovered_ts": None}
        if not victims:
            record["recovered_ts"] = now
            self.recovery_times.append(0.0)
        self.kill_records.append(record)
        self._emit([("fleet/failover_requeued", float(len(victims)),
                     self._next_event_step())])
        self._recorder_dump("lease_expired", now)

    def _requeue_attempt(self, fr: FleetRequest, now: float,
                         outcome: str) -> ServingRequest:
        """Displace one DISPATCHED attempt back to PENDING (lease expiry or
        an in-lease restart): tokens preserved up to the last connected
        sync, a COMPLETE router-side migration snapshot harvested for the
        KV-import fast path, the attempt span closed with the replica-side
        phase history folded and its open tail attributed to
        ``phase/fenced`` — on BOTH outcomes: an expired lease's work is
        discarded by the fence proper, and an in-lease restart's old-
        generation work is discarded by the epoch/generation fencing
        (transport_poll) — so transport-mode traces still tile
        [arrival, terminal] (scripts/trace_report.py).  Returns the
        displaced ServingRequest for the fencing audit."""
        del self._dispatched[fr.fid]
        rid, sr, gen = fr._current
        fr._current = None
        if fr.trace is not None:
            # the zombie frontend must not ALSO emit this attempt's phase
            # spans at its own (fenced, discarded) terminal — the tracer
            # is fleet-shared state, exactly like the request record the
            # fence audit reads, so dropping the ctx is bookkeeping on
            # this side of the partition, not a message through it.
            # Generation-gated: after an in-lease restart the frontend is
            # a NEW engine whose uids restart at 0 — a blind drop by uid
            # could discard a live new-generation request's trace ctx
            # (the old frontend died with the old generation; there is
            # nothing to drop there)
            rep = self.pool.replica(rid)
            if rep.serve is not None and rep.generation == gen:
                rep.serve.drop_trace(sr.uid)
        self._migrations.pop(fr.fid, None)
        rx = self._mig_rx.pop(fr.fid, None)
        if rx is not None and rx["snap"].complete and fr._kv_snapshot is None:
            fr._kv_snapshot = rx["snap"]
            self.stats["migration_failover_reuse"] += 1
        fr.failovers += 1
        self._taccount(fr.tenant)["failovers"] += 1
        fr.to(FleetState.PENDING, now)
        self._close_attempt(fr, outcome, now, displaced_sr=sr,
                            tail_phase="fenced")
        if fr.trace is not None and fr.trace["attempts"]:
            fr.trace["last_dead"] = fr.trace["attempts"][-1]["span_id"]
        self._pending.append(fr)
        self.stats["failovers"] += 1
        return sr

    def _send_fence(self, rid: int, now: float) -> None:
        epoch = self.lease.epoch[rid]
        first = self.lease.note_fence_sent(rid, now)
        if first:
            self.stats["fenced_replicas"] += 1
            self._emit([("fleet/fenced_replica", float(rid),
                         self._next_event_step())])
        else:
            self.transport.note_retransmit()
        self.transport.send("fence", "router", rid, {"epoch": epoch})

    def _on_fence_ack(self, rid: int, p: dict, now: float) -> None:
        if not self.lease.on_fence_ack(rid, p["epoch"], now):
            return   # stale/duplicate ack from an earlier episode
        # the late-completion audit: displaced attempts that reached DONE
        # on the zombie are exactly the completions fencing discarded —
        # each is an auditable event, never a second serve
        late = [fid for fid, sr in self._lease_displaced.pop(rid, [])
                if sr.state is RequestState.DONE]
        if late:
            self.stats["fenced_completions"] += len(late)
            self._emit([("fleet/fenced_completion", float(len(late)),
                         self._next_event_step())])
            logger.warning(f"fleet: discarded {len(late)} fenced late "
                           f"completion(s) from replica {rid}: fids {late}")
        # the zombie's cache may still be warm, but the router purged its
        # entries at expiry: pull a fresh full-digest snapshot
        self._request_dir_resync(rid, now)
        # the fencing episode is complete (zombie cancelled + re-admitted):
        # dump the black box while the whole story is still in the ring
        self._recorder_dump("fence", now)

    # ---------------------------------------------- lifecycle command plane

    def lifecycle_command(self, rid: int, op: str,
                          payload: Optional[dict] = None,
                          now: Optional[float] = None) -> Optional[int]:
        """Issue one lifecycle mutation against replica ``rid`` — the
        single entry point the autoscaler (and the migration pump) drives
        replica state through (docs/SERVING.md "Closed-loop control").

        Without a transport this IS the pre-r21 direct call, synchronous
        and unlosable.  With one, the mutation becomes a typed
        ``lifecycle_cmd`` message: seq-numbered (the replica's dedup key),
        stamped with the target's CURRENT lease epoch (the fencing token),
        re-sent stop-and-wait until acked.  An identical (rid, op) command
        already in flight is not duplicated — the retry timer owns it.
        Returns the command seq under a transport, else None."""
        now = self.clock.now() if now is None else now
        payload = dict(payload or {})
        if self.transport is None:
            self._lifecycle_direct(rid, op, payload)
            return None
        for cmd in self._lifecycle.values():
            if cmd.rid == rid and cmd.op == op and \
                    cmd.state in (LifecycleCmdState.PENDING,
                                  LifecycleCmdState.SENT):
                return cmd.seq   # already in flight: idempotent issue
        seq = next(self._lifecycle_seq)
        cmd = _LifecycleCmd(seq=seq, rid=rid, op=op, payload=payload,
                            epoch=self.lease.epoch[rid], issued_ts=now)
        self._lifecycle[seq] = cmd
        self.stats["lifecycle_cmds"] += 1
        self._emit([("fleet/lifecycle_cmd", float(rid),
                     self._next_event_step())])
        if self.recorder is not None:
            self.recorder.instant("ctrl/lifecycle", "ctrl/autoscale", now,
                                  attrs={"rid": rid, "op": op, "seq": seq,
                                         "epoch": cmd.epoch})
        self._send_lifecycle(cmd, now)
        return seq

    def _lifecycle_direct(self, rid: int, op: str, payload: dict) -> None:
        """The transportless path: exactly the synchronous calls the
        autoscaler made before lifecycle traffic was transported —
        byte-identical behavior with ``transport=None``."""
        if op == "recover":
            self.pool.recover(rid)
            self.warmup_replica(rid)
        elif op == "drain":
            self.pool.drain(rid)
        elif op == "park":
            victims = self.pool.kill(
                rid, reason=payload.get("reason", "parked (lifecycle)"))
            assert not victims, \
                f"lifecycle park of replica {rid} displaced in-flight " \
                f"work: {victims}"
        elif op == "restart":
            self.pool.restart(rid)
            self.warmup_replica(rid)
        elif op == "role_change":
            self.pool.set_role(rid, payload["role"])
            self.pool.restart(rid)
            self.warmup_replica(rid)
            self._emit([("fleet/role_change", float(rid),
                         self._next_event_step())])
        else:
            raise ValueError(f"unknown lifecycle op {op!r}")

    def _send_lifecycle(self, cmd: _LifecycleCmd, now: float) -> None:
        """(Re)send one command over the fabric.  A transient send-path
        fault leaves the record PENDING for the retry sweep; the fabric
        eating the message (loss/partition) is indistinguishable from a
        lost ack and the same timer recovers both."""
        try:
            # chaos site: the router's lifecycle send edge
            _fi.check("lifecycle.cmd.send")
        except _fi.InjectedCrash:
            raise  # simulated death of THIS driver process
        except OSError as e:
            self.stats["lifecycle_send_faults"] += 1
            logger.warning(f"lifecycle.cmd.send transient fault for "
                           f"seq={cmd.seq} ({cmd.op} -> replica "
                           f"{cmd.rid}): {e}")
            return
        if cmd.state is LifecycleCmdState.PENDING:
            cmd.to(LifecycleCmdState.SENT)
        else:
            self.transport.note_retransmit()
        cmd.sent_ts = now
        self.transport.send("lifecycle_cmd", "router", cmd.rid,
                            {"seq": cmd.seq, "op": cmd.op,
                             "epoch": cmd.epoch, "payload": cmd.payload},
                            seq=cmd.seq)

    def _sweep_lifecycle(self, now: float) -> None:
        """One retry round: abort commands whose target's epoch advanced
        mid-flight (stale intent must not be retried into the post-fence
        world), then (re)send everything unacked whose timer is due."""
        for seq in sorted(self._lifecycle):
            cmd = self._lifecycle[seq]
            if cmd.state not in (LifecycleCmdState.PENDING,
                                 LifecycleCmdState.SENT):
                continue
            if self.lease.epoch[cmd.rid] > cmd.epoch:
                cmd.to(LifecycleCmdState.ABORTED)
                self.stats["lifecycle_aborted"] += 1
                logger.warning(f"lifecycle cmd {seq} ({cmd.op} -> replica "
                               f"{cmd.rid}) aborted: epoch advanced "
                               f"{cmd.epoch} -> {self.lease.epoch[cmd.rid]}")
                continue
            if cmd.state is LifecycleCmdState.SENT and \
                    cmd.sent_ts is not None and \
                    now - cmd.sent_ts < self.lifecycle_retry:
                continue   # in flight, not yet timed out
            self._send_lifecycle(cmd, now)

    def _apply_lifecycle(self, rid: int, p: dict, now: float) -> None:
        """Replica-side command application: exactly-once effects under
        at-least-once delivery.  The pool-level seq ledger (it survives
        engine swaps, like the fencing epoch) re-acks the recorded outcome
        for duplicated/retried copies without re-applying; a command
        stamped with a pre-fencing epoch is rejected (``stale_epoch``) —
        a partitioned router's zombie command can never mutate a replica
        that was fenced after the command was issued."""
        seq, op = p["seq"], p["op"]
        seen = self.pool.lifecycle_seen(rid)
        status = seen.get(seq)
        if status is None:
            try:
                # chaos site: the replica's lifecycle apply edge
                _fi.check("lifecycle.cmd.apply")
            except _fi.InjectedCrash:
                raise  # simulated death of THIS driver process
            except OSError as e:
                # transient apply fault: nothing changed, nothing acked —
                # the router's retry timer re-delivers
                logger.warning(f"lifecycle.cmd.apply transient fault on "
                               f"replica {rid} (seq={seq} {op}): {e}")
                return
            if p["epoch"] < self.pool.fenced_epoch(rid):
                status = "stale_epoch"
                logger.warning(f"replica {rid}: rejected lifecycle cmd "
                               f"{seq} ({op}) from epoch {p['epoch']} "
                               f"(fenced at {self.pool.fenced_epoch(rid)})")
            else:
                status = self._lifecycle_apply_op(rid, op,
                                                  p.get("payload") or {})
            seen[seq] = status
            if status == "applied":
                self.stats["lifecycle_applied"] += 1
        self.transport.send("lifecycle_ack", rid, "router",
                            {"seq": seq, "op": op, "epoch": p["epoch"],
                             "status": status}, seq=seq)

    def _lifecycle_apply_op(self, rid: int, op: str, payload: dict) -> str:
        """Execute one op against the replica-LOCAL truth, state-guarded:
        a late or duplicated command the replica's state no longer fits is
        REJECTED with an auditable status instead of tripping the pool's
        transition asserts (e.g. a retried recover landing after the
        replica already recovered and died again)."""
        health = self.pool.health.state(rid)
        if op == "recover":
            if health is not ReplicaState.DEAD:
                return f"rejected:{health.value}"
            self.pool.recover(rid)
            return "applied"
        if op == "drain":
            if health not in (ReplicaState.HEALTHY, ReplicaState.DEGRADED):
                return f"rejected:{health.value}"
            self.pool.drain(rid)
            return "applied"
        if op in ("park", "restart", "role_change"):
            if health is not ReplicaState.DRAINING or not self.pool.is_idle(rid):
                return f"rejected:{health.value}"
            if op == "park":
                victims = self.pool.kill(
                    rid, reason=payload.get("reason", "parked (lifecycle)"))
                assert not victims, \
                    f"lifecycle park of replica {rid} displaced in-flight " \
                    f"work: {victims}"
            else:
                if op == "role_change":
                    self.pool.set_role(rid, payload["role"])
                self.pool.restart(rid)
            return "applied"
        if op == "mig_complete":
            rep = self.pool.replica(rid)
            uid = payload["uid"]
            if rep.serve is None:
                return "rejected:no_engine"
            sr = rep.serve._active.get(uid)
            if sr is None or sr.state is not RequestState.MIGRATING:
                # the source's copy already left MIGRATING (preempted,
                # restarted, or a duplicate raced the first apply): there
                # is nothing to close — the router-side handoff owns the
                # request either way
                return "rejected:not_migrating"
            rep.serve.complete_migration(uid)
            return "applied"
        return f"rejected:unknown_op:{op}"

    def _on_lifecycle_ack(self, rid: int, p: dict, now: float) -> None:
        """Fold one ack.  A fenced zombie's late ack — the target's epoch
        advanced after the command was stamped — is DISCARDED: whatever
        the zombie claims to have applied predates the fence and must not
        drive follow-up actions on this side."""
        cmd = self._lifecycle.get(p["seq"])
        if cmd is None or cmd.state is not LifecycleCmdState.SENT:
            return   # unknown seq, duplicate ack, or an aborted record
        if self.lease.epoch[cmd.rid] > cmd.epoch:
            self.stats["lifecycle_stale_acks"] += 1
            cmd.to(LifecycleCmdState.ABORTED)
            self.stats["lifecycle_aborted"] += 1
            logger.warning(f"fleet: discarded stale lifecycle ack seq="
                           f"{cmd.seq} ({cmd.op}) from fenced replica {rid}")
            return
        cmd.to(LifecycleCmdState.ACKED)
        cmd.status = p["status"]
        self.stats["lifecycle_acked"] += 1
        if p["status"] != "applied":
            return
        # router-side follow-ups the direct path ran synchronously
        if cmd.op in ("recover", "restart", "role_change"):
            self.warmup_replica(cmd.rid)
        if cmd.op == "role_change":
            self._emit([("fleet/role_change", float(cmd.rid),
                         self._next_event_step())])
        elif cmd.op == "park":
            # a deliberate park must never read as a failure: fold it into
            # the lease view NOW (epoch bump included) so the coming
            # heartbeat silence cannot expire a lease over it
            self.lease.declare_dead(cmd.rid, now, reason="parked (lifecycle)")

    def lifecycle_pending(self, rid: int, op: Optional[str] = None) -> bool:
        """Any lifecycle command still in flight for ``rid`` (optionally a
        specific op)?  The autoscaler gates follow-up decisions on this so
        it never stacks a second mutation on an unacked first.  Always
        False without a transport (direct calls complete synchronously)."""
        return any(c.rid == rid and (op is None or c.op == op)
                   and c.state in (LifecycleCmdState.PENDING,
                                   LifecycleCmdState.SENT)
                   for c in self._lifecycle.values())

    def replica_idle(self, rid: int) -> bool:
        """Is ``rid`` idle by the router's best evidence?  A direct pool
        probe without a transport; the last-known-good heartbeat payload
        under one — safe for drain gating, because a DRAINING replica
        takes no new dispatches, so its idleness only ever becomes MORE
        true after the observation."""
        if self.transport is None:
            return self.pool.is_idle(rid)
        stats, _age = self.lease.stats(rid)
        if stats is None:
            return self.pool.is_idle(rid)
        return stats["queue_depth"] == 0 and stats["active"] == 0

    # --------------------------------------------- directory feed + resync

    def _dir_apply(self, rid: int, op: str, digest: int) -> None:
        try:
            if op == "publish":
                self.directory.publish(rid, digest)
            elif op == "host_publish":
                # replica's kvtier staged the page host-side (serving/kvtier)
                self.directory.publish_host(rid, digest)
            elif op == "host_evict":
                self.directory.retract_host(rid, digest)
            else:
                self.directory.retract(rid, digest)
        except _fi.InjectedCrash:
            raise  # simulated death of THIS driver process
        except OSError as e:
            # a transient table-write fault drops THIS update (stale —
            # absorbed by the routing staleness ladder, never wrong)
            logger.warning(f"fleet: prefix directory {op} dropped for "
                           f"replica {rid}: {e}")

    def _on_dir_publish(self, rid: int, seq: int, p: dict, now: float) -> None:
        if self.directory is None:
            return
        feed = self._dir_feeds[rid]
        if feed.expect is None:
            return   # stream broken: awaiting resync, deliveries dropped
        if seq < feed.expect:
            return   # duplicate of an already-applied message
        if seq > feed.expect:
            feed.buffer[seq] = (p["op"], p["digest"])
            if feed.gap_since is None:
                feed.gap_since = now
            return
        self._dir_apply(rid, p["op"], p["digest"])
        feed.expect += 1
        while feed.expect in feed.buffer:
            op, digest = feed.buffer.pop(feed.expect)
            self._dir_apply(rid, op, digest)
            feed.expect += 1
        # a drain that exposes a FURTHER gap (buffer still non-empty)
        # restarts that gap's clock: it just formed, and inheriting the
        # old stamp would declare it lost dir_gap_timeout too early
        feed.gap_since = now if feed.buffer else None

    def _check_dir_feed(self, rid: int, feed: _DirFeed, now: float) -> None:
        """Declare a lost publish (gap outlived the reorder window or its
        timeout) and drive the resync request/retry timers."""
        if self.directory is None:
            return
        if feed.resync_since is not None:
            if now - feed.resync_since >= self.dir_resync_retry:
                self.transport.note_retransmit()
                self._request_dir_resync(rid, now)
            return
        if feed.expect is None:
            # broken stream with no outstanding request (the resync send
            # itself was eaten, or the break predates the rejoin)
            if self.lease.state(rid) is LeaseState.ALIVE:
                self._request_dir_resync(rid, now)
            return
        if feed.gap_since is None:
            return
        if len(feed.buffer) >= self.dir_reorder_window or \
                now - feed.gap_since >= self.dir_gap_timeout:
            # the missing publish is LOST, not late: detected, not absorbed
            self.stats["publish_gaps"] += 1
            self._emit([("prefix/publish_gap", float(rid),
                         self._next_event_step())])
            logger.warning(f"fleet: publish gap on replica {rid}'s prefix "
                           f"stream at seq {feed.expect} — pulling resync")
            feed.expect = None
            feed.buffer.clear()
            feed.gap_since = None
            self._request_dir_resync(rid, now)

    def _request_dir_resync(self, rid: int, now: float) -> None:
        if self.directory is None:
            return
        feed = self._dir_feeds[rid]
        feed.resync_since = now
        self.transport.send("dir_resync_req", "router", rid, {})

    def _on_dir_resync(self, rid: int, p: dict, now: float) -> None:
        if self.directory is None:
            return
        feed = self._dir_feeds[rid]
        if feed.resync_since is None or \
                (feed.expect is not None and p["barrier"] + 1 < feed.expect):
            # a duplicated (or badly reordered) resync reply: the first
            # copy already applied and the feed has moved on — applying
            # this one would purge live state, resurrect retracted digests
            # as ghost holders, and REWIND the sequence past messages
            # already consumed
            return
        feed.resync_since = None
        # the snapshot REPLACES this replica's view wholesale and
        # re-anchors the stream at its barrier; buffered ops past the
        # barrier (published while the snapshot traveled) apply on top
        self.directory.purge(rid)
        for digest in p["digests"]:
            self._dir_apply(rid, "publish", digest)
        for digest in p.get("host_digests", ()):
            self._dir_apply(rid, "host_publish", digest)
        feed.expect = p["barrier"] + 1
        feed.buffer = {s: v for s, v in feed.buffer.items() if s >= feed.expect}
        feed.gap_since = now if feed.buffer else None
        while feed.expect in feed.buffer:
            op, digest = feed.buffer.pop(feed.expect)
            self._dir_apply(rid, op, digest)
            feed.expect += 1
        if not feed.buffer:
            feed.gap_since = None
        self.stats["dir_resyncs"] += 1
        self._emit([("prefix/resync", float(rid), self._next_event_step())])

    # ----------------------------------------------------- migration chunks

    def _on_mig_chunk(self, rid: int, p: dict, now: float) -> None:
        """Idempotent chunk import on the router-side assembly: only the
        exactly-expected index appends (duplicates and reordered copies
        re-ack without touching the snapshot), so loss costs retransmits,
        never torn or double-applied chunks."""
        fid = p["fid"]
        rx = self._mig_rx.get(fid)
        if rx is None:
            return   # migration gone (fallback/lease harvest): no ack —
            # the source's exporter record died with it
        if p["idx"] == rx["next"]:
            rx["snap"].chunks.append(p["chunk"])
            rx["snap"].crcs.append(p["crc"])
            rx["next"] += 1
            if p["last"]:
                rx["snap"].complete = True
            self.stats["migration_chunks"] += 1
        self.transport.send("mig_ack", "router", rid,
                            {"fid": fid, "next": rx["next"]})

    # ----------------------------------------------------------- staleness

    def fleet_load_stats(self) -> Dict[int, dict]:
        """Per-replica load snapshot with a staleness ``age`` annotation —
        the autoscaler's (and any control consumer's) input.  Without a
        transport this is a live probe at age 0; with one it is each
        replica's LAST-KNOWN-GOOD heartbeat payload, however old (the
        consumer sees the age and can discount accordingly)."""
        if self.transport is None:
            return {rid: {**st, "age": 0.0}
                    for rid, st in self.pool.load_stats().items()}
        out = {}
        for rid in self.pool.rids:
            if self.lease.state(rid) is LeaseState.DEAD:
                continue
            stats, age = self.lease.stats(rid)
            if stats is not None:
                out[rid] = {**stats, "age": round(age, 9)}
        return out

    def dispatchable_rids(self) -> List[int]:
        if self.transport is None:
            return [r for r in self.pool.rids if self.pool.health.dispatchable(r)]
        return [r for r in self.pool.rids
                if self.lease.dispatchable(r)
                and self.pool.replica(r).serve is not None]

    # -------------------------------------------------------------- warm-up

    def warmup_replica(self, rid: int, max_chains: Optional[int] = None) -> int:
        """Directory-driven warm-up: pre-import the directory's hottest
        prefix chains onto replica ``rid`` (typically RECOVERING — a fresh
        engine with a stone-cold cache) from live donors, so its first
        dispatches land warm instead of eating cold-start recomputes.
        Every failure rung falls back to skipping the chain (the replica
        merely joins colder); returns chains imported."""
        if self.directory is None:
            return 0
        max_chains = self.warmup_chains if max_chains is None else max_chains
        if max_chains <= 0:
            return 0
        target = self.pool.replica(rid)
        if target.serve is None:
            return 0
        from ...resilience.fault_injection import DeviceLossError
        from ..kvtransfer import SnapshotError, export_prefix
        imported = 0
        for digest, holders in self.directory.hottest(4 * max_chains):
            if imported >= max_chains:
                break
            donor_rid = next((h for h in holders if h != rid
                              and self.pool.replica(h).serve is not None), None)
            if donor_rid is None:
                continue
            donor = self.pool.replica(donor_rid)
            pc = donor.serve.engine.kv.prefix_cache
            tokens = pc.chain_tokens(digest) if pc is not None else None
            if not tokens:
                continue   # evict-after-publish staleness: chain gone
            try:
                # one sentinel token past the chain: the export walk shares
                # match()'s last-token cap (a prompt of EXACTLY the chain
                # could only reuse all-but-one page, since the engine must
                # still compute >= 1 token) — warm-up wants the WHOLE
                # chain, and real matching prompts will be longer anyway
                snapshot = export_prefix(donor.serve.engine, tokens + [0],
                                         source=f"replica{donor_rid}")
                if snapshot is None:
                    continue
                n = target.serve.import_prefix(snapshot)
            except _fi.InjectedCrash:
                raise  # simulated death of THIS driver process
            except (DeviceLossError, SnapshotError, OSError) as e:
                # warm-up is strictly best-effort: any staging fault means
                # the replica joins colder, never later or wrong
                self.stats["warmup_fallbacks"] += 1
                logger.warning(f"fleet: warm-up import onto replica {rid} "
                               f"fell back ({e})")
                continue
            if n == 0:
                continue   # already held (a deeper chain covered it)
            if self.prefix_import_cost > 0:
                donor.clock.on_step(self.prefix_import_cost * snapshot.n_pages)
                target.clock.on_step(self.prefix_import_cost * n)
            imported += 1
        if imported:
            self.stats["warmup_imports"] += imported
            self._emit([("fleet/prefix_warmup", float(rid),
                         self._next_event_step())])
        return imported

    # ------------------------------------------------------------- schedule

    def control_timestamps(self, now: float) -> List[float]:
        """Future instants at which the CONTROL plane can make progress on
        its own — in-flight deliveries, partition boundaries, lease
        deadlines, fence/resync retry timers.  The simulator folds these
        into its idle-jump waits so a quiet fleet still wakes to expire a
        lease or heal a partition."""
        if self.transport is None:
            return []
        out = self.transport.next_wake(now) + self.lease.deadlines(now)
        for feed in self._dir_feeds.values():
            if feed.resync_since is not None:
                out.append(feed.resync_since + self.dir_resync_retry)
            if feed.gap_since is not None:
                out.append(feed.gap_since + self.dir_gap_timeout)
        for m in self._migrations.values():
            ch = m.get("chan")
            if ch is not None and ch["sent_ts"] is not None:
                out.append(ch["sent_ts"] + self.mig_retry)
        for cmd in self._lifecycle.values():
            if cmd.state is LifecycleCmdState.SENT and cmd.sent_ts is not None:
                out.append(cmd.sent_ts + self.lifecycle_retry)
            elif cmd.state is LifecycleCmdState.PENDING:
                out.append(now)   # a send-faulted command retries next poll
        # already-due wake-ups clamp to ``now`` (a zero-width jump: the
        # next round's transport_poll resolves them) rather than being
        # dropped — dropping one would let the idle-jump leap PAST a due
        # delivery and, e.g., suspect a replica whose heartbeat was
        # sitting undelivered in the inbox
        return [max(t, now) for t in out]

    def control_marker(self):
        """Discrete control-plane transitions for the simulator's stall
        detector (deliberately EXCLUDES raw send/deliver counters: a
        heartbeat per round is traffic, not progress — counting it would
        disable the idle-jump and spin the simulator through quiet
        stretches one round at a time)."""
        if self.transport is None:
            return None
        return (self.stats["lease_expirations"], self.stats["fenced_replicas"],
                self.stats["fenced_completions"], self.stats["fenced_requests"],
                self.stats["publish_gaps"], self.stats["dir_resyncs"],
                self.stats["lifecycle_applied"], self.stats["lifecycle_acked"],
                self.stats["lifecycle_aborted"],
                tuple(s.value for _, s in sorted(self.lease.states().items())))

    # ----------------------------------------------------------- migration

    def _decode_candidates(self, exclude_rid: int):
        """Dispatchable DECODE/MIXED-role replicas other than the source —
        the pool a completed export can hand off to."""
        return [(rid, rep, st) for rid, rep, st in self._candidates()
                if rid != exclude_rid
                and rep.role in (ReplicaRole.DECODE, ReplicaRole.MIXED)]

    def _precharge_migrations(self) -> None:
        """Charge each in-flight export's next chunk on its source's clock
        view (see dispatch_pending: runs before the round's ticks so the
        cost advances the clock inside the MIGRATING window)."""
        if self.migration_chunk_cost <= 0:
            return
        for m in self._migrations.values():
            if not m["exporter"].snapshot.complete \
                    and m["sr"].state is RequestState.MIGRATING:
                self.pool.replica(m["rid"]).clock.on_step(
                    self.migration_chunk_cost)

    def _start_migrations(self, now: float) -> None:
        """Begin exports for requests that reached DECODE on a PREFILL-role
        replica — only when a decode replica exists to take the handoff."""
        if self.overload is not None and self.overload.migrations_paused:
            # brownout rung 3: no NEW exports/prefix imports under overload
            # — the d2h/h2d staging bandwidth (and the decode pool's page
            # headroom) goes to serving; in-flight exports still complete
            return
        ok_states = (RequestState.PREFILL, RequestState.DECODE) \
            if self.prefill_handoff else (RequestState.DECODE, )
        # ONE candidate snapshot per round (same stance as
        # dispatch_pending): a per-request rebuild would run load_stats on
        # every replica for every dispatched request.  Only existence per
        # source rid matters here; the handoff picks its target later.
        decode_rids = {rid for rid, rep, _ in self._candidates()
                       if rep.role in (ReplicaRole.DECODE, ReplicaRole.MIXED)}
        if not decode_rids:
            return
        for fr in list(self._dispatched.values()):
            if fr.fid in self._migrations or fr._current is None:
                continue
            rid, sr, gen = fr._current
            rep = self.pool.replica(rid)
            if rep.role is not ReplicaRole.PREFILL or rep.serve is None:
                continue
            if sr.state not in ok_states:
                continue  # begin_migration arbitrates the exact window
            if not (decode_rids - {rid}):
                continue  # no handoff target: keep prefilling/decoding here
            exporter = rep.serve.begin_migration(
                sr.uid, chunk_pages=self.migration_chunk_pages,
                source=f"replica{rid}")
            if exporter is None:
                continue
            m = {"rid": rid, "sr": sr, "generation": gen,
                 "exporter": exporter, "started_ts": now}
            if self.transport is not None:
                # the chunks will cross the lossy fabric stop-and-wait; the
                # ROUTER assembles its own snapshot copy from delivered
                # chunks (idempotent by index) — the handoff uses THAT, so
                # a lost/duplicated chunk costs retransmits, never tearing
                from ..kvtransfer import KVSnapshot
                src = exporter.snapshot
                m["chan"] = {"base": 0, "sent_idx": None, "sent_ts": None}
                self._mig_rx[fr.fid] = {
                    "next": 0,
                    "snap": KVSnapshot(tokens=list(src.tokens),
                                       seen_tokens=src.seen_tokens,
                                       page_size=src.page_size,
                                       block_shape=src.block_shape,
                                       dtype=src.dtype, source=src.source)}
            self._migrations[fr.fid] = m
            fr.migrations += 1
            self.stats["migrations_started"] += 1
            self._emit([("fleet/migration_start", float(rid),
                         self._next_event_step())])

    def _pump_migrations(self, now: float) -> None:
        """One poll round of the two-phase dispatch (DistServe-style
        prefill→decode handoff; docs/SERVING.md "Disaggregated serving"):
        every in-flight export stages ONE chunk — the d2h copies overlap
        the source replica's ongoing steps for everything else it serves —
        and a completed snapshot is handed off: the source closes the
        request as MIGRATED, and the router re-dispatches it onto the
        least-loaded decode replica carrying the snapshot, where the
        KV-import fast path resumes decode without recomputing the prompt.

        Fallback ladder (never wrong, only slower): a transient export
        fault or a vanished handoff target resumes decode IN PLACE
        (``abort_migration``); a source-side preemption/timeout mid-export
        already moved the request back to the recompute path; an import
        rejection on the target falls back to recompute-on-resume inside
        the replica's admission.  Outputs are byte-identical on every rung."""
        from ...resilience.fault_injection import DeviceLossError
        for fid, m in list(self._migrations.items()):
            fr = self._dispatched.get(fid)
            if fr is None or fr._current is None or fr._current[1] is not m["sr"]:
                # displaced (replica death harvested the record) or terminal
                self._migrations.pop(fid, None)
                self._mig_rx.pop(fid, None)
                continue
            sr, rid = m["sr"], m["rid"]
            if sr.state is not RequestState.MIGRATING:
                # preempted (EVICTED→QUEUED) or expired on the source mid-
                # export: the recompute path owns the request again
                self._migration_fallback(fid, "source left MIGRATING")
                continue
            rep = self.pool.replica(rid)
            exporter = m["exporter"]
            if self.transport is not None and \
                    not self.transport.connected("router", rid, now):
                # partitioned source: chunks could neither deliver nor ack
                # — the pump waits for the heal (or the lease harvest)
                continue
            if not exporter.snapshot.complete:
                try:
                    done = exporter.step_chunk()
                except _fi.InjectedCrash:
                    raise  # simulated death of THIS driver process
                except DeviceLossError as e:
                    # the d2h staging found the source device gone — replica
                    # death; on_replica_dead harvests the migration record
                    self.on_replica_dead(rid, now, reason=str(e))
                    continue
                except SnapshotAborted as e:
                    self._migration_fallback(fid, str(e))
                    continue
                except OSError as e:
                    # transient staging fault: resume decode in place
                    if rep.serve is not None:
                        rep.serve.abort_migration(sr.uid)
                    self._migration_fallback(fid, f"export fault: {e}")
                    continue
                if self.transport is None:
                    self.stats["migration_chunks"] += 1
            else:
                done = True
            if self.transport is not None:
                # ack/retry delivery: one unacked chunk in flight at a time
                # (stop-and-wait), receiver-side assembly idempotent by
                # index; migration_chunks counts RECEIPTS, and completion
                # is the ROUTER-side snapshot's, not the exporter's
                self._pump_chunk_channel(fid, m, rid, now)
                rx = self._mig_rx.get(fid)
                if rx is None or not rx["snap"].complete:
                    continue
                snapshot = rx["snap"]
            else:
                if not done:
                    continue
                snapshot = exporter.snapshot
            targets = self._decode_candidates(rid)
            if not targets:
                # the decode pool vanished mid-export: decode continues on
                # the source exactly where it paused
                if rep.serve is not None:
                    rep.serve.abort_migration(sr.uid)
                self._migration_fallback(fid, "no decode replica for handoff")
                continue
            if self.transport is None:
                rep.serve.complete_migration(sr.uid)
            else:
                # the source-side close becomes a transported lifecycle
                # command (retried, epoch-fenced, idempotent per seq); the
                # handoff itself proceeds NOW on the router-side assembled
                # snapshot — the command only releases the source's copy
                self.lifecycle_command(rid, "mig_complete",
                                       {"uid": sr.uid}, now)
            self._migrations.pop(fid)
            self._mig_rx.pop(fid, None)
            del self._dispatched[fid]
            fr._current = None
            fr.to(FleetState.PENDING, now)
            fr._kv_snapshot = snapshot
            self._close_attempt(fr, "migrated", now)
            if fr.trace is not None and fr.trace["attempts"]:
                # the decode-side attempt links back to the prefill attempt
                fr.trace["last_dead"] = fr.trace["attempts"][-1]["span_id"]
            self._pending.append(fr)
            self.stats["migrations_completed"] += 1
            self._emit([("fleet/migration_complete", float(rid),
                         self._next_event_step())])
            # place on the least-outstanding decode replica NOW (a round of
            # pending latency saved); queue_full leaves it pending with the
            # snapshot for the next dispatch round
            tid, _, _ = min(targets,
                            key=lambda c: (c[2]["outstanding_tokens"],
                                           c[2]["queue_depth"], c[0]))
            self._dispatch_to(fr, tid, {"phase": "decode", "role_match": True,
                                        "migration": True}, now)

    def _pump_chunk_channel(self, fid: int, m: dict, rid: int,
                            now: float) -> None:
        """Send (or retransmit) the next unacked staged chunk of one
        migration over the transport — stop-and-wait with cumulative acks
        (``mig_ack.next``); the retransmit timer, not delivery failure
        notices, paces recovery from loss."""
        ch = m["chan"]
        exporter = m["exporter"]
        chunks = exporter.snapshot.chunks
        idx = ch["base"]
        if idx >= len(chunks):
            return   # every staged chunk acked; the exporter still staging
        if ch["sent_idx"] == idx and ch["sent_ts"] is not None:
            if now < ch["sent_ts"] + self.mig_retry:
                return   # in flight, not yet timed out
            self.transport.note_retransmit()
        last = exporter.snapshot.complete and idx == len(chunks) - 1
        self.transport.send("mig_chunk", rid, "router",
                            {"fid": fid, "idx": idx, "chunk": chunks[idx],
                             "crc": exporter.snapshot.crcs[idx],
                             "last": last}, seq=idx)
        ch["sent_idx"], ch["sent_ts"] = idx, now

    def _migration_fallback(self, fid: int, reason: str) -> None:
        self._migrations.pop(fid, None)
        self._mig_rx.pop(fid, None)
        self.stats["migration_fallbacks"] += 1
        logger.warning(f"fleet: migration of fid={fid} fell back ({reason})")
        self._emit([("fleet/migration_fallback", 1.0, self._next_event_step())])

    # ------------------------------------------------------------ failover

    def on_replica_dead(self, rid: int, now: Optional[float] = None,
                        reason: str = "killed") -> List[FleetRequest]:
        """Replica loss entry point (scripted kill, health-declared death,
        or an injected dispatch-time device loss): discards the replica's
        engine and moves every displaced fleet request back to PENDING with
        its delivered tokens preserved.  Idempotent per death."""
        now = self.clock.now() if now is None else now
        # pool.tick's health path may have killed the replica already (engine
        # discarded) — the fleet-side victims still need requeuing; only a
        # death with neither an engine to kill NOR displaced requests is a
        # true duplicate notification
        was_dead = self.pool.health.state(rid) is ReplicaState.DEAD \
            and self.pool.replica(rid).serve is None
        if not was_dead:
            self.pool.kill(rid, reason=reason)
        if self.transport is not None:
            # the router OBSERVED this death directly (a device loss on a
            # synchronous dispatch/staging RPC) — fold it into the lease
            # view now, with the epoch bump, so the eventual heartbeat
            # silence does not declare and account the same death twice
            self.lease.declare_dead(rid, now, reason=f"router-observed: {reason}")
        victims: List[FleetRequest] = []
        for fr in list(self._dispatched.values()):
            if fr._current is not None and fr._current[0] == rid:
                del self._dispatched[fr.fid]
                displaced_sr = fr._current[1]
                fr._current = None
                if displaced_sr.state.terminal:
                    # the request reached its terminal state on the replica
                    # BEFORE the death notice (a wall-clock driver can kill
                    # between the finishing tick and poll): nothing was
                    # displaced — resolve exactly as poll() would, with no
                    # failover charged and the replica-side finish time kept
                    if displaced_sr.state is RequestState.DONE:
                        fr.finish_ts = displaced_sr.finish_ts \
                            if displaced_sr.finish_ts is not None else now
                        self._close_attempt(fr, "done", fr.finish_ts)
                        self._finish(fr, FleetState.DONE, now)
                    else:
                        t_out = displaced_sr.history[-1][1]
                        self._close_attempt(fr, displaced_sr.state.value, t_out)
                        self._finish(fr, FleetState.TIMED_OUT, t_out)
                    continue
                # failover KV reuse: host-staged snapshots survive the
                # replica's death.  Either the SOURCE died with the export
                # already complete (migration record), or the TARGET died
                # before admitting a handed-off request (unconsumed
                # req.kv_snapshot) — both resume the survivor through the
                # KV-import fast path instead of a full recompute.
                m = self._migrations.pop(fr.fid, None)
                rx = self._mig_rx.pop(fr.fid, None)
                # under the transport only chunks that actually DELIVERED
                # count: the router-side assembly must be complete, not
                # merely the dead source's local staging
                snap = None
                if self.transport is not None:
                    if rx is not None and rx["snap"].complete:
                        snap = rx["snap"]
                elif m is not None and m["exporter"].snapshot.complete:
                    snap = m["exporter"].snapshot
                if snap is not None and fr._kv_snapshot is None:
                    fr._kv_snapshot = snap
                    self.stats["migration_failover_reuse"] += 1
                elif getattr(displaced_sr, "kv_snapshot", None) is not None:
                    dsnap = displaced_sr.kv_snapshot
                    displaced_sr.kv_snapshot = None
                    if isinstance(dsnap, HostKVHandle):
                        # a PARKED attempt (session tool stall) died with
                        # its KV in the dead replica's HOST tier — host
                        # memory survives the device loss, so resolve the
                        # handle to the raw snapshot NOW and carry it to a
                        # survivor's import path (the survivor needs no
                        # tier of its own).  A None resolution (the entry
                        # was LRU-evicted first) leaves _kv_snapshot unset:
                        # recompute-on-resume, the ladder's never-wrong rung.
                        dsnap = dsnap.tier.host.take_seq(dsnap.uid)
                    if dsnap is not None:
                        fr._kv_snapshot = dsnap
                        self.stats["migration_failover_reuse"] += 1
                fr.failovers += 1
                self._taccount(fr.tenant)["failovers"] += 1
                fr.to(FleetState.PENDING, now)
                # the dead attempt's spans close NOW (its frontend is
                # discarded, so the router folds the partial history); the
                # resumed attempt on a survivor will link back to this
                # span id — the client trace is continuous across the kill
                self._close_attempt(fr, "displaced", now, displaced_sr=displaced_sr)
                self._pending.append(fr)
                victims.append(fr)
                self.stats["failovers"] += 1
        # drop any remaining export records anchored on the dead replica
        # (e.g. a terminal-at-death request): their exporters' source
        # engine is gone and the next step_chunk would abort anyway
        for fid in [f for f, m in self._migrations.items() if m["rid"] == rid]:
            self._migrations.pop(fid)
            self._mig_rx.pop(fid, None)
        if was_dead and not victims:
            return []
        record = {"rid": rid, "ts": now, "reason": reason,
                  "victims": {fr.fid for fr in victims},
                  "n_victims": len(victims), "recovered_ts": None}
        if not victims:
            record["recovered_ts"] = now   # nothing displaced: recovery is free
            self.recovery_times.append(0.0)
        self.kill_records.append(record)
        self._emit([("fleet/replica_dead", float(rid), self._next_event_step()),
                    ("fleet/failover_requeued", float(len(victims)),
                     self._next_event_step())])
        self._recorder_dump("replica_dead", now)
        return victims

    def _note_victim_resolved(self, fr: FleetRequest, now: float) -> None:
        """Failover recovery time: a kill record closes when the LAST
        displaced request reaches a terminal state — the displaced work is
        fully re-served (or definitively expired), not merely back in a
        queue.  Re-dispatch alone would read ~0 whenever survivors have
        queue capacity and hide the recompute cost failover actually pays."""
        for rec in self.kill_records:
            if rec["recovered_ts"] is None and fr.fid in rec["victims"]:
                rec["victims"].discard(fr.fid)
                if not rec["victims"]:
                    rec["recovered_ts"] = now
                    self.recovery_times.append(now - rec["ts"])

    def _finish(self, fr: FleetRequest, state: FleetState, now: float) -> None:
        assert not fr.state.terminal, \
            f"fleet request {fr.fid} reached a second terminal state " \
            f"({fr.state.value} then {state.value})"
        if not state.terminal:
            # checked BEFORE the transition commits: _finish is the
            # terminal edge and nothing else — a PENDING/DISPATCHED
            # target would corrupt the conservation receipt (submitted ==
            # completed + timed_out + rejected), and it must fail with
            # the request record unmutated (no bogus history entry)
            raise ValueError(f"_finish called with non-terminal state "
                             f"{state.value} for fid={fr.fid}")
        fr.to(state, now)
        t = self._taccount(fr.tenant)
        if state is FleetState.DONE:
            t["completed"] += 1
            t["tokens"] += len(fr.tokens)
            if fr.met_deadline:
                t["deadline_met"] += 1
            if fr.ttft is not None:
                self.ttft_log.append(fr.ttft)
                if self.slo is not None:
                    self.slo.observe(fr.tenant, fr.ttft, now)
        elif state is FleetState.TIMED_OUT:
            t["timed_out"] += 1
        elif state is FleetState.REJECTED:
            t["rejected"] += 1
        else:
            raise AssertionError(f"unreachable: {state} passed the "
                                 "terminal precheck")  # guard above
        self._note_victim_resolved(fr, now)
        if fr.trace is not None:
            self._trace_finish(fr, state, now)
        self._emit([(f"fleet/{state.value}", 1.0, self._next_event_step())])

    # ----------------------------------------------------------- telemetry

    def _close_attempt(self, fr: FleetRequest, outcome: str, end_ts: float,
                       displaced_sr: Optional[ServingRequest] = None,
                       tail_phase: Optional[str] = None) -> None:
        """Materialize the current (last) attempt span.  For a displaced
        attempt the replica frontend is already discarded (kill) or no
        longer trusted (lease expiry), so its partial phase spans are
        folded here from the ServingRequest history, clamped to the
        dispatch instant; ``tail_phase="fenced"`` attributes the open
        tail past the last observed transition to ``phase/fenced``."""
        tr = fr.trace
        if tr is None or not tr["attempts"]:
            return
        att = tr["attempts"][-1]
        if att["end_ts"] is not None:  # already closed (duplicate death notice)
            return
        att["end_ts"] = end_ts
        track = f"replica{att['rid']}"
        if displaced_sr is not None:
            # fold the dead attempt's PARTIAL history — unless the request
            # already reached a terminal state on the replica (killed in
            # the window between its finishing tick and the router's
            # poll): its frontend emitted the phase spans at _finish, and
            # re-folding here would double every phase and break the
            # trace_report tiling invariant
            if not displaced_sr.state.terminal:
                emit_attempt_spans(self.tracer, displaced_sr, tr["trace_id"],
                                   att["span_id"], track, end_ts=end_ts,
                                   clamp_start=att["dispatch_ts"],
                                   tail_phase=tail_phase)
            elif tail_phase is not None:
                # the zombie finished BEFORE the lease expired (its own
                # frontend already emitted phases up to its terminal); the
                # stretch from that discarded terminal to the displacement
                # is fenced time, or the attempt window under-tiles
                t_term = displaced_sr.history[-1][1]
                if end_ts > t_term:
                    self.tracer.add_span(f"phase/{tail_phase}",
                                         tr["trace_id"], t_term, end_ts,
                                         parent_id=att["span_id"],
                                         track=track)
            tr["last_dead"] = att["span_id"]
        attrs = {"rid": att["rid"], "generation": att["generation"],
                 "outcome": outcome, "resume_tokens": att["resume_tokens"]}
        if att["resumed_from"] is not None:
            attrs["resumed_from"] = att["resumed_from"]
        self.tracer.add_span("attempt", tr["trace_id"], att["dispatch_ts"],
                             end_ts, parent_id=tr["root_id"],
                             span_id=att["span_id"], track=track, attrs=attrs)

    def _trace_finish(self, fr: FleetRequest, state: FleetState, now: float) -> None:
        """Materialize the client-request root span plus the router-queue
        ``phase/pending`` gaps (before first dispatch, between failover
        displacement and re-dispatch, after the last attempt) so the
        trace's phase spans tile [arrival, terminal] exactly — the
        invariant scripts/trace_report.py checks against TTFT/TPOT."""
        tr = fr.trace
        trace_id, root_id = tr["trace_id"], tr["root_id"]
        end = fr.finish_ts if state is FleetState.DONE and fr.finish_ts is not None \
            else now
        t = fr.arrival_ts
        for att in tr["attempts"]:
            if att["dispatch_ts"] > t:
                self.tracer.add_span("phase/pending", trace_id, t,
                                     att["dispatch_ts"], parent_id=root_id,
                                     track="router")
            att_end = att["end_ts"] if att["end_ts"] is not None else att["dispatch_ts"]
            t = max(t, att_end)
        if end > t:
            self.tracer.add_span("phase/pending", trace_id, t, end,
                                 parent_id=root_id, track="router")
        events = [("dispatch", ts, {"rid": rid}) for rid, ts in fr.dispatches]
        events += [("failover", ts, None) for st, ts in fr.history[1:]
                   if st is FleetState.PENDING]
        events.sort(key=lambda e: e[1])
        self.tracer.add_span(
            "request", trace_id, fr.arrival_ts, end, span_id=root_id,
            track="router", events=events,
            attrs={"fid": fr.fid, "state": state.value,
                   "prompt_len": len(fr.prompt), "n_tokens": len(fr.tokens),
                   "failovers": fr.failovers, "affinity_hits": fr.affinity_hits,
                   "reject_reason": fr.reject_reason,
                   "ttft": fr.ttft, "tpot": fr.tpot, "e2e": end - fr.arrival_ts,
                   "deadline_met": fr.met_deadline,
                   # the slowdown-attribution inputs (scripts/why_slow.py):
                   # which tenant's SLO this counts against, and whether a
                   # brownout rung truncated the output budget
                   "tenant": fr.tenant,
                   "brownout_capped": fr.brownout_capped})

    # ----------------------------------------------------------- lifecycle

    def kill_replica(self, rid: int, reason: str = "scripted kill") -> List[FleetRequest]:
        return self.on_replica_dead(rid, reason=reason)

    def recover_replica(self, rid: int) -> None:
        """Attach a fresh engine to a parked/dead replica and — when a
        prefix directory is attached — pre-import the directory's hottest
        chains so the replica joins the fleet WARM (directory-driven
        autoscale warm-up): its first post-recovery dispatches hit cache
        instead of paying the cold-start recompute."""
        self.pool.recover(rid)
        self.warmup_replica(rid)

    def drain(self, rid: int) -> None:
        """Rolling-restart entry: no NEW dispatches to ``rid``; its
        in-flight work runs to completion (``pool.is_idle`` then gates
        ``pool.restart``)."""
        self.pool.drain(rid)

    # ------------------------------------------------------------- metrics

    @property
    def outstanding(self) -> int:
        return len(self._pending) + len(self._dispatched)

    @property
    def queue_depth(self) -> int:
        """Requests waiting in the ROUTER queue (not yet on a replica) —
        a primary autoscaler/overload signal."""
        return len(self._pending)

    def export_replica_gauges(self) -> None:
        """The once-per-fleet-round observability sweep: publish each live
        replica's ``load_stats()`` snapshot as ``fleet/replica_*`` gauges
        on the pool's MetricsRegistry, the fleet-level serving-replica
        count, the brownout rung (when an overload controller is
        attached), and — under a control transport — the per-link health
        gauges (``transport/link_loss_ewma/<rid>``, retransmit depth,
        feed-gap age: ROADMAP's adaptive-lease-sizing input signal).  Also
        ticks the SLO burn-rate monitor.  The fleet driver calls this once
        per round; gauges are a no-op without a registry."""
        now = self.clock.now()
        if self.slo is not None:
            self.slo.tick(now)
        # the rate fold runs even without a registry: the predictive
        # autoscaler reads the raw (ewma, slope) pair via arrival_rate()
        self._fold_arrival_rate(now)
        metrics = self.pool.metrics
        if metrics is None:
            return
        if self.transport is not None:
            for rid in self.pool.rids:
                metrics.gauge(f"transport/link_loss_ewma/{rid}").set(
                    round(self.transport.link_loss_ewma("router", rid), 9))
                feed = self._dir_feeds.get(rid)
                age = 0.0 if feed is None or feed.gap_since is None \
                    else max(0.0, now - feed.gap_since)
                metrics.gauge(f"transport/feed_gap_age/{rid}").set(
                    round(age, 9))
            metrics.gauge("transport/retransmit_depth").set(
                self._retransmit_depth())
        stats = self.pool.load_stats()
        for rid in self.pool.rids:
            # DEAD/parked replicas are absent from load_stats — their
            # gauges read 0, not their last pre-kill values frozen forever
            st = stats.get(rid) or {"queue_depth": 0, "free_kv_pages": 0,
                                    "outstanding_tokens": 0, "active": 0}
            metrics.gauge(f"fleet/replica_queue_depth/{rid}").set(
                st["queue_depth"])
            metrics.gauge(f"fleet/replica_free_kv_pages/{rid}").set(
                st["free_kv_pages"])
            metrics.gauge(f"fleet/replica_outstanding_tokens/{rid}").set(
                st["outstanding_tokens"])
            metrics.gauge(f"fleet/replica_active/{rid}").set(st["active"])
        metrics.gauge("fleet/serving_replicas").set(sum(
            1 for rid in self.pool.rids if self.pool.health.serving(rid)))
        if self.overload is not None:
            metrics.gauge("fleet/overload_rung").set(self.overload.rung)
        if self.directory is not None:
            metrics.gauge("fleet/prefix_directory_entries").set(
                self.directory.entries)
        self._export_arrival_gauges(now, metrics)
        self._export_kv_gauges(metrics)

    def _fold_arrival_rate(self, now: float) -> None:
        """Fold the arrival-rate EWMA + derivative: the demand signal
        predictive scale-up provisions on — scale BEFORE the queue grows
        by reading the rate's slope, not the queue's depth.  One fold per
        fleet round; zero-advance rounds carry no new rate information
        and are skipped (the fold keeps its last value)."""
        if self._arr_last is None:
            self._arr_last = (now, self._arrival_count, None, 0.0)
            self._arr_rate = (0.0, 0.0)
            return
        t0, c0, ewma0, slope0 = self._arr_last
        dt = now - t0
        if dt <= 0:
            return
        inst = (self._arrival_count - c0) / dt
        alpha = 1.0 - math.exp(-dt / self.arrival_rate_tau)
        if ewma0 is None:
            ewma, slope = inst, 0.0
        else:
            ewma = ewma0 + alpha * (inst - ewma0)
            # the slope is smoothed with the SAME time constant: the raw
            # per-fold derivative of an EWMA is exactly the noise the EWMA
            # removed, scaled back up by 1/dt
            slope = slope0 + alpha * ((ewma - ewma0) / dt - slope0)
        self._arr_rate = (ewma, slope)
        self._arr_last = (now, self._arrival_count, ewma, slope)

    def arrival_rate(self) -> Tuple[float, float]:
        """The last-folded (rate EWMA, slope) pair, UNROUNDED — the
        predictive autoscaler's demand input (``fleet/arrival_rate_*``
        gauges publish the rounded rendering of the same fold)."""
        return self._arr_rate

    def _export_arrival_gauges(self, now: float, metrics) -> None:
        """Publish the current arrival-rate fold as the
        ``fleet/arrival_rate_ewma`` / ``fleet/arrival_rate_slope`` gauges
        (rounded at the export boundary like every gauge here)."""
        ewma, slope = self._arr_rate
        metrics.gauge("fleet/arrival_rate_ewma").set(round(ewma, 9))
        metrics.gauge("fleet/arrival_rate_slope").set(round(slope, 9))

    def _export_kv_gauges(self, metrics) -> None:
        """Per-replica KV-arena occupancy (``kv/<stat>/<rid>``), the
        per-replica step-anatomy host-gap fraction
        (``anatomy/host_gap_fraction/<rid>``), and the per-tenant page
        tallies (``kv/tenant_pages/<tenant>`` — the missing input for the
        ROADMAP per-tenant KV-quota item).  Tenant tallies attribute every
        in-use page exactly once, so they SUM to the fleet's pages in use
        (tested); a tenant that dropped to zero pages reads zero."""
        for rid in self.pool.rids:
            rep = self.pool.replica(rid)
            if rep.serve is None:
                # DEAD/parked: the arena died with the engine — gauges
                # must READ zero, not freeze their pre-death samples
                # (same stance as the fleet/replica_* gauges above)
                st = {"occupancy": 0.0, "free_run_fragmentation": 0.0,
                      "prefix_cache_share": 0.0}
            else:
                st = rep.serve.engine.kv.arena_stats()
            metrics.gauge(f"kv/page_occupancy/{rid}").set(st["occupancy"])
            metrics.gauge(f"kv/free_run_fragmentation/{rid}").set(
                st["free_run_fragmentation"])
            metrics.gauge(f"kv/prefix_cache_share/{rid}").set(
                st["prefix_cache_share"])
            if getattr(self.pool, "anatomy_enabled", False):
                # ALWAYS re-set from the current recorder: a replacement
                # engine's fresh recorder reads None (-> 0.0) until its
                # first step — the gauge must not keep attributing the
                # dead engine's loop tax to the new one
                anat = self.pool.anatomy(rid)
                frac = anat.host_gap_fraction() if anat is not None else None
                metrics.gauge(f"anatomy/host_gap_fraction/{rid}").set(
                    round(frac, 6) if frac is not None else 0.0)
        pages = self.tenant_kv_pages()
        for tenant in sorted(self._kv_tenants_seen | set(pages)):
            metrics.gauge(f"kv/tenant_pages/{tenant}").set(
                pages.get(tenant, 0))
        self._kv_tenants_seen |= set(pages)

    def tenant_kv_pages(self) -> Dict[str, int]:
        """KV pages currently held per tenant, fleet-wide.  Each in-use
        page is attributed EXACTLY ONCE: to the tenant of the first
        (uid-ordered) live sequence holding it — a prefix-shared page
        counts toward whoever admitted first, never twice — with two
        reserved keys: ``prefix_cache`` for pages only the prefix cache
        pins, and ``unattributed`` for sequences no fleet request owns
        (direct engine users).  The tallies therefore sum to the fleet's
        total pages in use — the conservation law the per-tenant KV-quota
        item needs to trust before it can enforce anything."""
        owner: Dict[Tuple[int, int], str] = {}
        for fr in self._dispatched.values():
            if fr._current is not None:
                rid, sr, _gen = fr._current
                owner[(rid, sr.uid)] = fr.tenant
        out: Dict[str, int] = {}
        for rid in self.pool.rids:
            rep = self.pool.replica(rid)
            if rep.serve is None:
                continue
            eng = rep.serve.engine
            seen = set()
            for uid in sorted(eng.state.seqs):
                seq = eng.state.seqs[uid]
                tenant = owner.get((rid, uid), "unattributed")
                n = 0
                for p in seq.pages:
                    if p not in seen:
                        seen.add(p)
                        n += 1
                if n:
                    out[tenant] = out.get(tenant, 0) + n
            # in_use straight from the allocator (arena_stats would pay
            # its O(free log free) fragmentation scan just for this field)
            in_use = (eng.kv.num_pages - 1) - eng.kv.allocator.free_pages
            cache_only = in_use - len(seen)
            if cache_only:
                out["prefix_cache"] = out.get("prefix_cache", 0) + cache_only
        return out

    def _retransmit_depth(self) -> int:
        """How many reliable-stream sends are currently awaiting an ack —
        unacked fences (FENCING leases), unacked migration chunks, and
        outstanding directory-resync requests.  A depth that stays high is
        the 'this link is sick' signal loss counters alone cannot give."""
        depth = sum(1 for rid in self.pool.rids
                    if self.lease.state(rid) is LeaseState.FENCING)
        depth += sum(1 for m in self._migrations.values()
                     if m.get("chan") is not None
                     and m["chan"]["sent_idx"] is not None)
        depth += sum(1 for feed in self._dir_feeds.values()
                     if feed.resync_since is not None)
        depth += sum(1 for c in self._lifecycle.values()
                     if c.state is LifecycleCmdState.SENT)
        return depth

    def _recorder_dump(self, reason: str, now: float) -> None:
        """Crash-scoped flight-recorder dump + its ``recorder/dump`` event.
        Guarded: a failed black-box write must never escalate a replica
        death into a driver death."""
        if self.recorder is None:
            return
        try:
            path = self.recorder.maybe_dump(reason, now)
        except _fi.InjectedCrash:
            raise  # simulated death of THIS driver process
        except Exception as e:
            logger.warning(f"flight-recorder dump failed ({reason}): {e}")
            return
        if path is not None:
            logger.warning(f"flight recorder: dumped {path} ({reason})")
            self._emit([("recorder/dump", float(self.recorder.dumps),
                         self._next_event_step())])

    def pending_timestamps(self) -> List[float]:
        """Future timestamps that could unblock progress (pending
        deadlines) — the simulator's idle-jump input."""
        return [fr.deadline for fr in self._pending if fr.deadline is not None] + \
               [fr.deadline for fr in self._dispatched.values() if fr.deadline is not None]

    def summary(self) -> dict:
        done = [r for r in self.requests if r.state is FleetState.DONE]
        met = [r for r in done if r.met_deadline]
        elapsed = max(self.clock.now() - self._t0, 1e-9)
        hits, misses = self.stats["affinity_hits"], self.stats["affinity_misses"]
        return {
            "policy": self.policy.name,
            "n_replicas": len(self.pool.replicas),
            "submitted": self.stats["submitted"],
            "completed": len(done),
            "timed_out": sum(1 for r in self.requests if r.state is FleetState.TIMED_OUT),
            "rejected": sum(1 for r in self.requests if r.state is FleetState.REJECTED),
            "dispatches": self.stats["dispatches"],
            "failovers": self.stats["failovers"],
            "dispatch_faults": self.stats["dispatch_faults"],
            "saturated_dispatches": self.stats["saturated_dispatches"],
            "deadline_met": len(met),
            "goodput_rps": round(len(met) / elapsed, 6),
            "completed_rps": round(len(done) / elapsed, 6),
            "tokens_generated": sum(len(r.tokens) for r in self.requests),
            "elapsed": round(elapsed, 6),
            "affinity": {
                "hits": hits, "misses": misses,
                "hit_rate": round(hits / (hits + misses), 4) if hits + misses else None,
            },
            "migration": {
                "started": self.stats["migrations_started"],
                "chunks": self.stats["migration_chunks"],
                "completed": self.stats["migrations_completed"],
                "fallbacks": self.stats["migration_fallbacks"],
                "failover_reuse": self.stats["migration_failover_reuse"],
                "migrated_requests": sum(1 for r in self.requests if r.migrations),
                # live-replica import accounting (engines discarded by kills
                # take their counters with them — same stance as load_stats)
                "kv_imports": sum(rep.serve.stats.kv_imports
                                  for rep in self.pool.replicas.values()
                                  if rep.serve is not None),
                "import_fallbacks": sum(rep.serve.stats.kv_import_fallbacks
                                        for rep in self.pool.replicas.values()
                                        if rep.serve is not None),
            },
            "prefix": None if self.directory is None else {
                "imports": self.stats["prefix_imports"],
                "import_pages": self.stats["prefix_import_pages"],
                "import_fallbacks": self.stats["prefix_import_fallbacks"],
                "imports_paused": self.stats["prefix_imports_paused"],
                "directory": self.directory.summary(),
            },
            "failover": {
                "kills": len(self.kill_records),
                "requeued": self.stats["failovers"],
                "recovery_times": [round(t, 6) for t in self.recovery_times],
                "unrecovered": sum(1 for r in self.kill_records
                                   if r["recovered_ts"] is None),
            },
            "ttft": percentile_summary([r.ttft for r in done if r.ttft is not None]),
            "tpot": percentile_summary([r.tpot for r in done if r.tpot is not None]),
            "e2e": percentile_summary([r.e2e for r in done if r.e2e is not None]),
            "tenants": self._tenant_summary(done),
            "control_plane": None if self.transport is None else {
                "transport": self.transport.summary(),
                "lease": self.lease.summary(),
                "lease_expirations": self.stats["lease_expirations"],
                "fenced_replicas": self.stats["fenced_replicas"],
                "fenced_completions": self.stats["fenced_completions"],
                "fenced_requests": self.stats["fenced_requests"],
                "publish_gaps": self.stats["publish_gaps"],
                "dir_resyncs": self.stats["dir_resyncs"],
                "warmup_imports": self.stats["warmup_imports"],
                "warmup_fallbacks": self.stats["warmup_fallbacks"],
                "partition_dispatch_skips":
                    self.stats["partition_dispatch_skips"],
                "lifecycle": {
                    "cmds": self.stats["lifecycle_cmds"],
                    "applied": self.stats["lifecycle_applied"],
                    "acked": self.stats["lifecycle_acked"],
                    "stale_acks": self.stats["lifecycle_stale_acks"],
                    "aborted": self.stats["lifecycle_aborted"],
                    "send_faults": self.stats["lifecycle_send_faults"],
                },
            },
            "overload": None if self.overload is None else self.overload.summary(),
            "slo": None if self.slo is None else self.slo.summary(),
            "recorder": None if self.recorder is None
            else self.recorder.summary(),
            "shed": self.stats["shed"],
            "brownout_capped": self.stats["brownout_capped"],
            "kv_quota_rejects": self.stats["kv_quota_rejects"],
            "health_transitions": len(self.pool.health.history),
        }

    def _tenant_summary(self, done: List[FleetRequest]) -> dict:
        """Per-tenant goodput/violation record.  ``sla_violations`` counts
        timeouts plus DONE-but-late completions plus (when the tenant has a
        ``ttft_slo``) on-time completions whose TTFT still blew the
        per-tenant budget; ``closed`` is the conservation receipt the
        property audit pins: submitted == completed+timed_out+rejected."""
        out = {}
        for name in sorted(self.tenant_stats):
            t = dict(self.tenant_stats[name])
            spec = self.tenants.spec(name)
            mine = [r for r in done if r.tenant == name]
            late = sum(1 for r in mine if not r.met_deadline)
            slo_miss = 0
            if spec.ttft_slo is not None:
                slo_miss = sum(1 for r in mine
                               if r.met_deadline and r.ttft is not None
                               and r.ttft > spec.ttft_slo)
            t["sla_violations"] = t["timed_out"] + late + slo_miss
            t["weight"] = spec.weight
            t["best_effort"] = spec.best_effort
            t["ttft"] = percentile_summary(
                [r.ttft for r in mine if r.ttft is not None])
            t["closed"] = (t["submitted"] ==
                           t["completed"] + t["timed_out"] + t["rejected"])
            out[name] = t
        return out

    def _next_event_step(self) -> int:
        self._events_step += 1
        return self._events_step

    def _emit(self, events) -> None:
        if self.monitor is None or not getattr(self.monitor, "enabled", True):
            return
        try:
            self.monitor.write_events(events)
        except _fi.InjectedCrash:
            raise  # simulated process death; chaos tests must see it
        except Exception as e:  # monitoring must never take down routing
            logger.warning(f"fleet monitor write failed: {e}")
