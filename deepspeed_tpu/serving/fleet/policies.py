"""Pluggable fleet routing policies.

A policy answers one question per request: which dispatchable replica gets
it.  Input is the candidate list the router assembled — ``(rid, replica,
stats)`` with ``stats = ServingEngine.load_stats()`` — so policies are pure
decisions over cheap snapshots and never touch engine internals except the
read-only prefix-cache warmth probe.

* :class:`RoundRobinPolicy` — rotate over dispatchable replicas; the
  baseline every serving stack ships.
* :class:`LeastOutstandingPolicy` — fewest outstanding decode tokens (the
  actual forward-pass work still owed), queue depth as tie-break: the
  classic least-loaded estimator for continuous batching, where "requests
  in flight" under-weights long generations.
* :class:`PrefixAffinityPolicy` — route to the replica whose
  ``PrefixCacheManager`` already holds the longest page run of the
  request's token history (probed via the non-mutating
  ``lookup_depth``), so shared-prefix traffic (system prompts, few-shot
  templates, failover resumes) reuses KV instead of recomputing it on a
  cold replica.  When the warmest replica is saturated — queue at or past
  ``saturation_queue_depth`` — the policy falls back to least-loaded:
  cache locality is a latency optimization, never a reason to queue behind
  a hot spot (the standard prefix-aware routing compromise).
* :class:`DisaggregatedPolicy` — role-aware placement for a
  prefill/decode-split fleet: fresh prompts land on PREFILL-role
  replicas, resumed/migrated requests on DECODE-role ones, least-loaded
  within the pool (DistServe/Splitwise-style phase splitting; the KV
  handoff between the pools is the router's migration machinery).
* :class:`PrefixDirectoryPolicy` — prefix affinity answered from the
  router-resident :class:`~.prefix_directory.PrefixDirectory` instead of
  probe fan-out: ZERO per-replica calls per dispatch.  When the warm
  target is saturated the request goes least-loaded — and the policy asks
  the router to IMPORT the hot prefix's KV pages onto that cold replica
  first (``prefix_import`` in the select info), turning warm-replica
  affinity into cluster-wide warmth (docs/SERVING.md "Prefix directory").
* :class:`SessionAffinityPolicy` — sticky-with-failover placement for
  agentic sessions (serving/sessions): every turn of a session lands on
  the replica that served the previous one — its prefix cache already
  holds the FULL transcript's pages, so turn N+1 prefills only the new
  user tokens.  When the sticky replica is dead or saturated the turn
  re-homes through the wrapped fallback (directory-warmth when a
  directory is attached, least-loaded otherwise) and the session
  re-sticks there (docs/SERVING.md "Agentic sessions").
"""

from typing import List, Optional, Tuple

from ..request import ServingRequest  # noqa: F401  (doc reference)


class RoutingPolicy:
    """Base: ``select`` returns ``(rid, info)``; rid None = nothing
    eligible (request stays pending).  ``info`` is a small dict of
    policy-specific facts the router folds into its stats (e.g.
    ``affinity_hit``)."""

    name = "base"

    def select(self, request, candidates: List[Tuple[int, object, dict]]):
        raise NotImplementedError


class RoundRobinPolicy(RoutingPolicy):

    name = "round_robin"

    def __init__(self):
        self._turn = 0

    def select(self, request, candidates):
        if not candidates:
            return None, {}
        rids = sorted(rid for rid, _, _ in candidates)
        rid = rids[self._turn % len(rids)]
        self._turn += 1
        return rid, {}


class LeastOutstandingPolicy(RoutingPolicy):

    name = "least_outstanding"

    def select(self, request, candidates):
        if not candidates:
            return None, {}
        rid = min(candidates,
                  key=lambda c: (c[2]["outstanding_tokens"], c[2]["queue_depth"], c[0]))[0]
        return rid, {}


class PrefixAffinityPolicy(RoutingPolicy):

    name = "prefix_affinity"

    def __init__(self, saturation_queue_depth: int = 4):
        assert saturation_queue_depth >= 1, saturation_queue_depth
        self.saturation_queue_depth = saturation_queue_depth
        self._fallback = LeastOutstandingPolicy()

    def _warmth(self, replica, tokens) -> int:
        pc = replica.serve.engine.kv.prefix_cache if replica.serve is not None else None
        if pc is None or not tokens:
            return 0
        return pc.lookup_depth(tokens)

    def select(self, request, candidates):
        if not candidates:
            return None, {}
        # probe with the full token history (prompt + already-generated):
        # a failover resume is exactly the traffic whose warm pages matter
        tokens = list(request.prompt) + list(request.tokens)
        warmth = {rid: self._warmth(rep, tokens) for rid, rep, _ in candidates}
        best = max(candidates, key=lambda c: (warmth[c[0]], -c[2]["queue_depth"], -c[0]))
        rid, _, stats = best
        if warmth[rid] > 0 and stats["queue_depth"] < self.saturation_queue_depth:
            return rid, {"affinity_hit": True, "warm_pages": warmth[rid]}
        # cold everywhere, or the warm target is saturated: least-loaded —
        # EXCLUDING the saturated warm target when an alternative exists
        # (falling back onto the hot spot it just rejected would defeat the
        # fallback; with no alternative it is still the only choice)
        saturated = warmth[rid] > 0
        fb_candidates = [c for c in candidates if c[0] != rid] if saturated else candidates
        if not fb_candidates:
            fb_candidates = candidates
        fb_rid, _ = self._fallback.select(request, fb_candidates)
        # the hit label reports where the request actually LANDED: a
        # fallback that still reaches a warm cache (e.g. the sole replica)
        # gets the prefill speedup all the same
        return fb_rid, {"affinity_hit": warmth.get(fb_rid, 0) > 0,
                        "warm_pages": warmth.get(fb_rid, 0),
                        "affinity_saturated": saturated}


class DisaggregatedPolicy(RoutingPolicy):
    """Role-aware placement for a prefill/decode-disaggregated fleet
    (docs/SERVING.md "Disaggregated serving").

    A FRESH request (no tokens yet) is prompt-processing work → place it
    on a PREFILL-role replica; a RESUMED request (failover victim or a
    migration handoff carrying generated tokens) is token-generation work
    → place it on a DECODE-role replica.  MIXED replicas qualify for
    either.  Within the matching pool the least-outstanding estimator
    breaks ties; when NO replica of the wanted role is dispatchable the
    policy falls back to the full candidate list — every replica runs the
    complete stack, and availability beats specialization (a decode-only
    fleet rump must still serve fresh prompts rather than starve them).

    The KV handoff itself (export → least-loaded decode replica → import)
    is the router's migration machinery; this policy only answers where
    NEW dispatches land."""

    name = "disaggregated"
    #: turns on the Router's two-phase dispatch: requests reaching DECODE
    #: on a PREFILL-role replica are exported + resumed on a decode replica
    migrates = True

    def __init__(self):
        self._fallback = LeastOutstandingPolicy()

    def select(self, request, candidates):
        from .pool import ReplicaRole
        if not candidates:
            return None, {}
        # token-generation work: the request already generated tokens OR
        # carries a host-staged KV snapshot (a late-prefill handoff or a
        # failover-reuse victim with no tokens yet) — routing it back to
        # the prefill pool would import there and immediately re-migrate
        decode_work = bool(getattr(request, "tokens", None)) \
            or getattr(request, "_kv_snapshot", None) is not None
        want = ReplicaRole.DECODE if decode_work else ReplicaRole.PREFILL
        matched = [c for c in candidates
                   if c[1].role in (want, ReplicaRole.MIXED)]
        rid, info = self._fallback.select(request, matched or candidates)
        return rid, {**info, "phase": want.value, "role_match": bool(matched)}


class PrefixDirectoryPolicy(RoutingPolicy):
    """Directory-resident prefix affinity with cold-replica KV import
    (docs/SERVING.md "Prefix directory").

    Same placement shape as :class:`PrefixAffinityPolicy` — warmest
    replica unless its queue is saturated, least-loaded otherwise — but
    warmth comes from ONE :class:`~.prefix_directory.PrefixDirectory`
    walk over the request's token digests: no ``lookup_depth`` probe
    fan-out, no engine reads, O(prefix pages) per dispatch however many
    replicas the fleet runs.  The probe policy stays available as the
    directory-less fallback and as the cross-check oracle in tests.

    The ambitious half: when the fleet IS warm for this prefix but the
    chosen (least-loaded) replica is cold — the saturated-hot-spot case
    where the probe policy eats a full recompute — the select info carries
    a ``prefix_import`` plan naming the warmest donor; the router exports
    those immutable full pages once to host and adopts them into the cold
    replica's prefix cache BEFORE the dispatch, so the request lands warm
    anyway.  ``import_min_pages`` gates the plan on the warmth deficit
    being worth a staging round-trip."""

    name = "prefix_directory"

    def __init__(self, directory, saturation_queue_depth: int = 4,
                 import_min_pages: int = 1):
        assert saturation_queue_depth >= 1, saturation_queue_depth
        assert import_min_pages >= 1, import_min_pages
        self.directory = directory
        self.saturation_queue_depth = saturation_queue_depth
        self.import_min_pages = import_min_pages
        self._fallback = LeastOutstandingPolicy()

    def select(self, request, candidates):
        if not candidates:
            return None, {}
        # full token history (prompt + already-generated): a failover
        # resume is exactly the traffic whose warm pages matter — same
        # stance as the probe policy
        tokens = list(request.prompt) + list(request.tokens)
        # two-tier warmth (serving/kvtier): device-resident pages attach
        # for free; host-staged pages cost a bounded h2d promote — better
        # than cold, worse than device-warm.  With no host tier attached
        # warm == device and this orders exactly like the old single-tier
        # key (the probe-policy oracle still holds).
        tiered = self.directory.tiered_depths(
            tokens, [rid for rid, _, _ in candidates])
        best = max(candidates, key=lambda c: (
            tiered[c[0]][0], tiered[c[0]][1], -c[2]["queue_depth"], -c[0]))
        rid, _, stats = best
        dev, warm = tiered[rid]
        if warm > 0 and stats["queue_depth"] < self.saturation_queue_depth:
            info = {"affinity_hit": True, "warm_pages": dev}
            if warm > dev:
                info["host_warm"] = True
                info["host_pages"] = warm - dev
            return rid, info
        # cold everywhere, or the warm target is saturated: least-loaded,
        # excluding the saturated warm target when an alternative exists
        # (identical fallback shape to PrefixAffinityPolicy)
        saturated = warm > 0
        fb_candidates = [c for c in candidates if c[0] != rid] if saturated else candidates
        if not fb_candidates:
            fb_candidates = candidates
        fb_rid, _ = self._fallback.select(request, fb_candidates)
        fb_dev, fb_warm = tiered.get(fb_rid, (0, 0))
        info = {"affinity_hit": fb_warm > 0,
                "warm_pages": fb_dev,
                "affinity_saturated": saturated}
        if fb_warm > fb_dev:
            info["host_warm"] = True
            info["host_pages"] = fb_warm - fb_dev
        if saturated and fb_rid is not None \
                and warm - fb_warm >= self.import_min_pages:
            # the fleet is warm, the landing replica is not: ask the router
            # to import the hot prefix there before dispatch (the router
            # flips affinity_hit to True if the import lands).  The donor
            # depth counts BOTH tiers — export_prefix sources the host-
            # staged tail from the donor's kvtier without touching its
            # device arena.
            info["prefix_import"] = {"donor": rid, "donor_depth": warm}
        return fb_rid, info


class SessionAffinityPolicy(RoutingPolicy):
    """Sticky-with-failover session placement (docs/SERVING.md "Agentic
    sessions").

    The map is ``session_id -> rid``, learned from wherever each session's
    LAST dispatch landed.  The sticky replica wins whenever it is a live
    candidate below ``saturation_queue_depth`` — its prefix cache holds
    the session's whole transcript (generated tokens included: the engine
    publishes full pages as decode progresses), so the sticky turn
    prefills only the fresh user suffix.  Otherwise the turn re-homes:

    * sticky replica DEAD (not in candidates) or SATURATED → fall back to
      the wrapped policy — :class:`PrefixDirectoryPolicy` when a
      directory is attached (a failover turn carries the transcript
      prefix, and the directory may know a second-warm replica or plan a
      ``prefix_import`` onto the landing one), least-loaded otherwise —
      and RE-STICK to wherever the turn lands.
    * session-less requests (``session_id`` is None) go straight to the
      fallback: mixing stateless traffic through the sticky map would
      pin it to arbitrary replicas.

    Info keys: ``session_sticky`` (the sticky fast path won),
    ``session_failover`` (a previously-stuck session re-homed), plus
    whatever the fallback contributes (``affinity_hit``,
    ``prefix_import`` ...)."""

    name = "session_affinity"

    def __init__(self, directory=None, saturation_queue_depth: int = 4,
                 import_min_pages: int = 1):
        assert saturation_queue_depth >= 1, saturation_queue_depth
        self.saturation_queue_depth = saturation_queue_depth
        self._sticky = {}          # session_id -> rid of the last dispatch
        self._fallback = PrefixDirectoryPolicy(
            directory, saturation_queue_depth=saturation_queue_depth,
            import_min_pages=import_min_pages) if directory is not None \
            else LeastOutstandingPolicy()

    def select(self, request, candidates):
        if not candidates:
            return None, {}
        sid = getattr(request, "session_id", None)
        if sid is None:
            return self._fallback.select(request, candidates)
        rid = self._sticky.get(sid)
        if rid is not None:
            for c_rid, _, stats in candidates:
                if c_rid == rid and \
                        stats["queue_depth"] < self.saturation_queue_depth:
                    return rid, {"session_sticky": True}
        fb_rid, info = self._fallback.select(request, candidates)
        if fb_rid is None:
            return None, {}
        info = {**info, "session_sticky": False}
        if rid is not None and fb_rid != rid:
            info["session_failover"] = True
        self._sticky[sid] = fb_rid
        return fb_rid, info


POLICIES = {p.name: p for p in (RoundRobinPolicy, LeastOutstandingPolicy,
                                PrefixAffinityPolicy, DisaggregatedPolicy,
                                PrefixDirectoryPolicy, SessionAffinityPolicy)}


def make_policy(name: str, **kwargs) -> RoutingPolicy:
    if name not in POLICIES:
        raise ValueError(f"unknown routing policy '{name}'; one of {sorted(POLICIES)}")
    return POLICIES[name](**kwargs)
