"""Fleet-global prefix cache directory: router-resident warmth, pushed
not probed (docs/SERVING.md "Prefix directory").

``prefix_affinity`` routing (r9) GUESSES warmth by fanning a
``lookup_depth`` probe out to every replica's ``PrefixCacheManager`` on
each dispatch — O(replicas) engine reads per request, and a hit rate that
tops out where the warm replica saturates.  The directory inverts the
dataflow: replicas PUBLISH their prefix-chain digests through the cache's
listener bus as pages enter/leave the cache (admission, extension, evict),
and the router answers "who is warm for these tokens" from its own table —
zero per-replica calls on the dispatch hot path.

The digest is :func:`~....inference.v2.ragged.prefix_chain_hashes` — the
SAME chain hash the cache keys pages by, so directory warmth is the
digest-level view of exactly what a subsequent ``match()`` would attach.
The directory stores hashes only (64-bit ints), never tokens or KV: its
footprint is bytes per page per replica, and a stale or colliding entry
can only mis-route, never corrupt (the replica-side ``match()`` verifies
tokens before attaching pages, and the prefix-import path re-checksums
staged bytes).

Staleness ladder (deterministic under ``FleetSimulator``, chaos-tested in
``tests/unit/resilience/test_prefix_chaos.py``):

* evict-after-publish — the directory promises warmth a replica has since
  evicted: the dispatch lands "warm" but ``match()``/``export_prefix``
  find less (or nothing) and the prefill recomputes — slower, never wrong;
* verify-fail — a torn prefix staging is rejected by the snapshot crc at
  import and the target dispatches cold;
* replica death — ``ReplicaPool.kill`` purges every entry the dead
  replica published (``purge``), so the router never routes to (or
  imports from) a ghost;
* directory pressure — the table is BOUNDED (``capacity`` (rid, digest)
  entries, LRU): overflow forgets the coldest entries, which costs at
  most a cold dispatch, exactly like a replica-side cache eviction.

The ``prefix.publish`` chaos site wraps every publish/retract so a drill
can drop directory updates (stale-cold or stale-warm, both rungs of the
ladder) or crash the driver mid-publish.
"""

from collections import OrderedDict
from typing import Dict, Iterable, List, Tuple

from ...inference.v2.ragged import iter_prefix_chain_hashes
from ...resilience import fault_injection as _fi

__all__ = ["PrefixDirectory"]


class PrefixDirectory:
    """Router-resident map ``chain digest -> replicas holding that page``.

    One instance spans the fleet: pass it to ``ReplicaPool(prefix_directory=
    ...)`` (which wires every attached engine's prefix cache to
    :meth:`publish`/:meth:`retract` and purges on death/restart) and to the
    ``prefix_directory`` routing policy (which reads :meth:`depths`).
    """

    def __init__(self, page_size: int, capacity: int = 65536, metrics=None):
        assert page_size >= 1, page_size
        assert capacity >= 1, capacity
        self.page_size = int(page_size)
        self.capacity = int(capacity)
        # telemetry: always-on counters on the fleet MetricsRegistry when
        # one is attached (the same registry the replica frontends share)
        self.metrics = metrics
        #: digest -> set of rids that published it
        self._holders: Dict[int, set] = {}
        #: (rid, digest) -> None, oldest first — the LRU the capacity
        #: bound evicts from; refreshed on re-publish and on lookup match
        self._lru: "OrderedDict[Tuple[int, int], None]" = OrderedDict()
        self.stats = {"published": 0, "retracted": 0, "purged": 0,
                      "lru_evicted": 0, "lookups": 0}

    # ------------------------------------------------------------- publish

    def publish(self, rid: int, digest: int) -> None:
        """A replica's cache registered a full page keyed by ``digest``.
        Idempotent per (rid, digest); a re-publish refreshes the LRU."""
        _fi.check("prefix.publish")   # chaos site: dropped/crashed publish
        key = (rid, digest)
        if key in self._lru:
            self._lru.move_to_end(key)
            return
        self._holders.setdefault(digest, set()).add(rid)
        self._lru[key] = None
        self.stats["published"] += 1
        if self.metrics is not None:
            self.metrics.counter("prefix/publish").inc()
        while len(self._lru) > self.capacity:
            (orid, odig), _ = self._lru.popitem(last=False)
            self._drop(orid, odig)
            self.stats["lru_evicted"] += 1

    def retract(self, rid: int, digest: int) -> None:
        """A replica's cache evicted the page keyed by ``digest``."""
        _fi.check("prefix.publish")   # same stream as publish: one site
        key = (rid, digest)
        if key not in self._lru:
            return
        del self._lru[key]
        self._drop(rid, digest)
        self.stats["retracted"] += 1
        if self.metrics is not None:
            self.metrics.counter("prefix/evict").inc()

    def purge(self, rid: int) -> int:
        """Forget every entry ``rid`` published — replica death (the
        engine and its cache are gone) or a fresh engine attach (restart:
        the new cache starts empty).  Returns entries dropped."""
        victims = [key for key in self._lru if key[0] == rid]
        for key in victims:
            del self._lru[key]
            self._drop(*key)
        self.stats["purged"] += len(victims)
        return len(victims)

    def _drop(self, rid: int, digest: int) -> None:
        holders = self._holders.get(digest)
        if holders is not None:
            holders.discard(rid)
            if not holders:
                del self._holders[digest]

    # -------------------------------------------------------------- lookup

    def depths(self, tokens: Iterable[int],
               rids: Iterable[int]) -> Dict[int, int]:
        """Per-replica warmth for ``tokens``: how many LEADING full pages
        of the token history each rid (per the directory) holds — the same
        quantity ``PrefixCacheManager.lookup_depth`` reports, including
        its last-token cap (the engine must still compute >= 1 token), so
        directory routing and the probe policy agree whenever the
        directory is fresh (the regression oracle in
        tests/unit/inference/test_prefix_directory.py).  One chain walk
        total — NO per-replica engine calls.  Matched entries' LRU is
        refreshed: routed-on prefixes are hot prefixes."""
        tokens = list(tokens)
        rids = list(rids)
        depth = {rid: 0 for rid in rids}
        self.stats["lookups"] += 1
        usable_pages = max(0, (len(tokens) - 1) // self.page_size)
        live = set(rids)
        for k, digest in enumerate(iter_prefix_chain_hashes(
                tokens[:usable_pages * self.page_size], self.page_size)):
            holders = self._holders.get(digest)
            if holders is None:
                break
            live &= holders
            if not live:
                break
            for rid in sorted(live):
                depth[rid] = k + 1
                self._lru.move_to_end((rid, digest))
        return depth

    def hottest(self, k: int) -> List[Tuple[int, List[int]]]:
        """The ``k`` most-recently-used digests (newest LRU end first),
        each with the sorted rids holding it — the directory-driven
        autoscale warm-up input: a RECOVERING replica pre-imports these
        chains' KV from a live donor so it joins the fleet warm instead of
        eating a cold-start recompute on its first dispatches.  Digests
        are deduplicated across replicas (one import warms the chain
        fleet-wide for the target)."""
        out: List[Tuple[int, List[int]]] = []
        seen = set()
        for rid, digest in reversed(self._lru):
            if digest in seen:
                continue
            seen.add(digest)
            out.append((digest, sorted(self._holders.get(digest, ()))))
            if len(out) >= k:
                break
        return out

    # ------------------------------------------------------------- surface

    @property
    def entries(self) -> int:
        return len(self._lru)

    def summary(self) -> dict:
        return {**self.stats, "entries": self.entries,
                "digests": len(self._holders), "capacity": self.capacity}
