"""Fleet-global prefix cache directory: router-resident warmth, pushed
not probed (docs/SERVING.md "Prefix directory").

``prefix_affinity`` routing (r9) GUESSES warmth by fanning a
``lookup_depth`` probe out to every replica's ``PrefixCacheManager`` on
each dispatch — O(replicas) engine reads per request, and a hit rate that
tops out where the warm replica saturates.  The directory inverts the
dataflow: replicas PUBLISH their prefix-chain digests through the cache's
listener bus as pages enter/leave the cache (admission, extension, evict),
and the router answers "who is warm for these tokens" from its own table —
zero per-replica calls on the dispatch hot path.

The digest is :func:`~....inference.v2.ragged.prefix_chain_hashes` — the
SAME chain hash the cache keys pages by, so directory warmth is the
digest-level view of exactly what a subsequent ``match()`` would attach.
The directory stores hashes only (64-bit ints), never tokens or KV: its
footprint is bytes per page per replica, and a stale or colliding entry
can only mis-route, never corrupt (the replica-side ``match()`` verifies
tokens before attaching pages, and the prefix-import path re-checksums
staged bytes).

Staleness ladder (deterministic under ``FleetSimulator``, chaos-tested in
``tests/unit/resilience/test_prefix_chaos.py``):

* evict-after-publish — the directory promises warmth a replica has since
  evicted: the dispatch lands "warm" but ``match()``/``export_prefix``
  find less (or nothing) and the prefill recomputes — slower, never wrong;
* verify-fail — a torn prefix staging is rejected by the snapshot crc at
  import and the target dispatches cold;
* replica death — ``ReplicaPool.kill`` purges every entry the dead
  replica published (``purge``), so the router never routes to (or
  imports from) a ghost;
* directory pressure — the table is BOUNDED (``capacity`` (rid, digest)
  entries, LRU): overflow forgets the coldest entries, which costs at
  most a cold dispatch, exactly like a replica-side cache eviction.

The ``prefix.publish`` chaos site wraps every publish/retract so a drill
can drop directory updates (stale-cold or stale-warm, both rungs of the
ladder) or crash the driver mid-publish.
"""

from collections import OrderedDict
from typing import Dict, Iterable, List, Tuple

from ...inference.v2.ragged import iter_prefix_chain_hashes
from ...resilience import fault_injection as _fi

__all__ = ["PrefixDirectory"]


class PrefixDirectory:
    """Router-resident map ``chain digest -> replicas holding that page``.

    One instance spans the fleet: pass it to ``ReplicaPool(prefix_directory=
    ...)`` (which wires every attached engine's prefix cache to
    :meth:`publish`/:meth:`retract` and purges on death/restart) and to the
    ``prefix_directory`` routing policy (which reads :meth:`depths`).
    """

    def __init__(self, page_size: int, capacity: int = 65536, metrics=None):
        assert page_size >= 1, page_size
        assert capacity >= 1, capacity
        self.page_size = int(page_size)
        self.capacity = int(capacity)
        # telemetry: always-on counters on the fleet MetricsRegistry when
        # one is attached (the same registry the replica frontends share)
        self.metrics = metrics
        #: digest -> set of rids that published it
        self._holders: Dict[int, set] = {}
        #: (rid, digest) -> None, oldest first — the LRU the capacity
        #: bound evicts from; refreshed on re-publish and on lookup match
        self._lru: "OrderedDict[Tuple[int, int], None]" = OrderedDict()
        #: host-tier mirror (serving/kvtier): digest -> rids whose HOST
        #: tier holds the page.  Host-warm is a second-class warmth — the
        #: target can promote the page h2d instead of recomputing — so it
        #: is tracked in its own table (same capacity bound, own LRU) and
        #: reported separately by :meth:`tiered_depths`.
        self._host_holders: Dict[int, set] = {}
        self._host_lru: "OrderedDict[Tuple[int, int], None]" = OrderedDict()
        self.stats = {"published": 0, "retracted": 0, "purged": 0,
                      "lru_evicted": 0, "lookups": 0,
                      "host_published": 0, "host_retracted": 0}

    # ------------------------------------------------------------- publish

    def publish(self, rid: int, digest: int) -> None:
        """A replica's cache registered a full page keyed by ``digest``.
        Idempotent per (rid, digest); a re-publish refreshes the LRU."""
        _fi.check("prefix.publish")   # chaos site: dropped/crashed publish
        key = (rid, digest)
        if key in self._lru:
            self._lru.move_to_end(key)
            return
        self._holders.setdefault(digest, set()).add(rid)
        self._lru[key] = None
        self.stats["published"] += 1
        if self.metrics is not None:
            self.metrics.counter("prefix/publish").inc()
        while len(self._lru) > self.capacity:
            (orid, odig), _ = self._lru.popitem(last=False)
            self._drop(orid, odig)
            self.stats["lru_evicted"] += 1

    def retract(self, rid: int, digest: int) -> None:
        """A replica's cache evicted the page keyed by ``digest``."""
        _fi.check("prefix.publish")   # same stream as publish: one site
        key = (rid, digest)
        if key not in self._lru:
            return
        del self._lru[key]
        self._drop(rid, digest)
        self.stats["retracted"] += 1
        if self.metrics is not None:
            self.metrics.counter("prefix/evict").inc()

    def publish_host(self, rid: int, digest: int) -> None:
        """``rid``'s HOST tier (serving/kvtier) staged the page keyed by
        ``digest``: a demotion parked it CPU-side, still promotable.
        Same chaos stream as device publishes — a dropped host publish is
        the stale-cold rung (the fleet forgets warmth it has)."""
        _fi.check("prefix.publish")
        key = (rid, digest)
        if key in self._host_lru:
            self._host_lru.move_to_end(key)
            return
        self._host_holders.setdefault(digest, set()).add(rid)
        self._host_lru[key] = None
        self.stats["host_published"] += 1
        if self.metrics is not None:
            self.metrics.counter("prefix/publish").inc()
        while len(self._host_lru) > self.capacity:
            (orid, odig), _ = self._host_lru.popitem(last=False)
            self._drop_host(orid, odig)
            self.stats["lru_evicted"] += 1

    def retract_host(self, rid: int, digest: int) -> None:
        """``rid``'s host tier dropped the page (promoted back to the
        device — now a device publish — or evicted under host pressure)."""
        _fi.check("prefix.publish")
        key = (rid, digest)
        if key not in self._host_lru:
            return
        del self._host_lru[key]
        self._drop_host(rid, digest)
        self.stats["host_retracted"] += 1
        if self.metrics is not None:
            self.metrics.counter("prefix/evict").inc()

    def purge(self, rid: int) -> int:
        """Forget every entry ``rid`` published — replica death (the
        engine, its cache AND its host tier are gone) or a fresh engine
        attach (restart: the new cache starts empty).  Returns entries
        dropped (both tiers)."""
        victims = [key for key in self._lru if key[0] == rid]
        for key in victims:
            del self._lru[key]
            self._drop(*key)
        host_victims = [key for key in self._host_lru if key[0] == rid]
        for key in host_victims:
            del self._host_lru[key]
            self._drop_host(*key)
        self.stats["purged"] += len(victims) + len(host_victims)
        return len(victims) + len(host_victims)

    def _drop(self, rid: int, digest: int) -> None:
        holders = self._holders.get(digest)
        if holders is not None:
            holders.discard(rid)
            if not holders:
                del self._holders[digest]

    def _drop_host(self, rid: int, digest: int) -> None:
        holders = self._host_holders.get(digest)
        if holders is not None:
            holders.discard(rid)
            if not holders:
                del self._host_holders[digest]

    # -------------------------------------------------------------- lookup

    def depths(self, tokens: Iterable[int],
               rids: Iterable[int]) -> Dict[int, int]:
        """Per-replica warmth for ``tokens``: how many LEADING full pages
        of the token history each rid (per the directory) holds — the same
        quantity ``PrefixCacheManager.lookup_depth`` reports, including
        its last-token cap (the engine must still compute >= 1 token), so
        directory routing and the probe policy agree whenever the
        directory is fresh (the regression oracle in
        tests/unit/inference/test_prefix_directory.py).  One chain walk
        total — NO per-replica engine calls.  Matched entries' LRU is
        refreshed: routed-on prefixes are hot prefixes."""
        tokens = list(tokens)
        rids = list(rids)
        depth = {rid: 0 for rid in rids}
        self.stats["lookups"] += 1
        usable_pages = max(0, (len(tokens) - 1) // self.page_size)
        live = set(rids)
        for k, digest in enumerate(iter_prefix_chain_hashes(
                tokens[:usable_pages * self.page_size], self.page_size)):
            holders = self._holders.get(digest)
            if holders is None:
                break
            live &= holders
            if not live:
                break
            for rid in sorted(live):
                depth[rid] = k + 1
                self._lru.move_to_end((rid, digest))
        return depth

    def tiered_depths(self, tokens: Iterable[int],
                      rids: Iterable[int]) -> Dict[int, Tuple[int, int]]:
        """Per-replica ``(device_depth, warm_depth)`` for ``tokens``.

        ``device_depth`` is exactly what :meth:`depths` reports: leading
        full pages resident in the replica's DEVICE cache (attach is
        free).  ``warm_depth >= device_depth`` extends the chain through
        pages the replica holds in EITHER tier — a host-tier page costs a
        bounded h2d promote instead of a prefill recompute, so a
        host-warm replica beats a cold one but loses to a device-warm
        one at equal depth.  One chain walk total, same last-token cap."""
        tokens = list(tokens)
        rids = list(rids)
        out = {rid: (0, 0) for rid in rids}
        self.stats["lookups"] += 1
        usable_pages = max(0, (len(tokens) - 1) // self.page_size)
        live_dev = set(rids)
        live_warm = set(rids)
        for k, digest in enumerate(iter_prefix_chain_hashes(
                tokens[:usable_pages * self.page_size], self.page_size)):
            dev = self._holders.get(digest, ())
            host = self._host_holders.get(digest, ())
            live_dev &= set(dev)
            live_warm &= set(dev) | set(host)
            if not live_warm:
                break
            for rid in sorted(live_warm):
                d, _ = out[rid]
                if rid in live_dev:
                    d = k + 1
                    self._lru.move_to_end((rid, digest))
                elif (rid, digest) in self._host_lru:
                    self._host_lru.move_to_end((rid, digest))
                out[rid] = (d, k + 1)
        return out

    def hottest(self, k: int) -> List[Tuple[int, List[int]]]:
        """The ``k`` most-recently-used digests (newest LRU end first),
        each with the sorted rids holding it — the directory-driven
        autoscale warm-up input: a RECOVERING replica pre-imports these
        chains' KV from a live donor so it joins the fleet warm instead of
        eating a cold-start recompute on its first dispatches.  Digests
        are deduplicated across replicas (one import warms the chain
        fleet-wide for the target)."""
        out: List[Tuple[int, List[int]]] = []
        seen = set()
        for rid, digest in reversed(self._lru):
            if digest in seen:
                continue
            seen.add(digest)
            out.append((digest, sorted(self._holders.get(digest, ()))))
            if len(out) >= k:
                break
        return out

    # ------------------------------------------------------------- surface

    @property
    def entries(self) -> int:
        return len(self._lru)

    @property
    def host_entries(self) -> int:
        return len(self._host_lru)

    def summary(self) -> dict:
        return {**self.stats, "entries": self.entries,
                "host_entries": self.host_entries,
                "digests": len(self._holders), "capacity": self.capacity}
