"""Replica health state machine: HEALTHY → DEGRADED → DEAD → RECOVERING.

The fleet router must keep dispatching while individual replicas misbehave,
so every replica carries an explicit health state the router's policies
consult (``dispatchable``) and the pool's failover path keys off
(``serving``).  Signals come from the resilience layer the training side
already uses: transient ``OSError``\\ s degrade, repeated ones (or a
device-loss classification — :class:`~..resilience.watchdog.StepHungError`,
:class:`~..resilience.fault_injection.DeviceLossError`, any error whose
message carries the ``DEVICE_LOST`` marker, or
:class:`~..resilience.fault_injection.InjectedCrash`) kill.

::

    HEALTHY ──errors──▶ DEGRADED ──more errors──▶ DEAD
       ▲  ▲              │    │                    │
       │  └──successes───┘    └───────fatal────────┤
       │                                           ▼
       └───────── probe ticks ────────────── RECOVERING
                                                   │ (probe failure)
                                                   ▼
                                                  DEAD

    HEALTHY | DEGRADED ──drain()──▶ DRAINING ──restart──▶ RECOVERING
    (DRAINING keeps serving its in-flight work but receives no new
     dispatches; a kill during DRAINING still goes to DEAD)

Transitions are validated — an illegal one is a tracker bug and raises —
and every transition is recorded in ``history`` and emitted as a
``fleet/health/<state>`` monitor event, so a fleet sim's failover timeline
is auditable on the surface operators already watch.
"""

import dataclasses
import enum
from typing import Callable, Dict, List, Optional, Tuple

from ...utils.logging import logger


class LeaseState(enum.Enum):
    """The ROUTER's partition-tolerant belief about one replica, derived
    purely from heartbeats over the control transport (docs/SERVING.md
    "Control-plane transport").  Distinct from :class:`ReplicaState`,
    which is the replica-LOCAL truth the pool tracks from tick outcomes:
    under a partition the two legitimately disagree — a perfectly healthy
    replica the router cannot hear from is lease-DEAD at the router while
    staying HEALTHY at the pool, and fencing is what reconciles them."""
    ALIVE = "alive"        # lease fresh: heartbeats arriving inside the window
    SUSPECT = "suspect"    # lease expiring: no new dispatches, work stays put
    DEAD = "dead"          # lease expired: fleet-declared death, work re-dispatched
    FENCING = "fencing"    # heartbeats resumed from a fleet-dead replica (a
    #                        zombie, or a legit recovery): a FENCE is in
    #                        flight; the replica rejoins only after the ack


#: validated lease transitions (dslint state-machine table; the generated
#: docs/STATE_MACHINES.md renders it).  ALIVE can expire straight to DEAD:
#: a long idle jump may land past the whole suspect window in one tick.
#: DEAD leaves only through FENCING — a fleet-dead replica's first
#: heartbeat starts a fencing episode, never a silent rejoin.
_LEASE_ALLOWED = {
    LeaseState.ALIVE: {LeaseState.SUSPECT, LeaseState.DEAD},
    LeaseState.SUSPECT: {LeaseState.ALIVE, LeaseState.DEAD},
    LeaseState.DEAD: {LeaseState.FENCING},
    LeaseState.FENCING: {LeaseState.ALIVE},
}


@dataclasses.dataclass(frozen=True)
class LeaseConfig:
    #: heartbeat silence (seconds of clock time since the newest heartbeat's
    #: SEND timestamp) after which a replica turns SUSPECT — dispatchable no
    #: more, but its in-flight work is left alone
    suspect_after: float = 2.0
    #: silence after which the lease expires: the router declares the
    #: replica fleet-dead, re-dispatches its work, and bumps its dispatch
    #: epoch so any surviving zombie's late completions are fenced off
    lease: float = 6.0
    #: minimum clock time between FENCE (re)sends to an unacked zombie
    fence_retry: float = 2.0
    #: ADAPTIVE LEASE SIZING (r21, docs/SERVING.md "Closed-loop control").
    #: Off by default: the fixed constants above hold and behavior is
    #: byte-identical to r20.  When on, each replica's effective
    #: suspect_after/lease is the base value times a per-replica scale in
    #: [1, max_scale], derived from the heartbeat interarrival EWMA, the
    #: per-link ``transport/link_loss_ewma``, and the standing
    #: ``transport/feed_gap_age`` — a fleet whose steps legitimately slow
    #: widens its leases instead of fencing healthy replicas, and
    #: tightens back when the links recover.
    adaptive: bool = False
    #: ceiling of the per-replica scale band: the effective lease never
    #: exceeds ``lease * max_scale`` — a real death is still detected
    #: within a bounded window (the BENCH receipt pins this)
    max_scale: float = 4.0
    #: silence tolerance in heartbeat interarrivals: the effective
    #: suspect_after targets ``miss_budget`` consecutive missed beats
    #: (loss-inflated: /(1 - link_loss_ewma)) before suspecting
    miss_budget: float = 3.0
    #: EWMA alpha for the heartbeat interarrival estimate
    interarrival_alpha: float = 0.3
    #: weight of the standing directory-feed gap age in the target
    #: (a stalling feed is fabric delay evidence, not death evidence)
    feed_gap_weight: float = 0.5
    #: hysteresis deadband: widen only when the target scale exceeds the
    #: current by this fraction ...
    widen_frac: float = 0.1
    #: ... and tighten only when it falls below by this (tighten > widen:
    #: widening is the false-fence guard, so it reacts faster)
    tighten_frac: float = 0.25

    def __post_init__(self):
        if not 0 < self.suspect_after < self.lease:
            raise ValueError(f"lease needs 0 < suspect_after < lease "
                             f"(got {self.suspect_after}, {self.lease})")
        if self.fence_retry <= 0:
            raise ValueError(f"fence_retry must be > 0, got {self.fence_retry}")
        if self.max_scale < 1.0:
            raise ValueError(f"max_scale must be >= 1, got {self.max_scale}")
        if self.miss_budget <= 0:
            raise ValueError(f"miss_budget must be > 0, got {self.miss_budget}")
        if not 0.0 < self.interarrival_alpha <= 1.0:
            raise ValueError(f"interarrival_alpha must be in (0, 1], got "
                             f"{self.interarrival_alpha}")
        if self.feed_gap_weight < 0:
            raise ValueError(f"feed_gap_weight must be >= 0, got "
                             f"{self.feed_gap_weight}")
        if self.widen_frac < 0 or not 0.0 <= self.tighten_frac < 1.0:
            raise ValueError(f"hysteresis fracs out of range (widen "
                             f"{self.widen_frac}, tighten {self.tighten_frac})")


class FleetHealthView:
    """Heartbeat-lease health: what the router can DEFENSIBLY believe
    about each replica when its only evidence is messages that may be
    lost, late, duplicated or partitioned away.

    Per replica it tracks the newest heartbeat (by sequence number — a
    reordered older heartbeat never rewinds the view), the last-known-good
    ``load_stats`` snapshot with its age (the staleness annotation routing
    and autoscaling read), the replica's self-reported local health state,
    and a monotonically increasing **dispatch epoch** that bumps on every
    lease expiry — the fencing token that makes a zombie's late
    completions discardable."""

    def __init__(self, replica_ids, config: LeaseConfig = None, clock=None,
                 emit: Optional[Callable[[str, float], None]] = None,
                 recorder=None):
        self.config = config or LeaseConfig()
        self._clock = clock
        self._emit_cb = emit
        #: optional flight recorder: lease lifecycles become first-class
        #: interval tracks — one ``ctrl/lease/replica/<rid>`` track per
        #: replica whose ``ctrl/lease/<state>`` intervals tile the run
        #: (ALIVE→SUSPECT→DEAD→FENCING→ALIVE visible at a glance in the
        #: crash dump, docs/OBSERVABILITY.md "Flight recorder")
        self.recorder = recorder
        t0 = clock.now() if clock is not None else 0.0
        rids = list(replica_ids)
        if recorder is not None:
            for rid in rids:
                recorder.note_state(f"ctrl/lease/replica/{rid}",
                                    f"ctrl/lease/{LeaseState.ALIVE.value}", t0)
        # the initial lease is granted at construction: a replica that
        # never heartbeats at all still expires on schedule
        self._last_hb: Dict[int, float] = {r: t0 for r in rids}
        self._last_seq: Dict[int, int] = {r: 0 for r in rids}
        self._reported: Dict[int, str] = {r: ReplicaState.HEALTHY.value for r in rids}
        self._stats: Dict[int, Optional[dict]] = {r: None for r in rids}
        self._stats_ts: Dict[int, float] = {r: t0 for r in rids}
        self._state: Dict[int, LeaseState] = {r: LeaseState.ALIVE for r in rids}
        #: newest self-reported engine generation — a restart INSIDE the
        #: lease window renews the lease but bumps this, which is how the
        #: router learns its old attempts died with the old engine
        self._generation: Dict[int, Optional[int]] = {r: None for r in rids}
        self._fence_sent_ts: Dict[int, Optional[float]] = {r: None for r in rids}
        #: per-replica dispatch epoch; bumped at every lease expiry
        self.epoch: Dict[int, int] = {r: 0 for r in rids}
        #: (rid, from, to, ts, reason) — the auditable lease timeline
        self.history: List[Tuple[int, LeaseState, LeaseState, float, str]] = []
        # --- adaptive lease sizing state (inert while config.adaptive is
        # off: every scale stays 1.0 and the fixed constants hold) ---
        #: per-replica lease scale in [1, max_scale]; effective
        #: suspect_after/lease are the base values times this
        self._scale: Dict[int, float] = {r: 1.0 for r in rids}
        #: heartbeat interarrival EWMA (send-timestamp gaps; None until
        #: the first gap is observed)
        self._hb_gap_ewma: Dict[int, Optional[float]] = {r: None for r in rids}
        #: freshest router-fed link-quality signals (note_link_quality)
        self._link_loss: Dict[int, float] = {r: 0.0 for r in rids}
        self._feed_gap_age: Dict[int, float] = {r: 0.0 for r in rids}
        #: (rid, ts, old_scale, new_scale, direction) — the auditable
        #: resize timeline behind every ``fleet/lease_resize`` event
        self.resizes: List[Tuple[int, float, float, float, str]] = []

    # ------------------------------------------------------------- queries

    def state(self, rid: int) -> LeaseState:
        return self._state[rid]

    def states(self) -> Dict[int, LeaseState]:
        return dict(self._state)

    def dispatchable(self, rid: int) -> bool:
        """May the router hand this replica NEW work?  Requires a fresh
        lease AND a self-reported dispatchable local state (a DRAINING or
        RECOVERING replica heartbeats, but takes no new dispatches)."""
        if self._state[rid] is not LeaseState.ALIVE:
            return False
        try:
            return ReplicaState(self._reported[rid]).dispatchable
        except ValueError:
            return False

    def stats(self, rid: int):
        """``(last_known_good_load_stats, age_seconds)`` — the staleness-
        annotated routing signal.  ``(None, age)`` before any heartbeat."""
        now = self._clock.now() if self._clock is not None else 0.0
        return self._stats[rid], max(0.0, now - self._stats_ts[rid])

    def generation(self, rid: int) -> Optional[int]:
        """Newest self-reported engine generation (None before any
        heartbeat)."""
        return self._generation[rid]

    def effective_lease(self, rid: int) -> Tuple[float, float]:
        """``(suspect_after, lease)`` currently in force for ``rid`` —
        the configured base times the replica's adaptive scale (exactly
        the base values while the scale sits at 1.0, so the static
        configuration stays byte-identical)."""
        s = self._scale[rid]
        if s == 1.0:
            return self.config.suspect_after, self.config.lease
        return (round(self.config.suspect_after * s, 9),
                round(self.config.lease * s, 9))

    # ------------------------------------------------- adaptive lease sizing

    def note_link_quality(self, rid: int, loss_ewma: float,
                          feed_gap_age: float, now: float) -> None:
        """Fold the router's per-link fabric evidence — the r18
        ``transport/link_loss_ewma`` and ``transport/feed_gap_age``
        signals — and re-derive the replica's lease scale.  No-op unless
        ``config.adaptive``; called once per control round from
        ``Router.transport_poll`` in sorted-rid order, so the resize
        timeline is deterministic."""
        if not self.config.adaptive:
            return
        self._link_loss[rid] = loss_ewma
        self._feed_gap_age[rid] = feed_gap_age
        self._resize(rid, now)

    def _resize(self, rid: int, now: float) -> None:
        """Recompute ``rid``'s lease scale from the closed-loop inputs,
        with hysteresis (widen fast — it is the false-fence guard —
        tighten slow) and the [1, max_scale] clamp that keeps real-death
        detection bounded.  Every applied adjustment is an auditable
        ``fleet/lease_resize`` event."""
        cfg = self.config
        gap = self._hb_gap_ewma[rid]
        if gap is None:
            return  # no interarrival evidence yet: the configured base holds
        # target silence tolerance: miss_budget interarrivals, inflated by
        # the link's observed loss (p lost => 1/(1-p) expected sends per
        # arrival), plus the standing feed gap (fabric delay, not death)
        loss = min(self._link_loss[rid], 0.75)
        target_suspect = cfg.miss_budget * gap / (1.0 - loss) \
            + cfg.feed_gap_weight * self._feed_gap_age[rid]
        target = min(max(target_suspect / cfg.suspect_after, 1.0),
                     cfg.max_scale)
        target = round(target, 9)
        cur = self._scale[rid]
        if target > cur * (1.0 + cfg.widen_frac):
            direction = "widen"
        elif target < cur * (1.0 - cfg.tighten_frac):
            direction = "tighten"
        else:
            return  # inside the hysteresis deadband: hold
        self._scale[rid] = target
        ts = round(now, 9)
        self.resizes.append((rid, ts, cur, target, direction))
        self._emit("fleet/lease_resize", float(rid))
        if self.recorder is not None:
            self.recorder.instant(
                "ctrl/lease_resize", f"ctrl/lease/replica/{rid}", now,
                attrs={"direction": direction, "scale": target,
                       "gap_ewma": round(gap, 9), "loss": round(loss, 9)})
        logger.info(f"fleet lease: replica {rid} {direction} scale "
                    f"{cur:.3f} -> {target:.3f}")

    # --------------------------------------------------------- transitions

    def _to(self, rid: int, state: LeaseState, ts: float, reason: str) -> None:
        cur = self._state[rid]
        if state is cur:
            return
        if state not in _LEASE_ALLOWED[cur]:
            raise ValueError(f"replica {rid}: illegal lease transition "
                             f"{cur.value} -> {state.value} ({reason})")
        self._state[rid] = state
        self.history.append((rid, cur, state, ts, reason))
        if self.recorder is not None:
            self.recorder.note_state(f"ctrl/lease/replica/{rid}",
                                     f"ctrl/lease/{state.value}", ts,
                                     attrs={"reason": reason,
                                            "epoch": self.epoch[rid]})
        logger.info(f"fleet lease: replica {rid} {cur.value} -> {state.value} "
                    f"({reason})")

    def _emit(self, name: str, value: float) -> None:
        if self._emit_cb is not None:
            self._emit_cb(name, value)

    # ------------------------------------------------------------- signals

    def observe_heartbeat(self, rid: int, seq: int, state: str, stats: dict,
                          sent_ts: float, now: float,
                          generation: Optional[int] = None) -> str:
        """Fold one delivered heartbeat.  Returns what the router must do:

        * ``"ok"``         — lease renewed (SUSPECT heals back to ALIVE);
        * ``"stale"``      — an old/duplicate heartbeat (seq not newer):
          lease extended no further than its send time, view unchanged;
        * ``"zombie"``     — the heartbeat came from a replica the router
          declared fleet-dead: it must be FENCED before it may rejoin
          (``"zombie"`` is returned again for every further heartbeat
          until the fence acks — the router's retry timer, not this
          return value, paces the resends).
        """
        if seq <= self._last_seq[rid]:
            return "stale"
        self._last_seq[rid] = seq
        if generation is not None:
            self._generation[rid] = generation
        cur = self._state[rid]
        if cur in (LeaseState.DEAD, LeaseState.FENCING):
            # a fleet-dead replica is heartbeating again: either the
            # partition healed (zombie — its fenced work must be cancelled)
            # or a replacement engine attached (nothing to cancel; the
            # fence is a cheap no-op).  Either way it rejoins via the ack.
            if cur is LeaseState.DEAD:
                self._to(rid, LeaseState.FENCING, now, "heartbeat from the fleet-dead")
            # keep the freshest report visible for the eventual rejoin
            self._reported[rid] = state
            self._stats[rid] = stats
            self._stats_ts[rid] = now
            return "zombie"
        # the lease is measured from the heartbeat's SEND time: a delayed
        # heartbeat proves the replica was alive when it SENT, nothing more
        if sent_ts > self._last_hb[rid]:
            gap = sent_ts - self._last_hb[rid]
            a = self.config.interarrival_alpha
            prev = self._hb_gap_ewma[rid]
            self._hb_gap_ewma[rid] = round(gap if prev is None
                                           else (1.0 - a) * prev + a * gap, 9)
        self._last_hb[rid] = max(self._last_hb[rid], sent_ts)
        self._reported[rid] = state
        self._stats[rid] = stats
        self._stats_ts[rid] = now
        if cur is LeaseState.SUSPECT:
            self._to(rid, LeaseState.ALIVE, now, "heartbeat resumed")
            self._emit("fleet/lease_renewed", float(rid))
        return "ok"

    def tick(self, now: float) -> List[int]:
        """Advance the lease clocks: ALIVE -> SUSPECT at ``suspect_after``
        of silence, SUSPECT -> DEAD at ``lease``.  Returns the rids whose
        lease EXPIRED this tick — the router must re-dispatch their work
        (``Router.on_lease_expired``).  Epochs bump here: every dispatch
        made before this instant is fenced."""
        expired = []
        for rid in sorted(self._state):
            cur = self._state[rid]
            if cur not in (LeaseState.ALIVE, LeaseState.SUSPECT):
                continue
            suspect_after, lease = self.effective_lease(rid)
            silence = now - self._last_hb[rid]
            if silence >= lease:
                self._to(rid, LeaseState.DEAD, now,
                         f"lease expired ({silence:.3f}s of silence)")
                self.epoch[rid] += 1
                self._fence_sent_ts[rid] = None
                self._emit("fleet/lease_expired", float(rid))
                expired.append(rid)
            elif cur is LeaseState.ALIVE and silence >= suspect_after:
                self._to(rid, LeaseState.SUSPECT, now,
                         f"lease expiring ({silence:.3f}s of silence)")
                self._emit("fleet/lease_suspect", float(rid))
        return expired

    def declare_dead(self, rid: int, now: float,
                     reason: str = "router-observed death") -> None:
        """Direct death evidence — a device loss surfaced through a
        SYNCHRONOUS dispatch/staging RPC the router itself made — is as
        conclusive as a lease expiry and is recorded immediately, so the
        lease sweep does not declare (and double-account) the same death
        again when the silence catches up."""
        if self._state[rid] in (LeaseState.ALIVE, LeaseState.SUSPECT):
            self._to(rid, LeaseState.DEAD, now, reason)
            self.epoch[rid] += 1
            self._fence_sent_ts[rid] = None

    # -------------------------------------------------------------- fencing

    def fence_pending(self, now: float) -> List[int]:
        """Rids in FENCING whose fence must be (re)sent now — never sent,
        or the last send aged past ``fence_retry`` unacked (the fence/ack
        pair crosses the same lossy fabric as everything else)."""
        out = []
        for rid in sorted(self._state):
            if self._state[rid] is not LeaseState.FENCING:
                continue
            sent = self._fence_sent_ts[rid]
            if sent is None or now - sent >= self.config.fence_retry:
                out.append(rid)
        return out

    def note_fence_sent(self, rid: int, now: float) -> bool:
        """Record a fence send; returns True when it was the FIRST send of
        this fencing episode (the caller counts/emits once per episode)."""
        first = self._fence_sent_ts[rid] is None
        self._fence_sent_ts[rid] = now
        return first

    def on_fence_ack(self, rid: int, epoch: int, now: float) -> bool:
        """A replica acknowledged the fence for ``epoch``.  Stale-epoch
        acks (a reordered ack from a previous episode) are ignored.
        Returns True when the replica rejoined the fleet (ALIVE, lease
        re-granted from now)."""
        if self._state[rid] is not LeaseState.FENCING or epoch != self.epoch[rid]:
            return False
        self._last_hb[rid] = now
        self._fence_sent_ts[rid] = None
        self._to(rid, LeaseState.ALIVE, now, f"fence acked (epoch {epoch})")
        self._emit("fleet/lease_renewed", float(rid))
        return True

    # ------------------------------------------------------------- schedule

    def deadlines(self, now: float) -> List[float]:
        """Future instants at which this view can change by itself —
        suspect/expiry boundaries and fence-retry timers; the simulator's
        idle-jump input (a quiet fleet must still wake to expire a
        lease)."""
        out = []
        for rid, cur in self._state.items():
            suspect_after, lease = self.effective_lease(rid)
            if cur is LeaseState.ALIVE:
                out.append(self._last_hb[rid] + suspect_after)
                out.append(self._last_hb[rid] + lease)
            elif cur is LeaseState.SUSPECT:
                out.append(self._last_hb[rid] + lease)
            elif cur is LeaseState.FENCING:
                sent = self._fence_sent_ts[rid]
                out.append(now if sent is None
                           else sent + self.config.fence_retry)
            elif cur is LeaseState.DEAD:
                pass  # no self-scheduled wake-up: a rejoin is driven by the
                # zombie's own heartbeat, which is a delivery, not a timer
        return [t for t in out if t > now]

    def summary(self) -> dict:
        return {
            "states": {r: s.value for r, s in sorted(self._state.items())},
            "epochs": dict(sorted(self.epoch.items())),
            "transitions": len(self.history),
            "lease_resizes": len(self.resizes),
            "scales": {r: s for r, s in sorted(self._scale.items())
                       if s != 1.0},
        }


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"
    DEGRADED = "degraded"     # serving, but deprioritized for new dispatch
    DRAINING = "draining"     # serving in-flight work only (rolling restart)
    DEAD = "dead"             # gone: in-flight requests must fail over
    RECOVERING = "recovering" # fresh engine warming; probe ticks decide

    @property
    def serving(self) -> bool:
        """May this replica run ticks (in-flight work keeps moving)?"""
        return self in (ReplicaState.HEALTHY, ReplicaState.DEGRADED,
                        ReplicaState.DRAINING, ReplicaState.RECOVERING)

    @property
    def dispatchable(self) -> bool:
        """May the router hand this replica NEW work?"""
        return self in (ReplicaState.HEALTHY, ReplicaState.DEGRADED)


_ALLOWED = {
    ReplicaState.HEALTHY: {ReplicaState.DEGRADED, ReplicaState.DRAINING, ReplicaState.DEAD},
    ReplicaState.DEGRADED: {ReplicaState.HEALTHY, ReplicaState.DRAINING, ReplicaState.DEAD},
    ReplicaState.DRAINING: {ReplicaState.RECOVERING, ReplicaState.DEAD},
    ReplicaState.DEAD: {ReplicaState.RECOVERING},
    ReplicaState.RECOVERING: {ReplicaState.HEALTHY, ReplicaState.DEAD},
}


def classify_fatal(exc: BaseException) -> bool:
    """Device-loss classification, mirroring ``DSElasticAgent``'s: hung
    steps, injected/real device losses and simulated process death are
    fatal to the replica; plain transient ``OSError``\\ s are not."""
    from ...resilience.fault_injection import DeviceLossError, InjectedCrash
    from ...resilience.watchdog import StepHungError
    if isinstance(exc, (DeviceLossError, StepHungError, InjectedCrash)):
        return True
    return "DEVICE_LOST" in str(exc)


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    # consecutive transient errors before HEALTHY degrades
    degrade_after: int = 1
    # consecutive transient errors before a (degraded) replica is declared
    # dead — repeated I/O failure on every tick is indistinguishable from a
    # lost host to the fleet
    dead_after: int = 3
    # consecutive successful ticks for DEGRADED to heal back to HEALTHY
    heal_after: int = 2
    # successful probe ticks for RECOVERING to graduate to HEALTHY
    recover_probe_ticks: int = 1


class HealthTracker:
    """Per-replica health states + validated transitions for one fleet."""

    def __init__(self, replica_ids, config: HealthConfig = None,
                 emit: Optional[Callable[[str, float], None]] = None,
                 clock=None):
        self.config = config or HealthConfig()
        self._emit = emit
        self._clock = clock
        self._state: Dict[int, ReplicaState] = {r: ReplicaState.HEALTHY for r in replica_ids}
        self._errors: Dict[int, int] = {r: 0 for r in replica_ids}      # consecutive
        self._successes: Dict[int, int] = {r: 0 for r in replica_ids}   # consecutive
        #: (rid, from, to, ts, reason) — the auditable failover timeline
        self.history: List[Tuple[int, ReplicaState, ReplicaState, float, str]] = []

    # ------------------------------------------------------------- queries

    def state(self, rid: int) -> ReplicaState:
        return self._state[rid]

    def serving(self, rid: int) -> bool:
        return self._state[rid].serving

    def dispatchable(self, rid: int) -> bool:
        return self._state[rid].dispatchable

    def replicas_in(self, *states: ReplicaState) -> List[int]:
        return sorted(r for r, s in self._state.items() if s in states)

    # --------------------------------------------------------- transitions

    def _now(self) -> float:
        return self._clock.now() if self._clock is not None else 0.0

    def _to(self, rid: int, state: ReplicaState, reason: str) -> None:
        cur = self._state[rid]
        if state is cur:
            return
        if state not in _ALLOWED[cur]:
            raise ValueError(f"replica {rid}: illegal health transition "
                             f"{cur.value} -> {state.value} ({reason})")
        ts = self._now()
        self._state[rid] = state
        self._errors[rid] = 0
        self._successes[rid] = 0
        self.history.append((rid, cur, state, ts, reason))
        logger.info(f"fleet health: replica {rid} {cur.value} -> {state.value} ({reason})")
        if self._emit is not None:
            self._emit(f"fleet/health/{state.value}", float(rid))

    # ------------------------------------------------------------- signals

    def record_success(self, rid: int) -> None:
        """One successful tick: heals DEGRADED after a streak, graduates
        RECOVERING after its probe quota."""
        self._errors[rid] = 0
        self._successes[rid] += 1
        cur = self._state[rid]
        if cur is ReplicaState.DEGRADED and self._successes[rid] >= self.config.heal_after:
            self._to(rid, ReplicaState.HEALTHY, "success streak")
        elif cur is ReplicaState.RECOVERING and \
                self._successes[rid] >= self.config.recover_probe_ticks:
            self._to(rid, ReplicaState.HEALTHY, "probe ticks passed")

    def record_error(self, rid: int, exc: BaseException) -> ReplicaState:
        """Classify one tick failure; returns the resulting state (the pool
        checks for DEAD to trigger failover)."""
        if classify_fatal(exc):
            if self._state[rid] is ReplicaState.RECOVERING:
                self._to(rid, ReplicaState.DEAD, f"probe failure: {exc}")
            else:
                self._to(rid, ReplicaState.DEAD, f"device loss: {exc}")
            return self._state[rid]
        self._successes[rid] = 0
        self._errors[rid] += 1
        cur = self._state[rid]
        if cur is ReplicaState.RECOVERING:
            # transient errors during the probe: the fresh engine cannot even
            # tick — treat as a failed recovery, don't oscillate
            self._to(rid, ReplicaState.DEAD, f"probe failure: {exc}")
        elif self._errors[rid] >= self.config.dead_after:
            self._to(rid, ReplicaState.DEAD,
                     f"{self._errors[rid]} consecutive transient errors")
        elif cur is ReplicaState.HEALTHY and self._errors[rid] >= self.config.degrade_after:
            self._to(rid, ReplicaState.DEGRADED, f"transient error: {exc}")
        return self._state[rid]

    def kill(self, rid: int, reason: str = "killed") -> None:
        """Operator/simulator-declared replica loss."""
        self._to(rid, ReplicaState.DEAD, reason)

    def drain(self, rid: int) -> None:
        """Stop new dispatches; in-flight work finishes (rolling restart)."""
        self._to(rid, ReplicaState.DRAINING, "drain requested")

    def recovering(self, rid: int, reason: str = "fresh engine attached") -> None:
        """A replacement engine is attached (from DEAD, or from a drained
        DRAINING replica being restarted)."""
        self._to(rid, ReplicaState.RECOVERING, reason)
