"""Replica health state machine: HEALTHY → DEGRADED → DEAD → RECOVERING.

The fleet router must keep dispatching while individual replicas misbehave,
so every replica carries an explicit health state the router's policies
consult (``dispatchable``) and the pool's failover path keys off
(``serving``).  Signals come from the resilience layer the training side
already uses: transient ``OSError``\\ s degrade, repeated ones (or a
device-loss classification — :class:`~..resilience.watchdog.StepHungError`,
:class:`~..resilience.fault_injection.DeviceLossError`, any error whose
message carries the ``DEVICE_LOST`` marker, or
:class:`~..resilience.fault_injection.InjectedCrash`) kill.

::

    HEALTHY ──errors──▶ DEGRADED ──more errors──▶ DEAD
       ▲  ▲              │    │                    │
       │  └──successes───┘    └───────fatal────────┤
       │                                           ▼
       └───────── probe ticks ────────────── RECOVERING
                                                   │ (probe failure)
                                                   ▼
                                                  DEAD

    HEALTHY | DEGRADED ──drain()──▶ DRAINING ──restart──▶ RECOVERING
    (DRAINING keeps serving its in-flight work but receives no new
     dispatches; a kill during DRAINING still goes to DEAD)

Transitions are validated — an illegal one is a tracker bug and raises —
and every transition is recorded in ``history`` and emitted as a
``fleet/health/<state>`` monitor event, so a fleet sim's failover timeline
is auditable on the surface operators already watch.
"""

import dataclasses
import enum
from typing import Callable, Dict, List, Optional, Tuple

from ...utils.logging import logger


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"
    DEGRADED = "degraded"     # serving, but deprioritized for new dispatch
    DRAINING = "draining"     # serving in-flight work only (rolling restart)
    DEAD = "dead"             # gone: in-flight requests must fail over
    RECOVERING = "recovering" # fresh engine warming; probe ticks decide

    @property
    def serving(self) -> bool:
        """May this replica run ticks (in-flight work keeps moving)?"""
        return self in (ReplicaState.HEALTHY, ReplicaState.DEGRADED,
                        ReplicaState.DRAINING, ReplicaState.RECOVERING)

    @property
    def dispatchable(self) -> bool:
        """May the router hand this replica NEW work?"""
        return self in (ReplicaState.HEALTHY, ReplicaState.DEGRADED)


_ALLOWED = {
    ReplicaState.HEALTHY: {ReplicaState.DEGRADED, ReplicaState.DRAINING, ReplicaState.DEAD},
    ReplicaState.DEGRADED: {ReplicaState.HEALTHY, ReplicaState.DRAINING, ReplicaState.DEAD},
    ReplicaState.DRAINING: {ReplicaState.RECOVERING, ReplicaState.DEAD},
    ReplicaState.DEAD: {ReplicaState.RECOVERING},
    ReplicaState.RECOVERING: {ReplicaState.HEALTHY, ReplicaState.DEAD},
}


def classify_fatal(exc: BaseException) -> bool:
    """Device-loss classification, mirroring ``DSElasticAgent``'s: hung
    steps, injected/real device losses and simulated process death are
    fatal to the replica; plain transient ``OSError``\\ s are not."""
    from ...resilience.fault_injection import DeviceLossError, InjectedCrash
    from ...resilience.watchdog import StepHungError
    if isinstance(exc, (DeviceLossError, StepHungError, InjectedCrash)):
        return True
    return "DEVICE_LOST" in str(exc)


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    # consecutive transient errors before HEALTHY degrades
    degrade_after: int = 1
    # consecutive transient errors before a (degraded) replica is declared
    # dead — repeated I/O failure on every tick is indistinguishable from a
    # lost host to the fleet
    dead_after: int = 3
    # consecutive successful ticks for DEGRADED to heal back to HEALTHY
    heal_after: int = 2
    # successful probe ticks for RECOVERING to graduate to HEALTHY
    recover_probe_ticks: int = 1


class HealthTracker:
    """Per-replica health states + validated transitions for one fleet."""

    def __init__(self, replica_ids, config: HealthConfig = None,
                 emit: Optional[Callable[[str, float], None]] = None,
                 clock=None):
        self.config = config or HealthConfig()
        self._emit = emit
        self._clock = clock
        self._state: Dict[int, ReplicaState] = {r: ReplicaState.HEALTHY for r in replica_ids}
        self._errors: Dict[int, int] = {r: 0 for r in replica_ids}      # consecutive
        self._successes: Dict[int, int] = {r: 0 for r in replica_ids}   # consecutive
        #: (rid, from, to, ts, reason) — the auditable failover timeline
        self.history: List[Tuple[int, ReplicaState, ReplicaState, float, str]] = []

    # ------------------------------------------------------------- queries

    def state(self, rid: int) -> ReplicaState:
        return self._state[rid]

    def serving(self, rid: int) -> bool:
        return self._state[rid].serving

    def dispatchable(self, rid: int) -> bool:
        return self._state[rid].dispatchable

    def replicas_in(self, *states: ReplicaState) -> List[int]:
        return sorted(r for r, s in self._state.items() if s in states)

    # --------------------------------------------------------- transitions

    def _now(self) -> float:
        return self._clock.now() if self._clock is not None else 0.0

    def _to(self, rid: int, state: ReplicaState, reason: str) -> None:
        cur = self._state[rid]
        if state is cur:
            return
        if state not in _ALLOWED[cur]:
            raise ValueError(f"replica {rid}: illegal health transition "
                             f"{cur.value} -> {state.value} ({reason})")
        ts = self._now()
        self._state[rid] = state
        self._errors[rid] = 0
        self._successes[rid] = 0
        self.history.append((rid, cur, state, ts, reason))
        logger.info(f"fleet health: replica {rid} {cur.value} -> {state.value} ({reason})")
        if self._emit is not None:
            self._emit(f"fleet/health/{state.value}", float(rid))

    # ------------------------------------------------------------- signals

    def record_success(self, rid: int) -> None:
        """One successful tick: heals DEGRADED after a streak, graduates
        RECOVERING after its probe quota."""
        self._errors[rid] = 0
        self._successes[rid] += 1
        cur = self._state[rid]
        if cur is ReplicaState.DEGRADED and self._successes[rid] >= self.config.heal_after:
            self._to(rid, ReplicaState.HEALTHY, "success streak")
        elif cur is ReplicaState.RECOVERING and \
                self._successes[rid] >= self.config.recover_probe_ticks:
            self._to(rid, ReplicaState.HEALTHY, "probe ticks passed")

    def record_error(self, rid: int, exc: BaseException) -> ReplicaState:
        """Classify one tick failure; returns the resulting state (the pool
        checks for DEAD to trigger failover)."""
        if classify_fatal(exc):
            if self._state[rid] is ReplicaState.RECOVERING:
                self._to(rid, ReplicaState.DEAD, f"probe failure: {exc}")
            else:
                self._to(rid, ReplicaState.DEAD, f"device loss: {exc}")
            return self._state[rid]
        self._successes[rid] = 0
        self._errors[rid] += 1
        cur = self._state[rid]
        if cur is ReplicaState.RECOVERING:
            # transient errors during the probe: the fresh engine cannot even
            # tick — treat as a failed recovery, don't oscillate
            self._to(rid, ReplicaState.DEAD, f"probe failure: {exc}")
        elif self._errors[rid] >= self.config.dead_after:
            self._to(rid, ReplicaState.DEAD,
                     f"{self._errors[rid]} consecutive transient errors")
        elif cur is ReplicaState.HEALTHY and self._errors[rid] >= self.config.degrade_after:
            self._to(rid, ReplicaState.DEGRADED, f"transient error: {exc}")
        return self._state[rid]

    def kill(self, rid: int, reason: str = "killed") -> None:
        """Operator/simulator-declared replica loss."""
        self._to(rid, ReplicaState.DEAD, reason)

    def drain(self, rid: int) -> None:
        """Stop new dispatches; in-flight work finishes (rolling restart)."""
        self._to(rid, ReplicaState.DRAINING, "drain requested")

    def recovering(self, rid: int, reason: str = "fresh engine attached") -> None:
        """A replacement engine is attached (from DEAD, or from a drained
        DRAINING replica being restarted)."""
        self._to(rid, ReplicaState.RECOVERING, reason)
