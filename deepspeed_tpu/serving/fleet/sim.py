"""Deterministic fleet simulator: arrivals + a scripted fault schedule.

Drives a :class:`~.router.Router` + :class:`~.pool.ReplicaPool` on the
pool's ONE shared clock in discrete *rounds* that model the fleet's
replicas stepping concurrently (``VirtualClock``: deterministic CPU
simulation; ``WallClock``: the same loop with real time — bench wall mode
reuses it rather than re-implementing the round structure):

  1. apply due schedule events (kill / recover / drain / restart);
  2. submit due arrivals, time out expired pending work, dispatch;
  3. tick every serving-capable replica once (each records its step cost
     into its :class:`~..clock.ReplicaClockView` instead of advancing);
  4. advance the shared clock by the MAX recorded cost — the round takes
     as long as its slowest replica, not the sum (that is what makes a
     4-replica fleet 4x the throughput of 1 in the simulation, as in
     life);
  5. fold per-replica completions up into fleet terminal states.

Everything is seeded/ordered deterministically (sorted replica order,
list-ordered arrivals and schedule, greedy decode), so the same inputs
produce bit-identical outputs on every run and machine — the property the
``bench_router.py --dryrun`` artifact and the chaos tests pin.

Token timestamps within a round are stamped at round START (the shared
clock advances only at step 4); latencies are therefore quantized to
round granularity — consistent across policies and replica counts, which
is what the comparisons need.

Schedule entries: ``(ts, action, rid)`` with action one of ``kill``,
``recover``, ``drain``, ``restart``.  ``restart`` of a DRAINING replica
defers until the replica is idle — the point of draining is that nothing
in flight is lost.
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from .health import ReplicaState
from .router import Router

_ACTIONS = ("kill", "recover", "drain", "restart")


# ------------------------------------------------------------------ workloads
#
# Seeded arrival generators for the fleet benches and tests.  All of them
# return Router.submit() kwarg dicts (with ``arrival_ts``) and are pure
# functions of their seed: same seed, bit-identical workload on every
# machine (np.random.default_rng is a seeded instance, so runs are
# deterministic and dslint's global-RNG rule stays satisfied).


def poisson_mixed_arrivals(seed: int, n_requests: int, rate: float, vocab: int,
                           short_len: int = 8, long_len: int = 96,
                           long_frac: float = 0.25,
                           short_new: int = 12, long_new: int = 12,
                           deadline_slack: Optional[float] = None) -> List[dict]:
    """Mixed long-prompt/short-prompt Poisson traffic — the workload
    prefill/decode disaggregation exists for: a minority of LONG prompts
    (``long_frac``) whose chunked prefills head-of-line-block every short
    request's decode steps on a monolithic replica.  Lengths jitter ±25%
    around their class mean so no two long prompts are identical.
    ``deadline_slack``: optional deadline = arrival + slack (None = no
    deadline — every request runs to completion, the shape divergence
    audits need)."""
    rng = np.random.default_rng(seed)
    t = 0.0
    arrivals = []
    for _ in range(n_requests):
        t += float(rng.exponential(1.0 / rate))
        is_long = bool(rng.random() < long_frac)
        mean_len = long_len if is_long else short_len
        p_len = max(2, int(rng.integers(int(mean_len * 0.75),
                                        int(mean_len * 1.25) + 1)))
        arrivals.append({
            "arrival_ts": round(t, 6),
            "prompt": [int(x) for x in rng.integers(1, vocab, p_len)],
            "max_new_tokens": int(long_new if is_long else short_new),
            "deadline": None if deadline_slack is None
            else round(t + deadline_slack, 6),
        })
    return arrivals


def heavy_tail_arrivals(seed: int, n_requests: int, rate: float, vocab: int,
                        prompt_median: int = 12, prompt_sigma: float = 0.8,
                        tail_frac: float = 0.1, tail_alpha: float = 1.2,
                        tail_scale: int = 32, max_prompt: int = 192,
                        out_median: int = 8, out_sigma: float = 0.5,
                        max_new: int = 24,
                        deadline_slack: Optional[float] = None) -> List[dict]:
    """Heavy-tailed production-shaped traffic: lognormal prompt/output
    length bodies with a Pareto(``tail_alpha``) prompt tail mixed in at
    ``tail_frac`` — the occasional pathological context that dominates
    p99s (alpha < 2: infinite-variance territory, clipped at
    ``max_prompt`` to the engine's geometry)."""
    rng = np.random.default_rng(seed)
    t = 0.0
    arrivals = []
    for _ in range(n_requests):
        t += float(rng.exponential(1.0 / rate))
        if rng.random() < tail_frac:
            p_len = int(tail_scale * float(rng.pareto(tail_alpha) + 1.0))
        else:
            p_len = int(rng.lognormal(np.log(prompt_median), prompt_sigma))
        p_len = int(np.clip(p_len, 2, max_prompt))
        o_len = int(np.clip(rng.lognormal(np.log(out_median), out_sigma),
                            2, max_new))
        arrivals.append({
            "arrival_ts": round(t, 6),
            "prompt": [int(x) for x in rng.integers(1, vocab, p_len)],
            "max_new_tokens": o_len,
            "deadline": None if deadline_slack is None
            else round(t + deadline_slack, 6),
        })
    return arrivals


def flash_crowd_arrivals(seed: int, n_requests: int, base_rate: float,
                         crowd_rate: float, crowd_start: float,
                         crowd_duration: float, vocab: int,
                         tenants: Optional[List[Tuple[str, float,
                                                      Optional[float]]]] = None,
                         prompt_median: int = 8, prompt_sigma: float = 0.5,
                         max_prompt: int = 64,
                         out_median: int = 10, out_sigma: float = 0.4,
                         max_new: int = 24) -> List[dict]:
    """Flash-crowd traffic with a tenant mix: Poisson arrivals at
    ``base_rate`` that spike to ``crowd_rate`` inside the window
    ``[crowd_start, crowd_start + crowd_duration)`` — the viral-moment
    shape the autoscaler + degradation ladder exist for.  ``tenants`` is a
    list of ``(name, mix_probability, deadline_slack_or_None)``; each
    arrival draws its tenant from the mix and gets ``deadline = arrival +
    slack`` (None = best-effort, runs to completion).  Deterministic in
    ``seed`` like every generator here."""
    rng = np.random.default_rng(seed)
    tenants = tenants or [("default", 1.0, None)]
    probs = np.asarray([t[1] for t in tenants], np.float64)
    probs = probs / probs.sum()
    t = 0.0
    arrivals = []
    crowd_end = crowd_start + crowd_duration
    for _ in range(n_requests):
        # piecewise-inhomogeneous Poisson: a gap that would cross a rate
        # boundary is re-drawn AT the boundary at the new rate (exactly
        # valid by memorylessness) — without this, one long base-rate gap
        # can jump clean over the whole crowd window
        while True:
            in_crowd = crowd_start <= t < crowd_end
            rate = crowd_rate if in_crowd else base_rate
            gap = float(rng.exponential(1.0 / rate))
            boundary = crowd_start if t < crowd_start \
                else (crowd_end if t < crowd_end else None)
            if boundary is not None and t + gap > boundary:
                t = boundary
                continue
            t += gap
            break
        i = int(rng.choice(len(tenants), p=probs))
        name, _, slack = tenants[i]
        p_len = int(np.clip(rng.lognormal(np.log(prompt_median), prompt_sigma),
                            2, max_prompt))
        o_len = int(np.clip(rng.lognormal(np.log(out_median), out_sigma),
                            2, max_new))
        arrivals.append({
            "arrival_ts": round(t, 6),
            "prompt": [int(x) for x in rng.integers(1, vocab, p_len)],
            "max_new_tokens": o_len,
            "deadline": None if slack is None else round(t + slack, 6),
            "tenant": name,
        })
    return arrivals


def diurnal_arrivals(seed: int, n_requests: int, base_rate: float,
                     amplitude: float, period: float, vocab: int,
                     phase: float = 0.0,
                     prefixes: Optional[List[List[int]]] = None,
                     prompt_median: int = 8, prompt_sigma: float = 0.5,
                     max_prompt: int = 64,
                     out_median: int = 10, out_sigma: float = 0.4,
                     max_new: int = 24,
                     deadline_slack: Optional[float] = None) -> List[dict]:
    """Diurnal sinusoid traffic: Poisson arrivals whose rate swings
    ``base_rate * (1 + amplitude * sin(2*pi*t/period))`` — the daily
    peak/trough shape planet-scale fleets provision for (the autoscale
    ROADMAP follow-on to the one-off ``flash_crowd_arrivals`` spike).
    Generated by THINNING, the piecewise-exact sibling of the flash
    crowd's boundary re-draw: candidate gaps are drawn at the PEAK rate
    and each candidate is kept with probability ``rate(t)/peak`` — exact
    for a smooth rate function, no discretization grid, and deterministic
    in ``seed`` like every generator here.

    ``phase`` (radians) shifts where in the cycle t=0 lands — ``-pi/2``
    starts at the trough, the natural 'day starts quiet' shape (and what
    lets caches warm before the first peak).  ``prefixes``: optional
    shared page-aligned prompt prefixes (system prompts / few-shot
    templates); each arrival draws one group uniformly and prepends it —
    the traffic shape prefix-directory routing exists for.
    ``deadline_slack``: deadline = arrival + slack (None = run to
    completion, as the divergence audits need)."""
    assert 0.0 <= amplitude < 1.0, amplitude
    rng = np.random.default_rng(seed)
    peak = base_rate * (1.0 + amplitude)
    t = 0.0
    arrivals = []
    for _ in range(n_requests):
        while True:
            t += float(rng.exponential(1.0 / peak))
            rate = base_rate * (1.0 + amplitude * np.sin(
                2.0 * np.pi * t / period + phase))
            if rng.random() < rate / peak:
                break
        p_len = int(np.clip(rng.lognormal(np.log(prompt_median), prompt_sigma),
                            2, max_prompt))
        o_len = int(np.clip(rng.lognormal(np.log(out_median), out_sigma),
                            2, max_new))
        prompt = [int(x) for x in rng.integers(1, vocab, p_len)]
        if prefixes:
            prompt = list(prefixes[int(rng.integers(0, len(prefixes)))]) + prompt
        arrivals.append({
            "arrival_ts": round(t, 6),
            "prompt": prompt,
            "max_new_tokens": o_len,
            "deadline": None if deadline_slack is None
            else round(t + deadline_slack, 6),
        })
    return arrivals


def session_arrivals(seed: int, n_sessions: int, vocab: int,
                     rate: Optional[float] = None,
                     turns_min: int = 2, turns_max: int = 4,
                     user_median: int = 12, user_sigma: float = 0.4,
                     max_user: int = 48,
                     new_median: int = 10, new_sigma: float = 0.3,
                     min_new: int = 4, max_new: int = 24,
                     think_median: float = 4.0, think_sigma: float = 0.6,
                     max_think: float = 60.0,
                     stall_prob: float = 0.35,
                     stall_at: Optional[Tuple[int, ...]] = None,
                     stall_median: float = 3.0, stall_sigma: float = 0.5,
                     max_stall: float = 30.0, tool_len: int = 6) -> List[dict]:
    """Agentic multi-turn session specs — the workload shape production
    serving actually sees (ROADMAP "Scenario diversity"): sessions x
    turns x lognormal think times x tool-stall probability, all seeded.
    Consumed by the :mod:`~..sessions` drivers (``SessionManager`` for
    one engine, ``FleetSessionCoordinator`` for a fleet) rather than
    submitted directly: sessions are CLOSED-LOOP — turn N+1's arrival is
    turn N's completion plus think time, and its prompt is the session's
    full transcript, neither knowable up front.

    Each element::

        {"sid": int, "start_ts": float, "turns": [
            {"user_tokens": [...], "max_new_tokens": int,
             "think_s": float,
             "stalls": [{"at_tokens": int, "stall_s": float,
                         "tool_tokens": [...]}, ...]}, ...]}

    ``rate``: Poisson session-start rate; None starts every session at
    t=0 (the resident-capacity shape ``bench_serving --kv-tier`` uses).
    ``stall_prob``: per-turn probability of ONE mid-generation tool
    stall at a seeded token offset; ``stall_at`` instead fires a stall
    at each of the given FIXED offsets in every turn (the deterministic
    bench shape — the r22 kv-tier leg is ``turns_min=turns_max=1,
    stall_at=(7, 14)``).  ``tool_len=0`` makes tool results empty (a
    pure pause, transcript unchanged).  Sigma-zero lognormals pin any
    length/duration to its median exactly.  Deterministic in ``seed``
    like every generator here."""
    assert 1 <= turns_min <= turns_max
    rng = np.random.default_rng(seed)
    t = 0.0
    sessions = []
    for sid in range(n_sessions):
        if rate is not None:
            t += float(rng.exponential(1.0 / rate))
        n_turns = int(rng.integers(turns_min, turns_max + 1))
        turns = []
        for _ in range(n_turns):
            u_len = int(np.clip(rng.lognormal(np.log(user_median), user_sigma),
                                2, max_user))
            o_len = int(np.clip(rng.lognormal(np.log(new_median), new_sigma),
                                min_new, max_new))
            think = round(float(np.clip(
                rng.lognormal(np.log(think_median), think_sigma),
                0.1, max_think)), 6)
            if stall_at is not None:
                offsets = [a for a in stall_at if a < o_len]
            else:
                offsets = ([int(rng.integers(2, max(3, o_len - 1)))]
                           if rng.random() < stall_prob else [])
            stalls = []
            for at in offsets:
                stalls.append({
                    "at_tokens": int(at),
                    "stall_s": round(float(np.clip(
                        rng.lognormal(np.log(stall_median), stall_sigma),
                        0.1, max_stall)), 6),
                    "tool_tokens": [int(x)
                                    for x in rng.integers(1, vocab, tool_len)],
                })
            turns.append({
                "user_tokens": [int(x) for x in rng.integers(1, vocab, u_len)],
                "max_new_tokens": o_len,
                "think_s": think,
                "stalls": stalls,
            })
        sessions.append({"sid": sid, "start_ts": round(t, 6), "turns": turns})
    return sessions


@dataclasses.dataclass(frozen=True)
class FleetEvent:
    ts: float
    action: str
    rid: int

    def __post_init__(self):
        assert self.action in _ACTIONS, f"unknown fleet event action '{self.action}'"


class FleetSimulator:

    def __init__(self, router: Router, max_rounds: int = 200_000,
                 autoscaler=None, controller=None):
        self.router = router
        self.pool = router.pool
        self.clock = router.clock
        #: optional closed-loop workload controller (duck-typed:
        #: ``pending() -> bool``, ``poll(now)`` submits work due now,
        #: ``next_wake(now) -> Optional[ts]`` joins the stall-guard wait
        #: list, ``marker()`` joins the progress signature).  The sessions
        #: ``FleetSessionCoordinator`` is the canonical one: open-loop
        #: ``arrivals`` can be listed up front, but a session's turn N+1
        #: arrives at turn N's completion + think time — only a controller
        #: polled inside the round loop can submit it.
        self.controller = controller
        # VirtualClock: deterministic rounds, time advances by max recorded
        # cost.  WallClock: the same round structure with real time (ticks
        # advance the clock themselves and there are no cost views to
        # drain, so the advance step below never fires), letting wall-mode
        # drivers reuse — instead of drift from — this loop.
        self.max_rounds = max_rounds
        self.rounds = 0
        # control plane (fleet/autoscale.py): stepped once per round,
        # BEFORE arrivals/dispatch, so a scale decision made from last
        # round's signals shapes this round's placement
        self.autoscaler = autoscaler
        #: provisioning cost receipts: ``replica_steps`` counts one unit
        #: per provisioned (non-DEAD) replica per WORKING round — the
        #: quantity static-max vs autoscaled provisioning is compared on;
        #: ``replica_seconds`` integrates provisioned count over clock
        #: time (idle waits included — a provisioned-but-idle replica
        #: still costs money)
        self.replica_steps = 0
        self.replica_seconds = 0.0

    def run(self, arrivals: List[dict],
            schedule: Optional[List[Tuple[float, str, int]]] = None) -> List:
        """``arrivals``: router ``submit()`` kwarg dicts, each with an
        ``arrival_ts``.  ``schedule``: ``(ts, action, rid)`` tuples.  Runs
        rounds until all arrivals are submitted, all schedule events
        applied, and every request is terminal.  Returns the
        ``FleetRequest`` objects in arrival order."""
        router, pool, clock = self.router, self.pool, self.clock
        pending_arrivals = sorted(arrivals, key=lambda a: (a["arrival_ts"],))
        events = sorted([e if isinstance(e, FleetEvent) else FleetEvent(*e)
                         for e in (schedule or [])], key=lambda e: (e.ts,))
        deferred_restarts: List[int] = []
        reqs = []
        a_i = e_i = 0

        for _ in range(self.max_rounds):
            self.rounds += 1
            now = clock.now()

            # 1. scripted fleet events due now
            while e_i < len(events) and events[e_i].ts <= now:
                ev = events[e_i]
                e_i += 1
                self._apply(ev, deferred_restarts)
            for rid in list(deferred_restarts):
                if pool.health.state(rid) is not ReplicaState.DRAINING:
                    # killed (or otherwise transitioned) while waiting to
                    # drain: the restart is moot — recovery owns it now
                    deferred_restarts.remove(rid)
                elif pool.is_idle(rid):
                    deferred_restarts.remove(rid)
                    pool.restart(rid)

            # 1.4 control-plane transport: drain due message deliveries
            # (heartbeats, publishes, fences, chunks), sweep the leases,
            # run the fence/resync retry timers — BEFORE dispatch, so this
            # round's placement sees the freshest view the fabric allows
            router.transport_poll(now)

            # 1.5 control plane: the autoscaler reads last round's signals
            # and acts (recover/drain/park, ladder moves) before this
            # round's dispatch sees the fleet
            if self.autoscaler is not None:
                self.autoscaler.step(now)

            # 2. arrivals + dispatch (a controller's closed-loop arrivals —
            # session turns due now — are polled in the same window, so
            # they see the same dispatch the open-loop arrivals do)
            while a_i < len(pending_arrivals) and \
                    pending_arrivals[a_i]["arrival_ts"] <= now:
                reqs.append(router.submit(**pending_arrivals[a_i]))
                a_i += 1
            if self.controller is not None:
                self.controller.poll(now)
            router.dispatch_pending(now)

            # 3. one concurrent tick across the fleet
            marker = self._marker(a_i, e_i)
            n_provisioned = sum(1 for rid in pool.rids
                                if pool.health.state(rid) is not ReplicaState.DEAD)
            costs = []
            for rid in pool.rids:
                if not pool.health.serving(rid):
                    continue
                _out, victims = pool.tick(rid)
                if victims and router.transport is None:
                    # perfect observation: the router learns of the death
                    # instantly.  Under the transport it must NOT — the
                    # replica simply stops heartbeating and the router's
                    # lease machinery diagnoses the silence (the victims'
                    # fleet records re-home at lease expiry, tokens intact)
                    router.on_replica_dead(rid, reason="health-declared death")
                view = pool.replica(rid).clock
                cost = view.take_cost() if hasattr(view, "take_cost") else 0.0
                if cost > 0:
                    costs.append(cost)

            # 4. the round took as long as its slowest replica
            if costs:
                clock.advance(max(costs))
                # provisioning receipt: every non-DEAD replica billed one
                # step for this working round (parked replicas are free —
                # the saving the autoscale bench measures)
                self.replica_steps += n_provisioned

            # 4.5 per-round observability: replica load_stats gauges (and
            # the serving-count/rung gauges) — no-op without a registry
            router.export_replica_gauges()

            # 5. completions
            router.poll(clock.now())
            self.replica_seconds += (clock.now() - now) * n_provisioned

            if a_i >= len(pending_arrivals) and e_i >= len(events) \
                    and not deferred_restarts and router.outstanding == 0 \
                    and (self.controller is None
                         or not self.controller.pending()):
                if self.autoscaler is not None:
                    self.autoscaler.finalize(clock.now())
                return reqs

            if not costs and self._marker(a_i, e_i) == marker:
                # nothing moved: only the passage of time can help — jump to
                # the next known event, or fail loudly instead of spinning
                waits = router.pending_timestamps()
                # control-plane wake-ups: in-flight deliveries, partition
                # boundaries, lease deadlines, fence/resync retries — a
                # quiet fleet must still wake to expire a lease or see a
                # partition heal (empty without a transport)
                waits.extend(router.control_timestamps(clock.now()))
                if a_i < len(pending_arrivals):
                    waits.append(pending_arrivals[a_i]["arrival_ts"])
                if e_i < len(events):
                    waits.append(events[e_i].ts)
                if self.autoscaler is not None:
                    wake = self.autoscaler.wake_ts(clock.now())
                    if wake is not None:
                        waits.append(wake)
                if self.controller is not None:
                    # closed-loop wake-ups: think-time turn starts, tool-
                    # stall resumes, prefetch leads — a fleet whose every
                    # session is thinking must still wake to start the
                    # next turn
                    wake = self.controller.next_wake(clock.now())
                    if wake is not None:
                        waits.append(wake)
                if not waits:
                    raise RuntimeError(
                        f"fleet simulation stalled at t={now}: "
                        f"{router.outstanding} outstanding request(s), "
                        f"replicas {[(r, pool.health.state(r).value) for r in pool.rids]}, "
                        "no future arrival/schedule/deadline to wait for")
                t_jump = clock.now()
                clock.wait_until(min(waits) + 1e-9)
                self.replica_seconds += (clock.now() - t_jump) * n_provisioned
                if clock.now() > t_jump:
                    # idle jump: exclude it from every replica's step
                    # anatomy (idle is absent load, not step-loop tax —
                    # same stance as ServingEngine._note_idle)
                    for rid in pool.rids:
                        anat = pool.anatomy(rid)
                        if anat is not None:
                            anat.note_idle()
        raise RuntimeError(f"fleet simulation exceeded max_rounds={self.max_rounds}")

    def _apply(self, ev: FleetEvent, deferred_restarts: List[int]) -> None:
        pool, router = self.pool, self.router
        state = pool.health.state(ev.rid)
        if ev.action == "kill":
            if router.transport is not None:
                # a scheduled kill under the transport is a silent host
                # loss: the engine dies, heartbeats stop, and the ROUTER
                # finds out the only way a partitioned-or-dead replica can
                # be found out — its lease expires
                pool.kill(ev.rid, reason="scheduled kill")
            else:
                router.on_replica_dead(ev.rid, reason="scheduled kill")
        elif ev.action == "recover":
            if state is ReplicaState.DEAD:
                # via the router: a prefix directory triggers the
                # directory-driven warm-up (hottest chains pre-imported
                # while the replica is still RECOVERING)
                router.recover_replica(ev.rid)
            # recovering a live replica is a schedule no-op, not an error —
            # chaos schedules are random and may recover before the kill
        elif ev.action == "drain":
            if state.dispatchable:
                pool.drain(ev.rid)
        elif ev.action == "restart":
            if state is ReplicaState.DRAINING:
                if pool.is_idle(ev.rid):
                    pool.restart(ev.rid)
                else:
                    deferred_restarts.append(ev.rid)

    def _marker(self, a_i: int, e_i: int):
        router = self.router
        # engine-side seen_tokens is part of progress: a multi-chunk
        # prefill advances for whole rounds without delivering a token, and
        # on a WallClock there are no step costs to prove the round worked
        seen = sum(s.seen_tokens
                   for rep in self.pool.replicas.values() if rep.serve is not None
                   for s in rep.serve.engine.state.seqs.values())
        return (a_i, e_i, len(router.requests), router.outstanding,
                router.stats["dispatches"], router.stats["failovers"],
                # migration pump progress: export chunks advance no clock
                # and deliver no tokens, but they ARE progress — without
                # these the stall detector would fire mid-export on an
                # otherwise-idle fleet
                router.stats["migration_chunks"],
                router.stats["migrations_started"],
                router.stats["migration_fallbacks"],
                sum(len(r.tokens) for r in router.requests), seen,
                len(self.pool.health.history),
                # control-plane progress: scale decisions and ladder moves
                # advance no clock and deliver no tokens, but they ARE
                # progress (a recover this round changes next round)
                self.autoscaler.marker() if self.autoscaler is not None else None,
                # closed-loop controller progress: a session state change
                # (turn started, stall entered/resumed) advances no clock
                # and may deliver no tokens this round, but it IS progress
                self.controller.marker() if self.controller is not None else None,
                # transport control transitions (lease/fence/resync) — same
                # stance; raw send counters are deliberately excluded (see
                # Router.control_marker)
                router.control_marker())
