"""ReplicaPool: N ServingEngine replicas behind one fleet clock.

Each replica owns a FULL serving stack — its own ``InferenceEngineV2``
(weights, KV arena, prefix cache, scheduler) wrapped by its own
``ServingEngine`` — exactly the unit a real deployment replicates per
mesh/host.  The pool adds what a fleet needs around them:

* a shared clock: one ``VirtualClock`` fans out through per-replica
  :class:`~..clock.ReplicaClockView`\\ s so a deterministic CPU simulation
  models replicas stepping CONCURRENTLY (the fleet driver advances time
  once per round by the slowest replica's cost); a ``WallClock`` is shared
  directly (real time needs no view);
* a :class:`~.health.HealthTracker` fed from tick outcomes;
* ``kill()`` — abrupt replica loss: the engine object is dropped and every
  in-flight ``ServingRequest`` is returned to the caller (the router) for
  failover re-dispatch onto survivors;
* ``recover()``/``restart()`` — attach a FRESH engine from the factory
  (state RECOVERING until its probe ticks pass), modelling a replacement
  host joining the fleet or a drained replica rebooting.
"""

import dataclasses
import enum
from typing import Callable, Dict, List, Optional, Sequence, Union

from ...resilience.fault_injection import InjectedCrash
from ...utils.logging import logger
from ..clock import ReplicaClockView, VirtualClock
from ..engine import ServingConfig, ServingEngine
from ..request import ServingRequest
from .health import HealthConfig, HealthTracker, ReplicaState


class ReplicaRole(enum.Enum):
    """Disaggregation role of one replica (DistServe prefill–decode
    disaggregation / Splitwise phase splitting — docs/SERVING.md
    "Disaggregated serving").  The role is a ROUTING preference, not a
    capability bound: every replica runs the full serving stack, so a
    decode replica can absorb a whole request when the prefill pool is
    gone (availability beats specialization) and vice versa."""
    PREFILL = "prefill"   # admission + prompt processing; requests migrate out
    DECODE = "decode"     # resumes migrated KV; token generation
    MIXED = "mixed"       # the classic monolithic replica (default)


@dataclasses.dataclass
class Replica:
    rid: int
    serve: Optional[ServingEngine]      # None while DEAD (engine discarded)
    clock: object                       # ReplicaClockView or the shared clock
    generation: int = 0                 # bumps on every fresh engine attach
    role: ReplicaRole = ReplicaRole.MIXED  # survives kill/recover/restart


class ReplicaPool:

    def __init__(self, engine_factory: Callable[[], object], n_replicas: int,
                 clock=None, serving_config: ServingConfig = None, monitor=None,
                 health_config: HealthConfig = None, tracer=None, metrics=None,
                 roles: Optional[Sequence[Union[str, ReplicaRole]]] = None,
                 role_factories: Optional[Dict] = None,
                 prefix_directory=None, transport=None,
                 hb_interval: float = 0.5, anatomy: bool = False,
                 anatomy_max_steps: int = 4096, kv_tier=None):
        assert n_replicas >= 1, n_replicas
        if roles is not None and len(roles) != n_replicas:
            raise ValueError(f"roles ({len(roles)}) must cover every replica "
                             f"({n_replicas})")
        self.engine_factory = engine_factory
        # phase-specialized engine configs (Splitwise-style pool tuning):
        # a PREFILL pool might run smaller prefill chunks and a lean KV
        # arena (it holds prompts only transiently), a DECODE pool a large
        # arena for the fleet's whole resident decode set.  KV migration
        # only requires the PER-PAGE geometry (layers, page_size, kv heads,
        # head_dim, dtype) to match across pools — arena page COUNTS may
        # differ freely.  Factories are keyed by role and survive
        # kill/recover/restart (the role does).
        self.role_factories = {ReplicaRole(k): v
                               for k, v in (role_factories or {}).items()}
        self.serving_config = serving_config or ServingConfig()
        self.monitor = monitor
        # telemetry: ONE tracer/metrics registry spans the whole fleet —
        # every replica frontend traces onto its own track (replica<rid>)
        # of the same span stream, and a fresh engine attached by
        # recover()/restart() inherits them (observability survives the
        # replica, like the clock does)
        self.tracer = tracer
        self.metrics = metrics
        #: fleet flight recorder — back-filled by Router(recorder=...) so
        #: replacement engines from recover()/restart() inherit it exactly
        #: like the tracer (the replica-side ctrl/fence instant must not
        #: depend on a tracer being attached)
        self.recorder = None
        # fleet prefix directory (docs/SERVING.md "Prefix directory"): the
        # pool is its ONE publish edge — every attached engine's prefix
        # cache streams its chain digests through the listener bus, and
        # death/restart purge the replica's entries, so the router-side
        # table can never outlive the cache it mirrors by more than the
        # documented staleness ladder
        self.prefix_directory = prefix_directory
        if prefix_directory is not None and metrics is not None \
                and prefix_directory.metrics is None:
            prefix_directory.metrics = metrics
        # host KV tier (serving/kvtier): a TierConfig (or True for the
        # defaults) gives every attached engine its own TieredKVManager —
        # park/resume, demotion-first preemption, warm-on-host prefix
        # pages.  Per-replica like the engine itself: a kill drops the
        # host tier with the arena (same failure domain), and the
        # directory purge on death/attach forgets its host publishes too.
        self.kv_tier = kv_tier
        # control-plane transport (docs/SERVING.md "Control-plane
        # transport"): when attached, the replica-side control flows stop
        # being perfect in-process calls — each tick sends a
        # sequence-numbered HEARTBEAT (local health state + load_stats)
        # and prefix-cache publishes become a per-replica seq-numbered
        # DIR_PUBLISH stream the router gap-detects; None keeps every
        # pre-r16 direct path byte-for-byte unchanged
        self.transport = transport
        #: heartbeats are TIME-paced, not round-paced: at most one per
        #: replica per ``hb_interval`` of clock time (a real lease protocol
        #: beats on a timer, and a round-paced beat would flood the fabric
        #: on zero-advance simulator rounds).  Must sit well under the
        #: router's ``LeaseConfig.suspect_after`` or the fleet suspects
        #: itself between beats.
        self.hb_interval = float(hb_interval)
        self._hb_last: Dict[int, Optional[float]] = {r: None for r in range(n_replicas)}
        #: per-replica heartbeat / directory-publish sequence counters —
        #: POOL-level so they survive engine swaps (a recovered replica's
        #: stream continues, it does not restart at 1 and look reordered)
        self._hb_seq: Dict[int, int] = {r: 0 for r in range(n_replicas)}
        self._dir_seq: Dict[int, int] = {r: 0 for r in range(n_replicas)}
        #: highest fencing epoch each replica has EXECUTED — fences are
        #: idempotent per epoch, so a duplicated/retried FENCE delivered
        #: after the replica rejoined re-acks without cancelling the
        #: legitimately re-dispatched post-rejoin work
        self._fenced_epoch: Dict[int, int] = {r: 0 for r in range(n_replicas)}
        #: lifecycle-command dedup ledger: cmd seq -> ack status for every
        #: command this replica already applied — POOL-level so it survives
        #: engine swaps (the point: a retried/duplicated ``lifecycle_cmd``
        #: delivered after a recover/restart must RE-ACK its recorded
        #: outcome, never re-apply the mutation)
        self._lifecycle_seen: Dict[int, Dict[int, str]] = \
            {r: {} for r in range(n_replicas)}
        # per-replica step anatomy (telemetry/step_anatomy.py): each
        # attached engine gets its OWN recorder on the replica's clock
        # view (one time domain with the serving charges), recreated
        # across kill/recover/restart like the engine itself; the
        # steady-state boundary stays PER-RECORDER: a replacement engine
        # from recover()/restart() starts un-steady, because a fresh
        # replica MUST compile its step set — that is recovery, not a
        # regression (mark_anatomy_steady() re-declares after warm-up)
        self.anatomy_enabled = bool(anatomy)
        self.anatomy_max_steps = int(anatomy_max_steps)
        self.clock = clock if clock is not None else VirtualClock()
        self._virtual = isinstance(self.clock, VirtualClock)
        self.replicas: Dict[int, Replica] = {}
        self.health = HealthTracker(range(n_replicas), config=health_config,
                                    emit=self._emit, clock=self.clock)
        for rid in range(n_replicas):
            role = ReplicaRole(roles[rid]) if roles is not None else ReplicaRole.MIXED
            self.replicas[rid] = Replica(rid=rid, serve=None,
                                         clock=self._make_view(), role=role)
            self._attach_engine(rid)

    def _make_view(self):
        return ReplicaClockView(self.clock) if self._virtual else self.clock

    def _attach_engine(self, rid: int) -> None:
        rep = self.replicas[rid]
        factory = self.role_factories.get(rep.role, self.engine_factory)
        rep.serve = ServingEngine(factory(), clock=rep.clock,
                                  config=self.serving_config, monitor=self.monitor,
                                  tracer=self.tracer, metrics=self.metrics,
                                  trace_track=f"replica{rid}",
                                  recorder=self.recorder)
        rep.generation += 1
        if self.anatomy_enabled:
            from ...telemetry.step_anatomy import StepAnatomy
            rep.serve.engine.set_anatomy(
                StepAnatomy(clock=rep.clock,
                            max_steps=self.anatomy_max_steps))
        if self.prefix_directory is not None:
            # a fresh engine's cache is empty: stale entries from the
            # replica's previous life (rolling restart) must go first
            # (purge drops BOTH tiers — the old host tier died with the
            # old engine)
            self.prefix_directory.purge(rid)
            pc = rep.serve.engine.kv.prefix_cache
            if pc is not None:
                pc.listener = self._directory_listener(rid)
        if self.kv_tier is not None:
            from ..kvtier import TierConfig, TieredKVManager
            cfg = self.kv_tier if isinstance(self.kv_tier, TierConfig) else None
            tier = TieredKVManager(rep.serve.engine, config=cfg,
                                   metrics=self.metrics)
            rep.serve.attach_tier(tier)
            if self.prefix_directory is not None:
                # host-tier publishes ride the SAME seq-numbered stream as
                # the device publishes — one ordered feed per replica, so
                # a demote(evict device, publish host) pair can never be
                # applied out of order router-side
                tier.listener = self._host_directory_listener(rid)

    def _directory_listener(self, rid: int):
        """Publish edge replica -> directory.  A transient fault at the
        ``prefix.publish`` site drops THIS update (the directory goes
        stale — cold or warm — which the routing staleness ladder absorbs:
        a mis-routed dispatch recomputes, never corrupts); ``InjectedCrash``
        is driver death and propagates.

        With a control transport attached the publish stops being a direct
        table write: it becomes a sequence-numbered ``dir_publish`` message
        on this replica's stream, and the ROUTER applies it on delivery —
        a dropped message now leaves a detectable seq GAP (the router pulls
        a full-digest resync) instead of being silently absorbed."""
        directory = self.prefix_directory

        def on_event(event: str, digest: int) -> None:
            if self.transport is not None:
                self._dir_seq[rid] += 1
                self.transport.send("dir_publish", rid, "router",
                                    {"op": event, "digest": digest},
                                    seq=self._dir_seq[rid])
                return
            try:
                if event == "publish":
                    directory.publish(rid, digest)
                else:
                    directory.retract(rid, digest)
            except InjectedCrash:
                raise
            except OSError as e:
                logger.warning(f"fleet: prefix directory {event} dropped for "
                               f"replica {rid}: {e}")
        return on_event

    def _host_directory_listener(self, rid: int):
        """Publish edge kvtier -> directory host table: ``host_publish``
        when a demoted prefix page lands host-side, ``host_evict`` when it
        leaves (promoted back or evicted under host pressure).  Same
        fault stance and (with a transport) the same ordered per-replica
        ``dir_publish`` stream as the device-tier listener."""
        directory = self.prefix_directory

        def on_event(event: str, digest: int) -> None:
            if self.transport is not None:
                self._dir_seq[rid] += 1
                self.transport.send("dir_publish", rid, "router",
                                    {"op": event, "digest": digest},
                                    seq=self._dir_seq[rid])
                return
            try:
                if event == "host_publish":
                    directory.publish_host(rid, digest)
                else:
                    directory.retract_host(rid, digest)
            except InjectedCrash:
                raise
            except OSError as e:
                logger.warning(f"fleet: prefix directory {event} dropped "
                               f"for replica {rid}: {e}")
        return on_event

    # ------------------------------------------------------- control plane

    def _send_heartbeat(self, rid: int) -> None:
        """One lease renewal: the replica's local health state plus its
        current ``load_stats()`` snapshot — the router's ONLY evidence of
        this replica under the transport (docs/SERVING.md "Control-plane
        transport").  No-op without a transport (perfect observation)."""
        if self.transport is None:
            return
        rep = self.replicas[rid]
        if rep.serve is None:
            return
        now = self.clock.now()
        last = self._hb_last[rid]
        if last is not None and now - last < self.hb_interval:
            return
        self._hb_last[rid] = now
        self._hb_seq[rid] += 1
        self.transport.send(
            "heartbeat", rid, "router",
            {"state": self.health.state(rid).value,
             "stats": rep.serve.load_stats(),
             "generation": rep.generation},
            seq=self._hb_seq[rid])

    def dir_snapshot(self, rid: int) -> Optional[dict]:
        """Full-digest resync snapshot of this replica's prefix cache plus
        the publish-stream BARRIER (the last seq folded into the snapshot)
        — the router's gap repair: everything at/below the barrier is IN
        the snapshot, buffered stream entries above it apply after.
        None when the replica has no engine (a resync request raced its
        death; the router's retry finds the replacement)."""
        rep = self.replicas[rid]
        if rep.serve is None:
            return None
        pc = rep.serve.engine.kv.prefix_cache
        digests = pc.held_digests() if pc is not None else []
        snap = {"digests": digests, "barrier": self._dir_seq[rid]}
        tier = rep.serve.tier
        if tier is not None:
            snap["host_digests"] = tier.host.held_prefix_digests()
        return snap

    def fence_replica(self, rid: int, epoch: int = 0) -> Dict[str, int]:
        """Execute a FENCE on this replica: cancel every in-flight request
        its frontend still holds (a zombie that outlived its lease keeps
        decoding work the router has already re-dispatched elsewhere —
        that work, and any late completion of it, must be discarded, never
        double-served).  Returns the frontend's cancel counts; a fresh
        engine (legit recovery, nothing to cancel) fences to zeros.

        Idempotent per ``epoch``: the fence/ack pair crosses the same
        lossy fabric as everything else, so a duplicated or retried FENCE
        can arrive AFTER the ack re-admitted the replica and the router
        re-dispatched new work to it — an already-executed epoch re-acks
        with zeros instead of cancelling that legitimate work."""
        if epoch <= self._fenced_epoch[rid]:
            return {"queued": 0, "active": 0}
        self._fenced_epoch[rid] = epoch
        rep = self.replicas[rid]
        if rep.serve is None:
            return {"queued": 0, "active": 0}
        return rep.serve.fence()

    def fenced_epoch(self, rid: int) -> int:
        """Highest fencing epoch this replica has EXECUTED — the
        replica-local half of the lifecycle-command epoch fence: a
        ``lifecycle_cmd`` stamped with an older epoch was issued before
        this replica was declared dead and must be rejected, not applied
        (``Router._apply_lifecycle``)."""
        return self._fenced_epoch[rid]

    def lifecycle_seen(self, rid: int) -> Dict[int, str]:
        """The replica's lifecycle-command dedup ledger (cmd seq -> ack
        status); survives engine swaps like the fencing epoch."""
        return self._lifecycle_seen[rid]

    def _emit(self, name: str, value: float) -> None:
        if self.monitor is None or not getattr(self.monitor, "enabled", True):
            return
        try:
            self.monitor.write_events([(name, value, len(self.health.history))])
        except InjectedCrash:
            raise  # simulated process death; chaos tests must see it
        except Exception as e:  # observability must never take down the fleet
            logger.warning(f"fleet monitor write failed: {e}")

    # ------------------------------------------------------------- queries

    @property
    def rids(self) -> List[int]:
        return sorted(self.replicas)

    def replica(self, rid: int) -> Replica:
        return self.replicas[rid]

    def is_idle(self, rid: int) -> bool:
        serve = self.replicas[rid].serve
        return serve is None or (not serve._queue and not serve._active)

    def load_stats(self) -> Dict[int, dict]:
        """Per-replica ``ServingEngine.load_stats()`` for every replica that
        currently has an engine (DEAD replicas are absent)."""
        return {rid: rep.serve.load_stats()
                for rid, rep in sorted(self.replicas.items()) if rep.serve is not None}

    def anatomy(self, rid: int):
        """The step-anatomy recorder of replica ``rid``'s CURRENT engine
        (None when anatomy is off or the replica is dead) — the router's
        per-round host-gap gauge input."""
        rep = self.replicas[rid]
        if rep.serve is None:
            return None
        anat = getattr(rep.serve.engine, "anatomy", None)
        return anat if getattr(anat, "enabled", False) else None

    def mark_anatomy_steady(self) -> None:
        """Declare warm-up over on every live replica's recorder: later
        JIT cache misses count as unexpected steady-state recompiles.
        Recover/restart replacements re-enter dispatch already steady —
        ``_warm_replacement`` AOT-compiles their step set and marks the
        fresh recorder before the replica serves its first request."""
        for rid in self.rids:
            anat = self.anatomy(rid)
            if anat is not None:
                anat.mark_steady()

    # ----------------------------------------------------------- lifecycle

    def rebase_clock(self) -> None:
        """Re-zero the shared clock so t=0 means 'serving starts' — pool
        construction builds and warms N engines, which on a WallClock takes
        long enough to age a workload's arrival timestamps and deadlines
        past before any request is served.  Every live frontend's epoch is
        re-stamped along with it (their ``_t0`` predates the reset)."""
        self.clock.reset()
        for rep in self.replicas.values():
            if rep.serve is not None:
                rep.serve.rebase_epoch()

    def kill(self, rid: int, reason: str = "killed") -> List[ServingRequest]:
        """Abrupt replica loss: discard the engine and return its in-flight
        requests (queued + active, in arrival order) for failover.  The
        returned ``ServingRequest`` objects carry the tokens they already
        delivered; the router resubmits them to survivors with
        ``resume_tokens`` so outputs stay recompute-identical."""
        rep = self.replicas[rid]
        if self.health.state(rid) is not ReplicaState.DEAD:
            self.health.kill(rid, reason)
        victims: List[ServingRequest] = []
        if rep.serve is not None:
            victims = sorted(
                list(rep.serve._queue) + list(rep.serve._active.values()),
                key=lambda r: (r.arrival_ts, r.uid))
            rep.serve.close()
            rep.serve = None
        if self.prefix_directory is not None:
            # death-with-directory-entries: the cache died with the engine,
            # so every digest this replica published is retracted at once —
            # the router must never route to (or import from) a ghost
            self.prefix_directory.purge(rid)
        return victims

    def _warm_replacement(self, rid: int) -> None:
        """A replacement engine must not pay its compile set inside the
        first served request's TTFT: AOT-compile the full reachable step
        set (``warm_all`` — an ``engine.aot_compile`` chaos fault falls
        back to lazy JIT per key, never a dead replica) and declare the
        fresh recorder steady — recovery compiles are deliberate warm-up
        by construction, and any LATER JIT miss on this replica is a real
        steady-state regression, not recovery noise."""
        rep = self.replicas[rid]
        warm = getattr(rep.serve.engine, "warm_all", None)
        if warm is not None:
            warm()
        anat = self.anatomy(rid)
        if anat is not None:
            anat.mark_steady()

    def recover(self, rid: int) -> None:
        """Attach a fresh engine to a DEAD replica (replacement host),
        pre-compiled and anatomy-steady before it re-enters dispatch."""
        assert self.health.state(rid) is ReplicaState.DEAD, \
            f"recover() on replica {rid} in state {self.health.state(rid).value}"
        self._attach_engine(rid)
        self._warm_replacement(rid)
        self.health.recovering(rid)

    def drain(self, rid: int) -> None:
        self.health.drain(rid)

    def set_role(self, rid: int, role) -> None:
        """Reassign the replica's serving role (MIXED⇄PREFILL/DECODE).
        Takes effect at the NEXT engine attach — ``restart``/``recover``
        pick the factory by ``Replica.role`` — so the caller drains
        first and no in-flight work is lost (autoscaler role loop,
        docs/SERVING.md "Closed-loop control")."""
        self.replicas[rid].role = ReplicaRole(role)

    def restart(self, rid: int) -> None:
        """Rolling restart of a DRAINED replica: must be idle (the point of
        draining is that nothing is lost), swaps in a fresh engine —
        pre-compiled and anatomy-steady, like a recovery replacement."""
        assert self.health.state(rid) is ReplicaState.DRAINING, \
            f"restart() on replica {rid} in state {self.health.state(rid).value}"
        assert self.is_idle(rid), f"restart() on replica {rid} before drained"
        rep = self.replicas[rid]
        if rep.serve is not None:
            rep.serve.close()
        self._attach_engine(rid)
        self._warm_replacement(rid)
        self.health.recovering(rid, "rolling restart")

    # ---------------------------------------------------------------- tick

    def tick(self, rid: int):
        """One serving iteration on replica ``rid``.  Returns
        ``(out, victims)``: the engine step's token dict, plus the in-flight
        requests to fail over when this tick KILLED the replica (transient
        errors degrade per the health policy; device-loss-class errors and
        error streaks go DEAD and the engine is discarded).

        :class:`~...resilience.fault_injection.InjectedCrash` is re-raised —
        it simulates death of THIS driver process, not of one replica, and
        nothing may absorb it (the resilience-layer contract)."""
        if not self.health.serving(rid):
            return {}, []
        rep = self.replicas[rid]
        if rep.serve is None:
            return {}, []
        try:
            out = rep.serve.tick()
        except InjectedCrash:
            raise
        except Exception as e:
            state = self.health.record_error(rid, e)
            logger.warning(f"fleet: replica {rid} tick failed ({e}); now {state.value}")
            if state is ReplicaState.DEAD:
                return {}, self.kill(rid, reason=f"tick failure: {e}")
            # still alive (merely degraded): the replica process keeps
            # heartbeating — transient tick errors are replica-local news
            # the router learns via the reported state, not via silence
            self._send_heartbeat(rid)
            return {}, []
        self.health.record_success(rid)
        self._send_heartbeat(rid)
        return out, []
