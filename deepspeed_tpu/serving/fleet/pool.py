"""ReplicaPool: N ServingEngine replicas behind one fleet clock.

Each replica owns a FULL serving stack — its own ``InferenceEngineV2``
(weights, KV arena, prefix cache, scheduler) wrapped by its own
``ServingEngine`` — exactly the unit a real deployment replicates per
mesh/host.  The pool adds what a fleet needs around them:

* a shared clock: one ``VirtualClock`` fans out through per-replica
  :class:`~..clock.ReplicaClockView`\\ s so a deterministic CPU simulation
  models replicas stepping CONCURRENTLY (the fleet driver advances time
  once per round by the slowest replica's cost); a ``WallClock`` is shared
  directly (real time needs no view);
* a :class:`~.health.HealthTracker` fed from tick outcomes;
* ``kill()`` — abrupt replica loss: the engine object is dropped and every
  in-flight ``ServingRequest`` is returned to the caller (the router) for
  failover re-dispatch onto survivors;
* ``recover()``/``restart()`` — attach a FRESH engine from the factory
  (state RECOVERING until its probe ticks pass), modelling a replacement
  host joining the fleet or a drained replica rebooting.
"""

import dataclasses
import enum
from typing import Callable, Dict, List, Optional, Sequence, Union

from ...resilience.fault_injection import InjectedCrash
from ...utils.logging import logger
from ..clock import ReplicaClockView, VirtualClock
from ..engine import ServingConfig, ServingEngine
from ..request import ServingRequest
from .health import HealthConfig, HealthTracker, ReplicaState


class ReplicaRole(enum.Enum):
    """Disaggregation role of one replica (DistServe prefill–decode
    disaggregation / Splitwise phase splitting — docs/SERVING.md
    "Disaggregated serving").  The role is a ROUTING preference, not a
    capability bound: every replica runs the full serving stack, so a
    decode replica can absorb a whole request when the prefill pool is
    gone (availability beats specialization) and vice versa."""
    PREFILL = "prefill"   # admission + prompt processing; requests migrate out
    DECODE = "decode"     # resumes migrated KV; token generation
    MIXED = "mixed"       # the classic monolithic replica (default)


@dataclasses.dataclass
class Replica:
    rid: int
    serve: Optional[ServingEngine]      # None while DEAD (engine discarded)
    clock: object                       # ReplicaClockView or the shared clock
    generation: int = 0                 # bumps on every fresh engine attach
    role: ReplicaRole = ReplicaRole.MIXED  # survives kill/recover/restart


class ReplicaPool:

    def __init__(self, engine_factory: Callable[[], object], n_replicas: int,
                 clock=None, serving_config: ServingConfig = None, monitor=None,
                 health_config: HealthConfig = None, tracer=None, metrics=None,
                 roles: Optional[Sequence[Union[str, ReplicaRole]]] = None,
                 role_factories: Optional[Dict] = None,
                 prefix_directory=None):
        assert n_replicas >= 1, n_replicas
        if roles is not None and len(roles) != n_replicas:
            raise ValueError(f"roles ({len(roles)}) must cover every replica "
                             f"({n_replicas})")
        self.engine_factory = engine_factory
        # phase-specialized engine configs (Splitwise-style pool tuning):
        # a PREFILL pool might run smaller prefill chunks and a lean KV
        # arena (it holds prompts only transiently), a DECODE pool a large
        # arena for the fleet's whole resident decode set.  KV migration
        # only requires the PER-PAGE geometry (layers, page_size, kv heads,
        # head_dim, dtype) to match across pools — arena page COUNTS may
        # differ freely.  Factories are keyed by role and survive
        # kill/recover/restart (the role does).
        self.role_factories = {ReplicaRole(k): v
                               for k, v in (role_factories or {}).items()}
        self.serving_config = serving_config or ServingConfig()
        self.monitor = monitor
        # telemetry: ONE tracer/metrics registry spans the whole fleet —
        # every replica frontend traces onto its own track (replica<rid>)
        # of the same span stream, and a fresh engine attached by
        # recover()/restart() inherits them (observability survives the
        # replica, like the clock does)
        self.tracer = tracer
        self.metrics = metrics
        # fleet prefix directory (docs/SERVING.md "Prefix directory"): the
        # pool is its ONE publish edge — every attached engine's prefix
        # cache streams its chain digests through the listener bus, and
        # death/restart purge the replica's entries, so the router-side
        # table can never outlive the cache it mirrors by more than the
        # documented staleness ladder
        self.prefix_directory = prefix_directory
        if prefix_directory is not None and metrics is not None \
                and prefix_directory.metrics is None:
            prefix_directory.metrics = metrics
        self.clock = clock if clock is not None else VirtualClock()
        self._virtual = isinstance(self.clock, VirtualClock)
        self.replicas: Dict[int, Replica] = {}
        self.health = HealthTracker(range(n_replicas), config=health_config,
                                    emit=self._emit, clock=self.clock)
        for rid in range(n_replicas):
            role = ReplicaRole(roles[rid]) if roles is not None else ReplicaRole.MIXED
            self.replicas[rid] = Replica(rid=rid, serve=None,
                                         clock=self._make_view(), role=role)
            self._attach_engine(rid)

    def _make_view(self):
        return ReplicaClockView(self.clock) if self._virtual else self.clock

    def _attach_engine(self, rid: int) -> None:
        rep = self.replicas[rid]
        factory = self.role_factories.get(rep.role, self.engine_factory)
        rep.serve = ServingEngine(factory(), clock=rep.clock,
                                  config=self.serving_config, monitor=self.monitor,
                                  tracer=self.tracer, metrics=self.metrics,
                                  trace_track=f"replica{rid}")
        rep.generation += 1
        if self.prefix_directory is not None:
            # a fresh engine's cache is empty: stale entries from the
            # replica's previous life (rolling restart) must go first
            self.prefix_directory.purge(rid)
            pc = rep.serve.engine.kv.prefix_cache
            if pc is not None:
                pc.listener = self._directory_listener(rid)

    def _directory_listener(self, rid: int):
        """Publish edge replica -> directory.  A transient fault at the
        ``prefix.publish`` site drops THIS update (the directory goes
        stale — cold or warm — which the routing staleness ladder absorbs:
        a mis-routed dispatch recomputes, never corrupts); ``InjectedCrash``
        is driver death and propagates."""
        directory = self.prefix_directory

        def on_event(event: str, digest: int) -> None:
            try:
                if event == "publish":
                    directory.publish(rid, digest)
                else:
                    directory.retract(rid, digest)
            except InjectedCrash:
                raise
            except OSError as e:
                logger.warning(f"fleet: prefix directory {event} dropped for "
                               f"replica {rid}: {e}")
        return on_event

    def _emit(self, name: str, value: float) -> None:
        if self.monitor is None or not getattr(self.monitor, "enabled", True):
            return
        try:
            self.monitor.write_events([(name, value, len(self.health.history))])
        except InjectedCrash:
            raise  # simulated process death; chaos tests must see it
        except Exception as e:  # observability must never take down the fleet
            logger.warning(f"fleet monitor write failed: {e}")

    # ------------------------------------------------------------- queries

    @property
    def rids(self) -> List[int]:
        return sorted(self.replicas)

    def replica(self, rid: int) -> Replica:
        return self.replicas[rid]

    def is_idle(self, rid: int) -> bool:
        serve = self.replicas[rid].serve
        return serve is None or (not serve._queue and not serve._active)

    def load_stats(self) -> Dict[int, dict]:
        """Per-replica ``ServingEngine.load_stats()`` for every replica that
        currently has an engine (DEAD replicas are absent)."""
        return {rid: rep.serve.load_stats()
                for rid, rep in sorted(self.replicas.items()) if rep.serve is not None}

    # ----------------------------------------------------------- lifecycle

    def rebase_clock(self) -> None:
        """Re-zero the shared clock so t=0 means 'serving starts' — pool
        construction builds and warms N engines, which on a WallClock takes
        long enough to age a workload's arrival timestamps and deadlines
        past before any request is served.  Every live frontend's epoch is
        re-stamped along with it (their ``_t0`` predates the reset)."""
        self.clock.reset()
        for rep in self.replicas.values():
            if rep.serve is not None:
                rep.serve.rebase_epoch()

    def kill(self, rid: int, reason: str = "killed") -> List[ServingRequest]:
        """Abrupt replica loss: discard the engine and return its in-flight
        requests (queued + active, in arrival order) for failover.  The
        returned ``ServingRequest`` objects carry the tokens they already
        delivered; the router resubmits them to survivors with
        ``resume_tokens`` so outputs stay recompute-identical."""
        rep = self.replicas[rid]
        if self.health.state(rid) is not ReplicaState.DEAD:
            self.health.kill(rid, reason)
        victims: List[ServingRequest] = []
        if rep.serve is not None:
            victims = sorted(
                list(rep.serve._queue) + list(rep.serve._active.values()),
                key=lambda r: (r.arrival_ts, r.uid))
            rep.serve.close()
            rep.serve = None
        if self.prefix_directory is not None:
            # death-with-directory-entries: the cache died with the engine,
            # so every digest this replica published is retracted at once —
            # the router must never route to (or import from) a ghost
            self.prefix_directory.purge(rid)
        return victims

    def recover(self, rid: int) -> None:
        """Attach a fresh engine to a DEAD replica (replacement host)."""
        assert self.health.state(rid) is ReplicaState.DEAD, \
            f"recover() on replica {rid} in state {self.health.state(rid).value}"
        self._attach_engine(rid)
        self.health.recovering(rid)

    def drain(self, rid: int) -> None:
        self.health.drain(rid)

    def restart(self, rid: int) -> None:
        """Rolling restart of a DRAINED replica: must be idle (the point of
        draining is that nothing is lost), swaps in a fresh engine."""
        assert self.health.state(rid) is ReplicaState.DRAINING, \
            f"restart() on replica {rid} in state {self.health.state(rid).value}"
        assert self.is_idle(rid), f"restart() on replica {rid} before drained"
        rep = self.replicas[rid]
        if rep.serve is not None:
            rep.serve.close()
        self._attach_engine(rid)
        self.health.recovering(rid, "rolling restart")

    # ---------------------------------------------------------------- tick

    def tick(self, rid: int):
        """One serving iteration on replica ``rid``.  Returns
        ``(out, victims)``: the engine step's token dict, plus the in-flight
        requests to fail over when this tick KILLED the replica (transient
        errors degrade per the health policy; device-loss-class errors and
        error streaks go DEAD and the engine is discarded).

        :class:`~...resilience.fault_injection.InjectedCrash` is re-raised —
        it simulates death of THIS driver process, not of one replica, and
        nothing may absorb it (the resilience-layer contract)."""
        if not self.health.serving(rid):
            return {}, []
        rep = self.replicas[rid]
        if rep.serve is None:
            return {}, []
        try:
            out = rep.serve.tick()
        except InjectedCrash:
            raise
        except Exception as e:
            state = self.health.record_error(rid, e)
            logger.warning(f"fleet: replica {rid} tick failed ({e}); now {state.value}")
            if state is ReplicaState.DEAD:
                return {}, self.kill(rid, reason=f"tick failure: {e}")
            return {}, []
        self.health.record_success(rid)
        return out, []
