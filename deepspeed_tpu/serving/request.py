"""Request lifecycle: the unit the SLA frontend schedules.

Reference: FastGen's serving methodology (``blogs/deepspeed-fastgen`` —
first-token + per-token SLAs under Poisson-arrival load) and Orca-style
iteration-level scheduling.  The v2 engine itself only knows *sequences*
(``inference/v2/ragged.py SequenceDescriptor``); a :class:`ServingRequest`
is the envelope around one — arrival time, deadline, output budget, and a
state machine the frontend drives:

    QUEUED → PREFILL → DECODE → DONE
       │        │         │
       │        └→ EVICTED ┘→ QUEUED   (KV-pressure preemption; resume
       │                  ▲             recomputes the generated tokens'
       │                  │             KV from the extended prompt)
       │            DECODE → PARKED → QUEUED  (kvtier: KV demoted to the
       │                        │              host tier; resume promotes
       │                        │              it back — no recompute)
       │  {PREFILL|DECODE} → MIGRATING → MIGRATED  (KV handed off to
       │                        │         another replica — kvtransfer;
       │                        │         late-prefill pause = the
       │                        │         DistServe boundary)
       │                        └→ {PREFILL|DECODE}  (migration aborted:
       │                                              resume in place)
       └→ REJECTED                      (admission: queue full / infeasible)
    any non-terminal → TIMED_OUT        (deadline passed)

Terminal states: DONE, TIMED_OUT, REJECTED, MIGRATED.  EVICTED is
transient — the frontend immediately requeues (or times out) the victim;
it appears in the history so preemption events are auditable per request.
MIGRATING is the host-staging window of a KV migration: the request's
engine sequence is paused (pages byte-stable for chunked export) and the
fleet router either hands it off (MIGRATED — the request continues on a
decode replica), aborts back to DECODE, or loses it to preemption
(EVICTED — recompute-on-resume, the migration's fallback ladder).
PARKED is the tiered-KV idle state (docs/SERVING.md "Tiered KV"): the
request left the engine with its KV demoted to the host tier; resume
re-enqueues it and admission promotes the pages back device-side, falling
back to recompute on any host-tier miss or fault.
"""

import dataclasses
import enum
from typing import Callable, List, Optional, Sequence, Tuple


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    MIGRATING = "migrating"   # paused for KV export (serving/kvtransfer)
    PARKED = "parked"         # idle; KV demoted to the host tier (serving/kvtier)
    DONE = "done"
    EVICTED = "evicted"
    TIMED_OUT = "timed_out"
    REJECTED = "rejected"
    MIGRATED = "migrated"     # handed off to another replica with its KV

    @property
    def terminal(self) -> bool:
        return self in (RequestState.DONE, RequestState.TIMED_OUT,
                        RequestState.REJECTED, RequestState.MIGRATED)


_ALLOWED = {
    RequestState.QUEUED: {RequestState.PREFILL, RequestState.TIMED_OUT, RequestState.REJECTED},
    RequestState.PREFILL: {RequestState.DECODE, RequestState.EVICTED, RequestState.TIMED_OUT,
                           RequestState.MIGRATING},
    RequestState.DECODE: {RequestState.DONE, RequestState.EVICTED, RequestState.TIMED_OUT,
                          RequestState.MIGRATING, RequestState.PARKED},
    # an idle session parked mid-decode: its KV was demoted to the host
    # tier and its engine sequence released; resume() re-enqueues it and
    # admission promotes the host pages back (or recomputes on any
    # host-tier fallback — slower, never wrong)
    RequestState.PARKED: {RequestState.QUEUED, RequestState.TIMED_OUT},
    # a migration can begin LATE IN PREFILL (the DistServe boundary: the
    # final chunk + first-token sampling run on the decode replica, so the
    # staging pause lands in TTFT, never TPOT) or mid-DECODE (short
    # prompts whose whole prefill fit one chunk); an abort resumes the
    # phase the pause interrupted
    RequestState.MIGRATING: {RequestState.PREFILL, RequestState.DECODE,
                             RequestState.MIGRATED,
                             RequestState.EVICTED, RequestState.TIMED_OUT},
    RequestState.EVICTED: {RequestState.QUEUED, RequestState.TIMED_OUT},
    RequestState.DONE: set(),
    RequestState.TIMED_OUT: set(),
    RequestState.REJECTED: set(),
    RequestState.MIGRATED: set(),
}


@dataclasses.dataclass
class ServingRequest:
    """One user request moving through the frontend.

    ``tokens`` accumulates every generated token across preemptions: on
    eviction the engine-side sequence (and its KV pages) is destroyed, but
    the request keeps what it already produced and resumes by prefilling
    ``prompt + tokens`` — greedy decode then continues with the identical
    next token, so a preempted request's final output equals an
    unpreempted run's.
    """
    uid: int
    prompt: List[int]
    arrival_ts: float
    max_new_tokens: int
    deadline: Optional[float] = None          # absolute timestamp, clock domain
    priority: float = 0.0                     # lower = more urgent; FCFS within a class
    stream: Optional[Callable] = None         # stream(request, new_tokens, ts)
    state: RequestState = RequestState.QUEUED
    admitted_ts: Optional[float] = None       # first admission only (queue-wait metric)
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    preemptions: int = 0
    reject_reason: Optional[str] = None
    #: clock-seconds the client should wait before retrying a TRANSIENT
    #: rejection (queue_full): the admission controller's queue-drain
    #: estimate, not a blind backoff.  None on structural rejections —
    #: retrying an infeasible request can never help.
    retry_after: Optional[float] = None
    history: List[Tuple[RequestState, float]] = dataclasses.field(default_factory=list)
    # speculative decoding (inference/v2/spec): per-request opt-in/out
    # (None = the engine's default — on whenever the engine carries a
    # SpecConfig) and lifetime acceptance accounting, folded in from
    # ``engine.last_spec_round`` each tick this request speculated
    spec: Optional[bool] = None
    spec_proposed: int = 0            # draft tokens fed to verify dispatches
    spec_accepted: int = 0            # drafts the model's argmax confirmed
    spec_rollback_pages: int = 0      # KV pages rolled back for rejected drafts
    # host-staged KV state to import at admission instead of recomputing
    # the prompt (serving/kvtransfer KVSnapshot, or a kvtier HostKVHandle
    # naming an entry parked in the engine-local host tier; consumed — and
    # cleared — on first admission whether the import succeeds or falls back)
    kv_snapshot: Optional[object] = None
    #: promotion transfer windows ``(t_start, t_ready)`` the host tier
    #: charged this request (kvtier prefetch): telemetry carves them out of
    #: the surrounding QUEUED interval as ``phase/promote`` spans, so a
    #: resume's TTFT splits into queue wait vs h2d promotion
    promote_windows: List[Tuple[float, float]] = dataclasses.field(default_factory=list)
    #: telemetry label for PARKED intervals: "parked" for an idle-session
    #: park, "tool_stall" when a session parked this request MID-GENERATION
    #: awaiting a tool result (serving/sessions).  A phase label, not a
    #: state — the PARKED machinery (demote/promote/resume ladder) is
    #: identical; only span/why_slow attribution differs.
    park_phase: str = "parked"

    def __post_init__(self):
        self.prompt = list(self.prompt)
        self.history.append((self.state, self.arrival_ts))

    def to(self, state: RequestState, ts: float) -> None:
        if state not in _ALLOWED[self.state]:
            raise ValueError(f"request {self.uid}: illegal transition "
                             f"{self.state.value} -> {state.value}")
        self.state = state
        self.history.append((state, ts))

    # ------------------------------------------------------------- metrics

    @property
    def remaining_new_tokens(self) -> int:
        return max(0, self.max_new_tokens - len(self.tokens))

    @property
    def spec_acceptance(self) -> Optional[float]:
        """Accepted / proposed draft tokens over this request's lifetime;
        None if it never speculated (spec off, or no draftable history)."""
        if not self.spec_proposed:
            return None
        return self.spec_accepted / self.spec_proposed

    @property
    def ttft(self) -> Optional[float]:
        """Time to first token, from ARRIVAL (queue wait included — the
        user-visible latency, the quantity FastGen's first-token SLA bounds)."""
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.arrival_ts

    @property
    def tpot(self) -> Optional[float]:
        """Time per output token after the first (the per-token SLA)."""
        if self.first_token_ts is None or self.finish_ts is None or len(self.tokens) < 2:
            return None
        return (self.finish_ts - self.first_token_ts) / (len(self.tokens) - 1)

    @property
    def queue_wait(self) -> Optional[float]:
        if self.admitted_ts is None:
            return None
        return self.admitted_ts - self.arrival_ts

    @property
    def met_deadline(self) -> bool:
        """Completed AND within deadline — the goodput numerator."""
        if self.state is not RequestState.DONE:
            return False
        return self.deadline is None or self.finish_ts <= self.deadline

    def engine_tokens(self) -> List[int]:
        """The token list to (re)admit into the engine: original prompt plus
        everything generated before any preemption (recompute-on-resume)."""
        return list(self.prompt) + list(self.tokens)
