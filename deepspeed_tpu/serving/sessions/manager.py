"""Session drivers: move :class:`~.session.Session` machines through an
engine or a fleet.

Two drivers share the Session bookkeeping and the tool-stall ladder:

* :class:`SessionManager` — closed-loop over ONE
  :class:`~..engine.ServingEngine` (the ``bench_serving.py --kv-tier``
  workload driver and the unit-test harness).  Tool stalls park the
  request through the engine's host KV tier (``serve.park(uid,
  phase="tool_stall")``), prefetch a lead interval before the seeded
  tool result lands, then resume — the r22 prefetch-hidden contract.
* :class:`FleetSessionCoordinator` — the same loop over a fleet
  :class:`~..fleet.router.Router`, implementing the
  :class:`~..fleet.sim.FleetSimulator` controller protocol
  (``pending()/poll(now)/next_wake(now)/marker()``).  Turns are routed
  with ``session=sid`` so the ``session_affinity`` policy can pin the
  session to the replica holding its warm pages; a sticky replica's
  death mid-stall is survived by the router's failover harvest (the
  parked host snapshot is re-imported on a survivor, or recompute runs
  — outputs golden either way), and the coordinator simply RE-PARKS the
  resurrected request for the stall's remainder.

Fault sites (docs/RESILIENCE.md): ``session.route`` fires at the
coordinator's turn-routing edge — an ``os_error`` there degrades ONE
turn to stateless routing (submitted without its session tag; counted,
never a crash).  ``session.tool_result`` fires at the seeded tool-result
delivery — an ``os_error`` extends the stall by ``tool_retry_s`` and the
delivery is retried (absorbed).  ``InjectedCrash`` propagates from both,
as everywhere.
"""

from typing import Dict, List, Optional

from ...resilience import fault_injection as _fi
from ..request import RequestState
from .session import Session, SessionConfig, SessionState

__all__ = ["SessionManager", "FleetSessionCoordinator"]

_TERMINAL = (RequestState.DONE, RequestState.TIMED_OUT, RequestState.REJECTED)


class _SessionDriverBase:
    """The state-walk both drivers share; subclasses supply the
    request-facing verbs (submit/park/prefetch/resume and the per-request
    token/state reads)."""

    def __init__(self, sessions: List[dict],
                 config: Optional[SessionConfig] = None):
        self.config = config or SessionConfig()
        self.sessions = [Session(s["sid"], s["turns"], s.get("start_ts", 0.0))
                         for s in sessions]
        self.stats = {"turns_submitted": 0, "turns_completed": 0,
                      "stalls": 0, "tool_results": 0, "route_faults": 0,
                      "tool_result_faults": 0, "reparks": 0,
                      "abandoned": 0}
        #: sid -> when the session's next driver action is due (think-time
        #: turn starts, stall resumes, prefetch leads) — the wake feed
        self._wakes: Dict[object, float] = {}

    # ------------------------------------------------- subclass contract

    def _submit_turn(self, sess: Session, prompt: List[int], now: float):
        raise NotImplementedError

    def _req_state(self, sess: Session) -> RequestState:
        raise NotImplementedError

    def _req_tokens(self, sess: Session) -> List[int]:
        raise NotImplementedError

    def _park(self, sess: Session) -> bool:
        raise NotImplementedError

    def _prefetch(self, sess: Session) -> bool:
        raise NotImplementedError

    def _resume(self, sess: Session) -> bool:
        raise NotImplementedError

    # -------------------------------------------------- controller hooks

    def pending(self) -> bool:
        return any(not s.closed for s in self.sessions)

    def next_wake(self, now: float) -> Optional[float]:
        due = [t for t in self._wakes.values() if t > now]
        return min(due) if due else None

    def marker(self):
        """Progress signature for the simulator's stall guard: any state
        or counter movement means the round worked."""
        return (tuple(sorted(self.stats.items())),
                tuple((s.sid, s.state.value, s.turn_idx)
                      for s in self.sessions))

    # ---------------------------------------------------------- the walk

    def poll(self, now: float) -> None:
        for sess in self.sessions:
            if sess.closed:
                self._wakes.pop(sess.sid, None)
                continue
            if sess.state is SessionState.PENDING:
                if now >= sess.start_ts:
                    self._start_turn(sess, now)
                else:
                    self._wakes[sess.sid] = sess.start_ts
            elif sess.state is SessionState.THINKING:
                if now >= self._wakes.get(sess.sid, 0.0):
                    self._start_turn(sess, now)
            elif sess.state is SessionState.ACTIVE_TURN:
                self._poll_active(sess, now)
            elif sess.state is SessionState.TOOL_STALL:
                self._poll_stalled(sess, now)
            else:
                pass  # CLOSED: handled above

    def _start_turn(self, sess: Session, now: float) -> None:
        prompt = sess.begin_turn(now)
        self._wakes.pop(sess.sid, None)
        self._submit_turn(sess, prompt, now)
        self.stats["turns_submitted"] += 1

    def _poll_active(self, sess: Session, now: float) -> None:
        state = self._req_state(sess)
        if state in _TERMINAL:
            if state is not RequestState.DONE:
                self.stats["abandoned"] += 1
                sess.abandon(now)
                self._wakes.pop(sess.sid, None)
                return
            think = sess.finish_turn(self._req_tokens(sess), now)
            self.stats["turns_completed"] += 1
            if think is None:
                self._wakes.pop(sess.sid, None)
            else:
                self._wakes[sess.sid] = now + think
            return
        tokens = self._req_tokens(sess)
        if sess.stall_due(tokens) and state is RequestState.DECODE \
                and self._park(sess):
            sess.enter_stall(tokens, now)
            self.stats["stalls"] += 1
            self._arm_stall_wake(sess)
        # stall due but unparkable this tick (mid-prefill, a migration
        # window, a dying replica): the detector keeps it armed and the
        # next delivered batch retries

    def _poll_stalled(self, sess: Session, now: float) -> None:
        cur = sess.cur
        lead = self.config.prefetch_lead_s
        if not cur["prefetched"] and now >= cur["resume_at"] - lead:
            self._prefetch(sess)   # best-effort; an unhinted resume still works
            cur["prefetched"] = True
            self._arm_stall_wake(sess)
        if now >= cur["resume_at"]:
            try:
                _fi.check("session.tool_result")
            except _fi.InjectedCrash:
                raise
            except OSError:
                # the tool backend hiccuped: the stall extends one retry
                # interval and the delivery is re-attempted — absorbed
                self.stats["tool_result_faults"] += 1
                cur["resume_at"] = now + self.config.tool_retry_s
                cur["prefetched"] = False
                self._arm_stall_wake(sess)
                return
            self._resume(sess)
            sess.exit_stall(now)
            self.stats["tool_results"] += 1
            self._wakes.pop(sess.sid, None)
            # the request may ALREADY be terminal (it kept generating
            # unparked — park_stalls off, or a failover recompute ran to
            # completion during the stall): fold it now, or the driver
            # loop sees an open session with nothing runnable and no wake
            self._poll_active(sess, now)

    def _arm_stall_wake(self, sess: Session) -> None:
        cur = sess.cur
        lead = self.config.prefetch_lead_s
        self._wakes[sess.sid] = (cur["resume_at"] if cur["prefetched"]
                                 else cur["resume_at"] - lead)

    # ----------------------------------------------------------- receipts

    def transcripts(self) -> Dict[object, List[int]]:
        return {s.sid: list(s.transcript) for s in self.sessions}

    def turn_ttfts(self) -> List[float]:
        return [t for s in self.sessions for t in s.turn_ttfts()]


class SessionManager(_SessionDriverBase):
    """Closed-loop session driver over one :class:`ServingEngine`.

    ``run()`` owns the whole loop (tick, poll, idle clock jumps); a
    caller embedding the manager in a larger loop instead calls
    ``poll(now)`` after its own ticks and honors ``next_wake``.
    """

    def __init__(self, serve, sessions: List[dict],
                 config: Optional[SessionConfig] = None, stream=None):
        super().__init__(sessions, config)
        self.serve = serve
        self._user_stream = stream
        self._reqs: Dict[object, object] = {}   # sid -> live ServingRequest

    # ------------------------------------------------------------- verbs

    def _submit_turn(self, sess, prompt, now):
        def stream(req, toks, ts, _sess=sess):
            _sess.note_first_token(ts)
            if self._user_stream is not None:
                self._user_stream(_sess, req, toks, ts)
        self._reqs[sess.sid] = self.serve.submit(
            prompt, max_new_tokens=sess.cur["spec"]["max_new_tokens"],
            arrival_ts=now, stream=stream)

    def _req_state(self, sess):
        return self._reqs[sess.sid].state

    def _req_tokens(self, sess):
        return list(self._reqs[sess.sid].tokens)

    def _park(self, sess):
        if not self.config.park_stalls:
            return True   # tests: stall accounting without a real park
        return self.serve.park(self._reqs[sess.sid].uid, phase="tool_stall")

    def _prefetch(self, sess):
        return self.serve.prefetch_resume(self._reqs[sess.sid].uid)

    def _resume(self, sess):
        if not self.config.park_stalls:
            return True
        return self.serve.resume(self._reqs[sess.sid].uid)

    # -------------------------------------------------------------- loop

    def run(self, max_steps: int = 1_000_000) -> List[Session]:
        serve = self.serve
        for _ in range(max_steps):
            now = serve.clock.now()
            self.poll(now)
            if not self.pending():
                return self.sessions
            if not serve._active and not serve._queue:
                wake = self.next_wake(now)
                if wake is None:
                    raise RuntimeError(
                        f"session loop wedged at t={now}: "
                        f"{sum(1 for s in self.sessions if not s.closed)} "
                        "open session(s), nothing runnable, no future wake")
                serve.clock.wait_until(wake + 1e-9)
                continue
            serve.tick()
        raise RuntimeError(f"session loop exceeded max_steps={max_steps}")


class FleetSessionCoordinator(_SessionDriverBase):
    """Fleet-side session driver: the :class:`FleetSimulator` controller
    that submits each turn through the router (``session=sid`` so the
    affinity policy can pin it), parks/resumes tool stalls on whichever
    replica currently runs the request, and re-parks a stalled request
    that failover resurrected on a survivor mid-stall."""

    def __init__(self, router, sessions: List[dict],
                 config: Optional[SessionConfig] = None):
        super().__init__(sessions, config)
        self.router = router
        self._frs: Dict[object, object] = {}    # sid -> live FleetRequest

    # ------------------------------------------------------------- verbs

    def _submit_turn(self, sess, prompt, now):
        mnt = sess.cur["spec"]["max_new_tokens"]
        try:
            _fi.check("session.route")
            fr = self.router.submit(prompt, max_new_tokens=mnt,
                                    arrival_ts=now, session=sess.sid)
        except _fi.InjectedCrash:
            raise
        except OSError:
            # the session-routing edge failed: this turn degrades to
            # stateless routing (no session tag, no stickiness) — counted,
            # never a crash; the NEXT turn re-enters the sticky path
            self.stats["route_faults"] += 1
            fr = self.router.submit(prompt, max_new_tokens=mnt,
                                    arrival_ts=now)
        self._frs[sess.sid] = fr

    def _fleet_req(self, sess):
        return self._frs[sess.sid]

    def _req_state(self, sess):
        from ..fleet.router import FleetState
        fr = self._fleet_req(sess)
        if fr.state is FleetState.DONE:
            return RequestState.DONE
        if fr.state in (FleetState.TIMED_OUT, FleetState.REJECTED):
            return RequestState.TIMED_OUT
        # PENDING/DISPATCHED (incl. a failover in flight): still working.
        # Report DECODE once tokens exist so the stall ladder can park.
        return (RequestState.DECODE if fr.tokens else RequestState.PREFILL)

    def _req_tokens(self, sess):
        return list(self._fleet_req(sess).tokens)

    def _park(self, sess):
        return self.router.park_request(self._fleet_req(sess),
                                        phase="tool_stall")

    def _prefetch(self, sess):
        return self.router.prefetch_resume_request(self._fleet_req(sess))

    def _resume(self, sess):
        return self.router.resume_request(self._fleet_req(sess))

    # ------------------------------------------------ failover awareness

    def _poll_active(self, sess, now):
        # the fleet path has no per-token stream into the session: fold
        # the router's first-token observation instant (idempotent — the
        # first call wins, so a failover's re-delivery cannot move it)
        ftt = self._fleet_req(sess).first_token_ts
        if ftt is not None:
            sess.note_first_token(ftt)
        super()._poll_active(sess, now)

    def _poll_stalled(self, sess, now):
        # a sticky-replica death displaced the parked request and failover
        # resurrected it generating on a survivor: re-park it for the
        # stall's remainder (bytes are unaffected — greedy continuation —
        # but the stall's TIMING contract is the session's to keep)
        if now < sess.cur["resume_at"] \
                and self.router.request_decoding(self._fleet_req(sess)) \
                and self._park(sess):
            self.stats["reparks"] += 1
        super()._poll_stalled(sess, now)
