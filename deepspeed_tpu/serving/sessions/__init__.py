"""Agentic session serving: multi-turn sessions over the fleet
(docs/SERVING.md "Agentic sessions").

The session layer the L7 serving stack exists for: validated
:class:`Session` state machines (ACTIVE_TURN → THINKING → … → CLOSED,
with mid-generation TOOL_STALL parks through the r22 host KV tier),
per-turn prefix growth (turn N+1's prompt = turn N's full transcript),
and the drivers that move sessions closed-loop through one
:class:`~..engine.ServingEngine` (:class:`SessionManager`) or a fleet
:class:`~..fleet.router.Router` (:class:`FleetSessionCoordinator`, the
``FleetSimulator`` controller).  The seeded workload generator is
:func:`~..fleet.sim.session_arrivals`; the fleet placement policy is
``session_affinity`` (fleet/policies.py).
"""

from .manager import FleetSessionCoordinator, SessionManager
from .session import Session, SessionConfig, SessionState, ToolCallDetector

__all__ = ["Session", "SessionConfig", "SessionState", "ToolCallDetector",
           "SessionManager", "FleetSessionCoordinator"]
