"""Agentic session state: one multi-turn conversation over the fleet.

Production traffic at scale is *sessions*, not single-shot arrivals
(ROADMAP "Scenario diversity"): multi-turn conversations and agent loops
with think-time gaps between turns, tool-call stalls *mid-generation*,
and per-turn prefix growth — turn N+1's prompt is turn N's full
transcript, the prefix directory's ideal customer.  This module is the
pure state half of the subsystem; the drivers that move sessions through
an engine or a fleet live in :mod:`.manager`.

A :class:`Session` is a validated state machine::

    PENDING → ACTIVE_TURN → THINKING → ACTIVE_TURN → … → CLOSED
                   │    ▲
                   ▼    │   (tool-call marker fired mid-generation: the
               TOOL_STALL    request PARKS via the host KV tier with its
                             partial generation intact and resumes
                             byte-identically when the seeded tool
                             result arrives)

Turn semantics:

* each turn is one serving request whose prompt is the session's full
  transcript so far plus the turn's user message;
* generated tokens join the transcript at the turn boundary, and a
  fired tool call's result tokens append AFTER the turn's generation —
  so a stalled turn's token stream is byte-identical to an unstalled
  run of the same prompt (greedy decode; the park/resume ladder never
  changes bytes, only timing);
* every turn's completed full pages publish into the replica's prefix
  cache as it generates (``StateManager.note_progress``), so turn N+1
  routed to the same replica re-attaches the whole transcript's pages
  and prefills only the new suffix — the warmth ``session_affinity``
  routing (fleet/policies.py) exists to preserve.

Terminal is CLOSED: every turn completed (or the session was abandoned
— rejection/timeout of a turn closes the session; the chaos tests pin
exactly-once closure).
"""

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence

__all__ = ["SessionState", "SessionConfig", "ToolCallDetector", "Session"]


class SessionState(enum.Enum):
    PENDING = "pending"           # generated: not yet started (start_ts future)
    ACTIVE_TURN = "active_turn"   # a turn's request is live on some replica
    TOOL_STALL = "tool_stall"     # parked mid-generation awaiting a tool result
    THINKING = "thinking"         # between turns (the user's think time)
    CLOSED = "closed"             # every turn done, or the session abandoned

    @property
    def terminal(self) -> bool:
        return self is SessionState.CLOSED


_SESSION_ALLOWED = {
    SessionState.PENDING: {SessionState.ACTIVE_TURN, SessionState.CLOSED},
    # a turn either fires a tool call (parks mid-generation), completes
    # into think time (more turns follow), or completes the session
    SessionState.ACTIVE_TURN: {SessionState.TOOL_STALL, SessionState.THINKING,
                               SessionState.CLOSED},
    # the seeded tool result arrived: the request resumes in place
    # (byte-identical continuation); CLOSED covers abandonment mid-stall
    SessionState.TOOL_STALL: {SessionState.ACTIVE_TURN, SessionState.CLOSED},
    SessionState.THINKING: {SessionState.ACTIVE_TURN, SessionState.CLOSED},
    SessionState.CLOSED: set(),
}


@dataclasses.dataclass(frozen=True)
class SessionConfig:
    """Driver knobs shared by the engine-level :class:`~.manager.
    SessionManager` and the fleet :class:`~.manager.FleetSessionCoordinator`."""
    #: issue ``prefetch_resume`` this many clock-seconds BEFORE a stall's
    #: scheduled resume, so the h2d promotion hides under other sessions'
    #: device windows (the r22 prefetch-hidden contract); 0 = unhinted.
    prefetch_lead_s: float = 0.0
    #: how long a ``session.tool_result`` delivery fault extends the
    #: stall before the next delivery attempt (absorbed, never wrong).
    tool_retry_s: float = 0.5
    #: park tool stalls through the host KV tier (False only makes sense
    #: in tests; the stateless bench baseline instead runs a zero-capacity
    #: tier so every park degrades to recompute-on-resume).
    park_stalls: bool = True


class ToolCallDetector:
    """Decides, per delivered-token batch, whether a turn just hit a
    tool-call boundary.

    Two trigger kinds, composable:

    * ``marker`` — a stop-sequence token run: fires when the generation's
      tail equals the marker (the production shape; testable on the tiny
      greedy model by choosing a run from the turn's own golden tokens);
    * ``at_counts`` — deterministic token-count triggers (the bench
      shape: seeded workloads fire stalls at exact offsets so runs are
      byte-comparable).

    Each trigger fires at most once per position: ``due()`` is a pure
    peek, ``fire()`` consumes — the split lets a driver whose park
    attempt failed this tick (e.g. the request is still in prefill)
    retry on the next delivery instead of losing the stall.
    """

    def __init__(self, marker: Optional[Sequence[int]] = None,
                 at_counts: Sequence[int] = ()):
        self.marker = [int(t) for t in marker] if marker else None
        self.at_counts = sorted(int(c) for c in at_counts)
        self._next = 0          # index of the next unconsumed at_count
        self._fired_len = 0     # generation length already consumed by fire()

    def due(self, tokens: Sequence[int]) -> bool:
        n = len(tokens)
        if self._next < len(self.at_counts) and n >= self.at_counts[self._next]:
            return True
        if self.marker and n > self._fired_len and n >= len(self.marker) \
                and [int(t) for t in tokens[-len(self.marker):]] == self.marker:
            return True
        return False

    def fire(self, tokens: Sequence[int]) -> None:
        assert self.due(tokens), "fire() without a due trigger"
        if self._next < len(self.at_counts) \
                and len(tokens) >= self.at_counts[self._next]:
            self._next += 1
        self._fired_len = len(tokens)


class Session:
    """One session's validated state + transcript bookkeeping.

    Pure bookkeeping — no engine or router reference.  The drivers in
    :mod:`.manager` call the turn-lifecycle methods below and own all
    clock/transport concerns, so the same Session moves identically
    through the single-engine manager, the fleet coordinator, and the
    chaos harnesses.

    ``turns`` is a list of turn spec dicts (the :func:`~..fleet.sim.
    session_arrivals` shape)::

        {"user_tokens": [...], "max_new_tokens": int, "think_s": float,
         "stalls": [{"at_tokens": int, "stall_s": float,
                     "tool_tokens": [...]}, ...],
         "tool_marker": [...]?}
    """

    def __init__(self, sid, turns: List[dict], start_ts: float = 0.0):
        assert turns, f"session {sid}: at least one turn required"
        self.sid = sid
        self.turns = [dict(t) for t in turns]
        self.start_ts = float(start_ts)
        self.state = SessionState.PENDING
        self.history = [(self.state, self.start_ts)]
        #: the full token history: prompts, generations, and tool results
        #: of every completed turn (+ the current turn's prompt while one
        #: is live) — turn N+1's prompt is exactly this list's value at
        #: its submit
        self.transcript: List[int] = []
        self.turn_idx = -1
        #: live-turn scratch (prompt, detector, stall bookkeeping); None
        #: between turns
        self.cur: Optional[Dict] = None
        #: per-completed-turn receipts: ``{"turn", "submit_ts",
        #: "first_token_ts", "turn_ttft", "finish_ts", "n_tokens",
        #: "stalls_fired"}``
        self.turn_records: List[dict] = []
        self.stalls_fired = 0

    def __repr__(self):
        return (f"Session(sid={self.sid}, state={self.state.value}, "
                f"turn={self.turn_idx + 1}/{len(self.turns)})")

    def to(self, state: SessionState, ts: float) -> None:
        if state not in _SESSION_ALLOWED[self.state]:
            raise ValueError(f"session {self.sid}: illegal transition "
                             f"{self.state.value} -> {state.value}")
        self.state = state
        self.history.append((state, ts))

    @property
    def closed(self) -> bool:
        return self.state is SessionState.CLOSED

    @property
    def completed_turns(self) -> int:
        return len(self.turn_records)

    # ------------------------------------------------------ turn lifecycle

    def begin_turn(self, ts: float) -> List[int]:
        """Start the next turn at ``ts``: extend the transcript with the
        turn's user message and return the full prompt to submit (the
        whole transcript — per-turn prefix growth is the point)."""
        self.turn_idx += 1
        spec = self.turns[self.turn_idx]
        self.transcript.extend(int(t) for t in spec["user_tokens"])
        prompt = list(self.transcript)
        self.cur = {
            "spec": spec,
            "prompt": prompt,
            "detector": ToolCallDetector(
                marker=spec.get("tool_marker"),
                at_counts=[s["at_tokens"] for s in spec.get("stalls", ())]),
            "submit_ts": ts,
            "first_token_ts": None,
            "stall_i": 0,        # next stall spec to consume on a fire
            "tool_tokens": [],   # fired stalls' results, joined at turn end
            "resume_at": None,   # while TOOL_STALL: when the result lands
            "prefetched": False,
        }
        self.to(SessionState.ACTIVE_TURN, ts)
        return prompt

    def note_first_token(self, ts: float) -> None:
        if self.cur is not None and self.cur["first_token_ts"] is None:
            self.cur["first_token_ts"] = ts

    def stall_due(self, tokens: Sequence[int]) -> bool:
        """Should the live turn park for a tool call, given its generated
        tokens so far?  Pure peek — :meth:`enter_stall` consumes."""
        return (self.state is SessionState.ACTIVE_TURN
                and self.cur is not None
                and self.cur["detector"].due(tokens))

    def enter_stall(self, tokens: Sequence[int], ts: float) -> dict:
        """Consume the due trigger and transition to TOOL_STALL; returns
        the stall spec (``stall_s``, ``tool_tokens``) the driver
        schedules the resume from.  A marker fire beyond the seeded
        stall list gets a zero-length default spec."""
        cur = self.cur
        cur["detector"].fire(tokens)
        stalls = cur["spec"].get("stalls", ())
        spec = (stalls[cur["stall_i"]] if cur["stall_i"] < len(stalls)
                else {"stall_s": 0.0, "tool_tokens": []})
        cur["stall_i"] += 1
        cur["resume_at"] = ts + float(spec.get("stall_s", 0.0))
        cur["prefetched"] = False
        self.stalls_fired += 1
        self.to(SessionState.TOOL_STALL, ts)
        return spec

    def exit_stall(self, ts: float) -> None:
        """The seeded tool result arrived: stage its tokens (joined to the
        transcript at turn end — generation itself continues
        byte-identically) and return to ACTIVE_TURN."""
        cur = self.cur
        stalls = cur["spec"].get("stalls", ())
        i = cur["stall_i"] - 1
        if 0 <= i < len(stalls):
            cur["tool_tokens"].extend(int(t)
                                      for t in stalls[i].get("tool_tokens", ()))
        cur["resume_at"] = None
        self.to(SessionState.ACTIVE_TURN, ts)

    def finish_turn(self, generated: Sequence[int], ts: float) -> Optional[float]:
        """The turn's request completed: fold its generation (then any
        tool results) into the transcript, record the turn receipt, and
        advance — returns the think time before the next turn, or None
        when the session just CLOSED."""
        cur = self.cur
        self.transcript.extend(int(t) for t in generated)
        self.transcript.extend(cur["tool_tokens"])
        ftt = cur["first_token_ts"]
        self.turn_records.append({
            "turn": self.turn_idx,
            "submit_ts": cur["submit_ts"],
            "first_token_ts": ftt,
            "turn_ttft": (None if ftt is None
                          else round(ftt - cur["submit_ts"], 9)),
            "finish_ts": ts,
            "n_tokens": len(generated),
            "stalls_fired": cur["stall_i"],
        })
        self.cur = None
        if self.turn_idx + 1 >= len(self.turns):
            self.to(SessionState.CLOSED, ts)
            return None
        think = float(self.turns[self.turn_idx].get("think_s", 0.0))
        self.to(SessionState.THINKING, ts)
        return think

    def abandon(self, ts: float) -> None:
        """Close the session from any live state (a turn was rejected or
        timed out; the session cannot meaningfully continue)."""
        if not self.closed:
            self.cur = None
            self.to(SessionState.CLOSED, ts)

    # ----------------------------------------------------------- receipts

    def turn_ttfts(self) -> List[float]:
        return [r["turn_ttft"] for r in self.turn_records
                if r["turn_ttft"] is not None]
