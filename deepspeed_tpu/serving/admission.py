"""Admission control: bound the queue, refuse what can never run, and gate
sequence starts on real KV/batch headroom.

The raw v2 engine accepts every ``put()`` and only discovers over-commit
mid-step, when ``BlockedAllocator.allocate`` raises "KV cache exhausted"
inside ``StateManager.pack`` — killing the whole serving step, innocent
batchmates included.  The controller moves that failure to the request
boundary (ref: the reference's ragged manager bounds
``max_ragged_sequence_count`` / ``max_tracked_sequences`` at config time;
FastGen's frontend backpressures instead of crashing):

* ``submit``-time:  queue-depth bound (backpressure) and an *infeasibility*
  check — a request whose prompt+output can never fit ``max_pages_per_seq``
  pages, the position table, or the whole arena is rejected immediately
  with a reason, not parked forever.
* ``start``-time:  a queued request is only handed to the engine when a
  batch slot is free and the arena can hold its (resume-)prompt plus one
  decode page — evicting cold prefix-cache pages if that's what it takes
  (the same pressure valve ``ensure_capacity`` uses mid-step).
"""

import dataclasses
from typing import Optional, Tuple

from .request import ServingRequest


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    # backpressure bound on QUEUED requests (submit() rejects past this);
    # <=0 disables the bound
    max_queue_depth: int = 256
    # pages kept free beyond a starting request's prompt demand, so running
    # decodes have room to grow before the preemption valve must open
    kv_headroom_pages: int = 0


class AdmissionController:

    def __init__(self, config: AdmissionConfig, engine):
        self.config = config
        self.engine = engine

    # ------------------------------------------------------------- submit

    def submit_ok(self, req: ServingRequest, queue_depth: int) -> Tuple[bool, Optional[str]]:
        """Admit into the QUEUE?  Returns (ok, reject_reason)."""
        kv = self.engine.kv
        total_tokens = len(req.prompt) + req.max_new_tokens
        if total_tokens > kv.max_pages_per_seq * kv.page_size:
            return False, "exceeds_max_pages_per_seq"
        max_pos = getattr(self.engine.cfg, "max_position_embeddings", None)
        if max_pos is not None and total_tokens > max_pos:
            return False, "exceeds_max_position_embeddings"
        # the whole arena (page 0 is the reserved null page) could not hold
        # this request even running alone — including the start-time headroom
        # can_start will demand, so everything QUEUED is eventually STARTABLE
        # (a queued-but-never-startable head would block the queue forever)
        if -(-total_tokens // kv.page_size) + self.config.kv_headroom_pages \
                > kv.num_pages - 1:
            return False, "exceeds_kv_arena"
        if self.config.max_queue_depth > 0 and queue_depth >= self.config.max_queue_depth:
            return False, "queue_full"
        return True, None

    def retry_after_hint(self, queue_depth: int,
                         ewma_step_s: Optional[float]) -> float:
        """Deterministic retry-after for a ``queue_full`` rejection: the
        estimated time for the standing queue to drain — queue depth times
        the observed per-step seconds (1.0 before the first step, the
        VirtualClock unit).  A conservative upper bound, so callers that
        can probe cheaply (``ServingEngine.submit``'s hinted wait) re-check
        as capacity frees instead of sitting out the whole estimate.  An
        informed wait beats the blind exponential ladder: the client (or
        the fleet router) comes back when capacity plausibly exists
        instead of probing through geometric guesses."""
        per_step = ewma_step_s if ewma_step_s else 1.0
        return round(max(1, queue_depth) * per_step, 6)

    # -------------------------------------------------------------- start

    def _start_pages(self, req: ServingRequest) -> int:
        """Pages a (resume-)prefill needs up front: the full engine prompt
        (original prompt + already-generated tokens) plus one decode page of
        slack — capped at the request's FINAL page count, so the demand never
        exceeds what submit_ok proved feasible (without the cap, a prompt
        ending exactly on a page boundary would demand one page more than it
        can ever use and deadlock at the head of the queue).  Prefix-cache
        hits only reduce this, so it is a safe bound."""
        kv = self.engine.kv
        final = -(-(len(req.prompt) + req.max_new_tokens) // kv.page_size)
        return min(-(-len(req.engine_tokens()) // kv.page_size) + 1, final)

    def can_start(self, req: ServingRequest, reserved_pages: int = 0) -> bool:
        """Hand ``req`` to the engine now?  May evict cache-only prefix
        pages to make room (they are reclaimable capacity, not commitments —
        same policy as ``BlockedKVCache.ensure_capacity``).  Batch capacity
        counts EVERY live engine sequence, not just frontend-admitted ones —
        mixed use (direct ``engine.put()`` callers) must not overflow
        ``StateManager.pack``'s batch bound.  ``reserved_pages``: pages
        already promised to requests admitted earlier in the SAME tick —
        ``put()`` allocates nothing until the step packs, so without the
        reservation every queued request would be tested against the same
        free-page count and the arena over-committed straight into
        preemption churn."""
        if len(self.engine.state.seqs) >= self.engine.state.max_batch:
            return False
        kv = self.engine.kv
        need = self._start_pages(req) + self.config.kv_headroom_pages + reserved_pages
        shortfall = need - kv.allocator.free_pages
        if shortfall > 0 and kv.prefix_cache is not None \
                and shortfall <= kv.prefix_cache.cached_pages:
            # only touch the cache when it could plausibly cover the gap —
            # a blocked head request probed every tick must not drain the
            # cache (and everyone's future prefix hits) for zero admissions.
            # cached_pages over-counts shared/pinned entries, so this can
            # still evict without admitting, but never when provably futile
            kv.prefix_cache.evict(shortfall)
            shortfall = need - kv.allocator.free_pages
        return shortfall <= 0
