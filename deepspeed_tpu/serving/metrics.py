"""Serving metric aggregation: latency percentiles, goodput, rates.

Definitions (docs/SERVING.md):
  TTFT      — first generated token ts minus ARRIVAL ts (queue wait included).
  TPOT      — (finish ts - first token ts) / (n_tokens - 1).
  goodput   — requests that finished WITHIN their deadline, per second of
              clock time (the FastGen blog's effective-throughput quantity:
              work that missed its SLA earns nothing).
  rejection_rate / preemption_rate / timeout_rate are per SUBMITTED request.
"""

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from .request import RequestState, ServingRequest


def percentile_summary(xs: List[float]) -> Dict[str, Optional[float]]:
    """p50/p95/p99 of a sample (None-filled when empty)."""
    if not xs:
        return {"p50": None, "p95": None, "p99": None, "mean": None, "n": 0}
    arr = np.asarray(xs, np.float64)
    return {"p50": round(float(np.percentile(arr, 50)), 6),
            "p95": round(float(np.percentile(arr, 95)), 6),
            "p99": round(float(np.percentile(arr, 99)), 6),
            "mean": round(float(arr.mean()), 6),
            "n": int(arr.size)}


@dataclasses.dataclass
class ServingStats:
    """Counters + completed-request log the frontend maintains.

    ``finished`` retains every terminal request (full prompt + tokens) so
    ``summary()`` can compute exact percentiles over a bench run's lifetime.
    A long-lived WallClock server should periodically swap in a fresh
    ``ServingStats`` (``engine.stats = ServingStats()``) after reporting a
    window, or memory grows linearly with request count."""
    submitted: int = 0
    rejected: int = 0
    timed_out: int = 0
    preemptions: int = 0       # events, not requests (one request can be evicted twice)
    migrated: int = 0          # requests handed off with their KV (kvtransfer)
    kv_imports: int = 0        # KV-import fast-path resumes on THIS replica
    kv_import_fallbacks: int = 0   # snapshot rejected -> recompute-on-resume
    parks: int = 0             # sessions parked to the host KV tier (kvtier)
    resumes: int = 0           # parked sessions re-enqueued for promotion
    prefix_imports: int = 0        # hot-prefix page imports adopted here
    prefix_import_pages: int = 0   # pages those imports scattered in
    reject_reasons: Dict[str, int] = dataclasses.field(default_factory=dict)
    finished: List[ServingRequest] = dataclasses.field(default_factory=list)

    def record_reject(self, reason: str) -> None:
        self.rejected += 1
        self.reject_reasons[reason] = self.reject_reasons.get(reason, 0) + 1

    def record_terminal(self, req: ServingRequest) -> None:
        if req.state is RequestState.TIMED_OUT:  # dslint-ok(state-machine): only the timed_out/migrated tallies live here — DONE is derived from `finished` and REJECTED is counted in record_reject
            self.timed_out += 1
        elif req.state is RequestState.MIGRATED:
            self.migrated += 1
        self.finished.append(req)

    @property
    def completed(self) -> List[ServingRequest]:
        return [r for r in self.finished if r.state is RequestState.DONE]

    def summary(self, elapsed: float) -> dict:
        """Aggregate record over ``elapsed`` seconds of clock time."""
        done = self.completed
        met = [r for r in done if r.met_deadline]
        n_sub = max(1, self.submitted)
        elapsed = max(elapsed, 1e-9)
        return {
            "submitted": self.submitted,
            "completed": len(done),
            "rejected": self.rejected,
            "timed_out": self.timed_out,
            "preemptions": self.preemptions,
            "preempted_requests": sum(1 for r in self.finished if r.preemptions),
            "migrated": self.migrated,
            "kv_imports": self.kv_imports,
            "kv_import_fallbacks": self.kv_import_fallbacks,
            "parks": self.parks,
            "resumes": self.resumes,
            "prefix_imports": self.prefix_imports,
            "prefix_import_pages": self.prefix_import_pages,
            "deadline_met": len(met),
            "rejection_rate": round(self.rejected / n_sub, 4),
            "preemption_rate": round(self.preemptions / n_sub, 4),
            "timeout_rate": round(self.timed_out / n_sub, 4),
            "goodput_rps": round(len(met) / elapsed, 6),
            "completed_rps": round(len(done) / elapsed, 6),
            "tokens_generated": sum(len(r.tokens) for r in self.finished),
            "elapsed": round(elapsed, 6),
            "ttft": percentile_summary([r.ttft for r in done if r.ttft is not None]),
            "tpot": percentile_summary([r.tpot for r in done if r.tpot is not None]),
            "queue_wait": percentile_summary(
                [r.queue_wait for r in done if r.queue_wait is not None]),
            "reject_reasons": dict(self.reject_reasons),
        }
