"""KV-pressure manager: make the next engine step feasible, or shed load.

Without this, a decode step that needs one more page than the arena has
raises ``KV cache exhausted`` from inside ``StateManager.pack`` — after
some batchmates already allocated theirs, so even the survivors' step is
lost.  The manager preflights the scheduler's plan against
``BlockedAllocator.free_pages`` and closes any gap in escalation order:

1. evict cold prefix-cache pages (pure cache — reclaimable, costs a future
   prefill speedup, never correctness);
2. preempt the YOUNGEST sequence (latest arrival — it has the least sunk
   prefill/decode work and, under FCFS, the weakest claim): release its
   pages via ``BlockedKVCache.release`` and hand the descriptor back to the
   frontend for requeue-with-tokens-preserved (recompute-on-resume).
   Decodes are preempted before prefills only via youth order falling out
   of FCFS admission; a mid-prefill victim loses only its partial pages.

When a host KV tier is attached (``serving/kvtier`` — set via
``ServingEngine``), step 2 becomes DEMOTION-FIRST: the victim's pages are
staged to the host tier before ``preempt`` frees them, so its resume
promotes the staged copy back instead of recomputing the prompt.  A
failed demotion (transient fault, host tier full) degrades to the plain
evict+recompute above — slower, never wrong.

The worst-case demand is evaluated at the single-token rung (k=1): the
fused multi-decode path already self-shrinks ``k`` under page pressure
(``engine_v2.step``), so k=1 feasibility guarantees the step runs.
"""

from typing import Callable, List, Optional

from ..inference.v2.ragged import SequenceDescriptor
from ..utils.logging import logger


class KVPressureManager:

    def __init__(self, engine, youth_key: Optional[Callable[[int], tuple]] = None):
        """``youth_key(uid)`` orders preemption victims — HIGHEST key is
        evicted first (youngest).  Default: uid order (uids are allocated
        monotonically by the frontend, so this is arrival order)."""
        self.engine = engine
        self.youth_key = youth_key or (lambda uid: uid)
        #: optional TieredKVManager (serving/kvtier): when set, victims are
        #: demoted to the host tier before preemption (demotion-first)
        self.tier = None

    def resolve(self):
        """Evict cache pages / preempt sequences until the planned step fits.
        Returns (preempted descriptors for the frontend to requeue, the
        final feasible StepPlan — valid until the state next mutates, so the
        caller can hand it straight to ``engine.step(plan)`` instead of
        re-planning)."""
        engine = self.engine
        kv = engine.kv
        evicted: List[SequenceDescriptor] = []
        while True:
            plan = engine.scheduler.plan(engine.state)
            need = engine.single_step_page_demand(plan)
            shortfall = need - kv.allocator.free_pages
            if shortfall <= 0:
                return evicted, plan
            if kv.prefix_cache is not None:
                if kv.prefix_cache.evict(shortfall) > 0:
                    continue  # re-check: cache pages may have covered it
            victims = [s for s in plan.decode] + [s for s, _ in plan.prefill]
            # paused sequences (mid-KV-migration) hold pages but take no
            # step work, so they never appear in the plan — they are still
            # preemptible capacity (the migration layer detects the
            # eviction and falls back to recompute-on-resume)
            victims += [s for s in engine.state.seqs.values()
                        if s.paused and not s.done]
            if not victims:
                # nothing to shed — pack() would raise; surface a clear error
                raise RuntimeError(
                    f"KV pressure unresolvable: step needs {need} pages, "
                    f"{kv.allocator.free_pages} free, nothing preemptible")
            victim = max(victims, key=lambda s: self.youth_key(s.uid))
            if self.tier is not None:
                # demotion-first: stage the victim's KV host-side while its
                # pages are still valid; the frontend attaches the handle
                # in _on_preempted so the resume promotes, not recomputes.
                # None (failed/refused demotion) falls through to plain
                # evict+recompute.
                self.tier.demote_sequence(victim.uid)
            logger.debug(f"KV pressure: preempting uid={victim.uid} "
                         f"({len(victim.pages)} pages, shortfall {shortfall})")
            evicted.append(engine.preempt(victim.uid))
