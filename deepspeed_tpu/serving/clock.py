"""Clock abstraction for the serving loop.

The frontend never calls ``time`` directly: all timestamps (arrival,
deadline, TTFT/TPOT) come from a clock object, so the SAME loop runs in
two modes:

* :class:`WallClock` — real serving: ``now()`` is monotonic wall time and
  engine steps take however long they take.
* :class:`VirtualClock` — deterministic CPU tests and the load harness's
  ``--dryrun``: time advances only when the loop says so (one configurable
  cost unit per engine step), so percentile latencies are reproducible
  bit-for-bit across runs and machines.  This is what lets the SLA harness
  be a tier-1 CPU test instead of a flaky timing test.
"""

import time


class VirtualClock:
    """Deterministic logical time; the serving loop advances it explicitly."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def reset(self) -> None:
        """Re-zero.  Callers that build expensive state (engine warmup)
        before serving reset the clock so t=0 means 'serving starts', not
        'process started'; on a virtual clock construction costs nothing so
        this is a no-op unless time was explicitly advanced."""
        self._now = 0.0

    def advance(self, dt: float) -> None:
        # explicit raise, not assert: time-domain integrity must hold under
        # ``python -O`` too — a negative (or NaN) step cost would silently
        # rewind every timestamp derived from this clock
        if not dt >= 0:
            raise ValueError(f"virtual clock cannot go backwards (dt={dt})")
        self._now += dt

    def wait_until(self, ts: float) -> None:
        """Jump to ``ts`` (idle gap between arrivals).  A ``ts`` in the
        past — a stale deadline, an out-of-order arrival — CLAMPS to
        ``now()``: the clock never rewinds (telemetry timestamps and
        latency accounting assume monotonic time).  NaN is rejected."""
        ts = float(ts)
        if ts != ts:
            raise ValueError("wait_until(NaN)")
        if ts > self._now:
            self._now = ts

    def on_step(self, cost: float) -> float:
        """One engine step consumed ``cost`` virtual seconds.  Returns the
        charged duration (clocks that account the cost themselves return it;
        WallClock returns None and the caller measures real elapsed time)."""
        self.advance(cost)
        return cost


class WallClock:
    """Monotonic wall time (zeroed at construction so timestamps are small
    and comparable with VirtualClock-based configs)."""

    def __init__(self):
        self._t0 = time.monotonic()

    def now(self) -> float:
        return time.monotonic() - self._t0

    def reset(self) -> None:
        """Re-zero so t=0 is 'serving starts' (see VirtualClock.reset —
        engine build/warmup before the drive loop must not age the
        workload's arrival timestamps and deadlines past before it runs)."""
        self._t0 = time.monotonic()

    def wait_until(self, ts: float) -> None:
        delta = ts - self.now()
        if delta > 0:
            time.sleep(delta)

    def on_step(self, cost: float) -> None:
        # real time already passed during the step; None tells the caller
        # to measure the wall-clock duration itself
        return None


class ReplicaClockView:
    """Per-replica view of one shared :class:`VirtualClock` for the fleet
    simulator.

    N replicas of a fleet step CONCURRENTLY in a real deployment, so a
    simulated round in which every replica runs one tick must advance time
    by the SLOWEST replica's step cost — not the sum (which would model the
    replicas taking turns and erase the fleet's throughput scaling).  Each
    replica's ServingEngine gets a view: ``now()`` reads the shared clock,
    ``on_step`` RECORDS the cost instead of advancing, and the fleet driver
    advances the shared clock once per round by ``max(take_cost())`` over
    the replicas that ticked."""

    def __init__(self, shared: VirtualClock):
        self.shared = shared
        self._pending_cost = 0.0

    def now(self) -> float:
        return self.shared.now()

    def wait_until(self, ts: float) -> None:
        self.shared.wait_until(ts)

    def on_step(self, cost: float) -> float:
        # same backwards-time stance as VirtualClock.advance: a negative
        # recorded cost would silently shrink the fleet round
        if not cost >= 0:
            raise ValueError(f"replica step cost cannot be negative (cost={cost})")
        self._pending_cost = max(self._pending_cost, cost)
        return cost

    def take_cost(self) -> float:
        """Drain the cost recorded since the last take (the fleet driver
        calls this once per replica per round)."""
        cost, self._pending_cost = self._pending_cost, 0.0
        return cost
