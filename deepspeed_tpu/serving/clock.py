"""Clock abstraction for the serving loop.

The frontend never calls ``time`` directly: all timestamps (arrival,
deadline, TTFT/TPOT) come from a clock object, so the SAME loop runs in
two modes:

* :class:`WallClock` — real serving: ``now()`` is monotonic wall time and
  engine steps take however long they take.
* :class:`VirtualClock` — deterministic CPU tests and the load harness's
  ``--dryrun``: time advances only when the loop says so (one configurable
  cost unit per engine step), so percentile latencies are reproducible
  bit-for-bit across runs and machines.  This is what lets the SLA harness
  be a tier-1 CPU test instead of a flaky timing test.
"""

import time


class VirtualClock:
    """Deterministic logical time; the serving loop advances it explicitly."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        assert dt >= 0, f"virtual clock cannot go backwards (dt={dt})"
        self._now += dt

    def wait_until(self, ts: float) -> None:
        """Jump to ``ts`` (idle gap between arrivals); never rewinds."""
        self._now = max(self._now, ts)

    def on_step(self, cost: float) -> None:
        """One engine step consumed ``cost`` virtual seconds."""
        self.advance(cost)


class WallClock:
    """Monotonic wall time (zeroed at construction so timestamps are small
    and comparable with VirtualClock-based configs)."""

    def __init__(self):
        self._t0 = time.monotonic()

    def now(self) -> float:
        return time.monotonic() - self._t0

    def wait_until(self, ts: float) -> None:
        delta = ts - self.now()
        if delta > 0:
            time.sleep(delta)

    def on_step(self, cost: float) -> None:
        # real time already passed during the step
        pass
