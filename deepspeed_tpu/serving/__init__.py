"""SLA-aware serving frontend over the FastGen-v2 engine.

Turns ``InferenceEngineV2`` (sequences, ``put()``/``step()``) into a
servable endpoint (requests, deadlines, admission, preemption, latency
percentiles).  See docs/SERVING.md for the state machine, policies, and
metric definitions.
"""

from .admission import AdmissionConfig, AdmissionController
from .clock import ReplicaClockView, VirtualClock, WallClock
from .engine import ServingConfig, ServingEngine
from .kv_pressure import KVPressureManager
from .kvtransfer import (KVExporter, KVImportError, KVSnapshot,
                         SnapshotAborted, SnapshotError,
                         SnapshotIntegrityError, import_snapshot)
from .metrics import ServingStats, percentile_summary
from .request import RequestState, ServingRequest

__all__ = [
    "AdmissionConfig", "AdmissionController", "ReplicaClockView",
    "VirtualClock", "WallClock",
    "ServingConfig", "ServingEngine", "KVPressureManager", "ServingStats",
    "percentile_summary", "RequestState", "ServingRequest",
    "KVExporter", "KVImportError", "KVSnapshot", "SnapshotAborted",
    "SnapshotError", "SnapshotIntegrityError", "import_snapshot",
]
