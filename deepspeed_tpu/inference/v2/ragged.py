"""Ragged-batching state: blocked KV allocator, sequence descriptors,
batch packing.

Reference: ``deepspeed/inference/v2/ragged/`` —
  BlockedAllocator   (blocked_allocator.py)  → :class:`BlockedAllocator`
  BlockedKVCache     (kv_cache.py:40)        → :class:`BlockedKVCache`
  DSSequenceDescriptor (sequence_descriptor.py) → :class:`SequenceDescriptor`
  RaggedBatchWrapper (ragged_wrapper.py:31)  → :class:`RaggedBatch`
  DSStateManager     (ragged_manager.py:19)  → :class:`StateManager`

The reference's C++ atom-builder/fast-host-buffer machinery
(``ragged/csrc``) exists to assemble device metadata quickly per step; here
the metadata are small numpy arrays handed to a jitted program, so plain
Python suffices on the host side while the device side stays compiled.
"""

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class BlockedAllocator:
    """Free-list allocator over KV pages (ref: blocked_allocator.py).
    Page 0 is reserved as the null page that unused block-table slots
    reference."""

    def __init__(self, num_pages: int):
        assert num_pages >= 2
        self.num_pages = num_pages
        self._free: List[int] = list(range(1, num_pages))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(f"KV cache exhausted: need {n} pages, have {len(self._free)}")
        pages, self._free = self._free[:n], self._free[n:]
        return pages

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            assert 0 < p < self.num_pages
        self._free.extend(pages)


@dataclasses.dataclass
class SequenceDescriptor:
    """Host-side state of one generation (ref: DSSequenceDescriptor)."""
    uid: int
    tokens: List[int]                      # full token history (prompt + generated)
    pages: List[int] = dataclasses.field(default_factory=list)
    seen_tokens: int = 0                   # tokens whose KV is in cache
    generated: List[int] = dataclasses.field(default_factory=list)
    done: bool = False

    @property
    def remaining_prefill(self) -> int:
        return len(self.tokens) - self.seen_tokens

    @property
    def in_prefill(self) -> bool:
        return self.remaining_prefill > 0

    @property
    def in_decode(self) -> bool:
        """Generating: the single unseen token is a sampled one (its KV write
        + next-token logits are one C=1 step)."""
        return bool(self.generated) and self.remaining_prefill <= 1


class BlockedKVCache:
    """Geometry + allocator pairing (ref: kv_cache.py:40).  The device
    arena itself lives in the engine (a donated jax array)."""

    def __init__(self, num_pages: int, page_size: int, max_pages_per_seq: int):
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self.allocator = BlockedAllocator(num_pages)

    def pages_needed(self, seq: SequenceDescriptor, new_tokens: int) -> int:
        total = len(seq.tokens) if new_tokens == 0 else seq.seen_tokens + new_tokens
        needed = -(-total // self.page_size)  # ceil
        return max(0, needed - len(seq.pages))

    def ensure_capacity(self, seq: SequenceDescriptor, new_tokens: int) -> None:
        n = self.pages_needed(seq, new_tokens)
        if n:
            if len(seq.pages) + n > self.max_pages_per_seq:
                raise RuntimeError(f"sequence {seq.uid} exceeds max_pages_per_seq={self.max_pages_per_seq}")
            seq.pages.extend(self.allocator.allocate(n))

    def release(self, seq: SequenceDescriptor) -> None:
        self.allocator.free(seq.pages)
        seq.pages = []


@dataclasses.dataclass
class RaggedBatch:
    """One step's packed device inputs (ref: RaggedBatchWrapper) — fixed
    max shapes so the compiled program is reused across steps."""
    tokens: np.ndarray        # [B, C] int32 (padded)
    start_pos: np.ndarray     # [B] int32 — context length before this chunk
    block_tables: np.ndarray  # [B, max_pages] int32 (null page 0 padded)
    chunk_lens: np.ndarray    # [B] int32 — real tokens this step (0 = padding row)
    uids: List[int]           # row → uid (len B; padding rows map to -1)

    @property
    def batch(self) -> int:
        return self.tokens.shape[0]


class StateManager:
    """uid → descriptor bookkeeping + batch packing (ref: DSStateManager)."""

    def __init__(self, kv: BlockedKVCache, max_batch: int = 64):
        self.kv = kv
        self.max_batch = max_batch
        self.seqs: Dict[int, SequenceDescriptor] = {}

    def get_or_create(self, uid: int, tokens: Optional[Sequence[int]] = None) -> SequenceDescriptor:
        if uid not in self.seqs:
            self.seqs[uid] = SequenceDescriptor(uid=uid, tokens=list(tokens or []))
        elif tokens:
            self.seqs[uid].tokens.extend(tokens)
        return self.seqs[uid]

    def flush(self, uid: int) -> None:
        """Release a sequence's KV + state (ref: engine_v2.py flush)."""
        seq = self.seqs.pop(uid, None)
        if seq is not None:
            self.kv.release(seq)

    def pack(self, work: List[Tuple[SequenceDescriptor, int]], chunk: int,
             pad_to: Optional[int] = None) -> RaggedBatch:
        """Pack (seq, n_tokens) work items into fixed [B, chunk] buffers.

        B is padded to ``pad_to`` (default ``max_batch``) so the compiled
        step program keeps ONE shape across scheduler decisions — padding
        rows have uid -1, chunk_len 0, and an all-null block table."""
        b = pad_to if pad_to is not None else self.max_batch
        assert len(work) <= b, f"{len(work)} work items exceed batch capacity {b}"
        tokens = np.zeros((b, chunk), np.int32)
        start_pos = np.zeros((b, ), np.int32)
        block_tables = np.zeros((b, self.kv.max_pages_per_seq), np.int32)
        chunk_lens = np.zeros((b, ), np.int32)
        uids = [-1] * b
        for i, (seq, n) in enumerate(work):
            self.kv.ensure_capacity(seq, n)
            sl = seq.tokens[seq.seen_tokens:seq.seen_tokens + n]
            tokens[i, :len(sl)] = sl
            start_pos[i] = seq.seen_tokens
            block_tables[i, :len(seq.pages)] = seq.pages
            chunk_lens[i] = n
            uids[i] = seq.uid
        return RaggedBatch(tokens=tokens, start_pos=start_pos, block_tables=block_tables,
                           chunk_lens=chunk_lens, uids=uids)
