"""Ragged-batching state: blocked KV allocator, sequence descriptors,
batch packing.

Reference: ``deepspeed/inference/v2/ragged/`` —
  BlockedAllocator   (blocked_allocator.py)  → :class:`BlockedAllocator`
  BlockedKVCache     (kv_cache.py:40)        → :class:`BlockedKVCache`
  DSSequenceDescriptor (sequence_descriptor.py) → :class:`SequenceDescriptor`
  RaggedBatchWrapper (ragged_wrapper.py:31)  → :class:`RaggedBatch`
  DSStateManager     (ragged_manager.py:19)  → :class:`StateManager`

The reference's C++ atom-builder/fast-host-buffer machinery
(``ragged/csrc``) exists to assemble device metadata quickly per step; here
the metadata are small numpy arrays handed to a jitted program, so plain
Python suffices on the host side while the device side stays compiled.
"""

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class BlockedAllocator:
    """Refcounted free-list allocator over KV pages (ref:
    blocked_allocator.py).  Page 0 is reserved as the null page that unused
    block-table slots reference.  Refcounts exist for prefix caching: a full
    page can be referenced by several sequences plus the
    :class:`PrefixCacheManager`; it returns to the free list only when the
    last reference drops."""

    def __init__(self, num_pages: int):
        assert num_pages >= 2
        self.num_pages = num_pages
        self._free: List[int] = list(range(1, num_pages))
        self._rc = np.zeros(num_pages, np.int32)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(f"KV cache exhausted: need {n} pages, have {len(self._free)}")
        pages, self._free = self._free[:n], self._free[n:]
        self._rc[pages] = 1
        return pages

    def retain(self, pages: Sequence[int]) -> None:
        for p in pages:
            assert self._rc[p] > 0, f"retain of unallocated page {p}"
            self._rc[p] += 1

    def refcount(self, page: int) -> int:
        return int(self._rc[page])

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            assert 0 < p < self.num_pages and self._rc[p] > 0
            self._rc[p] -= 1
            if self._rc[p] == 0:
                self._free.append(p)


#: seed of the prefix chain hash — shared by :class:`PrefixCacheManager`
#: and the fleet's router-resident prefix directory
#: (serving/fleet/prefix_directory.py), which must compute IDENTICAL
#: digests from tokens alone to know which replica holds which pages
PREFIX_CHAIN_SEED = 0x9E3779B9


def iter_prefix_chain_hashes(tokens: Sequence[int], page_size: int):
    """Lazily yield the chain hash of each FULL page of ``tokens``:
    ``h_k = hash(h_{k-1}, tokens[k*P:(k+1)*P])`` from
    :data:`PREFIX_CHAIN_SEED`, so a match on ``h_k`` transitively pins
    every earlier token.  This is THE digest rule the prefix cache keys
    pages by and the fleet prefix directory routes on — one rule, two
    consumers, no way to drift.  A generator so hot-path walkers that
    stop at the first miss stop HASHING there too.  Deterministic across
    processes for integer tokens (int/tuple hashing is not salted)."""
    h = PREFIX_CHAIN_SEED
    for i in range(len(tokens) // page_size):
        h = hash((h, tuple(tokens[i * page_size:(i + 1) * page_size])))
        yield h


def prefix_chain_hashes(tokens: Sequence[int], page_size: int) -> List[int]:
    """Materialized form of :func:`iter_prefix_chain_hashes`."""
    return list(iter_prefix_chain_hashes(tokens, page_size))


@dataclasses.dataclass
class SequenceDescriptor:
    """Host-side state of one generation (ref: DSSequenceDescriptor)."""
    uid: int
    tokens: List[int]                      # full token history (prompt + generated)
    pages: List[int] = dataclasses.field(default_factory=list)
    seen_tokens: int = 0                   # tokens whose KV is in cache
    generated: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    # migration pause (serving/kvtransfer): a paused sequence keeps its
    # state and KV pages but is excluded from step planning, so its pages
    # stay byte-stable while chunks of them are staged device->host between
    # the engine's ongoing decode steps
    paused: bool = False
    # prefix-cache cursor: pages [0, pc_pages) are already published (or came
    # from the cache); pc_hash is the running chain hash at that boundary, so
    # each register() call hashes only NEW full pages (O(1) amortized per
    # token instead of rehashing the whole history every step)
    pc_pages: int = 0
    pc_hash: int = 0

    @property
    def remaining_prefill(self) -> int:
        return len(self.tokens) - self.seen_tokens

    @property
    def in_prefill(self) -> bool:
        return self.remaining_prefill > 0

    @property
    def in_decode(self) -> bool:
        """Generating: the single unseen token is a sampled one (its KV write
        + next-token logits are one C=1 step)."""
        return bool(self.generated) and self.remaining_prefill <= 1


class PrefixCacheManager:
    """KV-page reuse across sequences sharing a token prefix
    (ref: inference/v2/ragged/prefix_cache_manager.py:13).

    Full, token-aligned pages are content-addressed by a *chain hash* over
    the whole token history they terminate — page k of a sequence is keyed
    by H_k = hash(H_{k-1}, tokens[k·P:(k+1)·P]) — so a hit on H_k
    transitively guarantees every earlier token matches too.  Matched pages
    are attached to the new sequence read-only (full pages are immutable:
    KV writes only ever land in the trailing partial page) and the prefill
    skips straight past them.  The cache holds one refcount on every
    registered page, so pages survive their creator's release and are
    evicted LRU only under allocator pressure."""

    _SEED = PREFIX_CHAIN_SEED

    def __init__(self, allocator: "BlockedAllocator", page_size: int):
        self.allocator = allocator
        self.page_size = page_size
        #: optional publish/evict notification sink: ``listener(event,
        #: chain_hash)`` with event ``"publish"`` (a full page entered the
        #: cache — register() or adopt()) or ``"evict"`` (it left).  The
        #: fleet ReplicaPool wires this to the router-resident
        #: PrefixDirectory so routing warmth is pushed, not probed; None
        #: (the default) costs one ``is None`` test per transition.
        self.listener = None
        #: optional eviction demoter: ``demoter(chain_hash, page_id,
        #: tokens, parent_hash)`` called by :meth:`evict` BEFORE the page
        #: is freed (while its KV bytes are still valid to gather) — the
        #: serving kvtier stages the page host-side so the chain stays
        #: warm-on-host instead of going cold.  Must not allocate or free
        #: device pages; None (the default) keeps eviction unchanged.
        self.demoter = None
        # chain hash → (page id, page's token tuple, parent chain hash).
        # The tokens are kept for verification on match: a 64-bit hash
        # collision would otherwise silently attach another prompt's KV
        # pages (wrong output + cross-request prompt leakage); verifying
        # costs O(page_size) per hit.  The parent hash maintains per-entry
        # child counts so eviction only ever removes LEAVES.
        self._pages: Dict[int, Tuple[int, tuple, Optional[int]]] = {}
        # chain hash → set of live CHILD hashes.  Edges are recorded even
        # when the parent entry is currently absent (evicted): if the parent
        # is later re-registered while the child still lives, the edge must
        # already exist or leaf-only eviction would free the parent and
        # strand the child (a count-based scheme can't survive that order)
        self._children: Dict[int, set] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # chain hash, oldest first
        self.hits = 0
        self.misses = 0

    def _chain(self, tokens: Sequence[int]):
        """Yield (chain_hash, page_index) for each FULL page of ``tokens``
        (delegates to :func:`iter_prefix_chain_hashes` — the one digest
        rule the fleet prefix directory shares; lazy, so a walker that
        stops at the first miss stops hashing there too)."""
        for i, h in enumerate(iter_prefix_chain_hashes(tokens, self.page_size)):
            yield h, i

    def _notify(self, event: str, h: int) -> None:
        if self.listener is not None:
            self.listener(event, h)

    def _walk(self, tokens: Sequence[int]):
        """Yield ``(chain_hash, page_id)`` for the longest run of cached
        full pages covering a prefix of ``tokens`` — the ONE matching rule
        (chain walk, token verification, last-token cap) shared by the
        mutating :meth:`match` and the read-only :meth:`lookup_depth`, so
        routing warmth can never desynchronize from what a subsequent
        match() actually attaches.  Caps at len(tokens)-1: the engine must
        still compute at least one prompt token (its logits seed
        generation)."""
        usable = len(tokens) - 1
        for h, i in self._chain(tokens):
            if (i + 1) * self.page_size > usable:
                return
            entry = self._pages.get(h)
            if entry is None or entry[1] != tuple(tokens[i * self.page_size:(i + 1) * self.page_size]):
                return
            yield h, entry[0]

    def match(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest run of cached pages covering a prefix of ``tokens``,
        plus the chain hash at the match boundary (the caller seeds the
        sequence's register() cursor with it).  Returned pages are retained
        on behalf of the caller."""
        matched: List[int] = []
        h_end = self._SEED
        for h, page in self._walk(tokens):
            matched.append(page)
            h_end = h
            self._lru.move_to_end(h)  # whole chain refreshed root→leaf
        if matched:
            self.allocator.retain(matched)
            self.hits += 1
        elif len(tokens) > self.page_size:
            self.misses += 1
        return matched, h_end

    def lookup_depth(self, tokens: Sequence[int]) -> int:
        """How many leading FULL pages of ``tokens`` this cache holds —
        WITHOUT retaining pages, touching the LRU, or counting a hit/miss.
        The fleet router's prefix-affinity policy probes every replica's
        cache with this to find the warmest one; a mutating probe would
        retain pages on replicas that never receive the request (leaking
        refcounts) and refresh their LRU for traffic they never served.
        Shares :meth:`match`'s traversal (``_walk``), so the reported
        warmth is exactly what a subsequent match() would attach."""
        return sum(1 for _ in self._walk(tokens))

    def register(self, seq: "SequenceDescriptor") -> None:
        """Publish ``seq``'s newly-completed full pages, resuming from the
        sequence's cursor so each page is hashed exactly once.  A hash
        already mapped to a different page keeps the existing mapping
        (dedup would require copying KV — not worth it)."""
        full = min(seq.seen_tokens // self.page_size, len(seq.pages))
        h = seq.pc_hash if seq.pc_pages else self._SEED
        for i in range(seq.pc_pages, full):
            parent = h if i else None
            page_toks = tuple(seq.tokens[i * self.page_size:(i + 1) * self.page_size])
            h = hash((h, page_toks))
            if h not in self._pages:
                self._pages[h] = (seq.pages[i], page_toks, parent)
                if parent is not None:
                    self._children.setdefault(parent, set()).add(h)
                self._lru[h] = None
                self.allocator.retain([seq.pages[i]])
                self._notify("publish", h)
        seq.pc_pages = full
        seq.pc_hash = h if full else seq.pc_hash

    def evict(self, n: int) -> int:
        """Drop up to ``n`` cache-only pages: LRU order, but LEAVES only.

        Freeing a chain's root would make every descendant unmatchable
        (match() walks from page 0) while their pages stay pinned — and a
        plain reversed-LRU walk would be global MRU eviction, thrashing the
        hottest chain first.  Entries with live children are skipped, so a
        cold chain dies leaf-by-leaf from the oldest while a hot chain's
        recently-touched entries survive.  Each freed leaf may expose its
        parent, so the sweep repeats until the quota is met or nothing is
        evictable.  Returns how many pages were freed."""
        freed = 0
        for h in list(self._lru):
            if freed >= n:
                break
            # cascade: freeing a leaf exposes its parent — keep consuming
            # THIS (older) chain before the sweep reaches hotter entries
            while h is not None and freed < n and h in self._pages:
                if self._children.get(h):
                    break  # has live descendants: they would be stranded
                page, toks, parent = self._pages[h]
                if self.allocator.refcount(page) != 1:
                    break  # a live sequence still shares this page
                if self.demoter is not None:
                    # stage the page host-side BEFORE freeing (kvtier)
                    self.demoter(h, page, toks, parent)
                self.allocator.free([page])
                del self._pages[h]
                del self._lru[h]
                self._children.pop(h, None)
                if parent is not None and parent in self._children:
                    self._children[parent].discard(h)
                    if not self._children[parent]:
                        del self._children[parent]
                freed += 1
                self._notify("evict", h)
                h = parent
        return freed

    def held_depth(self, tokens: Sequence[int]) -> int:
        """Leading FULL pages of ``tokens`` this cache holds, WITHOUT the
        last-token usable cap :meth:`lookup_depth` applies — cache-
        population accounting (what a prefix import may skip), not a match
        preview (what a prefill can reuse)."""
        depth = 0
        for h, i in self._chain(tokens):
            entry = self._pages.get(h)
            if entry is None or entry[1] != tuple(
                    tokens[i * self.page_size:(i + 1) * self.page_size]):
                break
            depth += 1
        return depth

    def adopt(self, tokens: Sequence[int], start_page: int,
              page_ids: Sequence[int]) -> None:
        """Insert externally-imported full pages ``start_page ..
        start_page+len(page_ids)-1`` of ``tokens`` (the fleet's hot-prefix
        KV import: the page CONTENT was scattered into the arena by the
        caller; this publishes the chain entries so the next ``match()``
        attaches them).  The caller transfers exactly ONE refcount per page
        to the cache — the allocation it made for the import — matching
        register()'s invariant that the cache holds one reference per
        entry.  A hash already present keeps its existing page and the
        duplicate id is freed (same dedup stance as register)."""
        chain = prefix_chain_hashes(tokens, self.page_size)
        assert start_page + len(page_ids) <= len(chain), \
            (start_page, len(page_ids), len(chain))
        for j, page in enumerate(page_ids):
            i = start_page + j
            h = chain[i]
            if h in self._pages:
                # raced with a local prefill publishing the same page:
                # keep the incumbent, return the duplicate's refcount
                self.allocator.free([page])
                continue
            parent = chain[i - 1] if i else None
            page_toks = tuple(tokens[i * self.page_size:(i + 1) * self.page_size])
            self._pages[h] = (page, page_toks, parent)
            if parent is not None:
                self._children.setdefault(parent, set()).add(h)
            self._lru[h] = None
            self._notify("publish", h)

    def held_digests(self) -> List[int]:
        """Chain hashes of every resident full page, in insertion order —
        the fleet directory's RESYNC snapshot (docs/SERVING.md
        "Control-plane transport"): when the router detects a gap in this
        replica's sequence-numbered publish stream, it pulls exactly this
        set and rebuilds its view instead of guessing."""
        return list(self._pages)

    def chain_tokens(self, h: int) -> Optional[List[int]]:
        """Reconstruct the full token prefix whose last page is chain
        entry ``h`` by walking parent links root-ward — the
        directory-driven warm-up input (the directory stores digests only;
        the DONOR's cache owns the tokens).  None when the chain is absent
        or broken (a concurrent eviction): warm-up just skips it."""
        parts = []
        while h is not None:
            entry = self._pages.get(h)
            if entry is None:
                return None
            _pid, toks, parent = entry
            parts.append(toks)
            h = parent
        return [t for part in reversed(parts) for t in part]

    @property
    def cached_pages(self) -> int:
        return len(self._pages)


class BlockedKVCache:
    """Geometry + allocator pairing (ref: kv_cache.py:40).  The device
    arena itself lives in the engine (a donated jax array)."""

    def __init__(self, num_pages: int, page_size: int, max_pages_per_seq: int,
                 enable_prefix_cache: bool = True):
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self.allocator = BlockedAllocator(num_pages)
        self.prefix_cache = (PrefixCacheManager(self.allocator, page_size)
                             if enable_prefix_cache else None)

    def pages_needed(self, seq: SequenceDescriptor, new_tokens: int) -> int:
        total = len(seq.tokens) if new_tokens == 0 else seq.seen_tokens + new_tokens
        needed = -(-total // self.page_size)  # ceil
        return max(0, needed - len(seq.pages))

    def ensure_capacity(self, seq: SequenceDescriptor, new_tokens: int) -> None:
        n = self.pages_needed(seq, new_tokens)
        if n:
            if len(seq.pages) + n > self.max_pages_per_seq:
                raise RuntimeError(f"sequence {seq.uid} exceeds max_pages_per_seq={self.max_pages_per_seq}")
            if self.prefix_cache is not None and n > self.allocator.free_pages:
                self.prefix_cache.evict(n - self.allocator.free_pages)
            seq.pages.extend(self.allocator.allocate(n))

    def release(self, seq: SequenceDescriptor) -> None:
        self.allocator.free(seq.pages)
        seq.pages = []

    def export_pages(self, arena, pages: Sequence[int]) -> np.ndarray:
        """Stage the KV blocks of ``pages`` device→host (the serving analog
        of the L6 ``swap_tensor`` d2h path): one gather over the arena's
        page axis, materialized as a host numpy array.  ``arena`` is the
        engine's ``[L, P, page, 2, n_kv, hd]`` cache (jax or numpy); the
        returned block is ``[L, len(pages), page, 2, n_kv, hd]``.  Page ids
        are validated against the arena geometry — exporting the reserved
        null page (0) or an out-of-range id is a caller bug, not data."""
        idx = np.asarray(list(pages), np.int64)
        if idx.size and not ((idx > 0) & (idx < self.num_pages)).all():
            raise ValueError(f"export_pages: page ids out of range: {idx.tolist()}")
        if idx.size == 0:
            return np.asarray(arena[:, :0])   # zero-width slice keeps the dtype
        return np.asarray(arena[:, idx])

    def import_pages(self, arena, pages: Sequence[int], block: np.ndarray):
        """Scatter a host-staged KV block back into ``pages`` of ``arena``
        (h2d: the inverse of :meth:`export_pages`).  Returns the updated
        arena — functional (``.at[].set``) for a jax arena so the engine
        reassigns its donated cache handle, in-place for numpy.  The block
        must match the arena's per-page geometry and dtype exactly; a
        mismatched snapshot is rejected here rather than silently cast
        (KV bytes from a different geometry are garbage, not data)."""
        idx = np.asarray(list(pages), np.int64)
        if idx.size and not ((idx > 0) & (idx < self.num_pages)).all():
            raise ValueError(f"import_pages: page ids out of range: {idx.tolist()}")
        want = (arena.shape[0], idx.size) + tuple(arena.shape[2:])
        if tuple(block.shape) != want:
            raise ValueError(f"import_pages: block shape {tuple(block.shape)} != "
                             f"arena slice {want}")
        if str(block.dtype) != str(arena.dtype):
            raise ValueError(f"import_pages: block dtype {block.dtype} != "
                             f"arena dtype {arena.dtype}")
        if idx.size == 0:
            return arena
        if hasattr(arena, "at"):   # jax arena: functional scatter
            return arena.at[:, idx].set(block)
        arena[:, idx] = block
        return arena

    def arena_stats(self) -> dict:
        """Point-in-time arena occupancy for the ``kv/*`` telemetry
        gauges (docs/OBSERVABILITY.md "Step anatomy"):

          usable                 — allocatable pages (the reserved null
                                   page 0 excluded)
          in_use / free          — pages held by sequences and/or the
                                   prefix cache vs on the free list
          occupancy              — in_use / usable
          free_run_fragmentation — 1 - (longest contiguous free page-id
                                   run / free pages).  Pages are fully
                                   indirected through block tables, so
                                   this measures allocation churn (how
                                   interleaved live pages are), the
                                   input a future multi-page block
                                   allocator would care about; 0.0 when
                                   the free ids form one run (or nothing
                                   is free).
          prefix_cache_pages     — pages pinned by prefix-cache entries
          prefix_cache_share     — prefix_cache_pages / in_use (0 when
                                   the arena is empty)
        O(free log free) for the sorted run scan — a once-per-fleet-round
        export, not a hot-path read."""
        usable = self.num_pages - 1
        free = self.allocator.free_pages
        in_use = usable - free
        frag = 0.0
        if free > 1:
            ids = sorted(self.allocator._free)
            longest = run = 1
            for prev, cur in zip(ids, ids[1:]):
                run = run + 1 if cur == prev + 1 else 1
                if run > longest:
                    longest = run
            frag = 1.0 - longest / free
        pc_pages = self.prefix_cache.cached_pages \
            if self.prefix_cache is not None else 0
        return {
            "usable": usable,
            "in_use": in_use,
            "free": free,
            "occupancy": round(in_use / usable, 6) if usable else 0.0,
            "free_run_fragmentation": round(frag, 6),
            "prefix_cache_pages": pc_pages,
            "prefix_cache_share": round(pc_pages / in_use, 6) if in_use else 0.0,
        }

    def release_tail(self, seq: SequenceDescriptor, keep_pages: int) -> int:
        """Return ``seq``'s pages past the first ``keep_pages`` to the
        allocator (speculative-decode rollback; EOS/limit mid-rung surplus).
        The freed capacity is visible to ``allocator.free_pages`` — and so
        to ``single_step_page_demand`` preflights — the same step.

        Pages the sequence already published to the prefix cache are never
        released here, whatever ``keep_pages`` says: ``register()``'s
        cursor (``pc_pages``) indexes into ``seq.pages``, so dropping a
        published page would shift every later index under the cursor.
        Callers only roll back past the seen/accepted boundary and the
        cache only holds FULL pages below it, so the clamp is a guard, not
        a policy.  Returns how many pages were freed."""
        keep = max(int(keep_pages), seq.pc_pages)
        tail = seq.pages[keep:]
        if tail:
            self.allocator.free(tail)
            del seq.pages[keep:]
        return len(tail)


@dataclasses.dataclass
class RaggedBatch:
    """One step's packed device inputs (ref: RaggedBatchWrapper) — fixed
    max shapes so the compiled program is reused across steps."""
    tokens: np.ndarray        # [B, C] int32 (padded)
    start_pos: np.ndarray     # [B] int32 — context length before this chunk
    block_tables: np.ndarray  # [B, max_pages] int32 (null page 0 padded)
    chunk_lens: np.ndarray    # [B] int32 — real tokens this step (0 = padding row)
    uids: List[int]           # row → uid (len B; padding rows map to -1)

    @property
    def batch(self) -> int:
        return self.tokens.shape[0]


class StateManager:
    """uid → descriptor bookkeeping + batch packing (ref: DSStateManager)."""

    def __init__(self, kv: BlockedKVCache, max_batch: int = 64):
        self.kv = kv
        self.max_batch = max_batch
        self.seqs: Dict[int, SequenceDescriptor] = {}

    def get_or_create(self, uid: int, tokens: Optional[Sequence[int]] = None) -> SequenceDescriptor:
        if uid not in self.seqs:
            seq = SequenceDescriptor(uid=uid, tokens=list(tokens or []))
            pc = self.kv.prefix_cache
            if pc is not None and seq.tokens:
                # reuse cached KV pages for the shared prompt prefix: the
                # matched run is attached read-only and prefill starts after it
                seq.pages, seq.pc_hash = pc.match(seq.tokens)
                seq.pc_pages = len(seq.pages)
                seq.seen_tokens = len(seq.pages) * self.kv.page_size
            self.seqs[uid] = seq
        elif tokens:
            self.seqs[uid].tokens.extend(tokens)
        return self.seqs[uid]

    def note_progress(self, seq: SequenceDescriptor) -> None:
        """Called after ``seen_tokens`` advances: publish newly-completed
        full pages to the prefix cache."""
        if self.kv.prefix_cache is not None:
            self.kv.prefix_cache.register(seq)

    def truncate(self, seq: SequenceDescriptor, n_tokens: int) -> int:
        """Drop KV state past the first ``n_tokens`` of ``seq``'s history:
        clamp ``seen_tokens`` and release wholly-surplus tail pages
        (:meth:`BlockedKVCache.release_tail`).  The paged-KV rollback
        primitive behind speculative decoding (rejected drafts' pages) and
        the fused-decode EOS/limit surplus fix — KV entries beyond the
        clamped boundary inside the retained trailing page are never
        attended (the kernels mask at ``start_pos``) and are overwritten
        by the next step's writes at those positions.  Returns pages
        freed."""
        seq.seen_tokens = min(seq.seen_tokens, int(n_tokens))
        keep = -(-int(n_tokens) // self.kv.page_size)   # ceil
        return self.kv.release_tail(seq, keep)

    def flush(self, uid: int) -> None:
        """Release a sequence's KV + state (ref: engine_v2.py flush)."""
        seq = self.seqs.pop(uid, None)
        if seq is not None:
            self.kv.release(seq)

    def preempt(self, uid: int) -> SequenceDescriptor:
        """KV-pressure eviction: release ``uid``'s pages and drop its state,
        returning the descriptor so the serving frontend can requeue the
        request with its generated tokens preserved.  Full pages the
        sequence published to the prefix cache keep the cache's refcount and
        survive — a resume-prefill of the same token history reattaches them
        via ``match()`` instead of recomputing their KV."""
        seq = self.seqs.pop(uid)
        self.kv.release(seq)
        return seq

    def pack(self, work: List[Tuple[SequenceDescriptor, int]], chunk: int,
             pad_to: Optional[int] = None) -> RaggedBatch:
        """Pack (seq, n_tokens) work items into fixed [B, chunk] buffers.

        B is padded to ``pad_to`` (default ``max_batch``) so the compiled
        step program keeps ONE shape across scheduler decisions — padding
        rows have uid -1, chunk_len 0, and an all-null block table."""
        b = pad_to if pad_to is not None else self.max_batch
        assert len(work) <= b, f"{len(work)} work items exceed batch capacity {b}"
        tokens = np.zeros((b, chunk), np.int32)
        start_pos = np.zeros((b, ), np.int32)
        block_tables = np.zeros((b, self.kv.max_pages_per_seq), np.int32)
        chunk_lens = np.zeros((b, ), np.int32)
        uids = [-1] * b
        for i, (seq, n) in enumerate(work):
            self.kv.ensure_capacity(seq, n)
            sl = seq.tokens[seq.seen_tokens:seq.seen_tokens + n]
            tokens[i, :len(sl)] = sl
            start_pos[i] = seq.seen_tokens
            block_tables[i, :len(seq.pages)] = seq.pages
            chunk_lens[i] = n
            uids[i] = seq.uid
        return RaggedBatch(tokens=tokens, start_pos=start_pos, block_tables=block_tables,
                           chunk_lens=chunk_lens, uids=uids)
