"""Speculative decoding for the SplitFuse hot path: draft k, verify once.

Reference: draft-verify speculative decoding (Leviathan et al. 2023) and
SpecInfer-style multi-token verification, specialized to the v2 engine's
paged-KV serving stack.  The decode hot path is one model dispatch per
emitted token; with a drafter proposing ``k`` tokens per pure-decode round
the engine instead runs ONE verify forward over ``k+1`` positions per row
and emits ``accepted + 1`` tokens:

* the VERIFY step feeds ``[last_sampled, draft_0 .. draft_{k-1}]`` through
  the same chunked forward that serves prefill (the KV for every fed
  position is written as a side effect) and returns the argmax at EVERY
  position — the model's own next-token choice after each fed prefix;
* the ACCEPT rule is host-side longest-prefix: draft token ``i`` is
  accepted iff it equals the argmax at position ``i``; the argmax at the
  last accepted position rides along as the bonus/correction token.
  Greedy outputs are therefore byte-identical to non-speculative decode
  *by construction* — every emitted token IS the model's argmax given the
  exact accepted history;
* ROLLBACK is host-side accounting: rejected drafts were fed as inputs
  only (never appended to the sequence's token history), so the engine
  clamps ``seen_tokens`` to the accepted boundary and releases
  wholly-surplus KV pages back to the arena
  (``StateManager.truncate`` / ``BlockedKVCache.release_tail``).  Stale KV
  entries inside the retained trailing page sit beyond the clamped seen
  boundary, are never attended (attention masks at ``start_pos``), and are
  overwritten by the next round's writes at those positions.

The default drafter is a deterministic n-gram / prompt-lookup scan over
the request's OWN token history (prompt + generated): no second model, no
device work, works on the CPU tier-1 suite.  Drafters are pluggable via
:data:`DRAFTERS` — a small draft model would slot in behind the same
``DraftProvider.draft`` contract.
"""

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional, Protocol, Sequence, Type

__all__ = ["SpecConfig", "SpecStats", "DraftProvider", "NGramDrafter",
           "DRAFTERS", "make_drafter"]


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Engine-level speculative-decoding configuration
    (``RaggedInferenceEngineConfig.spec``; None disables speculation).

    ``max_draft`` is the ``k`` of the verify program's ``(batch, k+1)``
    bucketing: every verify dispatch compiles at width ``k+1`` and shorter
    drafts ride as ragged rows (``chunk_lens``), so steady-state serving
    keeps ONE verify program per batch bucket."""
    max_draft: int = 4          # k: tokens drafted per pure-decode round
    drafter: str = "ngram"      # DRAFTERS registry key
    max_ngram: int = 3          # longest suffix n-gram tried first
    min_ngram: int = 1          # shortest suffix n-gram worth matching

    def __post_init__(self):
        if self.max_draft < 1:
            raise ValueError(f"spec.max_draft must be >= 1, got {self.max_draft}")
        if not (1 <= self.min_ngram <= self.max_ngram):
            raise ValueError(f"spec n-gram bounds need 1 <= min_ngram <= max_ngram, "
                             f"got [{self.min_ngram}, {self.max_ngram}]")


@dataclasses.dataclass
class SpecStats:
    """Engine-lifetime speculation counters (``engine.spec_stats``)."""
    rounds: int = 0             # verify dispatches run
    proposed: int = 0           # draft tokens fed to verify steps
    accepted: int = 0           # draft tokens accepted (bonus tokens excluded)
    emitted: int = 0            # tokens emitted by verify steps (accepted + bonus)
    rollback_pages: int = 0     # KV pages released by post-verify truncation

    @property
    def acceptance_rate(self):
        """Accepted / proposed over the engine's lifetime; None before the
        first draft."""
        return self.accepted / self.proposed if self.proposed else None


class DraftProvider(Protocol):
    """The drafter contract: propose up to ``max_tokens`` continuation
    tokens for a sequence whose full history (prompt + generated) is
    ``tokens``.  MUST be deterministic in ``tokens`` — the scheduler may
    re-draft the same history after a preemption/failover and greedy
    replay must converge to identical outputs.  Returning ``[]`` opts the
    row out of this round's speculation (it rides the verify dispatch as a
    plain 1-token decode row)."""

    def draft(self, tokens: Sequence[int], max_tokens: int) -> List[int]:
        ...


class _SeqNGramIndex:
    """Incremental n-gram → position index over ONE sequence's history.

    For every n in ``[min_n, max_n]`` it tracks the two most recent start
    positions of every n-gram (``last`` and ``prev``): the trailing suffix
    of the current history is always the single most recent occurrence of
    its own n-gram, so "most recent occurrence strictly before the
    suffix" — the prompt-lookup query — is exactly ``prev``.  Appending a
    token indexes the ``max_n - min_n + 1`` n-grams that END at the new
    position: O(max_ngram) per appended token, replacing the per-round
    right-to-left rescan of the whole history.

    The index pins a strong reference to the token list it mirrors, so
    CPython cannot recycle the list's identity while the entry is cached;
    a truncation below the indexed boundary or a tail-token mismatch
    (a different history behind a reused list) triggers a full rebuild."""

    __slots__ = ("tokens", "min_n", "max_n", "indexed", "tail", "last", "prev")

    def __init__(self, tokens: List[int], min_n: int, max_n: int):
        self.tokens = tokens
        self.min_n, self.max_n = min_n, max_n
        self.indexed = 0
        self.tail: Optional[int] = None   # tokens[indexed - 1] at index time
        self.last: Dict[tuple, int] = {}
        self.prev: Dict[tuple, int] = {}
        self.extend()

    def stale(self) -> bool:
        if len(self.tokens) < self.indexed:
            return True  # truncated below the indexed boundary
        return self.indexed > 0 and self.tokens[self.indexed - 1] != self.tail

    def extend(self) -> None:
        toks, last, prev = self.tokens, self.last, self.prev
        lo, hi = self.indexed, len(toks)
        for end in range(lo + 1, hi + 1):
            for n in range(self.min_n, min(self.max_n, end) + 1):
                i = end - n
                key = tuple(toks[i:end])
                old = last.get(key)
                if old is not None and old != i:
                    prev[key] = old
                last[key] = i
        self.indexed = hi
        self.tail = toks[hi - 1] if hi else None

    def lookup(self, n: int) -> Optional[int]:
        """Start position of the most recent occurrence of the trailing
        ``n``-gram STRICTLY before the trailing suffix itself, or None."""
        L = len(self.tokens)
        key = tuple(self.tokens[L - n:])
        cand = self.last.get(key)
        if cand is None:
            return None
        if cand != L - n:
            # the suffix's own occurrence is always the most recent; a
            # smaller ``last`` can only mean a rebuild raced a mutation —
            # it is still a valid strictly-earlier occurrence
            return cand
        return self.prev.get(key)


class NGramDrafter:
    """Deterministic prompt-lookup drafting: find the most recent earlier
    occurrence of the history's trailing n-gram (longest n first) and
    propose the tokens that followed it.

    Rationale: serving traffic — and small greedy models — repeat
    themselves (copied spans, looping continuations, templated output);
    the sequence's own history is a free draft model with zero device
    cost.  Matching runs on a per-sequence INCREMENTAL
    :class:`_SeqNGramIndex` keyed by the token list's identity (the
    engine mutates one list per live sequence in place): each call
    indexes only the tokens appended since the last call — O(max_ngram)
    per appended token — then answers every n-gram probe with two dict
    lookups, so drafting cost no longer grows with history length.
    Proposals are IDENTICAL to the r12 right-to-left rescan (the
    regression tests in tests/unit/inference/test_spec_index.py replay
    both); ``_scan_draft`` keeps the reference scan for non-list
    histories and those tests."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 max_cached_seqs: int = 256):
        if not (1 <= min_ngram <= max_ngram):
            raise ValueError(f"need 1 <= min_ngram <= max_ngram, "
                             f"got [{min_ngram}, {max_ngram}]")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        # id(list) -> _SeqNGramIndex, LRU-bounded: entries hold a strong
        # ref to their list (identity safety), so dead sequences' indexes
        # must age out rather than accumulate for the engine's lifetime
        self.max_cached_seqs = max_cached_seqs
        self._indexes: "OrderedDict[int, _SeqNGramIndex]" = OrderedDict()

    def _index_for(self, tokens: List[int]) -> _SeqNGramIndex:
        key = id(tokens)
        idx = self._indexes.get(key)
        if idx is not None and idx.tokens is tokens and not idx.stale():
            idx.extend()
            self._indexes.move_to_end(key)
            return idx
        idx = _SeqNGramIndex(tokens, self.min_ngram, self.max_ngram)
        self._indexes[key] = idx
        self._indexes.move_to_end(key)
        while len(self._indexes) > self.max_cached_seqs:
            self._indexes.popitem(last=False)
        return idx

    def draft(self, tokens: Sequence[int], max_tokens: int) -> List[int]:
        L = len(tokens)
        if max_tokens <= 0 or L < self.min_ngram + 1:
            return []
        if not isinstance(tokens, list):
            # identity-keyed indexing needs the engine's stable mutable
            # list; an immutable/ad-hoc history gets the reference scan
            return self._scan_draft(list(tokens), max_tokens)
        idx = self._index_for(tokens)
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            i = idx.lookup(n)
            if i is not None:
                return [int(t) for t in tokens[i + n:i + n + max_tokens]]
        return []

    def _scan_draft(self, toks: List[int], max_tokens: int) -> List[int]:
        """The r12 reference implementation: right-to-left rescan guarded
        on the first suffix token.  O(max_ngram * len(tokens)) per call —
        kept as the non-list fallback and the equivalence oracle for the
        index regression tests."""
        L = len(toks)
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            suffix = toks[L - n:]
            first = suffix[0]
            # most recent occurrence strictly before the suffix itself, so
            # the continuation exists and the match can't be the suffix
            for i in range(L - n - 1, -1, -1):
                if toks[i] == first and toks[i:i + n] == suffix:
                    return [int(t) for t in toks[i + n:i + n + max_tokens]]
        return []


#: pluggable drafter registry (SpecConfig.drafter selects by key)
DRAFTERS: Dict[str, Type] = {"ngram": NGramDrafter}


def make_drafter(config: SpecConfig) -> DraftProvider:
    cls = DRAFTERS.get(config.drafter)
    if cls is None:
        raise ValueError(f"unknown drafter '{config.drafter}'; "
                         f"registered: {sorted(DRAFTERS)}")
    return cls(max_ngram=config.max_ngram, min_ngram=config.min_ngram)
