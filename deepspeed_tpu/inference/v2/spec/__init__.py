"""Speculative decoding for the SplitFuse hot path: draft k, verify once.

Reference: draft-verify speculative decoding (Leviathan et al. 2023) and
SpecInfer-style multi-token verification, specialized to the v2 engine's
paged-KV serving stack.  The decode hot path is one model dispatch per
emitted token; with a drafter proposing ``k`` tokens per pure-decode round
the engine instead runs ONE verify forward over ``k+1`` positions per row
and emits ``accepted + 1`` tokens:

* the VERIFY step feeds ``[last_sampled, draft_0 .. draft_{k-1}]`` through
  the same chunked forward that serves prefill (the KV for every fed
  position is written as a side effect) and returns the argmax at EVERY
  position — the model's own next-token choice after each fed prefix;
* the ACCEPT rule is host-side longest-prefix: draft token ``i`` is
  accepted iff it equals the argmax at position ``i``; the argmax at the
  last accepted position rides along as the bonus/correction token.
  Greedy outputs are therefore byte-identical to non-speculative decode
  *by construction* — every emitted token IS the model's argmax given the
  exact accepted history;
* ROLLBACK is host-side accounting: rejected drafts were fed as inputs
  only (never appended to the sequence's token history), so the engine
  clamps ``seen_tokens`` to the accepted boundary and releases
  wholly-surplus KV pages back to the arena
  (``StateManager.truncate`` / ``BlockedKVCache.release_tail``).  Stale KV
  entries inside the retained trailing page sit beyond the clamped seen
  boundary, are never attended (attention masks at ``start_pos``), and are
  overwritten by the next round's writes at those positions.

The default drafter is a deterministic n-gram / prompt-lookup scan over
the request's OWN token history (prompt + generated): no second model, no
device work, works on the CPU tier-1 suite.  Drafters are pluggable via
:data:`DRAFTERS` — a small draft model would slot in behind the same
``DraftProvider.draft`` contract.
"""

import dataclasses
from typing import Dict, List, Protocol, Sequence, Type

__all__ = ["SpecConfig", "SpecStats", "DraftProvider", "NGramDrafter",
           "DRAFTERS", "make_drafter"]


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Engine-level speculative-decoding configuration
    (``RaggedInferenceEngineConfig.spec``; None disables speculation).

    ``max_draft`` is the ``k`` of the verify program's ``(batch, k+1)``
    bucketing: every verify dispatch compiles at width ``k+1`` and shorter
    drafts ride as ragged rows (``chunk_lens``), so steady-state serving
    keeps ONE verify program per batch bucket."""
    max_draft: int = 4          # k: tokens drafted per pure-decode round
    drafter: str = "ngram"      # DRAFTERS registry key
    max_ngram: int = 3          # longest suffix n-gram tried first
    min_ngram: int = 1          # shortest suffix n-gram worth matching

    def __post_init__(self):
        if self.max_draft < 1:
            raise ValueError(f"spec.max_draft must be >= 1, got {self.max_draft}")
        if not (1 <= self.min_ngram <= self.max_ngram):
            raise ValueError(f"spec n-gram bounds need 1 <= min_ngram <= max_ngram, "
                             f"got [{self.min_ngram}, {self.max_ngram}]")


@dataclasses.dataclass
class SpecStats:
    """Engine-lifetime speculation counters (``engine.spec_stats``)."""
    rounds: int = 0             # verify dispatches run
    proposed: int = 0           # draft tokens fed to verify steps
    accepted: int = 0           # draft tokens accepted (bonus tokens excluded)
    emitted: int = 0            # tokens emitted by verify steps (accepted + bonus)
    rollback_pages: int = 0     # KV pages released by post-verify truncation

    @property
    def acceptance_rate(self):
        """Accepted / proposed over the engine's lifetime; None before the
        first draft."""
        return self.accepted / self.proposed if self.proposed else None


class DraftProvider(Protocol):
    """The drafter contract: propose up to ``max_tokens`` continuation
    tokens for a sequence whose full history (prompt + generated) is
    ``tokens``.  MUST be deterministic in ``tokens`` — the scheduler may
    re-draft the same history after a preemption/failover and greedy
    replay must converge to identical outputs.  Returning ``[]`` opts the
    row out of this round's speculation (it rides the verify dispatch as a
    plain 1-token decode row)."""

    def draft(self, tokens: Sequence[int], max_tokens: int) -> List[int]:
        ...


class NGramDrafter:
    """Deterministic prompt-lookup drafting: find the most recent earlier
    occurrence of the history's trailing n-gram (longest n first) and
    propose the tokens that followed it.

    Rationale: serving traffic — and small greedy models — repeat
    themselves (copied spans, looping continuations, templated output);
    the sequence's own history is a free draft model with zero device
    cost.  O(max_ngram * len(tokens)) per call via a right-to-left scan
    guarded on the first suffix token, so the common non-matching
    position costs one int compare, not a slice; history lengths are
    bounded by ``max_pages_per_seq * page_size``, so the host-side cost
    stays far below one model dispatch.  (The production upgrade for
    very long histories is a per-sequence incremental n-gram→position
    index, O(max_ngram) per appended token — see ROADMAP.)"""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not (1 <= min_ngram <= max_ngram):
            raise ValueError(f"need 1 <= min_ngram <= max_ngram, "
                             f"got [{min_ngram}, {max_ngram}]")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def draft(self, tokens: Sequence[int], max_tokens: int) -> List[int]:
        L = len(tokens)
        if max_tokens <= 0 or L < self.min_ngram + 1:
            return []
        toks = tokens if isinstance(tokens, list) else list(tokens)
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            suffix = toks[L - n:]
            first = suffix[0]
            # most recent occurrence strictly before the suffix itself, so
            # the continuation exists and the match can't be the suffix
            for i in range(L - n - 1, -1, -1):
                if toks[i] == first and toks[i:i + n] == suffix:
                    return [int(t) for t in toks[i + n:i + n + max_tokens]]
        return []


#: pluggable drafter registry (SpecConfig.drafter selects by key)
DRAFTERS: Dict[str, Type] = {"ngram": NGramDrafter}


def make_drafter(config: SpecConfig) -> DraftProvider:
    cls = DRAFTERS.get(config.drafter)
    if cls is None:
        raise ValueError(f"unknown drafter '{config.drafter}'; "
                         f"registered: {sorted(DRAFTERS)}")
    return cls(max_ngram=config.max_ngram, min_ngram=config.min_ngram)
