"""HF checkpoint → TPU-native model policies.

ref: deepspeed/inference/v2/model_implementations/*/policy.py (each
``InferenceV2Policy`` maps a HF checkpoint's layer containers onto the
engine's kernel parameter layout) and module_inject's per-model containers.

Here a policy is (a) a config translation (HF config → LlamaConfig-family)
and (b) a weight translation: HF state-dict names → the flax param tree,
including transposes into DenseGeneral layouts and stacking per-layer
tensors along axis 0 for the scan-over-layers models.

Covered model_types (ref model_implementations dirs): llama (v1/v2/v3),
mistral, qwen2, phi3 (fused qkv/gate_up split), mixtral (MoE), opt
(learned positions / ReLU / biases), falcon (fused qkv, parallel
residual), phi (parallel block, partial rotary), qwen2_moe (top-k experts
+ shared expert).  llama-family configs additionally serve through the
FastGen-v2 paged engine; the rest serve via module_inject.replace_module +
init_inference/hybrid generate.
"""

import re
from typing import Any, Dict

import numpy as np

from ....models.llama import LlamaConfig
from ....utils.logging import logger


def _t(x):
    return np.ascontiguousarray(np.asarray(x).T)


def _get(sd, name):
    """Checkpoint tensor → fp32 numpy (torch or numpy input)."""
    t = sd[name]
    return np.asarray(t.float().numpy() if hasattr(t, "float") else t, np.float32)


def _stack(sd, fmt, L, conv=lambda w: w):
    """Stack per-layer tensors along axis 0 for the scan-over-layers models."""
    return np.stack([conv(_get(sd, fmt.format(i=i))) for i in range(L)])


def _tied_lm_head(sd, embedding):
    return {"kernel": _t(_get(sd, "lm_head.weight"))} if "lm_head.weight" in sd \
        else {"kernel": _t(embedding)}


def _proj(sd, L, E, D, fmt, heads, bias: bool):
    """Attention projection: HF [heads*D, E](+bias) → ours (E, heads, D).
    ``fmt`` is a format string with an ``{i}`` layer placeholder, e.g.
    'model.layers.{i}.self_attn.q_proj'."""
    out = {"kernel": _stack(sd, fmt + ".weight", L, lambda w: _t(w).reshape(E, heads, D))}
    if bias:
        out["bias"] = _stack(sd, fmt + ".bias", L, lambda b: b.reshape(heads, D))
    return out


def _experts(sd, L, NE, fmt):
    """[L, NE, in, out] stack of per-layer-per-expert kernels; ``fmt`` has
    ``{i}`` (layer) and ``{e}`` (expert) placeholders."""
    return np.stack([
        np.stack([_t(_get(sd, fmt.format(i=i, e=e))) for e in range(NE)]) for i in range(L)])


class InferenceV2Policy:
    """Base policy (ref: inference/v2/model_implementations/inference_policy_base.py)."""
    model_type = None

    def build_config(self, hf_cfg) -> LlamaConfig:
        return LlamaConfig.from_hf(hf_cfg)

    def build_model(self, cfg: LlamaConfig):
        from ....models.llama import LlamaForCausalLM
        return LlamaForCausalLM(cfg)

    # -- weight translation ------------------------------------------------
    def convert(self, sd: Dict[str, Any], cfg: LlamaConfig) -> Dict[str, Any]:
        """HF state dict (name → torch/np tensor) → flax params tree."""
        H, KV = cfg.num_attention_heads, cfg.num_key_value_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers

        get = lambda name: _get(sd, name)
        layer_stack = lambda fmt, conv: _stack(sd, fmt, L, conv)

        def qkv_kernel(fmt, heads):
            # HF [heads*D, E] → ours [E, heads, D]
            return layer_stack(fmt, lambda w: _t(w).reshape(E, heads, D))

        params = {
            "embed_tokens": {"embedding": get("model.embed_tokens.weight")},
            "norm": {"weight": get("model.norm.weight")},
            "model": {"layers": {
                "input_layernorm": {"weight": layer_stack("model.layers.{i}.input_layernorm.weight", lambda w: w)},
                "post_attention_layernorm": {
                    "weight": layer_stack("model.layers.{i}.post_attention_layernorm.weight", lambda w: w)},
                "self_attn": {
                    "q_proj": {"kernel": qkv_kernel("model.layers.{i}.self_attn.q_proj.weight", H)},
                    "k_proj": {"kernel": qkv_kernel("model.layers.{i}.self_attn.k_proj.weight", KV)},
                    "v_proj": {"kernel": qkv_kernel("model.layers.{i}.self_attn.v_proj.weight", KV)},
                    # HF o_proj [E, H*D] → ours [H, D, E]
                    "o_proj": {"kernel": layer_stack("model.layers.{i}.self_attn.o_proj.weight",
                                                     lambda w: _t(w).reshape(H, D, E))},
                },
                "mlp": {
                    "gate_proj": {"kernel": layer_stack("model.layers.{i}.mlp.gate_proj.weight", _t)},
                    "up_proj": {"kernel": layer_stack("model.layers.{i}.mlp.up_proj.weight", _t)},
                    "down_proj": {"kernel": layer_stack("model.layers.{i}.mlp.down_proj.weight", _t)},
                },
            }},
        }
        if cfg.attention_bias:
            for name, heads in (("q_proj", H), ("k_proj", KV), ("v_proj", KV)):
                params["model"]["layers"]["self_attn"][name]["bias"] = layer_stack(
                    "model.layers.{{i}}.self_attn.{0}.bias".format(name), lambda b: b.reshape(heads, D))
        if getattr(cfg, "attention_out_bias", False):
            params["model"]["layers"]["self_attn"]["o_proj"]["bias"] = layer_stack(
                "model.layers.{i}.self_attn.o_proj.bias", lambda b: b)
        if cfg.tie_word_embeddings or "lm_head.weight" not in sd:
            params["lm_head"] = {"kernel": _t(params["embed_tokens"]["embedding"])}
        else:
            params["lm_head"] = {"kernel": _t(get("lm_head.weight"))}
        return params


class LlamaPolicy(InferenceV2Policy):
    """ref: model_implementations/llama_v2/ (+v1/v3 via config)."""
    model_type = "llama"

    def build_config(self, hf_cfg):
        # HF llama's attention_bias flag covers q/k/v AND o_proj (unlike
        # qwen2, whose o_proj is bias-free)
        ab = getattr(hf_cfg, "attention_bias", False)
        return LlamaConfig.from_hf(hf_cfg, attention_out_bias=ab)


class MistralPolicy(InferenceV2Policy):
    """ref: model_implementations/mistral/ — llama layout + GQA; the
    sliding-window attention of mistral is honored at the attention level
    (paged decode masks beyond window)."""
    model_type = "mistral"


class Qwen2Policy(InferenceV2Policy):
    """ref: model_implementations/qwen_v2/ — llama layout + qkv bias."""
    model_type = "qwen2"

    def build_config(self, hf_cfg):
        return LlamaConfig.from_hf(hf_cfg, attention_bias=True)


class InternLMPolicy(InferenceV2Policy):
    """ref: module_inject/containers/internlm.py — InternLM-1: llama layout
    whose HF config spells the attention-bias flag ``bias`` and whose
    checkpoints carry q/k/v AND o_proj biases.  (InternLM-2's fused
    wqkv/w1-w3 naming is a different scheme and is not handled here.)"""
    model_type = "internlm"

    def build_config(self, hf_cfg):
        bias = bool(getattr(hf_cfg, "bias", False))
        return LlamaConfig.from_hf(hf_cfg, attention_bias=bias, attention_out_bias=bias)


class Phi3Policy(InferenceV2Policy):
    """ref: model_implementations/phi3/ — fused qkv_proj and gate_up_proj
    get split into the llama layout."""
    model_type = "phi3"

    def convert(self, sd, cfg):
        H, KV = cfg.num_attention_heads, cfg.num_key_value_heads
        D = cfg.hidden_size // H
        expanded = {}
        for name, t in sd.items():
            w = np.asarray(t.float().numpy() if hasattr(t, "float") else t, np.float32)
            m = re.match(r"model\.layers\.(\d+)\.self_attn\.qkv_proj\.weight", name)
            if m:
                i = m.group(1)
                q, k, v = np.split(w, [H * D, H * D + KV * D], axis=0)
                expanded[f"model.layers.{i}.self_attn.q_proj.weight"] = q
                expanded[f"model.layers.{i}.self_attn.k_proj.weight"] = k
                expanded[f"model.layers.{i}.self_attn.v_proj.weight"] = v
                continue
            m = re.match(r"model\.layers\.(\d+)\.mlp\.gate_up_proj\.weight", name)
            if m:
                i = m.group(1)
                g, u = np.split(w, 2, axis=0)
                expanded[f"model.layers.{i}.mlp.gate_proj.weight"] = g
                expanded[f"model.layers.{i}.mlp.up_proj.weight"] = u
                continue
            expanded[name] = w
        return super().convert(expanded, cfg)


class OPTPolicy(InferenceV2Policy):
    """ref: model_implementations/opt/ — learned positions, pre-LN, ReLU MLP,
    qkv/out/fc biases; maps onto models/opt.py."""
    model_type = "opt"

    def build_config(self, hf_cfg):
        from ....models.opt import OPTConfig
        return OPTConfig.from_hf(hf_cfg)

    def build_model(self, cfg):
        from ....models.opt import OPTForCausalLM
        return OPTForCausalLM(cfg)

    def convert(self, sd, cfg):
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers

        get = lambda name: _get(sd, name)

        stack = lambda fmt, conv=(lambda w: w): _stack(sd, fmt, L, conv)

        def ln(prefix):
            return {"scale": stack(prefix + ".weight"), "bias": stack(prefix + ".bias")}

        proj = lambda name: _proj(sd, L, E, D, "model.decoder.layers.{i}.self_attn." + name,
                                  H, bias=True)

        params = {
            "embed_tokens": {"embedding": get("model.decoder.embed_tokens.weight")},
            "embed_positions": {"embedding": get("model.decoder.embed_positions.weight")},
            # post-LN OPT (opt-350m) has no top-level final LN
            **({"final_layer_norm": {"scale": get("model.decoder.final_layer_norm.weight"),
                                     "bias": get("model.decoder.final_layer_norm.bias")}}
               if cfg.do_layer_norm_before else {}),
            # opt-350m: embeddings live in word_embed_proj_dim, projected
            # in/out around the decoder stack
            **({"project_in": {"kernel": _t(get("model.decoder.project_in.weight"))},
                "project_out": {"kernel": _t(get("model.decoder.project_out.weight"))}}
               if cfg.word_embed_proj_dim else {}),
            "layers": {
                "self_attn_layer_norm": ln("model.decoder.layers.{i}.self_attn_layer_norm"),
                "final_layer_norm": ln("model.decoder.layers.{i}.final_layer_norm"),
                "self_attn": {
                    "q_proj": proj("q_proj"), "k_proj": proj("k_proj"), "v_proj": proj("v_proj"),
                    "out_proj": {"kernel": stack("model.decoder.layers.{i}.self_attn.out_proj.weight",
                                                 lambda w: _t(w).reshape(H, D, E)),
                                 "bias": stack("model.decoder.layers.{i}.self_attn.out_proj.bias")},
                },
                "fc1": {"kernel": stack("model.decoder.layers.{i}.fc1.weight", _t),
                        "bias": stack("model.decoder.layers.{i}.fc1.bias")},
                "fc2": {"kernel": stack("model.decoder.layers.{i}.fc2.weight", _t),
                        "bias": stack("model.decoder.layers.{i}.fc2.bias")},
            },
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = _tied_lm_head(sd, params["embed_tokens"]["embedding"])
        return params


class MixtralPolicy(InferenceV2Policy):
    """ref: model_implementations/mixtral/ — MoE FFN: per-layer experts
    stacked onto the expert axis of our Mixtral model."""
    model_type = "mixtral"

    def build_config(self, hf_cfg):
        from ....models.mixtral import MixtralConfig
        return MixtralConfig.from_hf(hf_cfg)

    def build_model(self, cfg):
        from ....models.mixtral import MixtralForCausalLM
        return MixtralForCausalLM(cfg)

    def convert(self, sd, cfg):
        H, KV = cfg.num_attention_heads, cfg.num_key_value_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers
        NE = cfg.num_local_experts

        get = lambda name: _get(sd, name)

        stack = lambda fmt, conv=(lambda w: w): _stack(sd, fmt, L, conv)

        experts = lambda w_name: _experts(
            sd, L, NE, "model.layers.{i}.block_sparse_moe.experts.{e}." + w_name + ".weight")

        params = {
            "embed_tokens": {"embedding": get("model.embed_tokens.weight")},
            "norm": {"weight": get("model.norm.weight")},
            "layers": {
                "input_layernorm": {"weight": stack("model.layers.{i}.input_layernorm.weight")},
                "post_attention_layernorm": {"weight": stack("model.layers.{i}.post_attention_layernorm.weight")},
                "self_attn": {
                    "q_proj": {"kernel": stack("model.layers.{i}.self_attn.q_proj.weight",
                                               lambda w: _t(w).reshape(E, H, D))},
                    "k_proj": {"kernel": stack("model.layers.{i}.self_attn.k_proj.weight",
                                               lambda w: _t(w).reshape(E, KV, D))},
                    "v_proj": {"kernel": stack("model.layers.{i}.self_attn.v_proj.weight",
                                               lambda w: _t(w).reshape(E, KV, D))},
                    "o_proj": {"kernel": stack("model.layers.{i}.self_attn.o_proj.weight",
                                               lambda w: _t(w).reshape(H, D, E))},
                },
                "block_sparse_moe": {
                    "gate": {"kernel": stack("model.layers.{i}.block_sparse_moe.gate.weight", _t)},
                    # HF w1=gate, w3=up, w2=down; ours w_* in (in, out) layout
                    "experts": {"w_gate": experts("w1"), "w_up": experts("w3"), "w_down": experts("w2")},
                },
            },
        }
        params["lm_head"] = _tied_lm_head(sd, params["embed_tokens"]["embedding"])
        return params


class PhiPolicy(InferenceV2Policy):
    """ref: model_implementations/phi/ — parallel block, partial rotary,
    biases everywhere incl. lm_head; maps onto models/phi.py."""
    model_type = "phi"

    def build_config(self, hf_cfg):
        from ....models.phi import PhiConfig
        return PhiConfig.from_hf(hf_cfg)

    def build_model(self, cfg):
        from ....models.phi import PhiForCausalLM
        return PhiForCausalLM(cfg)

    def convert(self, sd, cfg):
        H, KV = cfg.num_attention_heads, cfg.num_key_value_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers
        get = lambda name: _get(sd, name)
        stack = lambda fmt, conv=(lambda w: w): _stack(sd, fmt, L, conv)

        proj = lambda name, heads: _proj(sd, L, E, D, "model.layers.{i}.self_attn." + name,
                                         heads, bias=True)

        params = {
            "embed_tokens": {"embedding": get("model.embed_tokens.weight")},
            "final_layernorm": {"scale": get("model.final_layernorm.weight"),
                                "bias": get("model.final_layernorm.bias")},
            "lm_head": {"kernel": _t(get("lm_head.weight")), "bias": get("lm_head.bias")},
            "layers": {
                "input_layernorm": {"scale": stack("model.layers.{i}.input_layernorm.weight"),
                                    "bias": stack("model.layers.{i}.input_layernorm.bias")},
                "self_attn": {
                    "q_proj": proj("q_proj", H),
                    "k_proj": proj("k_proj", KV),
                    "v_proj": proj("v_proj", KV),
                    "dense": {"kernel": stack("model.layers.{i}.self_attn.dense.weight",
                                              lambda w: _t(w).reshape(H, D, E)),
                              "bias": stack("model.layers.{i}.self_attn.dense.bias")},
                    **({"q_layernorm": {"scale": stack("model.layers.{i}.self_attn.q_layernorm.weight"),
                                        "bias": stack("model.layers.{i}.self_attn.q_layernorm.bias")},
                        "k_layernorm": {"scale": stack("model.layers.{i}.self_attn.k_layernorm.weight"),
                                        "bias": stack("model.layers.{i}.self_attn.k_layernorm.bias")}}
                       if cfg.qk_layernorm else {}),
                },
                "fc1": {"kernel": stack("model.layers.{i}.mlp.fc1.weight", _t),
                        "bias": stack("model.layers.{i}.mlp.fc1.bias")},
                "fc2": {"kernel": stack("model.layers.{i}.mlp.fc2.weight", _t),
                        "bias": stack("model.layers.{i}.mlp.fc2.bias")},
            },
        }
        return params


class FalconPolicy(InferenceV2Policy):
    """ref: model_implementations/falcon/ — fused query_key_value split into
    q/k/v for both the 7b (MQA, H q-heads then 1 k then 1 v) and
    new_decoder_architecture (per-KV-group [q_per_kv, k, v]) layouts."""
    model_type = "falcon"

    def build_config(self, hf_cfg):
        from ....models.falcon import FalconConfig
        return FalconConfig.from_hf(hf_cfg)

    def build_model(self, cfg):
        from ....models.falcon import FalconForCausalLM
        return FalconForCausalLM(cfg)

    def convert(self, sd, cfg):
        H, KV = cfg.num_attention_heads, cfg.num_kv_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers

        get = lambda name: _get(sd, name)

        stack = lambda fmt, conv=(lambda w: w): _stack(sd, fmt, L, conv)

        def group_qkv(t, trailing):
            """Reshape fused qkv rows into per-head groups; ``trailing`` is
            () for biases, (E,) for weights."""
            if cfg.new_decoder_architecture:
                qpk = H // KV
                g = t.reshape(KV, qpk + 2, D, *trailing)
                return (g[:, :qpk].reshape(KV * qpk, D, *trailing),
                        g[:, qpk].reshape(KV, D, *trailing),
                        g[:, qpk + 1].reshape(KV, D, *trailing))
            if KV == 1:  # 7b MQA: H q rows, then k, then v
                g = t.reshape(H + 2, D, *trailing)
                return g[:H], g[H:H + 1], g[H + 1:]
            # classic MHA (falcon-rw): per-head interleave [H, 3, D]
            g = t.reshape(H, 3, D, *trailing)
            return g[:, 0], g[:, 1], g[:, 2]

        def split_qkv(w):
            q, k, v = group_qkv(w, (E, ))
            # [heads, D, E] → ours (E, heads, D)
            to_ours = lambda t: np.ascontiguousarray(np.transpose(t, (2, 0, 1)))
            return to_ours(q), to_ours(k), to_ours(v)

        qs, ks, vs = [], [], []
        qbs, kbs, vbs = [], [], []
        for i in range(L):
            q, k, v = split_qkv(get(f"transformer.h.{i}.self_attention.query_key_value.weight"))
            qs.append(q); ks.append(k); vs.append(v)
            if cfg.bias:
                qb, kb, vb = group_qkv(get(f"transformer.h.{i}.self_attention.query_key_value.bias"), ())
                qbs.append(qb); kbs.append(kb); vbs.append(vb)

        ln_blocks = {}
        if not cfg.parallel_attn:
            # falcon-rw sequential residual: pre-attn + post-attn LNs
            for ours, theirs in (("input_layernorm", "input_layernorm"),
                                 ("post_attention_layernorm", "post_attention_layernorm")):
                ln_blocks[ours] = {"scale": stack(f"transformer.h.{{i}}.{theirs}.weight"),
                                   "bias": stack(f"transformer.h.{{i}}.{theirs}.bias")}
        elif cfg.num_ln_in_parallel_attn == 2:  # HF keys purely on this flag
            for name in ("ln_attn", "ln_mlp"):
                ln_blocks[name] = {"scale": stack(f"transformer.h.{{i}}.{name}.weight"),
                                   "bias": stack(f"transformer.h.{{i}}.{name}.bias")}
        else:
            # falcon-7b AND falcon-11B-style (num_ln_in_parallel_attn=1)
            ln_blocks["input_layernorm"] = {
                "scale": stack("transformer.h.{i}.input_layernorm.weight"),
                "bias": stack("transformer.h.{i}.input_layernorm.bias")}

        def with_bias(d, fmt):
            return {**d, "bias": stack(fmt)} if cfg.bias else d

        attn = {
            "q_proj": {"kernel": np.stack(qs)},
            "k_proj": {"kernel": np.stack(ks)},
            "v_proj": {"kernel": np.stack(vs)},
            "dense": with_bias({"kernel": stack("transformer.h.{i}.self_attention.dense.weight",
                                                lambda w: _t(w).reshape(H, D, E))},
                               "transformer.h.{i}.self_attention.dense.bias"),
        }
        if cfg.bias:
            attn["q_proj"]["bias"] = np.stack(qbs)
            attn["k_proj"]["bias"] = np.stack(kbs)
            attn["v_proj"]["bias"] = np.stack(vbs)

        params = {
            "word_embeddings": {"embedding": get("transformer.word_embeddings.weight")},
            "ln_f": {"scale": get("transformer.ln_f.weight"), "bias": get("transformer.ln_f.bias")},
            "h": {
                **ln_blocks,
                "self_attention": attn,
                "dense_h_to_4h": with_bias(
                    {"kernel": stack("transformer.h.{i}.mlp.dense_h_to_4h.weight", _t)},
                    "transformer.h.{i}.mlp.dense_h_to_4h.bias"),
                "dense_4h_to_h": with_bias(
                    {"kernel": stack("transformer.h.{i}.mlp.dense_4h_to_h.weight", _t)},
                    "transformer.h.{i}.mlp.dense_4h_to_h.bias"),
            },
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = _tied_lm_head(sd, params["word_embeddings"]["embedding"])
        return params


class Qwen2MoePolicy(InferenceV2Policy):
    """ref: model_implementations/qwen_v2_moe/ — qkv-bias attention +
    top-k expert MLP with shared expert; maps onto models/qwen2_moe.py."""
    model_type = "qwen2_moe"

    def build_config(self, hf_cfg):
        from ....models.qwen2_moe import Qwen2MoeConfig
        return Qwen2MoeConfig.from_hf(hf_cfg)

    def build_model(self, cfg):
        from ....models.qwen2_moe import Qwen2MoeForCausalLM
        return Qwen2MoeForCausalLM(cfg)

    def convert(self, sd, cfg):
        H, KV = cfg.num_attention_heads, cfg.num_key_value_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers
        NE = cfg.num_experts
        get = lambda name: _get(sd, name)
        stack = lambda fmt, conv=(lambda w: w): _stack(sd, fmt, L, conv)

        proj = lambda name, heads: _proj(sd, L, E, D, "model.layers.{i}.self_attn." + name,
                                         heads, bias=cfg.qkv_bias)

        experts = lambda w_name: _experts(
            sd, L, NE, "model.layers.{i}.mlp.experts.{e}." + w_name + ".weight")

        def one_layer_attn(i):
            p = f"model.layers.{i}.self_attn"
            out = {
                "q_proj": {"kernel": _t(get(f"{p}.q_proj.weight")).reshape(E, H, D)},
                "k_proj": {"kernel": _t(get(f"{p}.k_proj.weight")).reshape(E, KV, D)},
                "v_proj": {"kernel": _t(get(f"{p}.v_proj.weight")).reshape(E, KV, D)},
                "o_proj": {"kernel": _t(get(f"{p}.o_proj.weight")).reshape(H, D, E)},
            }
            if cfg.qkv_bias:
                for name, heads in (("q_proj", H), ("k_proj", KV), ("v_proj", KV)):
                    out[name]["bias"] = get(f"{p}.{name}.bias").reshape(heads, D)
            return out

        def one_layer_sparse_mlp(i):
            p = f"model.layers.{i}.mlp"
            return {
                "gate": {"kernel": _t(get(f"{p}.gate.weight"))},
                "w_gate": np.stack([_t(get(f"{p}.experts.{e}.gate_proj.weight")) for e in range(NE)]),
                "w_up": np.stack([_t(get(f"{p}.experts.{e}.up_proj.weight")) for e in range(NE)]),
                "w_down": np.stack([_t(get(f"{p}.experts.{e}.down_proj.weight")) for e in range(NE)]),
                "shared_gate_proj": {"kernel": _t(get(f"{p}.shared_expert.gate_proj.weight"))},
                "shared_up_proj": {"kernel": _t(get(f"{p}.shared_expert.up_proj.weight"))},
                "shared_down_proj": {"kernel": _t(get(f"{p}.shared_expert.down_proj.weight"))},
                "shared_expert_gate": {"kernel": _t(get(f"{p}.shared_expert_gate.weight"))},
            }

        def one_layer_dense_mlp(i):
            p = f"model.layers.{i}.mlp"
            return {
                "gate_proj": {"kernel": _t(get(f"{p}.gate_proj.weight"))},
                "up_proj": {"kernel": _t(get(f"{p}.up_proj.weight"))},
                "down_proj": {"kernel": _t(get(f"{p}.down_proj.weight"))},
            }

        params = {
            "embed_tokens": {"embedding": get("model.embed_tokens.weight")},
            "norm": {"weight": get("model.norm.weight")},
        }
        if cfg.mixed_stack:
            # per-layer trees for the unscanned model (layers_{i}): dense or
            # sparse mlp per the HF rule (ref: Qwen2MoeDecoderLayer)
            for i in range(L):
                params[f"layers_{i}"] = {
                    "input_layernorm": {"weight": get(f"model.layers.{i}.input_layernorm.weight")},
                    "post_attention_layernorm": {
                        "weight": get(f"model.layers.{i}.post_attention_layernorm.weight")},
                    "self_attn": one_layer_attn(i),
                    "mlp": (one_layer_sparse_mlp(i) if cfg.layer_is_sparse(i)
                            else one_layer_dense_mlp(i)),
                }
        else:
            params["layers"] = {
                "input_layernorm": {"weight": stack("model.layers.{i}.input_layernorm.weight")},
                "post_attention_layernorm": {"weight": stack("model.layers.{i}.post_attention_layernorm.weight")},
                "self_attn": {
                    "q_proj": proj("q_proj", H),
                    "k_proj": proj("k_proj", KV),
                    "v_proj": proj("v_proj", KV),
                    "o_proj": {"kernel": stack("model.layers.{i}.self_attn.o_proj.weight",
                                               lambda w: _t(w).reshape(H, D, E))},
                },
                "mlp": {
                    "gate": {"kernel": stack("model.layers.{i}.mlp.gate.weight", _t)},
                    "w_gate": experts("gate_proj"),
                    "w_up": experts("up_proj"),
                    "w_down": experts("down_proj"),
                    "shared_gate_proj": {"kernel": stack("model.layers.{i}.mlp.shared_expert.gate_proj.weight", _t)},
                    "shared_up_proj": {"kernel": stack("model.layers.{i}.mlp.shared_expert.up_proj.weight", _t)},
                    "shared_down_proj": {"kernel": stack("model.layers.{i}.mlp.shared_expert.down_proj.weight", _t)},
                    "shared_expert_gate": {"kernel": stack("model.layers.{i}.mlp.shared_expert_gate.weight", _t)},
                },
            }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = _tied_lm_head(sd, params["embed_tokens"]["embedding"])
        return params




class BloomPolicy(InferenceV2Policy):
    """ref: module_inject/containers/bloom.py (BLOOMLayerPolicy) — fused qkv
    stored (H, 3, D)-interleaved on the output dim, alibi positions, tied
    head, LN after the word embedding."""
    model_type = "bloom"

    def build_config(self, hf_cfg):
        from ....models.gpt_family import BloomConfig
        return BloomConfig.from_hf(hf_cfg)

    def build_model(self, cfg):
        from ....models.gpt_family import BloomForCausalLM
        return BloomForCausalLM(cfg)

    def convert(self, sd, cfg):
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers
        get = lambda name: _get(sd, name)
        # some checkpoints prefix with "transformer."
        pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
        stack = lambda fmt, conv=(lambda w: w): _stack(sd, pre + fmt, L, conv)
        ln = lambda fmt: {"scale": stack(fmt + ".weight"), "bias": stack(fmt + ".bias")}
        params = {
            "word_embeddings": {"embedding": get(pre + "word_embeddings.weight")},
            "word_embeddings_layernorm": {
                "scale": get(pre + "word_embeddings_layernorm.weight"),
                "bias": get(pre + "word_embeddings_layernorm.bias")},
            "ln_f": {"scale": get(pre + "ln_f.weight"), "bias": get(pre + "ln_f.bias")},
            "h": {
                "input_layernorm": ln("h.{i}.input_layernorm"),
                "post_attention_layernorm": ln("h.{i}.post_attention_layernorm"),
                "self_attention": {
                    # HF [3E, E] whose output reshapes (H, 3, D) → ours [E, H, 3, D]
                    "query_key_value": {
                        "kernel": stack("h.{i}.self_attention.query_key_value.weight",
                                        lambda w: _t(w).reshape(E, H, 3, D)),
                        "bias": stack("h.{i}.self_attention.query_key_value.bias",
                                      lambda b: b.reshape(H, 3, D))},
                    "dense": {"kernel": stack("h.{i}.self_attention.dense.weight",
                                              lambda w: _t(w).reshape(H, D, E)),
                              "bias": stack("h.{i}.self_attention.dense.bias")},
                },
                "dense_h_to_4h": {"kernel": stack("h.{i}.mlp.dense_h_to_4h.weight", _t),
                                  "bias": stack("h.{i}.mlp.dense_h_to_4h.bias")},
                "dense_4h_to_h": {"kernel": stack("h.{i}.mlp.dense_4h_to_h.weight", _t),
                                  "bias": stack("h.{i}.mlp.dense_4h_to_h.bias")},
            },
        }
        return params


class GPTNeoXPolicy(InferenceV2Policy):
    """ref: module_inject/containers/gptneox.py (GPTNEOXLayerPolicy) — fused
    qkv in per-head [q|k|v] layout, partial neox rotary, untied embed_out."""
    model_type = "gpt_neox"

    def build_config(self, hf_cfg):
        from ....models.gpt_family import GPTNeoXConfig
        return GPTNeoXConfig.from_hf(hf_cfg)

    def build_model(self, cfg):
        from ....models.gpt_family import GPTNeoXForCausalLM
        return GPTNeoXForCausalLM(cfg)

    def convert(self, sd, cfg):
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers
        get = lambda name: _get(sd, name)
        stack = lambda fmt, conv=(lambda w: w): _stack(sd, "gpt_neox." + fmt, L, conv)
        ln = lambda fmt: {"scale": stack(fmt + ".weight"), "bias": stack(fmt + ".bias")}
        params = {
            "embed_in": {"embedding": get("gpt_neox.embed_in.weight")},
            "final_layer_norm": {"scale": get("gpt_neox.final_layer_norm.weight"),
                                 "bias": get("gpt_neox.final_layer_norm.bias")},
            "embed_out": {"kernel": _t(get("embed_out.weight"))},
            "layers": {
                "input_layernorm": ln("layers.{i}.input_layernorm"),
                "post_attention_layernorm": ln("layers.{i}.post_attention_layernorm"),
                # HF [3E, E] whose output reshapes (H, 3*D) with per-head
                # [q_h | k_h | v_h] → ours [E, H, 3, D] (3D row-major = (3, D))
                "query_key_value": {
                    "kernel": stack("layers.{i}.attention.query_key_value.weight",
                                    lambda w: _t(w).reshape(E, H, 3, D)),
                    "bias": stack("layers.{i}.attention.query_key_value.bias",
                                  lambda b: b.reshape(H, 3, D))},
                "dense": {"kernel": stack("layers.{i}.attention.dense.weight",
                                          lambda w: _t(w).reshape(H, D, E)),
                          "bias": stack("layers.{i}.attention.dense.bias")},
                "dense_h_to_4h": {"kernel": stack("layers.{i}.mlp.dense_h_to_4h.weight", _t),
                                  "bias": stack("layers.{i}.mlp.dense_h_to_4h.bias")},
                "dense_4h_to_h": {"kernel": stack("layers.{i}.mlp.dense_4h_to_h.weight", _t),
                                  "bias": stack("layers.{i}.mlp.dense_4h_to_h.bias")},
            },
        }
        return params


class GPTJPolicy(InferenceV2Policy):
    """ref: module_inject/containers/gptj.py (HFGPTJLayerPolicy) — separate
    unbiased q/k/v, interleaved rotary, one shared LN, biased lm_head."""
    model_type = "gptj"

    def build_config(self, hf_cfg):
        from ....models.gpt_family import GPTJConfig
        return GPTJConfig.from_hf(hf_cfg)

    def build_model(self, cfg):
        from ....models.gpt_family import GPTJForCausalLM
        return GPTJForCausalLM(cfg)

    def convert(self, sd, cfg):
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers
        get = lambda name: _get(sd, name)
        stack = lambda fmt, conv=(lambda w: w): _stack(sd, "transformer." + fmt, L, conv)
        params = {
            "wte": {"embedding": get("transformer.wte.weight")},
            "ln_f": {"scale": get("transformer.ln_f.weight"), "bias": get("transformer.ln_f.bias")},
            "lm_head": {"kernel": _t(get("lm_head.weight")), "bias": get("lm_head.bias")},
            "h": {
                "ln_1": {"scale": stack("h.{i}.ln_1.weight"), "bias": stack("h.{i}.ln_1.bias")},
                "q_proj": {"kernel": stack("h.{i}.attn.q_proj.weight",
                                           lambda w: _t(w).reshape(E, H, D))},
                "k_proj": {"kernel": stack("h.{i}.attn.k_proj.weight",
                                           lambda w: _t(w).reshape(E, H, D))},
                "v_proj": {"kernel": stack("h.{i}.attn.v_proj.weight",
                                           lambda w: _t(w).reshape(E, H, D))},
                "out_proj": {"kernel": stack("h.{i}.attn.out_proj.weight",
                                             lambda w: _t(w).reshape(H, D, E))},
                "fc_in": {"kernel": stack("h.{i}.mlp.fc_in.weight", _t),
                          "bias": stack("h.{i}.mlp.fc_in.bias")},
                "fc_out": {"kernel": stack("h.{i}.mlp.fc_out.weight", _t),
                           "bias": stack("h.{i}.mlp.fc_out.bias")},
            },
        }
        return params


class GPTNeoPolicy(InferenceV2Policy):
    """ref: module_inject/containers/gptneo.py (HFGPTNEOLayerPolicy) —
    learned positions, alternating global/local attention, tied head."""
    model_type = "gpt_neo"

    def build_config(self, hf_cfg):
        from ....models.gpt_family import GPTNeoConfig
        return GPTNeoConfig.from_hf(hf_cfg)

    def build_model(self, cfg):
        from ....models.gpt_family import GPTNeoForCausalLM
        return GPTNeoForCausalLM(cfg)

    def convert(self, sd, cfg):
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers
        get = lambda name: _get(sd, name)
        stack = lambda fmt, conv=(lambda w: w): _stack(sd, "transformer." + fmt, L, conv)
        params = {
            "wte": {"embedding": get("transformer.wte.weight")},
            "wpe": {"embedding": get("transformer.wpe.weight")},
            "ln_f": {"scale": get("transformer.ln_f.weight"), "bias": get("transformer.ln_f.bias")},
            "h": {
                "ln_1": {"scale": stack("h.{i}.ln_1.weight"), "bias": stack("h.{i}.ln_1.bias")},
                "ln_2": {"scale": stack("h.{i}.ln_2.weight"), "bias": stack("h.{i}.ln_2.bias")},
                "q_proj": {"kernel": stack("h.{i}.attn.attention.q_proj.weight",
                                           lambda w: _t(w).reshape(E, H, D))},
                "k_proj": {"kernel": stack("h.{i}.attn.attention.k_proj.weight",
                                           lambda w: _t(w).reshape(E, H, D))},
                "v_proj": {"kernel": stack("h.{i}.attn.attention.v_proj.weight",
                                           lambda w: _t(w).reshape(E, H, D))},
                "out_proj": {"kernel": stack("h.{i}.attn.attention.out_proj.weight",
                                             lambda w: _t(w).reshape(H, D, E)),
                             "bias": stack("h.{i}.attn.attention.out_proj.bias")},
                "c_fc": {"kernel": stack("h.{i}.mlp.c_fc.weight", _t),
                         "bias": stack("h.{i}.mlp.c_fc.bias")},
                "c_proj": {"kernel": stack("h.{i}.mlp.c_proj.weight", _t),
                           "bias": stack("h.{i}.mlp.c_proj.bias")},
            },
        }
        return params




class BertPolicy(InferenceV2Policy):
    """ref: module_inject/containers/bert.py (HFBertLayerPolicy) — encoder
    serving via the jitted v1 forward (no generation loop); converts HF
    BertForMaskedLM into models/bert.BertForMaskedLM (scan-over-layers,
    tied-decoder MLM head)."""
    model_type = "bert"

    def build_config(self, hf_cfg):
        pet = getattr(hf_cfg, "position_embedding_type", "absolute")
        if pet != "absolute":
            raise ValueError(f"bert position_embedding_type={pet!r} unsupported "
                             "(distance embeddings have no translation here); silently "
                             "dropping them would serve wrong logits")
        act = getattr(hf_cfg, "hidden_act", "gelu")
        if act not in ("gelu", "gelu_new", "gelu_python"):
            raise ValueError(f"bert hidden_act={act!r} unsupported (model uses gelu)")
        from ....models.bert import BertConfig
        return BertConfig.from_hf(hf_cfg)

    def build_model(self, cfg):
        from ....models.bert import BertForMaskedLM
        return BertForMaskedLM(cfg)

    def convert(self, sd, cfg):
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers
        get = lambda name: _get(sd, name)
        stack = lambda fmt, conv=(lambda w: w): _stack(sd, "bert.encoder.layer.{i}." + fmt, L, conv)
        ln = lambda fmt: {"scale": stack(fmt + ".weight"), "bias": stack(fmt + ".bias")}
        proj = lambda name: _proj(sd, L, E, D, "bert.encoder.layer.{i}.attention.self." + name,
                                  H, bias=True)
        params = {
            "bert": {
                "word_embeddings": {"embedding": get("bert.embeddings.word_embeddings.weight")},
                "position_embeddings": {"embedding": get("bert.embeddings.position_embeddings.weight")},
                "token_type_embeddings": {"embedding": get("bert.embeddings.token_type_embeddings.weight")},
                "embeddings_ln": {"scale": get("bert.embeddings.LayerNorm.weight"),
                                  "bias": get("bert.embeddings.LayerNorm.bias")},
                "encoder": {
                    "attention": {
                        "query": proj("query"),
                        "key": proj("key"),
                        "value": proj("value"),
                        "output": {"kernel": stack("attention.output.dense.weight",
                                                   lambda w: _t(w).reshape(H, D, E)),
                                   "bias": stack("attention.output.dense.bias")},
                    },
                    "attention_output_ln": ln("attention.output.LayerNorm"),
                    "intermediate": {"kernel": stack("intermediate.dense.weight", _t),
                                     "bias": stack("intermediate.dense.bias")},
                    "output": {"kernel": stack("output.dense.weight", _t),
                               "bias": stack("output.dense.bias")},
                    "output_ln": ln("output.LayerNorm"),
                },
            },
            "transform": {"kernel": _t(get("cls.predictions.transform.dense.weight")),
                          "bias": get("cls.predictions.transform.dense.bias")},
            "transform_ln": {"scale": get("cls.predictions.transform.LayerNorm.weight"),
                             "bias": get("cls.predictions.transform.LayerNorm.bias")},
            "decoder": {"kernel": _t(get("cls.predictions.decoder.weight"))
                        if "cls.predictions.decoder.weight" in sd
                        else _t(get("bert.embeddings.word_embeddings.weight")),
                        "bias": get("cls.predictions.decoder.bias")
                        if "cls.predictions.decoder.bias" in sd
                        else get("cls.predictions.bias")},
        }
        return params




class DistilBertPolicy(InferenceV2Policy):
    """ref: module_inject/containers/distil_bert.py (HFDistilBertLayerPolicy)
    — DistilBERT is BERT minus token-type embeddings and pooler with renamed
    modules (q_lin/k_lin/v_lin, sa_layer_norm, ffn.lin1/lin2,
    vocab_transform/vocab_projector); served through the same
    models/bert.BertForMaskedLM with a zero token-type table (the add is a
    no-op for token_type_ids=0)."""
    model_type = "distilbert"

    def build_config(self, hf_cfg):
        act = getattr(hf_cfg, "activation", "gelu")
        if act != "gelu":
            raise ValueError(f"distilbert activation={act!r} unsupported (model uses gelu)")
        from ....models.bert import BertConfig
        return BertConfig(vocab_size=hf_cfg.vocab_size,
                          hidden_size=hf_cfg.dim,
                          num_hidden_layers=hf_cfg.n_layers,
                          num_attention_heads=hf_cfg.n_heads,
                          intermediate_size=hf_cfg.hidden_dim,
                          max_position_embeddings=hf_cfg.max_position_embeddings,
                          type_vocab_size=2,
                          layer_norm_eps=1e-12)

    def build_model(self, cfg):
        from ....models.bert import BertForMaskedLM
        return BertForMaskedLM(cfg)

    def convert(self, sd, cfg):
        H = cfg.num_attention_heads
        E = cfg.hidden_size
        D = E // H
        L = cfg.num_hidden_layers
        get = lambda name: _get(sd, name)
        stack = lambda fmt, conv=(lambda w: w): _stack(
            sd, "distilbert.transformer.layer.{i}." + fmt, L, conv)
        ln = lambda fmt: {"scale": stack(fmt + ".weight"), "bias": stack(fmt + ".bias")}
        proj = lambda name: _proj(sd, L, E, D,
                                  "distilbert.transformer.layer.{i}.attention." + name,
                                  H, bias=True)
        return {
            "bert": {
                "word_embeddings": {"embedding": get("distilbert.embeddings.word_embeddings.weight")},
                "position_embeddings": {"embedding": get("distilbert.embeddings.position_embeddings.weight")},
                # distilbert has no token types: a zero table makes the
                # shared encoder's add a no-op
                "token_type_embeddings": {"embedding": np.zeros((cfg.type_vocab_size, E), np.float32)},
                "embeddings_ln": {"scale": get("distilbert.embeddings.LayerNorm.weight"),
                                  "bias": get("distilbert.embeddings.LayerNorm.bias")},
                "encoder": {
                    "attention": {
                        "query": proj("q_lin"),
                        "key": proj("k_lin"),
                        "value": proj("v_lin"),
                        "output": {"kernel": stack("attention.out_lin.weight",
                                                   lambda w: _t(w).reshape(H, D, E)),
                                   "bias": stack("attention.out_lin.bias")},
                    },
                    "attention_output_ln": ln("sa_layer_norm"),
                    "intermediate": {"kernel": stack("ffn.lin1.weight", _t),
                                     "bias": stack("ffn.lin1.bias")},
                    "output": {"kernel": stack("ffn.lin2.weight", _t),
                               "bias": stack("ffn.lin2.bias")},
                    "output_ln": ln("output_layer_norm"),
                },
            },
            "transform": {"kernel": _t(get("vocab_transform.weight")),
                          "bias": get("vocab_transform.bias")},
            "transform_ln": {"scale": get("vocab_layer_norm.weight"),
                             "bias": get("vocab_layer_norm.bias")},
            "decoder": {"kernel": _t(get("vocab_projector.weight")),
                        "bias": get("vocab_projector.bias")},
        }


class ClipPolicy(InferenceV2Policy):
    """ref: module_inject/containers/clip.py (HFCLIPLayerPolicy) — the CLIP
    dual encoder (stable-diffusion's text conditioner).  Whole-model
    conversion of HF CLIPModel onto models/clip.ClipModel (pre-LN towers,
    quick-GELU, EOS pooling, patch-conv vision embeddings)."""
    model_type = "clip"

    def build_config(self, hf_cfg):
        from ....models.clip import ClipConfig, ClipTextConfig, ClipVisionConfig
        t, v = hf_cfg.text_config, hf_cfg.vision_config
        for tower in (t, v):
            act = getattr(tower, "hidden_act", "quick_gelu")
            if act != "quick_gelu":
                raise ValueError(f"clip hidden_act={act!r} unsupported (the towers "
                                 "compute quick_gelu; serving other activations would "
                                 "silently diverge from HF)")
        text = ClipTextConfig(vocab_size=t.vocab_size, hidden_size=t.hidden_size,
                              num_hidden_layers=t.num_hidden_layers,
                              num_attention_heads=t.num_attention_heads,
                              intermediate_size=t.intermediate_size,
                              max_position_embeddings=t.max_position_embeddings,
                              layer_norm_eps=t.layer_norm_eps,
                              eos_token_id=getattr(t, "eos_token_id", 49407))
        vision = ClipVisionConfig(hidden_size=v.hidden_size,
                                  num_hidden_layers=v.num_hidden_layers,
                                  num_attention_heads=v.num_attention_heads,
                                  intermediate_size=v.intermediate_size,
                                  image_size=v.image_size, patch_size=v.patch_size,
                                  num_channels=v.num_channels,
                                  layer_norm_eps=v.layer_norm_eps)
        return ClipConfig(text=text, vision=vision, projection_dim=hf_cfg.projection_dim)

    def build_model(self, cfg):
        import dataclasses as _dc

        from ....models.clip import ClipModel
        return ClipModel(_dc.replace(cfg.text, dtype=cfg.dtype),
                         _dc.replace(cfg.vision, dtype=cfg.dtype),
                         projection_dim=cfg.projection_dim)

    def _tower(self, sd, prefix, cfg, H):
        E = cfg.hidden_size
        D = E // H
        get = lambda name: _get(sd, prefix + name)
        out = {}
        for i in range(cfg.num_hidden_layers):
            lp = f"encoder.layers.{i}."
            lnp = lambda n: {"scale": get(lp + n + ".weight"), "bias": get(lp + n + ".bias")}
            pj = lambda n: {"kernel": _t(get(lp + f"self_attn.{n}.weight")).reshape(E, H, D),
                            "bias": get(lp + f"self_attn.{n}.bias").reshape(H, D)}
            out[f"layers_{i}"] = {
                "self_attn": {"q_proj": pj("q_proj"), "k_proj": pj("k_proj"),
                              "v_proj": pj("v_proj"),
                              "out_proj": {"kernel": _t(get(lp + "self_attn.out_proj.weight"))
                                           .reshape(H, D, E),
                                           "bias": get(lp + "self_attn.out_proj.bias")}},
                "layer_norm1": lnp("layer_norm1"),
                "layer_norm2": lnp("layer_norm2"),
                "fc1": {"kernel": _t(get(lp + "mlp.fc1.weight")), "bias": get(lp + "mlp.fc1.bias")},
                "fc2": {"kernel": _t(get(lp + "mlp.fc2.weight")), "bias": get(lp + "mlp.fc2.bias")},
            }
        return out

    def convert(self, sd, cfg):
        text, vision = cfg.text, cfg.vision
        get = lambda name: _get(sd, name)
        tm = self._tower(sd, "text_model.", text, text.num_attention_heads)
        tm.update({
            "token_embedding": {"embedding": get("text_model.embeddings.token_embedding.weight")},
            "position_embedding": get("text_model.embeddings.position_embedding.weight"),
            "final_layer_norm": {"scale": get("text_model.final_layer_norm.weight"),
                                 "bias": get("text_model.final_layer_norm.bias")},
        })
        vm = self._tower(sd, "vision_model.", vision, vision.num_attention_heads)
        vm.update({
            # HF conv weight [E, C, ph, pw] → flax [ph, pw, C, E]
            "patch_embedding": {"kernel": np.ascontiguousarray(
                np.transpose(get("vision_model.embeddings.patch_embedding.weight"), (2, 3, 1, 0)))},
            "class_embedding": get("vision_model.embeddings.class_embedding"),
            "position_embedding": get("vision_model.embeddings.position_embedding.weight"),
            # "pre_layrnorm" is the HF checkpoint's own (sic) spelling
            "pre_layrnorm": {"scale": get("vision_model.pre_layrnorm.weight"),
                             "bias": get("vision_model.pre_layrnorm.bias")},
            "post_layernorm": {"scale": get("vision_model.post_layernorm.weight"),
                               "bias": get("vision_model.post_layernorm.bias")},
        })
        return {
            "text_model": tm,
            "vision_model": vm,
            "text_projection": {"kernel": _t(get("text_projection.weight"))},
            "visual_projection": {"kernel": _t(get("visual_projection.weight"))},
            "logit_scale": get("logit_scale"),
        }


class QwenV1Policy(InferenceV2Policy):
    """ref: the reference's qwen (v1) container (module_inject) — the
    trust_remote_code QWenLMHeadModel: llama math with a fused biased
    c_attn, SwiGLU as c_proj(w1(x)·silu(w2(x))), RMSNorm ln_1/ln_2.
    Mapped onto LlamaForCausalLM: c_attn split into q/k/v (MHA),
    gate=w2 (the silu side), up=w1, down=c_proj."""
    model_type = "qwen"

    def build_config(self, hf_cfg):
        cfg = LlamaConfig(
            vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.hidden_size,
            intermediate_size=getattr(hf_cfg, "intermediate_size", 4 * hf_cfg.hidden_size) // 2,
            num_hidden_layers=hf_cfg.num_hidden_layers,
            num_attention_heads=hf_cfg.num_attention_heads,
            num_key_value_heads=hf_cfg.num_attention_heads,
            max_position_embeddings=getattr(hf_cfg, "max_position_embeddings", 8192),
            rope_theta=getattr(hf_cfg, "rotary_emb_base", 10000.0),
            rms_norm_eps=getattr(hf_cfg, "layer_norm_epsilon", 1e-6),
            attention_bias=True, tie_word_embeddings=False)
        return cfg

    def convert(self, sd, cfg):
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers
        get = lambda name: _get(sd, name)
        stack = lambda fmt, conv=(lambda w: w): _stack(sd, "transformer.h.{i}." + fmt, L, conv)

        # c_attn [3E, E] (+bias [3E]) fused as [q; k; v] — convert the fused
        # tensor ONCE (it is the largest per-layer weight), then slice thirds
        fused_w = stack("attn.c_attn.weight", lambda w: _t(w).reshape(E, 3, H, D))
        fused_b = stack("attn.c_attn.bias", lambda b: b.reshape(3, H, D))

        def split_qkv(part):
            i = "qkv".index(part)
            return {"kernel": np.ascontiguousarray(fused_w[:, :, i]),
                    "bias": np.ascontiguousarray(fused_b[:, i])}

        params = {
            "embed_tokens": {"embedding": get("transformer.wte.weight")},
            "norm": {"weight": get("transformer.ln_f.weight")},
            "lm_head": {"kernel": _t(get("lm_head.weight"))},
            "model": {"layers": {
                "input_layernorm": {"weight": stack("ln_1.weight")},
                "post_attention_layernorm": {"weight": stack("ln_2.weight")},
                "self_attn": {
                    "q_proj": split_qkv("q"), "k_proj": split_qkv("k"), "v_proj": split_qkv("v"),
                    "o_proj": {"kernel": stack("attn.c_proj.weight",
                                               lambda w: _t(w).reshape(H, D, E))},
                },
                "mlp": {
                    "gate_proj": {"kernel": stack("mlp.w2.weight", _t)},
                    "up_proj": {"kernel": stack("mlp.w1.weight", _t)},
                    "down_proj": {"kernel": stack("mlp.c_proj.weight", _t)},
                },
            }},
        }
        return params


class MegatronGPTPolicy(InferenceV2Policy):
    """ref: module_inject/containers/megatron_gpt.py (MegatronLayerPolicy,
    megatron_v2) — Megatron-LM GPT checkpoints: fused biased query_key_value,
    sequential residual, rotary positions (the NeoX lineage IS megatron-
    derived, so the NeoX flax model is the structural twin; classic
    megatron-v1 learned-position checkpoints are rejected with a clear
    error).  Both state-dict namings are honored:
    ``language_model.encoder.layers.*`` (modern M-LM) and
    ``transformer.layers.*`` (legacy), with ``self_attention``/``attention``
    module names (ref: megatron_gpt.py version switch)."""
    model_type = "megatron-gpt"

    def build_config(self, cfg):
        from ....models.gpt_family import GPTNeoXConfig
        g = lambda *names, d=None: next((getattr(cfg, n) for n in names if hasattr(cfg, n)), d)
        return GPTNeoXConfig(
            vocab_size=g("padded_vocab_size", "vocab_size", d=50432),
            hidden_size=g("hidden_size", d=64),
            intermediate_size=g("ffn_hidden_size", "intermediate_size",
                                d=4 * g("hidden_size", d=64)),
            num_hidden_layers=g("num_layers", "num_hidden_layers", d=2),
            num_attention_heads=g("num_attention_heads", d=8),
            rotary_pct=g("rotary_percent", "rotary_pct", d=1.0),
            use_parallel_residual=False)  # megatron residual is sequential

    def build_model(self, cfg):
        from ....models.gpt_family import GPTNeoXForCausalLM
        return GPTNeoXForCausalLM(cfg)

    def _layer_fmt(self, sd):
        for enc, attn in (("language_model.encoder.layers", "self_attention"),
                          ("transformer.layers", "attention"),
                          ("transformer.layers", "self_attention")):
            if any(k.startswith(f"{enc}.0.{attn}.query_key_value") for k in sd):
                return enc, attn
        raise KeyError("state dict has no recognizable Megatron layer naming "
                       "(language_model.encoder.layers / transformer.layers)")

    def convert(self, sd, cfg):
        if any("position_embeddings" in k for k in sd):
            raise ValueError(
                "classic megatron-v1 checkpoints with learned position embeddings "
                "are not supported — the serving twin is rotary (megatron_v2)")
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        E = cfg.hidden_size
        L = cfg.num_hidden_layers
        enc, attn = self._layer_fmt(sd)
        get = lambda name: _get(sd, name)
        stack = lambda fmt, conv=(lambda w: w): _stack(sd, f"{enc}." + fmt, L, conv)
        ln = lambda fmt: {"scale": stack(fmt + ".weight"), "bias": stack(fmt + ".bias")}
        embed = get("language_model.embedding.word_embeddings.weight"
                    if enc.startswith("language_model") else
                    "transformer.word_embeddings.weight")[:cfg.vocab_size]
        out_name = ("language_model.output_layer.weight"
                    if enc.startswith("language_model") else "lm_head.weight")
        # megatron ties by default; _get handles torch bf16 checkpoints
        out_w = _get(sd, out_name) if out_name in sd else embed
        final_ln = ("language_model.encoder.final_layernorm"
                    if enc.startswith("language_model") else "transformer.final_layernorm")
        params = {
            "embed_in": {"embedding": embed},
            "final_layer_norm": {"scale": get(final_ln + ".weight"),
                                 "bias": get(final_ln + ".bias")},
            "embed_out": {"kernel": _t(out_w)[:, :cfg.vocab_size]},
            "layers": {
                "input_layernorm": ln("{i}.input_layernorm"),
                "post_attention_layernorm": ln("{i}.post_attention_layernorm"),
                # megatron_v2 fused qkv [H·3·D, E]: per-head [q_h | k_h | v_h]
                # — the SAME interleave NeoX uses (ref: features/megatron.py
                # qkv_copy transposes only for v1)
                "query_key_value": {
                    "kernel": stack(f"{{i}}.{attn}.query_key_value.weight",
                                    lambda w: _t(w).reshape(E, H, 3, D)),
                    "bias": stack(f"{{i}}.{attn}.query_key_value.bias",
                                  lambda b: b.reshape(H, 3, D))},
                "dense": {"kernel": stack(f"{{i}}.{attn}.dense.weight",
                                          lambda w: _t(w).reshape(H, D, E)),
                          "bias": stack(f"{{i}}.{attn}.dense.bias")},
                "dense_h_to_4h": {"kernel": stack("{i}.mlp.dense_h_to_4h.weight", _t),
                                  "bias": stack("{i}.mlp.dense_h_to_4h.bias")},
                "dense_4h_to_h": {"kernel": stack("{i}.mlp.dense_4h_to_h.weight", _t),
                                  "bias": stack("{i}.mlp.dense_4h_to_h.bias")},
            },
        }
        return params


class MegatronGPTMoEPolicy(MegatronGPTPolicy):
    """ref: module_inject/containers/megatron_gpt_moe.py — megatron layers
    whose MLP is a DeepSpeed-MoE expert bank
    (``mlp.deepspeed_moe.experts.deepspeed_experts.{e}.dense_*``).  The
    expert weights translate into the stacked-experts layout our MoE layer
    scans over ([L, NE, ...], moe/experts.py); the dense trunk follows the
    parent policy."""
    model_type = "megatron-gpt-moe"

    def convert_experts(self, sd, cfg, num_experts: int):
        L = cfg.num_hidden_layers
        enc, _ = self._layer_fmt(sd)
        moe = "mlp.deepspeed_moe.experts.deepspeed_experts"

        def bank(fmt, conv):
            return np.stack([
                np.stack([conv(_get(sd, f"{enc}.{i}.{moe}.{e}.{fmt}"))
                          for e in range(num_experts)]) for i in range(L)])

        return {
            "wi": bank("dense_h_to_4h.weight", lambda w: w.T),   # [L, NE, E, F]
            "wi_bias": bank("dense_h_to_4h.bias", lambda b: b),  # [L, NE, F]
            "wo": bank("dense_4h_to_h.weight", lambda w: w.T),   # [L, NE, F, E]
            "wo_bias": bank("dense_4h_to_h.bias", lambda b: b),  # [L, NE, E]
        }


POLICY_REGISTRY = {
    "megatron-gpt": MegatronGPTPolicy(),
    "megatron-gpt-moe": MegatronGPTMoEPolicy(),
    "llama": LlamaPolicy(),
    "mistral": MistralPolicy(),
    "qwen2": Qwen2Policy(),
    "phi3": Phi3Policy(),
    "mixtral": MixtralPolicy(),
    "opt": OPTPolicy(),
    "falcon": FalconPolicy(),
    "phi": PhiPolicy(),
    "qwen2_moe": Qwen2MoePolicy(),
    "bloom": BloomPolicy(),
    "gpt_neox": GPTNeoXPolicy(),
    "gptj": GPTJPolicy(),
    "gpt_neo": GPTNeoPolicy(),
    "bert": BertPolicy(),
    "distilbert": DistilBertPolicy(),
    "clip": ClipPolicy(),
    "qwen": QwenV1Policy(),
    "internlm": InternLMPolicy(),
}


def policy_for(model_type: str) -> InferenceV2Policy:
    if model_type not in POLICY_REGISTRY:
        raise ValueError(f"no inference policy for model_type={model_type!r}; "
                         f"known: {sorted(POLICY_REGISTRY)}")
    return POLICY_REGISTRY[model_type]


def convert_hf_state_dict(sd, hf_cfg, model_type=None) -> tuple:
    """(LlamaConfig-family cfg, flax params) from an HF state dict."""
    mt = model_type or getattr(hf_cfg, "model_type", "llama")
    pol = policy_for(mt)
    cfg = pol.build_config(hf_cfg)
    return cfg, pol.convert(sd, cfg)
