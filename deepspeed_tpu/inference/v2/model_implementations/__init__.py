"""Per-architecture inference policies (ref:
deepspeed/inference/v2/model_implementations/ — falcon, llama_v2, mistral,
mixtral, opt, phi, phi3, qwen, qwen_v2, qwen_v2_moe)."""

from .policies import (POLICY_REGISTRY, InferenceV2Policy, LlamaPolicy, MistralPolicy, MixtralPolicy,
                       Phi3Policy, Qwen2Policy, convert_hf_state_dict, policy_for)
