"""InferenceEngineV2 — FastGen-style continuous batching on TPU.

Reference: ``deepspeed/inference/v2/engine_v2.py:33 InferenceEngineV2``
(``put:124`` takes (uids, token-id lists)) and ``engine_factory.py:69
build_hf_engine``.  The serving loop composes:

  SplitFuseScheduler (scheduler.py)  — token-budget step planning
  StateManager/BlockedKVCache (ragged.py) — page allocation + batch packing
  LlamaForCausalLMWithCache (models/llama_cache.py) — one chunked forward
    program serving prefill, continuation and decode
  paged_attention[_pallas] — the blocked-KV attention kernel

TPU specifics vs the reference:
  * ONE compiled step program per (batch-bucket, chunk-bucket) pair — the
    scheduler quantises both, so steady-state serving reuses 2–4 programs
    instead of the reference's per-shape CUDA kernel launches.
  * the KV arena is donated through the jitted step, so XLA updates pages
    in place (the reference's global InferenceContext arena, inference_context.h).
  * sampling is greedy or categorical on-device; logits for each row are
    taken at its last *real* token via ``chunk_lens``.
"""

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...models.llama import LlamaConfig
from ...models.llama_cache import LlamaForCausalLMWithCache, PagedKVConfig, init_kv_cache
from ...telemetry.step_anatomy import NULL_ANATOMY
from ...utils.logging import logger
from .ragged import BlockedKVCache, RaggedBatch, StateManager
from .scheduler import SchedulerConfig, SplitFuseScheduler, StepPlan
from .spec import SpecConfig, SpecStats, make_drafter


def build_cache_model(cfg, page_size: int):
    """Per-arch paged-cache model dispatch (the reference's
    model_implementations registry role, ref: inference/v2/engine_factory.py
    arch switch)."""
    from ...models.mixtral import MixtralConfig
    if isinstance(cfg, MixtralConfig):
        from ...models.mixtral_cache import MixtralForCausalLMWithCache
        if cfg.drop_tokens:
            # serving must be dropless: capacity drops would silently zero
            # routed tokens and diverge from HF (the reference FastGen moe
            # gating has no capacity limit at inference)
            cfg = cfg.__class__(**{**cfg.__dict__, "drop_tokens": False})
        return MixtralForCausalLMWithCache(cfg, page_size=page_size)
    from ...models.cache_zoo import CACHE_MODEL_REGISTRY
    for cfg_cls, model_cls in CACHE_MODEL_REGISTRY.items():
        if isinstance(cfg, cfg_cls):
            return model_cls(cfg, page_size=page_size)
    return LlamaForCausalLMWithCache(cfg, page_size=page_size)


@dataclasses.dataclass(frozen=True)
class RaggedInferenceEngineConfig:
    """ref: inference/v2/config_v2.py RaggedInferenceEngineConfig."""
    kv: PagedKVConfig = PagedKVConfig()
    scheduler: SchedulerConfig = SchedulerConfig()
    max_new_tokens: int = 128
    eos_token_id: Optional[int] = None
    greedy: bool = True
    temperature: float = 1.0
    kv_dtype: object = jnp.bfloat16
    # KV page reuse across shared prompt prefixes
    # (ref: inference/v2/ragged/prefix_cache_manager.py)
    enable_prefix_cache: bool = True
    # pure-decode rounds fused into ONE compiled program (the reference's
    # CUDA-graphs analog): dispatch/host overhead amortizes K×, which
    # dominates decode at small models or over tunneled chips.  Sequences
    # hitting EOS mid-block have their surplus tokens discarded host-side.
    decode_steps_per_dispatch: int = 8
    # unroll the layer loop in the decode trunk (llama-family twin only;
    # other families and quantized checkpoints keep the scanned layout with
    # a warning): straight-line code drops the scan's while/dus bookkeeping
    # at tiny decode shapes; scan-stacked checkpoints are converted at
    # engine init (models/llama_cache.unstack_layer_params — no data
    # movement)
    unroll_layers: bool = False
    # TP-sharded serving (ref: inference/v2/engine_v2.py:118 honors
    # tensor_parallel.tp_size; model_implementations/sharding/qkv.py et al.).
    # Weights shard via the logical-axis rules (module_inject/tp_rules.py),
    # the KV arena over its kv-heads dim, and GSPMD inserts the o_proj /
    # down_proj allreduces AutoTP hand-wires.  An explicit ``mesh=`` to the
    # engine takes precedence over this degree.
    tensor_parallel: int = 1
    # speculative decoding (spec/): a drafter proposes up to k tokens per
    # pure-decode round and ONE (k+1)-position verify dispatch emits
    # accepted+1 of them, greedy-parity by construction.  Greedy only; on
    # pure-decode rounds speculation takes precedence over the fused
    # multi-step rung (which stays the fallback when no row drafts or KV
    # pages are short).  None disables.
    spec: Optional[SpecConfig] = None


def _make_step_fn(model, qparams, greedy: bool, temperature: float):
    """The unified SplitFuse step program: one chunked forward serving
    prefill, continuation and decode, then per-row last-token sampling.
    Pure in (params, cache, batch arrays) so both the live engine and the
    AOT serving-budget path (compile_aot_serving) jit the same function."""

    def step(params, cache, tokens, start_pos, block_tables, chunk_lens, rng):
        if qparams is not None:
            params = {"params": qparams.dequantize(params["params"])}
        logits, cache = model.apply(params, tokens, start_pos, block_tables, cache, chunk_lens)
        # logits of each row's LAST real token
        last = jnp.maximum(chunk_lens - 1, 0)
        row_logits = jnp.take_along_axis(logits, last[:, None, None], axis=1)[:, 0]   # [B, V]
        if greedy:
            next_tok = jnp.argmax(row_logits, axis=-1)
        else:
            next_tok = jax.random.categorical(rng, row_logits / temperature, axis=-1)
        return next_tok.astype(jnp.int32), cache

    return step


def _serving_shardings(model, cfg, kvcfg, kv_dtype, mesh):
    """TP shardings shared by the live engine (_setup_tp) and the AOT budget
    path: params via the logical-axis rules (zero_stage=0), the scanned KV
    arena [L, P, page, 2, n_kv, hd] over its kv-heads dim, host-side batch
    arrays replicated.  One derivation so the AOT memory budget can never
    desynchronize from what the engine actually shards."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ...comm.mesh import TENSOR_AXIS
    from ...module_inject.tp_rules import param_shardings
    cache_abs = jax.eval_shape(lambda: init_kv_cache(cfg, kvcfg, dtype=kv_dtype))
    toks1 = jnp.zeros((1, 1), jnp.int32)
    one = jnp.zeros((1, ), jnp.int32)
    bt1 = jnp.zeros((1, kvcfg.max_pages_per_seq), jnp.int32)
    abs_vars = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), toks1, one, bt1, cache_abs,
                           jnp.ones((1, ), jnp.int32)))
    param_sh = param_shardings(abs_vars, mesh, zero_stage=0)
    cache_sh = NamedSharding(mesh, P(None, None, None, None, TENSOR_AXIS, None))
    repl = NamedSharding(mesh, P())
    return abs_vars, cache_abs, param_sh, cache_sh, repl


def compile_aot_serving(cfg, mesh, engine_config: RaggedInferenceEngineConfig = None,
                        batch: int = 8, chunk: int = 1):
    """AOT-compile the TP-sharded serving step against an offline topology.

    No weights are ever allocated — params/cache lower as ShapeDtypeStructs —
    so this proves a serving config (e.g. Llama-3-8B at TP8 on v5p) fits
    per-chip HBM without the chips: the compiler's own buffer assignment,
    paged-attention kernel and GSPMD allreduces included.  Returns
    (compiled, n_params); ``compiled.memory_analysis()`` has the budget.
    Ref: the reference sizes its serving worlds by launcher convention
    (inference/v2/engine_v2.py:118) — no equivalent no-hardware proof exists
    there."""
    import numpy as np

    from ...comm.mesh import trace_mesh
    eng_cfg = engine_config or RaggedInferenceEngineConfig()
    kvcfg = eng_cfg.kv
    model = build_cache_model(cfg, kvcfg.page_size)
    abs_params, cache_abs, param_sh, cache_sh, r = _serving_shardings(
        model, cfg, kvcfg, eng_cfg.kv_dtype, mesh)
    step = _make_step_fn(model, None, eng_cfg.greedy, eng_cfg.temperature)
    jitted = jax.jit(step, donate_argnums=(1, ),
                     in_shardings=(param_sh, cache_sh, r, r, r, r, r),
                     out_shardings=(r, cache_sh))
    sds = jax.ShapeDtypeStruct
    args = (abs_params, cache_abs,
            sds((batch, chunk), jnp.int32), sds((batch, ), jnp.int32),
            sds((batch, kvcfg.max_pages_per_seq), jnp.int32), sds((batch, ), jnp.int32),
            jax.eval_shape(lambda: jax.random.PRNGKey(0)))
    with mesh, trace_mesh(mesh):
        compiled = jitted.lower(*args).compile()
    n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(abs_params))
    return compiled, n_params


class InFlightStep:
    """A dispatched-but-not-folded engine step: the device program has
    been enqueued (JAX async dispatch) and the host-side fold inputs are
    snapshotted here, so ``complete_step`` can run an arbitrary amount of
    host work later — the async double-buffered serving tick schedules
    step g+1 while this one executes.  ``tokens`` is the un-materialized
    device array; everything else is plain host state captured at
    dispatch time (sequence descriptors by OBJECT identity, so a flush
    that replaced a uid while the step was in flight is detectable)."""

    __slots__ = ("kind", "tokens", "rows", "seqs", "drafts", "base_len", "k")

    def __init__(self, kind: str):
        self.kind = kind          # "single" | "multi" | "spec"
        self.tokens = None        # device array: sampled tokens / argmax
        self.rows = None          # single: [(uid, n, seq, row_index)]
        self.seqs = None          # multi/spec: descriptor list at dispatch
        self.drafts = None        # spec: per-row draft token lists
        self.base_len = None      # spec: pre-splice history lengths
        self.k = None             # multi: fused rounds in the dispatch


class InferenceEngineV2:
    """Continuous-batching engine over a paged-KV Llama model."""

    def __init__(self, cfg: LlamaConfig, params, engine_config: RaggedInferenceEngineConfig = None,
                 rng: Optional[jax.Array] = None, mesh=None):
        self.econfig = engine_config or RaggedInferenceEngineConfig()
        # speculative decoding: greedy-only (the accept rule is an argmax
        # identity — under sampling, emitted tokens would need the full
        # rejection-sampling correction, not implemented), and the verify
        # slots must be charged against the scheduler's token budget
        if self.econfig.spec is not None and not self.econfig.greedy:
            logger.warning("spec decoding requires greedy sampling "
                           "(accept-longest-prefix parity is an argmax identity); "
                           "disabling speculation")
            self.econfig = dataclasses.replace(self.econfig, spec=None)
        if self.econfig.spec is not None and \
                self.econfig.scheduler.spec_verify_tokens == 0:
            self.econfig = dataclasses.replace(
                self.econfig, scheduler=dataclasses.replace(
                    self.econfig.scheduler,
                    spec_verify_tokens=self.econfig.spec.max_draft))
        self.drafter = (make_drafter(self.econfig.spec)
                        if self.econfig.spec is not None else None)
        self.spec_stats = SpecStats()
        # uid -> (proposed, accepted, rollback_pages) of the LAST step's
        # verify round (cleared every step): the serving frontend folds
        # these into per-request acceptance accounting and metrics
        self.last_spec_round: Dict[int, Tuple[int, int, int]] = {}
        self._spec_on: Dict[int, bool] = {}
        kvcfg = self.econfig.kv
        from ..quantization import QuantizedParams
        self.mesh = self._resolve_mesh(mesh)
        if self.mesh is not None:
            if isinstance(params, QuantizedParams):
                raise NotImplementedError(
                    "TP-sharded serving of weight-only-quantized checkpoints is not "
                    "implemented (int8 blocks would need per-shard scale re-layout)")
            if self.econfig.unroll_layers:
                logger.warning("tensor_parallel: the unrolled decode trunk is single-device; "
                               "keeping the scanned layout")
                self.econfig = dataclasses.replace(self.econfig, unroll_layers=False)
        model = build_cache_model(cfg, kvcfg.page_size)
        if self.econfig.unroll_layers and getattr(cfg, "scan_layers", False):
            # only the llama-family twin implements the unrolled trunk; other
            # families' twins are scan-only and would fail with a converted
            # param tree / tupled cache
            if not isinstance(model, LlamaForCausalLMWithCache):
                logger.warning(f"unroll_layers: {type(model).__name__} has no unrolled "
                               "trunk; keeping the scanned layout")
            elif isinstance(params, QuantizedParams):
                logger.warning("unroll_layers: quantized checkpoints keep the scanned "
                               "layout (per-layer dequant conversion not implemented)")
            else:
                cfg = dataclasses.replace(cfg, scan_layers=False)
                from ...models.llama_cache import unstack_layer_params
                params = unstack_layer_params(params, cfg.num_hidden_layers)
                model = build_cache_model(cfg, kvcfg.page_size)
        self.cfg = cfg
        self.model = model
        # weight-only-quantized checkpoints: int8 stays in HBM, dequant is
        # traced into the step program (ref: inference/quantization kernels)
        if isinstance(params, QuantizedParams):
            self._qparams = params
            self.params = {"params": params.tree}
        else:
            self._qparams = None
            self.params = params
        self.kv = BlockedKVCache(kvcfg.num_pages, kvcfg.page_size, kvcfg.max_pages_per_seq,
                                 enable_prefix_cache=self.econfig.enable_prefix_cache)
        self.state = StateManager(self.kv, max_batch=self.econfig.scheduler.max_seqs)
        self.scheduler = SplitFuseScheduler(self.econfig.scheduler)
        cache = init_kv_cache(cfg, kvcfg, dtype=self.econfig.kv_dtype)
        if not getattr(cfg, "scan_layers", True):
            # unrolled trunk: per-layer arena tuple (donated leaf-wise; a
            # stacked arena would cost a whole-arena dus per layer per round)
            cache = tuple(cache[i] for i in range(cfg.num_hidden_layers))
        self.cache = cache
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._max_new: Dict[int, int] = {}
        self._step_fns: Dict[Tuple[int, int], callable] = {}
        # per-step anatomy (telemetry/step_anatomy.py): NULL by default —
        # one attribute read + one predicate per hook when disabled
        self.anatomy = NULL_ANATOMY
        self._fresh_compile = False
        self._param_sh = self._cache_sh = self._repl_sh = None
        if self.mesh is not None:
            self._setup_tp()

    def set_anatomy(self, anatomy):
        """Attach a :class:`~...telemetry.step_anatomy.StepAnatomy`
        recorder (None restores the allocation-free NULL recorder).  The
        recorder's clock should be the serving clock when a frontend
        drives this engine, so host-gap windows and device charges live
        in one time domain."""
        self.anatomy = anatomy if anatomy is not None else NULL_ANATOMY
        return self.anatomy

    def _note_compile(self, key: str) -> None:
        """One JIT cache miss: the NEXT dispatch of this program pays the
        trace+compile synchronously, so the step's dispatch segment is
        tagged ``compile_wait`` and the compile tracker records the miss
        (warm-up vs steady-state — the AOT regression guard)."""
        self._fresh_compile = True
        self.anatomy.note_compile(key)

    # ------------------------------------------------------------------ TP

    def _resolve_mesh(self, mesh):
        """Explicit mesh wins; else ``tensor_parallel > 1`` builds a pure-TP
        mesh over the first tp devices (ref: engine_v2.py:118 — the reference
        reads tp_size from config and expects the launcher to have sized the
        world; here the engine claims the devices itself)."""
        if mesh is not None:
            if mesh.size <= 1:
                return None
            if mesh.shape.get("tensor", 1) <= 1:
                raise ValueError(
                    f"serving mesh {dict(mesh.shape)} has no 'tensor' axis with degree > 1 — "
                    "the v2 engine shards over TP only; build it with e.g. "
                    "create_mesh(MeshSpec(data=1, tensor=N))")
            return mesh
        tp = self.econfig.tensor_parallel
        if tp <= 1:
            return None
        devs = jax.devices()
        if len(devs) < tp:
            raise ValueError(f"tensor_parallel={tp} but only {len(devs)} devices visible")
        from ...comm.mesh import MeshSpec, create_mesh
        return create_mesh(MeshSpec(data=1, tensor=tp), devices=devs[:tp])

    def _setup_tp(self):
        """Shard weights + KV arena over the mesh's tensor axis.

        The serving analog of AutoTP (ref: model_implementations/sharding/
        qkv.py:14 et al. hand-shard each weight class): every cache twin
        already carries logical axis names on its params, so the training-side
        rules (module_inject/tp_rules.py, zero_stage=0) produce the same
        Megatron layout — q/k/v column-parallel over heads, o/down
        row-parallel, vocab-parallel embedding/lm_head — and GSPMD inserts
        the paired allreduces.  The KV arena shards over its kv-heads dim so
        per-chip KV bytes drop by 1/tp (the reference's
        ``kv_cache.py`` splits head_count across ranks the same way)."""
        from ...comm.mesh import TENSOR_AXIS
        mesh = self.mesh
        tp = mesh.shape.get(TENSOR_AXIS, 1)
        if not isinstance(self.cache, jax.Array):
            # scan_layers=False builds a per-layer arena TUPLE for leaf-wise
            # donation — a single-device decode optimization; the TP path is
            # scanned-only (same stance as the unroll_layers guard in init)
            raise NotImplementedError(
                "TP-sharded serving requires scan_layers=True (the per-layer "
                "unrolled arena tuple is a single-device layout)")
        n_kv = self.cache.shape[-2]
        heads = self.cfg.num_attention_heads
        if tp > 1 and (n_kv % tp or heads % tp):
            raise ValueError(f"tensor_parallel={tp} must divide num_key_value_heads={n_kv} "
                             f"and num_attention_heads={heads}")
        _, _, self._param_sh, self._cache_sh, self._repl_sh = _serving_shardings(
            self.model, self.cfg, self.econfig.kv, self.econfig.kv_dtype, mesh)
        self.params = jax.device_put(self.params, self._param_sh)
        self.cache = jax.device_put(self.cache, self._cache_sh)
        logger.info(f"InferenceEngineV2: TP-sharded serving over tensor={tp} "
                    f"({mesh.size}-device mesh)")

    # ---------------------------------------------------------------- put

    def put(self, batch_uids: Sequence[int], batch_tokens: Sequence[Sequence[int]],
            max_new_tokens: Optional[int] = None) -> None:
        """Admit new sequences (ref: engine_v2.py:124 put)."""
        max_pos = getattr(self.cfg, "max_position_embeddings", None)
        # validate ALL before admitting ANY — a partial put would leave
        # earlier sequences admitted when a later one raises
        for uid, tokens in zip(batch_uids, batch_tokens):
            need = len(tokens) + (max_new_tokens or self.econfig.max_new_tokens)
            if max_pos is not None and need > max_pos:
                # learned/rotary position tables end here; clamped positions
                # would silently produce degraded logits (e.g. OPT's table)
                raise ValueError(f"sequence {uid}: prompt+max_new_tokens = {need} exceeds the "
                                 f"model's max_position_embeddings = {max_pos}")
        for uid, tokens in zip(batch_uids, batch_tokens):
            self.state.get_or_create(uid, list(tokens))
            self._max_new[uid] = max_new_tokens or self.econfig.max_new_tokens

    def flush(self, uid: int) -> None:
        self.state.flush(uid)
        self._max_new.pop(uid, None)
        self._spec_on.pop(uid, None)
        self.last_spec_round.pop(uid, None)

    def preempt(self, uid: int):
        """Evict one sequence under KV pressure (serving frontend): pages
        released, descriptor returned for requeue-with-tokens-preserved.
        Unlike ``flush`` the uid must exist — preempting a finished/unknown
        sequence is a frontend bug, not a no-op."""
        self._max_new.pop(uid, None)
        self._spec_on.pop(uid, None)
        self.last_spec_round.pop(uid, None)
        return self.state.preempt(uid)

    def set_spec(self, uid: int, enabled: bool) -> None:
        """Per-sequence speculation opt-in/out (the serving frontend's
        per-request control).  No-op when the engine carries no spec
        config — a request asking for speculation on a spec-less engine
        just decodes normally."""
        if self.econfig.spec is not None:
            self._spec_on[uid] = bool(enabled)

    def single_step_page_demand(self, plan: Optional[StepPlan] = None) -> int:
        """KV pages the NEXT step needs beyond what its sequences hold, at
        the guaranteed-progress rung (decode k=1 — the fused multi-decode
        path already self-shrinks k under pressure in ``step``).  The
        serving frontend preflights this against ``allocator.free_pages``
        and preempts until the step fits, instead of letting ``pack`` raise
        mid-step."""
        if plan is None:
            plan = self.scheduler.plan(self.state)
        return (sum(self.kv.pages_needed(s, 1) for s in plan.decode) +
                sum(self.kv.pages_needed(s, n) for s, n in plan.prefill))

    # --------------------------------------------------------------- step

    def _jit_kwargs(self):
        """Explicit shardings under TP: params/cache committed to their
        shards, host-side batch arrays (tokens, tables, positions) and the
        sampled tokens replicated."""
        if self.mesh is None:
            return {}
        r = self._repl_sh
        return dict(in_shardings=(self._param_sh, self._cache_sh, r, r, r, r, r),
                    out_shardings=(r, self._cache_sh))

    def _invoke(self, fn, *args):
        """Run a compiled step; under TP the trace happens inside the mesh +
        trace_mesh context so the Pallas paged kernel self-wraps in shard_map
        (ops/paged_attention._paged_sharded)."""
        if self.mesh is None:
            return fn(*args)
        from ...comm.mesh import trace_mesh
        with self.mesh, trace_mesh(self.mesh):
            return fn(*args)

    def _build_step_jit(self):
        """The jitted single/mixed step program — ONE builder shared by
        the lazy per-shape cache and the AOT ``warm_all`` path, so the
        two can never trace different computations for the same key."""
        step = _make_step_fn(self.model, self._qparams, self.econfig.greedy,
                             self.econfig.temperature)
        return jax.jit(step, donate_argnums=(1, ), **self._jit_kwargs())

    def _build_multi_jit(self, batch: int, k: int):
        """The fused k-round decode program (shapes close over batch/k)."""
        def mstep(params, cache, tokens0, start_pos, block_tables, chunk_lens, rng):
            if self._qparams is not None:
                params = {"params": self._qparams.dequantize(params["params"])}

            def body(i, carry):
                cache, toks, out = carry
                logits, cache = self.model.apply(params, toks[:, None], start_pos + i,
                                                 block_tables, cache, chunk_lens)
                row_logits = logits[:, 0]
                if self.econfig.greedy:
                    nxt = jnp.argmax(row_logits, axis=-1).astype(jnp.int32)
                else:
                    nxt = jax.random.categorical(
                        jax.random.fold_in(rng, i),
                        row_logits / self.econfig.temperature, axis=-1).astype(jnp.int32)
                return (cache, nxt, out.at[:, i].set(nxt))

            out0 = jnp.zeros((batch, k), jnp.int32)
            cache, _, out = jax.lax.fori_loop(0, k, body, (cache, tokens0, out0))
            return out, cache

        return jax.jit(mstep, donate_argnums=(1, ), **self._jit_kwargs())

    def _build_verify_jit(self):
        """The speculative verify program (argmax at EVERY position)."""
        def vstep(params, cache, tokens, start_pos, block_tables, chunk_lens):
            if self._qparams is not None:
                params = {"params": self._qparams.dequantize(params["params"])}
            logits, cache = self.model.apply(params, tokens, start_pos,
                                             block_tables, cache, chunk_lens)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        kwargs = {}
        if self.mesh is not None:
            r = self._repl_sh
            kwargs = dict(in_shardings=(self._param_sh, self._cache_sh, r, r, r, r),
                          out_shardings=(r, self._cache_sh))
        return jax.jit(vstep, donate_argnums=(1, ), **kwargs)

    def _compiled_step(self, batch: int, chunk: int):
        key = (batch, chunk)
        if key not in self._step_fns:
            logger.info(f"InferenceEngineV2: compiling step program batch={batch} chunk={chunk}")
            self._step_fns[key] = self._build_step_jit()
            self._note_compile(f"step:b{batch}:c{chunk}")
        return self._step_fns[key]

    def _compiled_multi_step(self, batch: int, k: int):
        key = ("multi", batch, k)
        if key not in self._step_fns:
            logger.info(f"InferenceEngineV2: compiling multi-decode program batch={batch} k={k}")
            self._step_fns[key] = self._build_multi_jit(batch, k)
            self._note_compile(f"multi:b{batch}:k{k}")
        return self._step_fns[key]

    def _compiled_verify(self, batch: int, width: int):
        """The speculative VERIFY program: ONE chunked forward over
        ``width = max_draft + 1`` positions per row, returning the argmax
        at EVERY position (the model's own next-token choice after each
        fed prefix) instead of a single last-token sample.  Shorter drafts
        ride as ragged rows via ``chunk_lens`` — KV writes and attention
        mask at the per-row length, exactly like ragged prefill chunks —
        so steady-state serving keeps ONE verify program per batch
        bucket."""
        key = ("verify", batch, width)
        if key not in self._step_fns:
            logger.info(f"InferenceEngineV2: compiling verify program batch={batch} "
                        f"width={width}")
            self._step_fns[key] = self._build_verify_jit()
            self._note_compile(f"verify:b{batch}:w{width}")
        return self._step_fns[key]

    # ------------------------------------------------------------- AOT set

    @staticmethod
    def _key_label(key) -> str:
        if key[0] == "multi":
            return f"multi:b{key[1]}:k{key[2]}"
        if key[0] == "verify":
            return f"verify:b{key[1]}:w{key[2]}"
        return f"step:b{key[0]}:c{key[1]}"

    def step_shape_set(self) -> List[tuple]:
        """Enumerate every program key steady-state serving can reach,
        straight from the scheduler's bucket table: batch buckets are the
        ``decode_bucket`` multiples up to ``max_seqs``; chunk buckets are
        {1, prefill_chunk} (the only two ``_dispatch_single`` produces);
        the fused-decode rung adds its halving ladder (k_cfg, k_cfg/2,
        ..., 2 — exactly the pressure fallbacks ``_dispatch_inner``
        walks); a drafter adds one verify width (``max_draft + 1``).
        This closure is what makes ``warm_all`` a guarantee rather than a
        heuristic: a steady-state dispatch outside this set would be an
        engine bug, and the ``engine/recompile_steady_state`` guard would
        name it."""
        sched = self.econfig.scheduler
        q = sched.decode_bucket
        maxb = self.state.max_batch
        batches = sorted({min(maxb, m * q) for m in range(1, -(-maxb // q) + 1)})
        keys: List[tuple] = [(b, c) for b in batches
                             for c in sorted({1, sched.prefill_chunk})]
        k_cfg = self.econfig.decode_steps_per_dispatch
        if k_cfg > 1:
            ks = set()
            k = k_cfg
            while k > 1:
                ks.add(k)
                k //= 2
            keys += [("multi", b, k) for b in batches for k in sorted(ks)]
        if self.drafter is not None:
            width = self.econfig.spec.max_draft + 1
            keys += [("verify", b, width) for b in batches]
        return keys

    def _aot_compile(self, key):
        """``lower(...).compile()`` one program key against abstract
        params/cache (the ``compile_aot_serving`` machinery, aimed at the
        LIVE engine's shapes): nothing executes, no engine state moves —
        unlike ``warm_verify``'s all-padding dispatches — and the
        returned Compiled is call-compatible with the lazily jitted
        version because both come from the same builder."""
        sds = jax.ShapeDtypeStruct
        kvcfg = self.econfig.kv
        params_abs = jax.tree.map(lambda x: sds(x.shape, x.dtype), self.params)
        cache_abs = jax.tree.map(lambda x: sds(x.shape, x.dtype), self.cache)
        rng_abs = sds(self.rng.shape, self.rng.dtype)

        def batch_args(b, w):
            return (sds((b, w), jnp.int32), sds((b, ), jnp.int32),
                    sds((b, kvcfg.max_pages_per_seq), jnp.int32),
                    sds((b, ), jnp.int32))

        if key[0] == "multi":
            _, b, k = key
            jitted = self._build_multi_jit(b, k)
            args = (params_abs, cache_abs, sds((b, ), jnp.int32)) + \
                batch_args(b, 1)[1:] + (rng_abs, )
        elif key[0] == "verify":
            _, b, w = key
            jitted = self._build_verify_jit()
            args = (params_abs, cache_abs) + batch_args(b, w)
        else:
            b, c = key
            jitted = self._build_step_jit()
            args = (params_abs, cache_abs) + batch_args(b, c) + (rng_abs, )
        if self.mesh is None:
            return jitted.lower(*args).compile()
        from ...comm.mesh import trace_mesh
        with self.mesh, trace_mesh(self.mesh):
            return jitted.lower(*args).compile()

    def warm_all(self) -> Dict[str, object]:
        """AOT-compile the full reachable step set (``step_shape_set``)
        into the program cache, so steady-state serving NEVER pays a
        trace+compile inside a dispatch — the ROADMAP's AOT serving-step
        item.  ``ServingEngine`` startup and ``ReplicaPool`` recovery
        call this before entering dispatch.

        Failure stance: an ``engine.aot_compile`` chaos injection (or a
        real compiler error) on one key falls back to the lazy JIT path
        for that key — the first dispatch compiles it synchronously,
        slower but never wrong, and NEVER a dead replica.  Only
        ``InjectedCrash`` (simulated process death) propagates.  Each
        pre-compiled key lands in the compile log as ``aot=True`` —
        deliberate warm-up, exempt from the steady-state-recompile
        guard."""
        from ...resilience import fault_injection as _fi
        anat = self.anatomy
        compiled = cached = fallback = 0
        keys = self.step_shape_set()
        for key in keys:
            if key in self._step_fns:
                cached += 1
                continue
            label = self._key_label(key)
            try:
                _fi.check("engine.aot_compile")
                fn = self._aot_compile(key)
            except _fi.InjectedCrash:
                raise
            except Exception as e:
                fallback += 1
                logger.warning(f"InferenceEngineV2: AOT compile of {label} failed "
                               f"({e}); falling back to lazy JIT on first dispatch")
                continue
            self._step_fns[key] = fn
            compiled += 1
            anat.note_compile(label, aot=True)
        if anat.enabled and compiled:
            # inside an open step window the compile time is attributed
            # explicitly; outside one, mark() is a no-op by design
            anat.mark("aot_compile")
        return {"compiled": compiled, "cached": cached, "fallback": fallback,
                "keys": [self._key_label(k) for k in keys]}

    def warm_verify(self, batch_sizes: Sequence[int]) -> None:
        """Pre-compile the speculative verify program for the given raw
        batch sizes (bucketed, width pinned at ``max_draft + 1``) by
        running one ALL-PADDING dispatch per bucket: every row has
        chunk_len 0 and an all-null block table, so KV writes land in the
        null scratch page and engine state is untouched.  Serving
        harnesses call this next to their step-program warmup — drafting
        is history-dependent, so a short warm generation may never reach a
        verify round, and the first real one would otherwise pay a
        multi-second jit inside measured request latency.  No-op without a
        spec config."""
        if self.drafter is None:
            return
        width = self.econfig.spec.max_draft + 1
        for b in sorted({self._bucket_batch(n) for n in batch_sizes}):
            fn = self._compiled_verify(b, width)
            zeros = jnp.zeros((b, ), jnp.int32)
            _, self.cache = self._invoke(
                fn, self.params, self.cache, jnp.zeros((b, width), jnp.int32),
                zeros, jnp.zeros((b, self.kv.max_pages_per_seq), jnp.int32), zeros)

    def _plan_drafts(self, seqs) -> List[List[int]]:
        """Draft up to ``max_draft`` tokens per decode row, then shrink
        under pressure.  Per-row caps keep the verify dispatch feasible by
        construction: a draft never proposes past the row's ``max_new``
        limit (emitting ``accepted + 1`` tokens, only ``remaining - 1``
        drafts can ever be useful), the verify-slot width the scheduler
        charges (``spec_verify_tokens``), the position table, or its page
        capacity.  Aggregate demand self-shrinks the same way the fused
        rung does — halve every draft until the arena can take the round
        AND the round's total fed tokens (1 + draft per row) fit the
        SplitFuse ``token_budget`` — so the KV-pressure preflight's k=1
        guarantee still holds when every draft reaches zero."""
        spec = self.econfig.spec
        sched = self.econfig.scheduler
        width = min(spec.max_draft, sched.spec_verify_tokens or spec.max_draft)
        cap = min(self.kv.max_pages_per_seq * self.kv.page_size,
                  getattr(self.cfg, "max_position_embeddings", None) or (1 << 30))
        drafts: List[List[int]] = []
        for s in seqs:
            if not self._spec_on.get(s.uid, True):
                drafts.append([])
                continue
            limit = self._max_new.get(s.uid, self.econfig.max_new_tokens)
            room = min(width, limit - len(s.generated) - 1,
                       cap - len(s.tokens))
            drafts.append(self.drafter.draft(s.tokens, room) if room > 0 else [])
        while any(drafts) and (
                sum(1 + len(d) for d in drafts) > sched.token_budget or
                sum(self.kv.pages_needed(s, 1 + len(d)) for s, d in zip(seqs, drafts))
                > self.kv.allocator.free_pages):
            drafts = [d[:len(d) // 2] for d in drafts]
        return drafts

    def _dispatch_spec(self, seqs, drafts: List[List[int]]) -> InFlightStep:
        """Enqueue one draft-verify round for a pure-decode batch: feed
        ``[last_sampled, draft_0 .. draft_{d-1}]`` per row through the
        verify program.  The accept fold (``_complete_spec``) accepts the
        longest prefix of drafts matching the model's per-position argmax
        host-side, emits ``accepted + 1`` tokens (the argmax after the
        last accepted draft rides along as the bonus/correction token),
        and rolls rejected tokens' KV back via ``StateManager.truncate``.
        Greedy outputs are byte-identical to non-speculative decode by
        construction — every emitted token IS the model's argmax given
        the exact accepted history."""
        from ...resilience import fault_injection as _fi
        anat = self.anatomy
        width = self.econfig.spec.max_draft + 1
        batch = self._bucket_batch(len(seqs))
        base_len = [len(s.tokens) for s in seqs]
        # drafts ride in the token history for pack() (sliced back out
        # in the fold — they are verify INPUTS, not accepted output)
        for s, d in zip(seqs, drafts):
            s.tokens.extend(d)
        try:
            rb: RaggedBatch = self.state.pack([(s, 1 + len(d)) for s, d in zip(seqs, drafts)],
                                              width, pad_to=batch)
            if anat.enabled:
                anat.mark("verify_plan")
            fn = self._compiled_verify(batch, width)
            if anat.enabled:
                anat.note_shape("spec_verify", batch, width)
            _fi.check("engine.verify_step")  # chaos site: device loss mid-verify
            argmax, self.cache = self._invoke(fn, self.params, self.cache,
                                              jnp.asarray(rb.tokens), jnp.asarray(rb.start_pos),
                                              jnp.asarray(rb.block_tables),
                                              jnp.asarray(rb.chunk_lens))
            if anat.enabled:
                anat.mark("compile_wait" if self._fresh_compile else "dispatch")
        except BaseException:
            # a failed verify dispatch must never bake unverified drafts
            # into the history: restore every row's token list so a caller
            # that survives the error (chaos drill, retry layer) decodes
            # from exactly the pre-round state.  seen_tokens/pages were not
            # advanced yet; extra pages pack() allocated are plain capacity
            # the next round reuses.
            for s, L in zip(seqs, base_len):
                del s.tokens[L:]
            raise
        inf = InFlightStep("spec")
        inf.tokens = argmax
        inf.seqs = list(seqs)
        inf.drafts = drafts
        inf.base_len = base_len
        return inf

    def _complete_spec(self, inf: InFlightStep) -> Dict[int, List[int]]:
        anat = self.anatomy
        seqs, drafts, base_len = inf.seqs, inf.drafts, inf.base_len
        try:
            argmax = np.asarray(inf.tokens)
        except BaseException:
            # the deferred readback surfaced the device failure here (the
            # pipelined tick blocks at complete, not dispatch): the
            # unverified drafts are still spliced into every still-live
            # row's history — restore exactly as the dispatch-path
            # handler does before re-raising
            for s, L in zip(seqs, base_len):
                if self.state.seqs.get(s.uid) is s:
                    del s.tokens[L:]
            raise
        if anat.enabled:
            anat.device_mark()

        out: Dict[int, List[int]] = {}
        eos = self.econfig.eos_token_id
        self.spec_stats.rounds += 1
        for i, (s, d) in enumerate(zip(seqs, drafts)):
            if self.state.seqs.get(s.uid) is not s:
                continue  # flushed while in flight (pipelined tick)
            L = base_len[i]
            s.seen_tokens += 1 + len(d)
            # g[j] = the model's choice for history index L+j given the
            # prefix through index L-1+j; draft j (at index L+j) is
            # accepted iff it equals g[j]
            g = [int(t) for t in argmax[i, :1 + len(d)]]
            a = 0
            while a < len(d) and d[a] == g[a]:
                a += 1
            del s.tokens[L:]
            before = len(s.generated)
            limit = self._max_new.get(s.uid, self.econfig.max_new_tokens)
            for t in d[:a] + [g[a]]:
                s.tokens.append(int(t))
                s.generated.append(int(t))
                if len(s.generated) >= limit or (eos is not None and int(t) == eos):
                    s.done = True
                    break
            # rollback: rejected drafts' KV lies past the accepted
            # boundary — clamp seen_tokens and return wholly-surplus pages
            # to the arena THIS step (free capacity is visible to the next
            # preflight immediately, not at sequence death)
            freed = self.state.truncate(s, min(L + a, len(s.tokens)))
            self.state.note_progress(s)
            out[s.uid] = list(s.generated[before:])
            self.spec_stats.proposed += len(d)
            self.spec_stats.accepted += a
            self.spec_stats.emitted += len(out[s.uid])
            self.spec_stats.rollback_pages += freed
            self.last_spec_round[s.uid] = (len(d), a, freed)
        if anat.enabled:
            anat.mark("sample_accept")
        return out

    def _dispatch_multi(self, seqs, k: int) -> InFlightStep:
        """Enqueue ``k`` fused decode rounds for a pure-decode batch."""
        batch = self._bucket_batch(len(seqs))
        for s in seqs:
            # capacity for the WHOLE block up front; pack()'s per-token
            # ensure_capacity then finds nothing left to allocate.  Capped
            # at the row's remaining max_new budget: a short-tail row keeps
            # at most `remaining` of the k tokens, and KV writes past its
            # reservation land in the null scratch page — reserving the
            # full k would over-allocate pages the row can never use
            remaining = self._max_new.get(s.uid, self.econfig.max_new_tokens) \
                - len(s.generated)
            self.kv.ensure_capacity(s, min(k, remaining))
        rb: RaggedBatch = self.state.pack([(s, 1) for s in seqs], 1, pad_to=batch)

        anat = self.anatomy
        self.rng, sub = jax.random.split(self.rng)
        fn = self._compiled_multi_step(batch, k)
        if anat.enabled:
            anat.note_shape("multi_decode", batch, k)
        toks, self.cache = self._invoke(fn, self.params, self.cache, jnp.asarray(rb.tokens[:, 0]),
                                        jnp.asarray(rb.start_pos), jnp.asarray(rb.block_tables),
                                        jnp.asarray(rb.chunk_lens), sub)
        if anat.enabled:
            anat.mark("compile_wait" if self._fresh_compile else "dispatch")
        inf = InFlightStep("multi")
        inf.tokens = toks
        inf.seqs = list(seqs)
        inf.k = k
        return inf

    def _complete_multi(self, inf: InFlightStep) -> Dict[int, List[int]]:
        anat = self.anatomy
        toks = np.asarray(inf.tokens)
        if anat.enabled:
            anat.device_mark()

        out: Dict[int, List[int]] = {}
        eos = self.econfig.eos_token_id
        k = inf.k
        for i, s in enumerate(inf.seqs):
            if self.state.seqs.get(s.uid) is not s:
                continue  # flushed while in flight (pipelined tick)
            before = len(s.generated)
            s.seen_tokens += k
            limit = self._max_new.get(s.uid, self.econfig.max_new_tokens)
            for t in toks[i]:
                s.tokens.append(int(t))
                s.generated.append(int(t))
                if len(s.generated) >= limit or (eos is not None and int(t) == eos):
                    # surplus tokens computed past EOS/limit are discarded;
                    # truncate() clamps the seen boundary past them AND
                    # returns their wholly-surplus KV pages to the arena
                    # this step (visible to the next KV-pressure preflight
                    # immediately — not held until the sequence dies)
                    s.done = True
                    break
            self.state.truncate(s, len(s.tokens))
            self.state.note_progress(s)
            out[s.uid] = list(s.generated[before:])
        if anat.enabled:
            anat.mark("sample_accept")
        return out

    def _bucket_batch(self, n: int) -> int:
        q = self.econfig.scheduler.decode_bucket
        return min(self.state.max_batch, -(-n // q) * q)

    def step(self, plan: Optional[StepPlan] = None) -> Dict[int, List[int]]:
        """Run one scheduled step; returns {uid: [new tokens]} for
        sequences that produced tokens this call — one token per uid on
        the single-step path, up to ``decode_steps_per_dispatch`` on the
        fused decode path.  ``plan`` lets a caller that already planned
        (the serving frontend's KV-pressure preflight) skip the re-plan;
        it must have been computed against the CURRENT state.

        Composition of the async-capable halves: ``dispatch_step``
        enqueues the device program and ``complete_step`` blocks at the
        readback and folds tokens — called back-to-back here, the serial
        loop is byte-identical to the pre-split engine (same dispatch
        order, same rng splits, same fold), and the pipelined serving
        tick interleaves its own host work between the two."""
        inf = self.dispatch_step(plan)
        if inf is None:
            return {}
        return self.complete_step(inf)

    def dispatch_step(self, plan: Optional[StepPlan] = None) -> Optional[InFlightStep]:
        """Plan (unless given one) and ENQUEUE one step on the device,
        without blocking on its outputs: JAX async dispatch returns as
        soon as the program is in flight, so the caller owns the device
        window for overlapped host work.  Returns None when there is
        nothing to run (empty plan).

        With a :class:`~...telemetry.step_anatomy.StepAnatomy` attached
        (``set_anatomy``), this opens the step window (``step_begin`` is
        idempotent — a frontend that planned first opens it itself) and
        the window stays OPEN across the in-flight stretch; an empty or
        failed dispatch closes it here so no window ever leaks."""
        anat = self.anatomy
        self._fresh_compile = False
        if anat.enabled:
            anat.step_begin()
        inflight = None
        try:
            if plan is None:
                plan = self.scheduler.plan(self.state)
                if anat.enabled:
                    anat.mark("schedule")
            inflight = self._dispatch_inner(plan)
            return inflight
        finally:
            if inflight is None and anat.enabled:
                anat.step_end()

    def complete_step(self, inf: InFlightStep) -> Dict[int, List[int]]:
        """Block on the in-flight step's readback and fold its tokens
        into engine state — the sample/accept half of ``step``.  Rows
        whose sequence was flushed while the step was in flight (the
        pipelined tick's expire path) are skipped by object identity;
        their computed tokens are discarded whole, never half-applied.
        Closes the anatomy step window even when the readback raises."""
        anat = self.anatomy
        try:
            if inf.kind == "spec":
                return self._complete_spec(inf)
            if inf.kind == "multi":
                return self._complete_multi(inf)
            return self._complete_single(inf)
        finally:
            if anat.enabled:
                anat.step_end()

    def _dispatch_inner(self, plan: StepPlan) -> Optional[InFlightStep]:
        anat = self.anatomy
        # per-step spec accounting: entries describe THIS step's verify
        # round only (the serving frontend reads them right after the
        # step's completion)
        self.last_spec_round.clear()
        if self.drafter is not None and plan.decode and not plan.prefill:
            # speculation outranks the fused rung on pure-decode rounds: a
            # round with any non-empty draft emits accepted+1 tokens per
            # drafting row for ONE dispatch.  When no row drafts (cold
            # history, per-request opt-out, page pressure shrank every
            # draft to zero) fall through to the fused/single-step rungs —
            # a drained-draft round must still make k=1 progress.
            drafts = self._plan_drafts(plan.decode)
            if anat.enabled:
                anat.mark("draft_plan")
            if any(drafts):
                return self._dispatch_spec(plan.decode, drafts)
        k_cfg = self.econfig.decode_steps_per_dispatch
        if k_cfg > 1 and plan.decode and not plan.prefill:
            # OVERSHOOT policy (r4): always run the full k rung and discard
            # surplus tokens host-side (the KV written past a row's limit
            # lies beyond its clamped seen boundary).  The pre-r4 halving
            # ladder (k, k/2, ... 1) matched `remaining` exactly but paid
            # the ~100-300ms fixed dispatch overhead per rung and compiled
            # a fresh single-step program for 1-token tails mid-serve —
            # 64 tokens cost 6 dispatches instead of 2.  k only shrinks
            # when the page arena, per-seq page capacity, or the position
            # table can't take the full block.
            max_pos = getattr(self.cfg, "max_position_embeddings", None) or (1 << 30)
            seq_room = min(min(self.kv.max_pages_per_seq * self.kv.page_size, max_pos) -
                           len(s.tokens) for s in plan.decode)
            k = k_cfg
            while k > 1 and (seq_room < k or sum(self.kv.pages_needed(s, k) for s in plan.decode)
                             > self.kv.allocator.free_pages):
                k //= 2
            if k > 1:
                return self._dispatch_multi(plan.decode, k)
        work: List = [(s, 1) for s in plan.decode] + list(plan.prefill)
        if not work:
            return None
        chunk = max(n for _, n in work)
        # chunk buckets: 1 (pure decode) or the prefill quantum
        chunk = 1 if chunk == 1 else self.econfig.scheduler.prefill_chunk
        batch = self._bucket_batch(len(work))
        rb: RaggedBatch = self.state.pack(work, chunk, pad_to=batch)

        self.rng, sub = jax.random.split(self.rng)
        fn = self._compiled_step(batch, chunk)
        if anat.enabled:
            path = ("mixed" if plan.prefill and plan.decode
                    else "prefill" if plan.prefill else "decode")
            anat.note_shape(path, batch, chunk)
        next_tok, self.cache = self._invoke(fn, self.params, self.cache, jnp.asarray(rb.tokens),
                                            jnp.asarray(rb.start_pos), jnp.asarray(rb.block_tables),
                                            jnp.asarray(rb.chunk_lens), sub)
        if anat.enabled:
            anat.mark("compile_wait" if self._fresh_compile else "dispatch")
        inf = InFlightStep("single")
        inf.tokens = next_tok
        inf.rows = [(int(uid), int(rb.chunk_lens[i]), self.state.seqs[uid], i)
                    for i, uid in enumerate(rb.uids) if uid >= 0]
        return inf

    def _complete_single(self, inf: InFlightStep) -> Dict[int, List[int]]:
        anat = self.anatomy
        next_tok = np.asarray(inf.tokens)
        if anat.enabled:
            anat.device_mark()

        out: Dict[int, List[int]] = {}
        for uid, n, seq, i in inf.rows:
            if self.state.seqs.get(uid) is not seq:
                continue  # flushed while in flight (pipelined tick)
            seq.seen_tokens += n
            self.state.note_progress(seq)
            if seq.in_prefill:
                continue  # mid-prompt chunk: logits not used
            tok = int(next_tok[i])
            seq.tokens.append(tok)
            seq.generated.append(tok)
            out[uid] = [tok]
            eos = self.econfig.eos_token_id
            if len(seq.generated) >= self._max_new.get(uid, self.econfig.max_new_tokens) or \
                    (eos is not None and tok == eos):
                seq.done = True
        if anat.enabled:
            anat.mark("sample_accept")
        return out

    # ----------------------------------------------------------- generate

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: Optional[int] = None) -> List[List[int]]:
        """Synchronous convenience: admit all prompts, run steps to
        completion, return generated token lists in order."""
        uids = list(range(len(prompts)))
        base = max(self.state.seqs.keys(), default=-1) + 1
        uids = [base + u for u in uids]
        self.put(uids, prompts, max_new_tokens=max_new_tokens)
        pending = set(uids)
        while pending:
            before = sum(s.seen_tokens + len(s.generated) for s in self.state.seqs.values())
            self.step()
            after = sum(s.seen_tokens + len(s.generated) for s in self.state.seqs.values())
            if after == before:
                raise RuntimeError("generation step made no progress "
                                   "(token budget / batch capacity exhausted?)")
            for u in list(pending):
                if self.state.seqs[u].done:
                    pending.discard(u)
        outs = [list(self.state.seqs[u].generated) for u in uids]
        for u in uids:
            self.flush(u)
        return outs


def build_engine(cfg: LlamaConfig, params, engine_config: RaggedInferenceEngineConfig = None,
                 mesh=None):
    """Factory (ref: inference/v2/engine_factory.py:69 build_hf_engine —
    there it loads an HF checkpoint; here weights come from the training
    engine or a checkpoint restore, already in the shared param layout)."""
    return InferenceEngineV2(cfg, params, engine_config, mesh=mesh)
