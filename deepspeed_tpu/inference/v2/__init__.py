from .ragged import (BlockedAllocator, BlockedKVCache, RaggedBatch, SequenceDescriptor,  # noqa: F401
                     StateManager)
from .scheduler import SchedulerConfig, SplitFuseScheduler, StepPlan  # noqa: F401
