from .ragged import (BlockedAllocator, BlockedKVCache, RaggedBatch, SequenceDescriptor,  # noqa: F401
                     StateManager)
from .scheduler import SchedulerConfig, SplitFuseScheduler, StepPlan  # noqa: F401
from .spec import (DRAFTERS, DraftProvider, NGramDrafter, SpecConfig,  # noqa: F401
                   SpecStats, make_drafter)
from .engine_v2 import (InferenceEngineV2, RaggedInferenceEngineConfig,  # noqa: F401
                        build_engine, compile_aot_serving)
