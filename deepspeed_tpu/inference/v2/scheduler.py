"""Dynamic SplitFuse continuous-batching scheduler.

Reference: FastGen's scheduling policy (``deepspeed/inference/v2/engine_v2.py
put()`` + the SplitFuse description in ``blogs/deepspeed-fastgen``): each
engine step runs a *fixed token budget*, filled by (a) every running decode
sequence (1 token each) and (b) chunks of pending prefills — long prompts
are split across steps, short ones fused, keeping step latency flat.

Here the budget additionally quantises to a few chunk-size buckets so XLA
reuses a handful of compiled programs (TPU static shapes) instead of
recompiling per ragged shape — the scheduling *policy* is the reference's,
the *shapes* are TPU-friendly.
"""

import dataclasses
from typing import List, Tuple

from .ragged import SequenceDescriptor, StateManager


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    token_budget: int = 512            # ref: max ragged batch token count
    max_seqs: int = 64                 # ref: max ragged sequence count
    prefill_chunk: int = 128           # SplitFuse chunk quantum
    decode_bucket: int = 8             # decode batch rounds up to a multiple


@dataclasses.dataclass
class StepPlan:
    """One engine step = one decode batch + up to one prefill chunk batch."""
    decode: List[SequenceDescriptor]
    prefill: List[Tuple[SequenceDescriptor, int]]   # (seq, n_tokens)


class SplitFuseScheduler:

    def __init__(self, config: SchedulerConfig):
        self.config = config

    def plan(self, manager: StateManager) -> StepPlan:
        cfg = self.config
        running = [s for s in manager.seqs.values() if not s.done]
        decodes = [s for s in running if s.in_decode]
        prefills = [s for s in running if s.in_prefill and not s.in_decode]

        decodes = decodes[:cfg.max_seqs]
        budget = cfg.token_budget - len(decodes)

        plan_prefill: List[Tuple[SequenceDescriptor, int]] = []
        for seq in prefills:
            if budget <= 0 or len(plan_prefill) + len(decodes) >= cfg.max_seqs:
                break
            n = min(seq.remaining_prefill, cfg.prefill_chunk, budget)
            if n <= 0:
                break
            plan_prefill.append((seq, n))
            budget -= n
        return StepPlan(decode=decodes, prefill=plan_prefill)
