"""Dynamic SplitFuse continuous-batching scheduler.

Reference: FastGen's scheduling policy (``deepspeed/inference/v2/engine_v2.py
put()`` + the SplitFuse description in ``blogs/deepspeed-fastgen``): each
engine step runs a *fixed token budget*, filled by (a) every running decode
sequence (1 token each) and (b) chunks of pending prefills — long prompts
are split across steps, short ones fused, keeping step latency flat.

Here the budget additionally quantises to a few chunk-size buckets so XLA
reuses a handful of compiled programs (TPU static shapes) instead of
recompiling per ragged shape — the scheduling *policy* is the reference's,
the *shapes* are TPU-friendly.
"""

import dataclasses
from typing import List, Tuple

from .ragged import SequenceDescriptor, StateManager


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    token_budget: int = 512            # ref: max ragged batch token count
    max_seqs: int = 64                 # ref: max ragged sequence count
    prefill_chunk: int = 128           # SplitFuse chunk quantum
    decode_bucket: int = 8             # decode batch rounds up to a multiple
    # speculative decoding (engine_v2 sets this from SpecConfig.max_draft):
    # the verify-slot width a speculating decode row may grow to.  Verify
    # rounds run ONLY on pure-decode steps (no prefill planned), so plan()
    # keeps charging mixed steps 1 token per bucketed decode row — charging
    # 1+k there would throttle prefill for verify work that cannot happen.
    # The budget is enforced where verify slots are actually planned:
    # engine_v2._plan_drafts caps each row's draft at this width and
    # shrinks the round until its total fed tokens (1 + draft per row) fit
    # token_budget.
    spec_verify_tokens: int = 0


@dataclasses.dataclass
class StepPlan:
    """One engine step = one decode batch + up to one prefill chunk batch."""
    decode: List[SequenceDescriptor]
    prefill: List[Tuple[SequenceDescriptor, int]]   # (seq, n_tokens)

    @property
    def planned_tokens(self) -> int:
        """Real tokens this step will feed: one per decode row plus the
        prefill chunk tokens — the serving ``step_cost`` model's input
        and the step-anatomy row's token-volume attribution (one
        definition, two consumers, no drift)."""
        return len(self.decode) + sum(n for _, n in self.prefill)


class SplitFuseScheduler:

    def __init__(self, config: SchedulerConfig):
        self.config = config
        # optional ordering hook (the serving frontend installs FCFS-with-
        # aging here): ``order_key(seq) -> sortable``, lowest served first.
        # None keeps dict-insertion (put) order — the historical behaviour
        # for direct engine users.
        self.order_key = None

    def plan(self, manager: StateManager) -> StepPlan:
        cfg = self.config
        # paused sequences (mid-KV-migration — serving/kvtransfer) keep
        # their state and pages but take no step work: their pages must stay
        # byte-stable while export chunks overlap the other sequences' steps
        running = [s for s in manager.seqs.values() if not s.done and not s.paused]
        if self.order_key is not None:
            running.sort(key=self.order_key)
        decodes = [s for s in running if s.in_decode]
        prefills = [s for s in running if s.in_prefill and not s.in_decode]

        decodes = decodes[:cfg.max_seqs]
        # TOKEN BUDGET charges the BUCKETED decode count: the compiled step
        # pads the batch to a decode_bucket multiple, and the padded rows
        # flow through the whole program whether or not they carry tokens.
        # The SEQUENCE-SLOT bound below keeps the RAW count — the engine
        # buckets the COMBINED decode+prefill work (_bucket_batch), so a
        # prefill can ride in a padding slot; charging bucketed decode there
        # would starve prefill whenever decode_bucket approaches max_seqs
        n_bucketed = min(cfg.max_seqs,
                         -(-len(decodes) // cfg.decode_bucket) * cfg.decode_bucket) \
            if decodes else 0
        budget = cfg.token_budget - n_bucketed

        plan_prefill: List[Tuple[SequenceDescriptor, int]] = []
        for seq in prefills:
            if budget <= 0 or len(plan_prefill) + len(decodes) >= cfg.max_seqs:
                break
            n = min(seq.remaining_prefill, cfg.prefill_chunk, budget)
            if n <= 0:
                # defensive: unreachable under the current filters (prefills
                # all have remaining_prefill >= 1, budget > 0 checked above)
                # — but a zero-work seq must SKIP, not break: breaking would
                # starve every sequence queued behind it
                continue
            plan_prefill.append((seq, n))
            budget -= n
        return StepPlan(decode=decodes, prefill=plan_prefill)
