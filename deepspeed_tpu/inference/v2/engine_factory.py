"""Build a FastGen-v2 engine from a HuggingFace checkpoint directory.

ref: deepspeed/inference/v2/engine_factory.py:69 build_hf_engine — reads the
HF config, picks the per-arch policy, maps the checkpoint into the engine's
parameter containers, returns an InferenceEngineV2.

Loading uses transformers' local machinery only (no hub download): the
checkpoint directory must contain config.json + weights
(model.safetensors / pytorch_model.bin shards).
"""

import os
from typing import Optional

from ...utils.logging import logger
from .engine_v2 import InferenceEngineV2, RaggedInferenceEngineConfig
from .model_implementations import convert_hf_state_dict


def _load_state_dict(path: str):
    """Collect the full torch state dict from a local HF checkpoint dir."""
    import glob
    import torch

    sts = sorted(glob.glob(os.path.join(path, "*.safetensors")))
    if sts:
        from safetensors.torch import load_file
        sd = {}
        for f in sts:
            sd.update(load_file(f))
        return sd
    bins = sorted(glob.glob(os.path.join(path, "pytorch_model*.bin"))) or \
        sorted(glob.glob(os.path.join(path, "*.bin")))
    if bins:
        sd = {}
        for f in bins:
            sd.update(torch.load(f, map_location="cpu", weights_only=True))
        return sd
    raise FileNotFoundError(f"no weight files (*.safetensors / pytorch_model*.bin) under {path}")


def build_hf_engine(path: str,
                    engine_config: Optional[RaggedInferenceEngineConfig] = None,
                    debug_level: int = 0,
                    quantization_mode: Optional[str] = None) -> InferenceEngineV2:
    """ref: engine_factory.py:69.  ``quantization_mode``: None | 'wf6af16'
    -style strings accepted; any non-None value enables int8 weight-only
    quantization of the loaded checkpoint (inference/quantization)."""
    from transformers import AutoConfig

    hf_cfg = AutoConfig.from_pretrained(path, local_files_only=True)
    sd = _load_state_dict(path)
    cfg, params = convert_hf_state_dict(sd, hf_cfg)
    logger.info(f"build_hf_engine: model_type={hf_cfg.model_type} "
                f"{sum(p.size for p in _leaves(params))/1e6:.1f}M params")

    # v1-era archs (bloom / gpt-neox / gptj / gpt-neo) have conversion
    # policies but no paged cache twin — the reference serves them through
    # v1 kernel injection (module_inject/containers); here they route to the
    # v1 jitted-forward engine behind a generate()-compatible surface
    from ...models.llama import LlamaConfig
    from ...models.cache_zoo import CACHE_MODEL_REGISTRY
    from ...models.mixtral import MixtralConfig
    twin_cfgs = (LlamaConfig, MixtralConfig, *CACHE_MODEL_REGISTRY.keys())
    if not isinstance(cfg, twin_cfgs):
        import deepspeed_tpu as ds
        from .model_implementations.policies import policy_for
        if quantization_mode is not None:
            raise NotImplementedError(
                f"quantization_mode={quantization_mode!r} requires the paged v2 engine; "
                f"{hf_cfg.model_type} has no paged cache twin and serves via the v1 path")
        if engine_config is not None:
            logger.warning(f"build_hf_engine: engine_config is ignored for {hf_cfg.model_type} "
                           "(v1 fallback path — no ragged scheduler/KV arena)")
        model = policy_for(hf_cfg.model_type).build_model(cfg)
        logger.info(f"build_hf_engine: {hf_cfg.model_type} has no paged twin — "
                    "serving through the v1 engine (ref: v1 kernel-injection containers)")
        return ds.init_inference(model=model, config={"dtype": "fp32"},
                                 params={"params": params})

    if quantization_mode is not None:
        from ..quantization import quantize_inference_params
        return InferenceEngineV2(cfg, quantize_inference_params(params), engine_config=engine_config)

    return InferenceEngineV2(cfg, {"params": params}, engine_config=engine_config)


def _leaves(tree):
    import jax
    return jax.tree.leaves(tree)
