"""InferenceEngine (v1) — ``deepspeed.init_inference`` parity.

Reference: ``deepspeed/inference/engine.py:40 InferenceEngine`` — wraps an
HF torch model with optional kernel injection (policy containers), AutoTP
sharding, quantization and CUDA-graph capture; ``forward:554`` and a
``generate`` wrapper (``:583``).

TPU-native realisation: the model is a flax module; "kernel injection" is
selecting the Pallas attention path (``attention_impl='flash'``), AutoTP is
the logical-axis→mesh sharding rules (``module_inject/tp_rules.py`` — the
``AutoTP.tp_parser`` analog), and CUDA-graph capture is jit compilation
(every forward IS a captured graph).  ``generate`` runs greedy/sampled
decoding; for Llama-family configs it upgrades to the paged-KV continuous-
batching engine (inference/v2) under the same API.
"""

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..comm.mesh import MeshSpec, create_mesh, get_global_mesh, has_global_mesh, set_global_mesh
from ..module_inject.tp_rules import param_shardings
from ..utils.logging import log_dist, logger


@dataclasses.dataclass
class DeepSpeedInferenceConfig:
    """Subset of ref ``inference/config.py DeepSpeedInferenceConfig`` that is
    meaningful on TPU (no cuda-graph / kernel-inject build knobs)."""
    dtype: Any = jnp.bfloat16
    tensor_parallel: int = 1          # ref: tp_size
    replace_with_kernel_inject: bool = False   # → Pallas attention path
    max_out_tokens: int = 256
    min_out_tokens: int = 1
    eos_token_id: Optional[int] = None

    @staticmethod
    def from_dict(d: Dict) -> "DeepSpeedInferenceConfig":
        d = dict(d or {})
        tp = d.pop("tensor_parallel", d.pop("mp_size", 1))
        if isinstance(tp, dict):
            tp = tp.get("tp_size", 1)
        dtype = d.pop("dtype", jnp.bfloat16)
        if isinstance(dtype, str):
            dtype = {"fp16": jnp.float16, "half": jnp.float16, "bf16": jnp.bfloat16,
                     "bfloat16": jnp.bfloat16, "fp32": jnp.float32, "float32": jnp.float32}[dtype]
        known = {f.name for f in dataclasses.fields(DeepSpeedInferenceConfig)}
        return DeepSpeedInferenceConfig(dtype=dtype, tensor_parallel=int(tp),
                                        **{k: v for k, v in d.items() if k in known})


class InferenceEngine:
    """ref: inference/engine.py:40.  ``model`` is a flax module (or a
    (module, params) pair via ``params=``); ``config`` a dict/dataclass."""

    def __init__(self, model=None, config=None, params=None, mesh=None, rng=None, **kwargs):
        assert model is not None, "init_inference: model is required"
        self.config = config if isinstance(config, DeepSpeedInferenceConfig) \
            else DeepSpeedInferenceConfig.from_dict(config)
        self.module = self._maybe_inject_kernels(model)
        tp = self.config.tensor_parallel
        if mesh is None:
            if has_global_mesh():
                mesh = get_global_mesh()
            else:
                mesh = create_mesh(MeshSpec(data=-1, tensor=tp))
                set_global_mesh(mesh)
        self.mesh = mesh
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.params = params
        self._params_cast = False
        self._fwd = None
        self._gen_step: Dict = {}
        log_dist(f"InferenceEngine: tp={tp} dtype={jnp.dtype(self.config.dtype).name} "
                 f"kernel_inject={self.config.replace_with_kernel_inject}", ranks=[0])

    # ------------------------------------------------------------ params

    def _maybe_inject_kernels(self, model):
        """"Kernel injection" = switch the model's attention impl to the
        Pallas path (ref: module_inject/replace_module.py:183
        replace_transformer_layer — there, policy containers swap fused CUDA
        kernels in; here the config field selects the fused kernel)."""
        if not self.config.replace_with_kernel_inject:
            return model
        cfg = getattr(model, "cfg", None)
        if cfg is not None and dataclasses.is_dataclass(cfg) and hasattr(cfg, "attention_impl"):
            new_cfg = dataclasses.replace(cfg, attention_impl="flash")
            kw = {f.name: getattr(model, f.name) for f in dataclasses.fields(model)
                  if f.name not in ("cfg", "parent", "name")}
            return type(model)(new_cfg, **kw)
        logger.warning("replace_with_kernel_inject: model has no attention_impl config; "
                       "running the module unchanged")
        return model

    def _cast_params(self, tree):
        dt = self.config.dtype

        def cast(x):
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(dt)
            return x

        return jax.tree.map(cast, tree)

    def _ensure_params(self, *example_inputs):
        if self.params is not None:
            if not self._params_cast:
                self.params = self._cast_params(self.params)
                self._params_cast = True
            return
        self._rng, sub = jax.random.split(self._rng)
        abs_vars = jax.eval_shape(lambda: self.module.init(sub, *example_inputs))
        shardings = param_shardings(abs_vars, self.mesh, zero_stage=0)

        def init_fn():
            return self._cast_params(self.module.init(sub, *example_inputs))

        with self.mesh:
            self.params = jax.jit(init_fn, out_shardings=shardings)()
        self._params_cast = True

    # ----------------------------------------------------------- forward

    def forward(self, *args, **kwargs):
        """Jitted module forward (ref: engine.py:554 — the cuda-graph-capture
        branch is simply jit here).  Non-array kwargs (flags like
        ``deterministic``) are static — closed over per cache entry — so
        module control flow sees real Python values, not tracers."""
        self._ensure_params(*args)
        static = {k: v for k, v in kwargs.items() if not hasattr(v, "shape")
                  and not isinstance(v, (np.ndarray, jnp.ndarray))}
        traced = {k: v for k, v in kwargs.items() if k not in static}
        key = tuple(sorted(static.items()))
        if not isinstance(self._fwd, dict) or self._fwd.get("key") != key:
            self._fwd = {"key": key,
                         "fn": jax.jit(lambda p, a, kw: self.module.apply(p, *a, **kw, **static))}
        from ..comm.mesh import trace_mesh
        with self.mesh, trace_mesh(self.mesh):
            return self._fwd["fn"](self.params, args, traced)

    __call__ = forward

    # ---------------------------------------------------------- generate

    def generate(self, input_ids, max_new_tokens: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0, **kwargs):
        """Greedy/sampled decoding (ref: engine.py:583 _generate wrapper).

        ``input_ids``: [B, S] int array.  Recomputes the full prefix each
        step (KV-cache-free fallback); Llama-family serving should use
        ``inference.v2`` for the paged-KV path.
        """
        max_new = max_new_tokens or self.config.max_out_tokens
        ids = jnp.asarray(input_ids)
        self._ensure_params(ids)
        b, s0 = ids.shape
        # fixed [B, S0+max_new] buffer: ONE compiled program for the whole
        # decode (causal attention never sees the zero-padding ahead of cur)
        buf = jnp.zeros((b, s0 + max_new), ids.dtype).at[:, :s0].set(ids)

        def step(params, buf, cur, rng):
            out = self.module.apply(params, buf)
            logits = out[0] if isinstance(out, tuple) else out
            last = jnp.take_along_axis(
                logits, jnp.full((b, 1, 1), cur - 1), axis=1)[:, 0]  # [B, V]
            if do_sample:
                nxt = jax.random.categorical(rng, last / temperature, axis=-1)
            else:
                nxt = jnp.argmax(last, axis=-1)
            nxt = nxt.astype(buf.dtype)
            buf = jax.lax.dynamic_update_slice_in_dim(buf, nxt[:, None], cur, axis=1)
            return buf, nxt

        key = (buf.shape, do_sample, float(temperature))
        if self._gen_step.get("key") != key:
            self._gen_step = {"key": key, "fn": jax.jit(step, donate_argnums=(1, ))}
        jstep = self._gen_step["fn"]
        eos = self.config.eos_token_id
        done = np.zeros(b, bool)
        n_done_at = np.full(b, s0 + max_new, np.int64)
        from ..comm.mesh import trace_mesh
        with self.mesh, trace_mesh(self.mesh):
            for t in range(max_new):
                self._rng, sub = jax.random.split(self._rng)
                buf, nxt = jstep(self.params, buf, jnp.int32(s0 + t), sub)
                if eos is not None and t + 1 >= self.config.min_out_tokens:
                    done |= np.asarray(nxt) == eos
                    n_done_at = np.minimum(n_done_at, np.where(done, s0 + t + 1, s0 + max_new))
                    if done.all():
                        break
        out = np.asarray(buf)
        if eos is not None:
            # blank everything after each row's eos (ragged stop)
            cols = np.arange(out.shape[1])[None, :]
            out = np.where(cols < n_done_at[:, None], out, eos)
        return out[:, :int(n_done_at.max())]
