from .engine import DeepSpeedInferenceConfig, InferenceEngine  # noqa: F401
