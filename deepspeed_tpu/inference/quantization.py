"""Weight-only quantization for inference.

ref: deepspeed/inference/quantization/ (quantize-on-load of HF checkpoints,
intX weight-only with on-the-fly dequant in the CUDA kernels) and
csrc/transformer/inference dequantize kernels.

TPU-native: selected weight leaves are stored as {q: int8, scale: f32}
group-quantized payloads inside the param tree; ``dequantize_params`` runs
INSIDE the jitted step, so XLA holds int8 in HBM and fuses the dequant into
each consuming matmul — halving (bf16) or quartering (fp32) weight HBM
footprint, which is what the reference's kernels achieve.
"""

from typing import Any, Dict, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _quantize_leaf(x: np.ndarray, group: int) -> Dict[str, Any]:
    flat = np.asarray(x, np.float32).reshape(-1)
    pad = (-flat.size) % group
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    g = flat.reshape(-1, group)
    amax = np.abs(g).max(axis=1, keepdims=True) + 1e-12
    scale = (amax / 127.0).astype(np.float32)
    q = np.clip(np.round(g / scale), -128, 127).astype(np.int8)
    return {"q": q, "scale": scale}


def _dequantize_leaf(node, shape, dtype):
    flat = (node["q"].astype(jnp.float32) * node["scale"]).reshape(-1)
    n = int(np.prod(shape))
    return flat[:n].reshape(shape).astype(dtype)


class QuantizedParams:
    """Quantized param tree + metadata to rebuild compute-dtype params.

    ``tree`` is a valid jax pytree (int8/f32 leaves) that can be passed
    through jit; ``dequantize(tree)`` is traced inside the step program.
    """

    def __init__(self, tree, shapes: Dict[Tuple[str, ...], tuple], dtype=jnp.bfloat16, group: int = 128):
        self.tree = tree
        self.shapes = shapes
        self.dtype = dtype
        self.group = group

    def dequantize(self, tree=None):
        tree = self.tree if tree is None else tree

        def walk(node, path=()):
            if isinstance(node, dict):
                if path in self.shapes:
                    return _dequantize_leaf(node, self.shapes[path], self.dtype)
                return {k: walk(v, path + (k, )) for k, v in node.items()}
            return node

        return walk(tree)

    @property
    def nbytes(self):
        return sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(self.tree))


def quantize_inference_params(variables, bits: int = 8, group: int = 128,
                              min_size: int = 4096, dtype=jnp.bfloat16) -> QuantizedParams:
    """Quantize every float leaf with ≥min_size elements (weights), leaving
    small tensors (norms, biases) intact (ref: inference/quantization
    quantize_model selective matmul-weight coverage)."""
    assert bits == 8, "weight-only int8 supported (int4 via ops.quantizer for ZeRO++ comm)"
    tree = variables["params"] if isinstance(variables, dict) and "params" in variables else variables
    shapes: Dict[Tuple[str, ...], tuple] = {}

    def walk(node, path=()):
        if isinstance(node, dict):
            return {k: walk(v, path + (k, )) for k, v in node.items()}
        arr = np.asarray(node)
        if arr.dtype.kind == "f" and arr.size >= min_size:
            shapes[path] = arr.shape
            return _quantize_leaf(arr, group)
        return node

    qtree = walk(tree)
    return QuantizedParams(qtree, shapes, dtype=dtype, group=group)
