from .profiler import FlopsProfiler, get_model_profile, xla_cost_analysis, number_to_string, flops_to_string, params_to_string
