"""FLOPs profiler — XLA-native cost accounting.

TPU-native analog of ``deepspeed/profiling/flops_profiler/profiler.py:30
FlopsProfiler`` (~1,300 LoC).  The reference monkey-patches ~50 torch
functional ops to count MACs as eager calls happen; under XLA the compiler
already knows the exact op-level cost of the compiled program, so:

* whole-program numbers come from ``Compiled.cost_analysis()`` (flops,
  bytes accessed, peak memory) on the jitted step — exact, fusion-aware,
  zero overhead;
* the per-module table comes from ``flax.linen.tabulate(compute_flops=
  True, compute_vjp_flops=True)`` which costs each submodule's forward
  and backward separately;
* wall-clock per step comes from the engine timers.

Same public surface: ``start_profile / stop_profile / reset_profile /
end_profile / get_total_flops / get_total_macs / get_total_duration /
get_total_params / print_model_profile`` and the standalone
``get_model_profile(model, input_shape)``.
"""

import time
from typing import Any, Optional

import jax
import numpy as np


# ------------------------------------------------------------------ helpers


def number_to_string(num, units=None, precision=2):
    if units is None:
        if num >= 1e12:
            return f"{num / 1e12:.{precision}f} T"
        if num >= 1e9:
            return f"{num / 1e9:.{precision}f} G"
        if num >= 1e6:
            return f"{num / 1e6:.{precision}f} M"
        if num >= 1e3:
            return f"{num / 1e3:.{precision}f} K"
        return f"{num:.{precision}f} "
    scale = {"T": 1e12, "G": 1e9, "M": 1e6, "K": 1e3, "": 1.0}[units]
    return f"{num / scale:.{precision}f} {units}"


def flops_to_string(flops, units=None, precision=2):
    return number_to_string(flops, units=units, precision=precision) + "FLOPS"


def macs_to_string(macs, units=None, precision=2):
    return number_to_string(macs, units=units, precision=precision) + "MACs"


def params_to_string(params_num, units=None, precision=2):
    return number_to_string(params_num, units=units, precision=precision).strip()


def duration_to_string(duration, units=None, precision=2):
    if duration > 1:
        return f"{duration:.{precision}f} s"
    if duration > 1e-3:
        return f"{duration * 1e3:.{precision}f} ms"
    return f"{duration * 1e6:.{precision}f} us"


def xla_cost_analysis(fn, *args, **kwargs):
    """Compile ``fn`` and return XLA's cost analysis dict:
    ``{'flops': .., 'bytes accessed': .., ...}`` (exact, post-fusion)."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    return dict(ca or {})


# ------------------------------------------------------------------ profiler


class FlopsProfiler:
    """ref: flops_profiler/profiler.py:30.

    ``model`` is a flax module; ``ds_engine`` the DeepSpeedEngine (optional).
    When attached to an engine, profiles the engine's compiled train step;
    standalone, profiles ``model.apply`` on the example batch passed to
    ``start_profile``.
    """

    def __init__(self, model=None, ds_engine=None, recompute_fwd_factor=0.0):
        self.model = model
        self.ds_engine = ds_engine
        self.recompute_fwd_factor = recompute_fwd_factor
        self.started = False
        self.metrics_registry = None
        self.reset_profile()

    def attach_metrics(self, registry) -> "FlopsProfiler":
        """Publish each profile's numbers into a telemetry
        ``MetricsRegistry`` (docs/OBSERVABILITY.md): gauges
        ``profiler/flops_per_step``, ``profiler/macs_per_step``,
        ``profiler/params``, ``profiler/bytes_per_step`` and
        ``profiler/step_duration_s`` are set every time ``stop_profile``
        collects — the bridge from the one-shot profile printout to the
        always-on metrics surface."""
        self.metrics_registry = registry
        return self

    # -- lifecycle (ref: profiler.py:74 start_profile / :134 stop / :203 end)

    def start_profile(self, ignore_list=None, example_batch=None):
        self.reset_profile()
        self.started = True
        self._t0 = time.perf_counter()  # dslint-ok(determinism): flops profiler measures the real step wall duration it reports
        self._example_batch = example_batch

    def stop_profile(self):
        if not self.started:
            return
        self._duration = time.perf_counter() - self._t0  # dslint-ok(determinism): flops profiler measures the real step wall duration it reports
        self._collect()

    def reset_profile(self):
        self._duration = 0.0
        self._flops = 0
        self._macs = 0
        self._params = 0
        self._bytes = 0
        self._table = None
        self._example_batch = None

    def end_profile(self):
        self.started = False

    # -- collection

    def _engine_cost(self):
        eng = self.ds_engine
        if eng is None or eng._train_step_fn is None or eng.state is None:
            return None
        fn = eng._train_step_fn
        try:
            # lower() alone re-traces but skips the expensive XLA compile —
            # the executable for this (state, batch) signature is already in
            # jit's cache from the step that just ran
            ca = fn.lower(eng.state, self._example_batch).cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return dict(ca or {})
        except Exception:
            return None

    def _collect(self):
        ca = None
        if self.ds_engine is not None and self._example_batch is not None:
            ca = self._engine_cost()
        if ca is None and self.model is not None and self._example_batch is not None:
            model = self.model
            # init OUTSIDE the analyzed fn: parameter init flops must not
            # count toward the forward-pass cost
            variables = model.init(jax.random.PRNGKey(0), self._example_batch)

            def apply_fn(batch):
                return model.apply(variables, batch)

            try:
                ca = xla_cost_analysis(apply_fn, self._example_batch)
            except Exception:
                ca = {}
        ca = ca or {}
        self._flops = int(ca.get("flops", 0))
        self._macs = self._flops // 2  # 1 MAC = 2 flops on the MXU
        self._bytes = int(ca.get("bytes accessed", 0))
        if self.ds_engine is not None and self.ds_engine.state is not None:
            self._params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(self.ds_engine.state.params))
        if self.metrics_registry is not None:
            reg = self.metrics_registry
            reg.gauge("profiler/flops_per_step").set(self._flops)
            reg.gauge("profiler/macs_per_step").set(self._macs)
            reg.gauge("profiler/params").set(self._params)
            reg.gauge("profiler/bytes_per_step").set(self._bytes)
            reg.gauge("profiler/step_duration_s").set(self._duration)

    # -- getters (ref: profiler.py:232-279)

    def get_total_flops(self, as_string=False):
        return flops_to_string(self._flops) if as_string else self._flops

    def get_total_macs(self, as_string=False):
        return macs_to_string(self._macs) if as_string else self._macs

    def get_total_duration(self, as_string=False):
        return duration_to_string(self._duration) if as_string else self._duration

    def get_total_params(self, as_string=False):
        return params_to_string(self._params) if as_string else self._params

    def get_total_bytes(self, as_string=False):
        return number_to_string(self._bytes) + "B" if as_string else self._bytes

    # -- printing (ref: profiler.py:286 print_model_profile)

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1, detailed=True, output_file=None):
        import sys
        out = open(output_file, "w") if output_file else sys.stdout
        dur = self._duration or 1e-9
        print("\n-------------------------- DeepSpeed-TPU Flops Profiler --------------------------", file=out)
        print(f"Profile Summary at step {profile_step}:", file=out)
        print("Notations:\n"
              "data parallel size (dp_size), model parallel size(mp_size),\n"
              "number of parameters (params), number of multiply-accumulate operations(MACs),\n"
              "number of floating-point operations (flops), floating-point operations per second (FLOPS)",
              file=out)
        if self.ds_engine is not None:
            print(f"dp/world size:                                          {jax.device_count()}", file=out)
        print(f"params:                                                 {self.get_total_params(True)}", file=out)
        print(f"fwd+bwd MACs per step:                                  {self.get_total_macs(True)}", file=out)
        print(f"fwd+bwd flops per step:                                 {self.get_total_flops(True)}", file=out)
        print(f"HBM bytes accessed per step:                            {self.get_total_bytes(True)}", file=out)
        print(f"step latency:                                           {self.get_total_duration(True)}", file=out)
        print(f"achieved FLOPS:                                         {flops_to_string(self._flops / dur)}", file=out)
        if detailed and self._table:
            print(self._table, file=out)
        print("-----------------------------------------------------------------------------------", file=out)
        if output_file:
            out.close()

    def print_model_aggregated_profile(self, module_depth=-1, top_modules=1):
        self.print_model_profile(module_depth=module_depth, top_modules=top_modules, detailed=False)


# -------------------------------------------------------- standalone profile


def get_model_profile(model,
                      input_shape=None,
                      args=(),
                      kwargs=None,
                      print_profile=True,
                      detailed=True,
                      module_depth=-1,
                      top_modules=1,
                      warm_up=1,
                      as_string=True,
                      output_file=None,
                      ignore_modules=None,
                      mode='forward',
                      rngs=None):
    """Profile a flax model (ref: profiler.py get_model_profile): returns
    (flops, macs, params).  Per-module breakdown via ``nn.tabulate`` with
    flops costing; whole-program totals from XLA cost analysis.
    """
    import jax.numpy as jnp
    from flax import linen as nn

    kwargs = kwargs or {}
    if input_shape is not None:
        assert isinstance(input_shape, (tuple, list)), "input_shape must be a tuple/list"
        args = (jnp.ones(input_shape, jnp.int32), )

    rng = rngs if rngs is not None else jax.random.PRNGKey(0)

    # totals: compile fwd (and optionally bwd) and read XLA's numbers
    variables = jax.eval_shape(lambda: model.init(rng, *args, **kwargs))
    params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(variables))

    def fwd(v, *a):
        return model.apply(v, *a, **kwargs)

    concrete_vars = model.init(rng, *args, **kwargs)
    ca = xla_cost_analysis(fwd, concrete_vars, *args)
    flops = int(ca.get("flops", 0))

    if mode == 'generate' or mode == 'forward':
        pass
    elif mode == 'train':
        def train_fwd_bwd(v, *a):
            def loss(vv):
                out = model.apply(vv, *a, **kwargs)
                leaf = out[0] if isinstance(out, (tuple, list)) else out
                return jnp.sum(leaf.astype(jnp.float32))
            return jax.grad(loss)(v)
        ca = xla_cost_analysis(train_fwd_bwd, concrete_vars, *args)
        flops = int(ca.get("flops", 0))
    macs = flops // 2

    table = None
    if detailed:
        try:
            tab_fn = nn.tabulate(model, rng, compute_flops=True, compute_vjp_flops=(mode == 'train'),
                                 depth=None if module_depth < 0 else module_depth)
            table = tab_fn(*args, **kwargs)
        except Exception:
            table = None

    if print_profile:
        import sys
        out = open(output_file, "w") if output_file else sys.stdout
        print(f"params: {params_to_string(params)}  flops: {flops_to_string(flops)}  "
              f"macs: {macs_to_string(macs)}", file=out)
        if table:
            print(table, file=out)
        if output_file:
            out.close()

    if as_string:
        return flops_to_string(flops), macs_to_string(macs), params_to_string(params)
    return flops, macs, params
