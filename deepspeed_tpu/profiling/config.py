"""Flops-profiler config (ref: deepspeed/profiling/config.py) — the model
lives with the other feature blocks in runtime/config.py; re-exported here
for import-path parity."""

from ..runtime.config import FlopsProfilerConfig as DeepSpeedFlopsProfilerConfig  # noqa: F401
