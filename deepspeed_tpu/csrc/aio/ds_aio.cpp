// ds_aio — asynchronous file IO engine for tensor swapping.
//
// TPU-native equivalent of the reference's AsyncIO extension
// (ref: csrc/aio/common/deepspeed_aio_common.cpp + py_lib/
// deepspeed_py_aio_handle.cpp — libaio O_DIRECT read/write handles that
// back ZeRO-Infinity NVMe swapping).  On TPU-VM hosts the swap targets are
// local NVMe SSDs; this engine uses a pthread pool issuing positional
// pread/pwrite in block_size chunks (optionally O_DIRECT) — the same handle
// semantics (submit N requests, overlap with compute, wait for drain)
// without the libaio dependency.
//
// C ABI (consumed via ctypes from ops/aio):
//   aio_handle_new(block_size, queue_depth, n_threads, use_o_direct)
//   aio_pread(h, buf, path, offset, nbytes)   -> 0 on submit
//   aio_pwrite(h, buf, path, offset, nbytes)  -> 0 on submit
//   aio_wait(h)          -> number of requests completed since last wait,
//                           or negative errno of the first failed request
//   aio_pending(h)       -> requests not yet completed
//   aio_file_size(path)  -> size or -errno
//   aio_handle_free(h)
//
// A request writes/reads the WHOLE [offset, offset+nbytes) range in
// block_size chunks on one worker thread; distinct requests run on
// distinct threads (queue_depth bounds the submission queue).

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

struct Request {
    bool is_read;
    void* buf;
    std::string path;
    long long offset;
    long long nbytes;
};

struct Handle {
    long long block_size;
    size_t queue_depth;
    bool o_direct;

    std::vector<std::thread> workers;
    std::deque<Request> queue;
    std::mutex mu;
    std::condition_variable cv_submit;   // signalled when queue has room / shutdown
    std::condition_variable cv_worker;   // signalled when work arrives
    std::condition_variable cv_done;     // signalled when a request completes
    std::atomic<long long> in_flight{0};
    std::atomic<long long> completed{0};
    std::atomic<int> first_error{0};
    bool shutdown = false;

    explicit Handle(long long bs, size_t qd, int threads, bool direct)
        : block_size(bs), queue_depth(qd), o_direct(direct) {
        for (int i = 0; i < threads; ++i) workers.emplace_back([this] { run(); });
    }

    ~Handle() {
        {
            std::lock_guard<std::mutex> g(mu);
            shutdown = true;
        }
        cv_worker.notify_all();
        for (auto& t : workers) t.join();
    }

    int submit(Request r) {
        std::unique_lock<std::mutex> lk(mu);
        cv_submit.wait(lk, [this] { return queue.size() < queue_depth || shutdown; });
        if (shutdown) return -1;
        in_flight.fetch_add(1);
        queue.push_back(std::move(r));
        cv_worker.notify_one();
        return 0;
    }

    void run() {
        for (;;) {
            Request r;
            {
                std::unique_lock<std::mutex> lk(mu);
                cv_worker.wait(lk, [this] { return !queue.empty() || shutdown; });
                if (shutdown && queue.empty()) return;
                r = std::move(queue.front());
                queue.pop_front();
                cv_submit.notify_one();
            }
            int err = execute(r);
            if (err != 0) {
                int expected = 0;
                first_error.compare_exchange_strong(expected, err);
            }
            {
                // decrement + notify under the mutex: a waiter that checked
                // the predicate just before this decrement must not miss the
                // wakeup (classic lost-wakeup race)
                std::lock_guard<std::mutex> g(mu);
                in_flight.fetch_sub(1);
                completed.fetch_add(1);
                cv_done.notify_all();
            }
        }
    }

    int execute(const Request& r) {
        int flags = r.is_read ? O_RDONLY : (O_WRONLY | O_CREAT);
#ifdef O_DIRECT
        if (o_direct) flags |= O_DIRECT;
#endif
        int fd = ::open(r.path.c_str(), flags, 0644);
        if (fd < 0 && o_direct) {  // O_DIRECT unsupported (e.g. tmpfs): retry buffered
#ifdef O_DIRECT
            fd = ::open(r.path.c_str(), flags & ~O_DIRECT, 0644);
#endif
        }
        if (fd < 0) return -errno;
        long long done = 0;
        int err = 0;
        char* p = static_cast<char*>(r.buf);
        while (done < r.nbytes) {
            long long chunk = r.nbytes - done;
            if (chunk > block_size) chunk = block_size;
            ssize_t n = r.is_read ? ::pread(fd, p + done, chunk, r.offset + done)
                                  : ::pwrite(fd, p + done, chunk, r.offset + done);
            if (n < 0) { err = -errno; break; }
            if (n == 0) { err = -EIO; break; }  // unexpected EOF on read
            done += n;
        }
        ::close(fd);
        return err;
    }

    long long wait_all() {
        std::unique_lock<std::mutex> lk(mu);
        cv_done.wait(lk, [this] { return in_flight.load() == 0; });
        long long n = completed.exchange(0);
        int err = first_error.exchange(0);
        return err != 0 ? (long long)err : n;
    }
};

}  // namespace

extern "C" {

void* aio_handle_new(long long block_size, long long queue_depth, int n_threads, int use_o_direct) {
    if (block_size <= 0) block_size = 1 << 20;
    if (queue_depth <= 0) queue_depth = 32;
    if (n_threads <= 0) n_threads = 4;
    return new Handle(block_size, (size_t)queue_depth, n_threads, use_o_direct != 0);
}

void aio_handle_free(void* h) { delete static_cast<Handle*>(h); }

int aio_pread(void* h, void* buf, const char* path, long long offset, long long nbytes) {
    return static_cast<Handle*>(h)->submit(Request{true, buf, path, offset, nbytes});
}

int aio_pwrite(void* h, const void* buf, const char* path, long long offset, long long nbytes) {
    return static_cast<Handle*>(h)->submit(
        Request{false, const_cast<void*>(buf), path, offset, nbytes});
}

long long aio_wait(void* h) { return static_cast<Handle*>(h)->wait_all(); }

long long aio_pending(void* h) { return static_cast<Handle*>(h)->in_flight.load(); }

long long aio_file_size(const char* path) {
    struct stat st;
    if (::stat(path, &st) != 0) return -(long long)errno;
    return (long long)st.st_size;
}

}  // extern "C"
