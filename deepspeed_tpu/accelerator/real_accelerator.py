"""Accelerator auto-detection (ref: accelerator/real_accelerator.py:51
get_accelerator; DS_ACCELERATOR env override honored as DS_TPU_ACCELERATOR
or the reference's own DS_ACCELERATOR)."""

import os

ds_accelerator = None


def get_accelerator():
    global ds_accelerator
    if ds_accelerator is not None:
        return ds_accelerator

    override = os.environ.get("DS_ACCELERATOR") or os.environ.get("DS_TPU_ACCELERATOR")
    if override == "cpu":
        from .cpu_accelerator import CPU_Accelerator
        ds_accelerator = CPU_Accelerator()
        return ds_accelerator
    if override == "tpu":
        from .tpu_accelerator import TPU_Accelerator
        ds_accelerator = TPU_Accelerator()
        return ds_accelerator

    try:
        import jax
        platform = jax.devices()[0].platform
    except Exception:
        platform = "cpu"
    if platform in ("tpu", "axon"):
        from .tpu_accelerator import TPU_Accelerator
        ds_accelerator = TPU_Accelerator()
    else:
        from .cpu_accelerator import CPU_Accelerator
        ds_accelerator = CPU_Accelerator()
    return ds_accelerator


def set_accelerator(accel):
    global ds_accelerator
    ds_accelerator = accel
