"""CPU accelerator (host-device testing; ref: accelerator/cpu_accelerator.py)."""

import jax

from .abstract_accelerator import DeepSpeedAccelerator


class CPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "xla"

    def device_name(self, device_index=None):
        return "cpu"

    def device(self, device_index=None):
        return jax.devices("cpu")[device_index or 0]

    def device_count(self):
        return jax.device_count()

    def current_device(self):
        return 0

    def synchronize(self, device_index=None):
        jax.effects_barrier()

    def memory_allocated(self, device_index=None):
        return 0

    def max_memory_allocated(self, device_index=None):
        return 0

    def total_memory(self, device_index=None):
        try:
            import psutil
            return psutil.virtual_memory().total
        except Exception:
            return 0

    def available_memory(self, device_index=None):
        try:
            import psutil
            return psutil.virtual_memory().available
        except Exception:
            return 0

    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True

    def supported_dtypes(self):
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16]

    def is_available(self):
        return True

    def communication_backend_name(self):
        return self._communication_backend_name

    def create_op_builder(self, class_name):
        builder = self.get_op_builder(class_name)
        return builder() if builder is not None else None

    def get_op_builder(self, class_name):
        from ..ops.op_builder import get_builder
        return get_builder(class_name)
