"""Accelerator abstraction (ref: accelerator/abstract_accelerator.py:10
DeepSpeedAccelerator — ~80-method ABC).

The JAX execution model eliminates several method families by construction:
streams/events (XLA async dispatch + program order), graph capture (jit IS
capture), pinned memory (handled by the runtime's transfer manager).  Those
appear here as explicit no-ops so engine code written against the reference
surface keeps working; the meaningful surface (device/memory/dtype/RNG/
communication-backend probes and op-builder lookup) is real.
"""

import abc


class DeepSpeedAccelerator(abc.ABC):

    def __init__(self):
        self._name = None
        self._communication_backend_name = None

    # ---- device APIs
    @abc.abstractmethod
    def device_name(self, device_index=None):
        ...

    @abc.abstractmethod
    def device(self, device_index=None):
        ...

    @abc.abstractmethod
    def device_count(self):
        ...

    @abc.abstractmethod
    def current_device(self):
        ...

    def current_device_name(self):
        return self.device_name(self.current_device())

    def set_device(self, device_index):
        pass  # single-controller: placement is via shardings, not a current-device

    @abc.abstractmethod
    def synchronize(self, device_index=None):
        ...

    # ---- RNG (threaded PRNG keys; these exist for API parity)
    def random(self):
        import jax
        return jax.random

    def manual_seed(self, seed):
        self._seed = seed

    def manual_seed_all(self, seed):
        self._seed = seed

    def initial_seed(self):
        return getattr(self, "_seed", 0)

    def default_generator(self, device_index):
        import jax
        return jax.random.PRNGKey(getattr(self, "_seed", 0))

    # ---- streams/events: no-ops (XLA program order replaces stream discipline)
    class _NoOpStream:

        def __init__(self, *a, **k):
            ...

        def synchronize(self):
            import jax
            jax.effects_barrier()

        def wait_stream(self, other):
            ...

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    def Stream(self, *args, **kwargs):
        return self._NoOpStream()

    def stream(self, stream):
        return stream if hasattr(stream, "__enter__") else self._NoOpStream()

    def current_stream(self, device_index=None):
        return self._NoOpStream()

    def default_stream(self, device_index=None):
        return self._NoOpStream()

    class _NoOpEvent:

        def __init__(self, *a, **k):
            ...

        def record(self, stream=None):
            ...

        def synchronize(self):
            import jax
            jax.effects_barrier()

        def elapsed_time(self, other):
            return 0.0

        def query(self):
            return True

    def Event(self, *args, **kwargs):
        return self._NoOpEvent()

    # ---- memory
    @abc.abstractmethod
    def memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def max_memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def total_memory(self, device_index=None):
        ...

    @abc.abstractmethod
    def available_memory(self, device_index=None):
        ...

    def reset_peak_memory_stats(self, device_index=None):
        ...

    def empty_cache(self):
        ...

    def memory_stats(self, device_index=None):
        return {}

    # ---- dtype support
    @abc.abstractmethod
    def is_bf16_supported(self):
        ...

    @abc.abstractmethod
    def is_fp16_supported(self):
        ...

    @abc.abstractmethod
    def supported_dtypes(self):
        ...

    # ---- misc
    @abc.abstractmethod
    def is_available(self):
        ...

    @abc.abstractmethod
    def communication_backend_name(self):
        ...

    def is_triton_supported(self):
        return False

    def use_host_timers(self):
        return True

    def range_push(self, msg):
        """NVTX analog: jax profiler trace annotation."""
        try:
            import jax.profiler
            self._trace_ctx = jax.profiler.TraceAnnotation(msg)
            self._trace_ctx.__enter__()
        except Exception:
            self._trace_ctx = None

    def range_pop(self):
        ctx = getattr(self, "_trace_ctx", None)
        if ctx is not None:
            ctx.__exit__(None, None, None)
            self._trace_ctx = None

    # ---- graph capture: jit IS the graph; these gate the reference's CUDA-graph paths off
    def create_graph(self):
        return None

    def capture_to_graph(self, graph, pool=None, stream=None):
        import contextlib
        return contextlib.nullcontext()

    def replay_graph(self, graph):
        ...

    # ---- op builder surface
    @abc.abstractmethod
    def create_op_builder(self, class_name):
        ...

    @abc.abstractmethod
    def get_op_builder(self, class_name):
        ...

    def op_builder_dir(self):
        return "deepspeed_tpu.ops"

    # ---- tensor helpers
    def pin_memory(self, tensor, align_bytes=1):
        return tensor

    def is_pinned(self, tensor):
        return True

    def on_accelerator(self, tensor):
        try:
            import jax
            return isinstance(tensor, jax.Array)
        except Exception:
            return False
