"""TPU accelerator (the BASELINE.json north-star's ``tpu_accelerator``;
pattern ref: accelerator/cuda_accelerator.py)."""

import jax

from .abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "xla"

    # ---- device
    def device_name(self, device_index=None):
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device(self, device_index=None):
        return jax.devices("tpu")[device_index or 0]

    def device_count(self):
        return jax.device_count()

    def current_device(self):
        return 0

    def synchronize(self, device_index=None):
        jax.effects_barrier()

    # ---- memory
    def _stats(self, device_index=None):
        try:
            return jax.local_devices()[device_index or 0].memory_stats() or {}
        except Exception:
            return {}

    def memory_allocated(self, device_index=None):
        return self._stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index=None):
        return self._stats(device_index).get("peak_bytes_in_use", 0)

    def total_memory(self, device_index=None):
        return self._stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        s = self._stats(device_index)
        return s.get("bytes_limit", 0) - s.get("bytes_in_use", 0)

    def memory_stats(self, device_index=None):
        return self._stats(device_index)

    # ---- dtypes
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True  # emulated via fp32 accumulate; bf16 is the native type

    def supported_dtypes(self):
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    # ---- misc
    def is_available(self):
        try:
            return any(d.platform == "tpu" for d in jax.devices())
        except Exception:
            return False

    def communication_backend_name(self):
        return self._communication_backend_name

    def is_triton_supported(self):
        return False

    def device_kind(self):
        return jax.devices()[0].device_kind

    # ---- op builders: return Pallas/XLA-implemented op modules
    def create_op_builder(self, class_name):
        builder = self.get_op_builder(class_name)
        return builder() if builder is not None else None

    def get_op_builder(self, class_name):
        from ..ops.op_builder import get_builder
        return get_builder(class_name)
