"""Accelerator abstraction (ref: deepspeed/accelerator/).

``get_accelerator()`` auto-detects TPU vs CPU (env override DS_ACCELERATOR,
ref: real_accelerator.py:51).
"""

from .abstract_accelerator import DeepSpeedAccelerator
from .real_accelerator import get_accelerator, set_accelerator
