"""Progressive Layer Dropping (PLD).

ref: runtime/progressive_layer_drop.py (theta schedule
theta(t) = (1-p)·exp(-gamma·t) + p) + engine hook (config key
``progressive_layer_drop``; the reference's models read pld_theta from
``get_state()`` and stochastically skip transformer blocks).

TPU-native model integration: ``pld_layer_mask(rng, num_layers, theta)``
draws the per-layer keep mask with the PLD depth-scaled keep probability
(deeper layers drop more often, per the paper), shaped for the
scan-over-layers models: multiply each block's residual branch by
mask[layer]/keep_prob inside the scan body — static shapes, one compiled
program for all steps, theta enters as a traced scalar.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist


class ProgressiveLayerDrop:
    """ref: progressive_layer_drop.py:10."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})", ranks=[0])

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step: int):
        self.current_theta = (1.0 - self.theta) * float(np.exp(-self.gamma * global_step)) + self.theta
        return self.current_theta


def pld_layer_mask(rng, num_layers: int, theta, dtype=jnp.float32):
    """(mask[L], inv_keep[L]) — keep mask and 1/keep_prob scaling.

    Layer l keeps with probability 1 - (l+1)/L · (1-theta): identity at
    theta=1, linear depth scaling as theta decays (PLD eq. 6).  Multiply a
    block's residual delta by mask[l]*inv_keep[l] to apply.
    """
    depth = (jnp.arange(num_layers, dtype=jnp.float32) + 1.0) / num_layers
    keep_p = 1.0 - depth * (1.0 - jnp.asarray(theta, jnp.float32))
    mask = jax.random.bernoulli(rng, keep_p).astype(dtype)
    return mask, (1.0 / keep_p).astype(dtype)
