"""Sparse gradient container.

ref: runtime/sparse_tensor.py (SparseTensor — index/value form of sparse
embedding grads, reduced via ``sparse_allreduce_no_retain``
engine.py:2683).  JAX-native: jax.experimental.sparse.BCOO is the
index/value form; the allreduce analog concatenates every rank's (index,
value) pairs — here expressed as an all_gather of both arrays inside
shard_map, or densification when the consumer needs it.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


class SparseTensor:
    """ref: runtime/sparse_tensor.py:SparseTensor."""

    def __init__(self, dense_tensor=None, indices=None, values=None, dense_size=None,
                 max_nnz: Optional[int] = None):
        """``max_nnz`` gives the static nonzero-row capacity needed to build
        a SparseTensor inside jit/shard_map (dynamic nnz is untraceable);
        padded slots carry zero values so to_dense/allreduce stay exact."""
        if dense_tensor is not None:
            rows = jnp.any(dense_tensor != 0, axis=tuple(range(1, dense_tensor.ndim)))
            if max_nnz is not None:
                idx = jnp.nonzero(rows, size=max_nnz, fill_value=0)[0]
                vals = dense_tensor[idx]
                valid = jnp.arange(max_nnz) < jnp.sum(rows)
                vals = vals * valid.reshape((max_nnz, ) + (1, ) * (dense_tensor.ndim - 1)).astype(vals.dtype)
                self.indices, self.values = idx, vals
            else:
                self.indices = jnp.nonzero(rows)[0]
                self.values = dense_tensor[self.indices]
            self.dense_size = dense_tensor.shape
        else:
            self.indices = indices
            self.values = values
            self.dense_size = tuple(dense_size)

    @staticmethod
    def type():
        return "deepspeed_tpu.runtime.sparse_tensor.SparseTensor"

    def to_coo_tensor(self):
        from jax.experimental import sparse as jsparse
        idx = self.indices[:, None].astype(jnp.int32)
        return jsparse.BCOO((self.values, idx), shape=self.dense_size)

    def to_dense(self):
        out = jnp.zeros(self.dense_size, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self) -> Tuple[int, int]:
        import numpy as np
        return int(self.values.size + self.indices.size), int(np.prod(self.dense_size))


def sparse_allreduce(st: SparseTensor, axis_name: str) -> SparseTensor:
    """Concatenate (indices, values) across an axis inside shard_map /
    pmap — the reference's NCCL allgather of indices+values
    (engine.py:2719 sparse_allreduce)."""
    idx = jax.lax.all_gather(st.indices, axis_name, tiled=True)
    vals = jax.lax.all_gather(st.values, axis_name, tiled=True)
    return SparseTensor(indices=idx, values=vals, dense_size=st.dense_size)
