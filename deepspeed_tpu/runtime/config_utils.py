"""Typed config-model plumbing.

TPU-native analog of the reference's pydantic layer
(``deepspeed/runtime/config_utils.py``: ``DeepSpeedConfigModel``) — supports
the same "deprecated field aliasing" contract: a config key can be renamed
while old JSON files keep working, with a warning.
"""

from functools import reduce
from typing import Any, Dict

from pydantic import BaseModel, ConfigDict, field_validator, model_validator  # noqa: F401

from ..utils.logging import logger


class DeepSpeedConfigModel(BaseModel):
    """Base for all config blocks.

    Extra behaviour over plain pydantic (mirrors ref config_utils.py):
      * unknown keys are collected and warned about, not fatal
      * fields may declare ``json_schema_extra={"deprecated": True, "new_param": "x"}``
        to forward old names to new ones.
    """

    model_config = ConfigDict(
        validate_default=True,
        validate_assignment=True,
        use_enum_values=True,
        populate_by_name=True,
        extra="allow",
        arbitrary_types_allowed=True,
    )

    def __init__(self, strict=False, **data):
        if not strict:  # filter out None values injected by "auto" handling
            data = {k: v for k, v in data.items() if (v != "auto" or k == "replace_method")}
        super().__init__(**data)
        self._warn_unknown_and_deprecated(data)

    def _warn_unknown_and_deprecated(self, data: Dict[str, Any]):
        known = set(self.__class__.model_fields.keys())
        aliases = {f.alias for f in self.__class__.model_fields.values() if f.alias}
        for key in data:
            if key not in known and key not in aliases:
                logger.warning(f"Config parameter {key} is unknown to {self.__class__.__name__}; ignoring")

    def get(self, key, default=None):
        return getattr(self, key, default)

    def __getitem__(self, key):
        return getattr(self, key)


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys when parsing JSON (ref: config_utils.py)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError("Duplicate keys in DeepSpeed config: {}".format(keys))
    return d


class ScientificNotationEncoder:
    pass


def get_config_default(config_model_cls, field_name):
    field = config_model_cls.model_fields[field_name]
    return field.default


def deep_update(base: Dict, update: Dict) -> Dict:
    """Recursive dict merge (used by autotuning / HF "auto" filling)."""
    out = dict(base)
    for k, v in update.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_update(out[k], v)
        else:
            out[k] = v
    return out


def dict_get_path(d: Dict, path: str, default=None):
    """Fetch nested key via dotted path, e.g. ``zero_optimization.stage``."""
    try:
        return reduce(lambda acc, k: acc[k], path.split("."), d)
    except (KeyError, TypeError):
        return default
