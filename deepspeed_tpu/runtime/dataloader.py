"""Data loaders (ref: deepspeed/runtime/dataloader.py).

``RepeatingLoader`` is API-identical.  ``DeepSpeedDataLoader``'s distributed
sampler role changes on TPU: in the single-controller model each process
feeds its local shard of the GLOBAL batch; ``deepspeed_io``
(ref: runtime/engine.py:1854) becomes a thin wrapper that batches an
iterable dataset into global-batch-sized numpy pytrees.
"""

from typing import Callable, Iterable, Optional

import numpy as np


class RepeatingLoader:
    """ref: runtime/dataloader.py RepeatingLoader — wraps an iterator to
    restart on StopIteration."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)

    def __len__(self):
        return len(self.loader)


class DeepSpeedDataLoader:
    """Batches an indexable/iterable dataset into numpy pytrees of
    ``batch_size`` (the GLOBAL micro-batch across the DP mesh axes)."""

    def __init__(self,
                 dataset,
                 batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 drop_last: bool = True,
                 shuffle: bool = False,
                 seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0

    def __len__(self):
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    def __iter__(self):
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        self.epoch += 1
        for start in range(0, len(idx) - (self.batch_size - 1 if self.drop_last else 0), self.batch_size):
            chunk = idx[start:start + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                break
            yield self.collate_fn([self.dataset[int(i)] for i in chunk])


def default_collate(samples):
    """Stack a list of dict/tuple/array samples into a batched pytree."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([s[i] for s in samples]) for i in range(len(first)))
    return np.stack(samples)
