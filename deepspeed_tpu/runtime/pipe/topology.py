"""Process/axis topology bookkeeping.

Analog of ``deepspeed/runtime/pipe/topology.py`` (``ProcessTopology:12``,
``PipelineParallelGrid:251``).  On TPU the mesh itself is the topology, but
the coordinate algebra (axis↔rank mapping, slicing along axes) is still
needed by the pipeline engine, checkpoint naming and tests — reimplemented
here over plain integers with the same public surface
(``get_rank``, ``get_coord``, ``get_axis_comm_lists``, ``filter_match`` …).
"""

from collections import namedtuple
from itertools import product
from typing import Dict, List


class ProcessTopology:
    """Maps n-dimensional axis coordinates ↔ linear ranks (row-major, first
    axis outermost — same convention as the reference)."""

    def __init__(self, axes: List[str], dims: List[int]):
        assert len(axes) == len(dims)
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping = {}
        for rank, coord in enumerate(product(*[range(d) for d in dims])):
            key = dict(zip(axes, coord))
            self.mapping[self.ProcessCoord(**key)] = rank

    def get_rank(self, **coord_kwargs) -> int:
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}, got {list(coord_kwargs)}")
        return self.mapping[self.ProcessCoord(**coord_kwargs)]

    def get_axis_names(self) -> List[str]:
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", ), inner_sep="_", outer_sep="-"):
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank: int):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks varying only along ``axis`` (the reference uses
        these to build communicator subgroups; we use them for checkpoint
        naming and tests)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for other_coord in product(*[range(self.get_dim(a)) for a in other_axes]):
            fixed = dict(zip(other_axes, other_coord))
            ranks = [self.get_rank(**{axis: i, **fixed}) for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        def matches(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())

        return sorted(r for c, r in self.mapping.items() if matches(c))

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return self.filter_match(**{axis: idx})

    def world_size(self) -> int:
        return len(self.mapping)

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """ref: topology.py PipeDataParallelTopology — (pipe, data) grid."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """ref: topology.py PipeModelDataParallelTopology — (pipe, data, model)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """ref: topology.py:251 — axis-size/rank queries used by the pipeline
    engine.  Backed by a ProcessTopology; in the TPU rebuild the "ranks" are
    logical mesh coordinates rather than torch.distributed ranks."""

    def __init__(self, topology: ProcessTopology, my_rank: int = 0):
        self._topo = topology
        self.global_rank = my_rank
        self.data_parallel_size = max(1, topology.get_dim("data"))
        self.pipe_parallel_size = max(1, topology.get_dim("pipe"))
        self.model_parallel_size = max(1, topology.get_dim("model"))
        self.slice_parallel_size = self.model_parallel_size

    def get_stage_id(self):
        return getattr(self._topo.get_coord(self.global_rank), "pipe", 0)

    def get_data_parallel_id(self):
        return getattr(self._topo.get_coord(self.global_rank), "data", 0)

    def get_pipe_parallel_rank(self):
        return self.get_stage_id()

    def get_data_parallel_rank(self):
        return self.get_data_parallel_id()

    def get_model_parallel_rank(self):
        return getattr(self._topo.get_coord(self.global_rank), "model", 0)

    def get_global_rank(self):
        return self.global_rank

    def topology(self):
        return self._topo

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self.pipe_parallel_size - 1

    def stage_to_global(self, stage_id, data=None, model=None):
        data = data if data is not None else self.get_data_parallel_id()
        kwargs = {"pipe": stage_id, "data": data}
        if "model" in self._topo.get_axis_names():
            kwargs["model"] = model if model is not None else self.get_model_parallel_rank()
        return self._topo.get_rank(**kwargs)
