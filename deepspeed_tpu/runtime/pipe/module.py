"""PipelineModule — layer-list model container for pipeline parallelism.

API parity with ``deepspeed/runtime/pipe/module.py`` (``LayerSpec:30``,
``TiedLayerSpec:78``, ``PipelineModule:96``): the user supplies a flat list
of layers; the module partitions them over pipeline stages.

TPU-native semantics: SPMD pipelining (runtime/pipe/pipeline.py) requires
the pipelined body to be *homogeneous* — the same block program runs on
every stage with stage-resident weights.  ``PipelineModule`` therefore
splits the layer list into:

  pre   — everything before the longest run of same-class layers (embedding
          etc.); computed pipe-replicated (cheap, params replicated on pipe),
  body  — the longest run of same-class layers (the transformer stack),
          stacked ``[L, ...]`` and sharded over the ``pipe`` mesh axis,
  post  — the remainder (final norm, LM head); pipe-replicated.

This matches how the reference is used in practice (embed → N×block →
norm/head) while replacing its per-rank module slicing
(``PipelineModule._partition_layers``) with sharding of the stacked-layer
axis.  ``partition_method`` is accepted for parity; SPMD stacking implies
a uniform split, so "parameters"/"type:" methods reduce to uniform here.

The class duck-types the flax Module surface the engine consumes
(``init(rng, *args)`` / ``apply(variables, *args)``) so DeepSpeedEngine and
checkpointing work unchanged.
"""

import warnings
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax.core import meta as nn_meta

from ...comm.mesh import get_global_mesh
from ...utils.logging import logger
from .pipeline import STAGE_LAYERS, pipelined_apply


class PipelineError(Exception):
    """Errors in pipeline-parallel module construction."""


class LayerSpec:
    """Lazily-built layer description (ref: pipe/module.py:30 LayerSpec).
    ``typename`` is a flax ``nn.Module`` subclass (or any callable for
    param-less layers like reshapes)."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        name = getattr(self.typename, "__name__", str(self.typename))
        return f"LayerSpec({name})"


class TiedLayerSpec(LayerSpec):
    """A layer whose params are shared with every other TiedLayerSpec of the
    same ``key`` (ref: pipe/module.py:78 — tied embeddings).  ``forward_fn``
    maps ``(module, variables, x) -> out`` for reuse sites that call the tied
    module differently (e.g. ``embed.attend`` for the LM head)."""

    def __init__(self, key, typename, *module_args, forward_fn=None, tied_weight_attr=("weight", ), **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Stage boundary indices for a uniform split (ref: ds_utils
    partition_uniform); returns num_parts+1 boundaries."""
    if num_items % num_parts != 0:
        raise PipelineError(f"{num_items} layers not divisible into {num_parts} stages")
    step = num_items // num_parts
    return [i * step for i in range(num_parts + 1)]


def _build(layer):
    if isinstance(layer, LayerSpec):
        return layer.build()
    return layer


def _is_module(layer) -> bool:
    return isinstance(layer, nn.Module)


def _apply_layer(module, variables, x, extras):
    """Call a layer, passing extras only if its signature accepts them
    (signature inspection, NOT try/except — a TypeError raised *inside* the
    layer must surface, and init/apply must bind extras identically)."""
    take = extras if _accepts_extras(module, x, extras, init=False) else ()
    if not _is_module(module):
        return module(x, *take)
    return module.apply(variables, x, *take)


def _longest_same_class_run(layers) -> tuple:
    """(start, stop) of the longest run of same-class nn.Module layers."""
    best = (0, 0)
    i = 0
    n = len(layers)
    while i < n:
        if not _is_module(layers[i]):
            i += 1
            continue
        j = i + 1
        while j < n and _is_module(layers[j]) and type(layers[j]) is type(layers[i]):
            j += 1
        if j - i > best[1] - best[0]:
            best = (i, j)
        i = j
    return best


class PipelineModule:
    """Sequential container executed as an SPMD pipeline.

    ref: deepspeed/runtime/pipe/module.py:96 ``PipelineModule(layers,
    num_stages, topology, loss_fn, partition_method,
    activation_checkpoint_interval)``.
    """

    def __init__(self,
                 layers: Sequence,
                 num_stages: Optional[int] = None,
                 topology=None,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 1,
                 checkpointable_layers=None,
                 schedule: str = "gpipe"):
        if num_stages is None and topology is None:
            raise PipelineError("must provide num_stages or topology")
        if topology is not None and num_stages is None:
            num_stages = topology.get_dim("pipe")
        self.num_stages = int(num_stages)
        if schedule not in ("gpipe", "1f1b"):
            raise PipelineError(f"unknown pipeline schedule {schedule!r} (gpipe | 1f1b)")
        self.schedule = schedule
        self._1f1b_cache = {}
        self.loss_fn = loss_fn
        self.micro_batches = 1  # set by PipelineEngine (= gradient_accumulation_steps)
        self.remat = activation_checkpoint_interval != 0
        if partition_method not in ("parameters", "uniform") and not partition_method.startswith("type:"):
            raise PipelineError(f"unknown partition_method {partition_method}")

        built = [(_build(l), l) for l in layers]
        self._layers = [b for b, _ in built]
        self._specs = [s for _, s in built]

        start, stop = _longest_same_class_run(self._layers)
        n_body = stop - start
        if self.num_stages > 1:
            if n_body == 0:
                raise PipelineError("no homogeneous block run found to pipeline")
            partition_uniform(n_body, self.num_stages)  # raises if not divisible
        self._body_range = (start, stop)
        self.pre = self._layers[:start]
        self.body = self._layers[start:stop]
        self.post = self._layers[stop:]
        self.forward_funcs = self._layers  # parity attribute
        # tied-module registry: key → (module, first_index)
        self._tied: dict = {}
        for idx, spec in enumerate(self._specs):
            if isinstance(spec, TiedLayerSpec):
                if not (idx < start or idx >= stop):
                    raise PipelineError("tied layers inside the pipelined body are not supported")
                self._tied.setdefault(spec.key, (self._layers[idx], idx))
        if n_body:
            logger.debug(f"PipelineModule: pre={start} body={n_body}x{type(self.body[0]).__name__} "
                         f"post={len(self._layers) - stop} stages={self.num_stages}")

    # ------------------------------------------------------------- flax duck

    def _param_name(self, idx: int) -> Optional[str]:
        spec = self._specs[idx]
        if isinstance(spec, TiedLayerSpec):
            return f"tied_{spec.key}"
        return f"layer_{idx}"

    def init(self, rng, x, *extras, **kwargs):
        if kwargs:
            raise PipelineError(
                f"PipelineModule does not accept keyword model inputs {sorted(kwargs)}; pipeline "
                "blocks derive positions internally and batches must not carry segment_ids — "
                "pass a model_inputs_fn returning positional extras instead.")
        return self._init(rng, x, *extras)

    def _init(self, rng, x, *extras):
        """Initialise boxed (logically-partitioned) variables.  The body is
        init'd per-layer with split rngs and stacked — the ``zero.Init``-
        style partition-at-construction applies because the engine jits this
        with ZeRO/pipe out_shardings (engine._materialize_state)."""
        start, stop = self._body_range
        params = {}
        h = x

        def init_one(mod, rng, h, idx):
            spec = self._specs[idx]
            if isinstance(spec, TiedLayerSpec) and self._param_name(idx) in params:
                variables = {"params": params[self._param_name(idx)]}
                if spec.forward_fn is not None:
                    return spec.forward_fn(mod, variables, h)
                return _apply_layer(mod, variables, h, extras)
            variables = mod.init(rng, h, *extras) if _accepts_extras(mod, h, extras, init=True) else mod.init(rng, h)
            params[self._param_name(idx)] = variables["params"]
            return _apply_layer(mod, variables, h, extras)

        for idx in range(start):
            mod = self._layers[idx]
            if not _is_module(mod):
                h = mod(h)
                continue
            rng, sub = jax.random.split(rng)
            h = init_one(mod, sub, h, idx)

        if self.body:
            block = self.body[0]
            rng, sub = jax.random.split(rng)
            rngs = jax.random.split(sub, len(self.body))
            stacked = jax.vmap(lambda r: block.init(r, h, *extras)
                               if _accepts_extras(block, h, extras, init=True) else block.init(r, h))(rngs)
            # prepend the stacked-layer logical axis to each box's names
            stacked = jax.tree.map(
                lambda box: nn_meta.Partitioned(box.value, names=(STAGE_LAYERS, ) + tuple(box.names))
                if isinstance(box, nn_meta.Partitioned) else box,
                stacked,
                is_leaf=lambda v: isinstance(v, nn_meta.AxisMetadata))
            params["body"] = stacked["params"]
            layer0 = jax.tree.map(lambda b: b.value[0] if isinstance(b, nn_meta.Partitioned) else b[0],
                                  stacked["params"],
                                  is_leaf=lambda v: isinstance(v, nn_meta.AxisMetadata))
            h_out = jax.eval_shape(lambda p, hh: _apply_layer(block, {"params": p}, hh, extras), layer0, h)
            if h_out.shape != jnp.shape(h) or h_out.dtype != jnp.result_type(h):
                raise PipelineError(f"pipelined block must preserve shape/dtype: {jnp.shape(h)} -> {h_out.shape}")
            # post-layer param shapes depend only on h's shape, not values
            h = jnp.zeros(h_out.shape, h_out.dtype)

        for idx in range(stop, len(self._layers)):
            mod = self._layers[idx]
            if not _is_module(mod):
                h = mod(h)
                continue
            rng, sub = jax.random.split(rng)
            h = init_one(mod, sub, h, idx)

        return {"params": params}

    def apply(self, variables, x, *extras, **kwargs):
        if kwargs:
            raise PipelineError(
                f"PipelineModule does not accept keyword model inputs {sorted(kwargs)}; pipeline "
                "blocks derive positions internally — pass positional extras via model_inputs_fn.")
        params = variables["params"]
        mesh = get_global_mesh()
        start, stop = self._body_range
        h = x

        for idx in range(start):
            h = self._apply_indexed(idx, params, h, extras)

        if self.body:
            block = self.body[0]

            def body_fn(layer_params, h, *ex):
                return block.apply({"params": layer_params}, h, *ex) \
                    if _accepts_extras(block, h, ex, init=False) else block.apply({"params": layer_params}, h)

            h = pipelined_apply(body_fn, params["body"], h, extras,
                                mesh=mesh,
                                num_stages=self.num_stages,
                                micro_batches=self.micro_batches,
                                remat=self.remat)

        for idx in range(stop, len(self._layers)):
            h = self._apply_indexed(idx, params, h, extras)
        return h

    def __call__(self, variables, x, *extras, **kwargs):
        return self.apply(variables, x, *extras, **kwargs)

    def apply_loss_1f1b(self, variables, loss_fn, batch, x, *extras):
        """Loss of one full batch under the TRUE 1F1B schedule (ref:
        pipe/schedule.py:189 TrainSchedule): the post-stack + loss runs
        inside the pipeline loop per microbatch, backward interleaves with
        forward, live activations are bounded by the stash depth.  The pre
        layers (embedding) stay outside and differentiate through dx."""
        from .pipeline import make_pipelined_1f1b
        params = variables["params"]
        mesh = get_global_mesh()
        start, stop = self._body_range
        h = x
        for idx in range(start):
            h = self._apply_indexed(idx, params, h, extras)
        if not self.body:
            raise PipelineError("1f1b schedule requires a pipelined body")
        blockmod = self.body[0]

        def body_fn(layer_params, h, *ex):
            return blockmod.apply({"params": layer_params}, h, *ex) \
                if _accepts_extras(blockmod, h, ex, init=False) else blockmod.apply({"params": layer_params}, h)

        nonbody = {k: v for k, v in params.items() if k != "body"}

        def head_fn(nonbody_params, h_mb, mb_batch):
            for idx in range(stop, len(self._layers)):
                mod = self._layers[idx]
                if not _is_module(mod):
                    h_mb = mod(h_mb)
                    continue
                spec = self._specs[idx]
                vs = {"params": nonbody_params[self._param_name(idx)]}
                if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None \
                        and idx != self._tied[spec.key][1]:
                    h_mb = spec.forward_fn(mod, vs, h_mb)
                else:
                    h_mb = _apply_layer(mod, vs, h_mb, ())
            return loss_fn(h_mb, mb_batch)

        # cache key must not be id()-based: a recycled address after GC would
        # silently reuse an executor closed over a dead mesh/loss_fn
        # (advisor r2).  Key on the mesh's stable identity (axis names +
        # shape + device ids) and hold a strong ref to loss_fn so its id is
        # pinned for the cache's lifetime.
        mesh_key = (tuple(mesh.axis_names), tuple(mesh.devices.shape),
                    tuple(int(d.id) for d in mesh.devices.flat))
        key = (mesh_key, self.micro_batches, id(loss_fn))
        if key not in self._1f1b_cache:
            self._1f1b_cache[key] = (make_pipelined_1f1b(
                body_fn, head_fn, mesh=mesh, num_stages=self.num_stages,
                micro_batches=self.micro_batches, remat=self.remat), loss_fn)
            # the strong loss_fn ref pins its id (no GC recycling), but a
            # caller building a fresh closure per step would then grow the
            # cache without bound — keep the newest few executors (FIFO)
            while len(self._1f1b_cache) > 8:
                self._1f1b_cache.pop(next(iter(self._1f1b_cache)))
        return self._1f1b_cache[key][0](params["body"], nonbody, h, extras, batch)

    def _apply_indexed(self, idx, params, h, extras):
        mod = self._layers[idx]
        if not _is_module(mod):
            return mod(h)
        spec = self._specs[idx]
        variables = {"params": params[self._param_name(idx)]}
        if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None and idx != self._tied[spec.key][1]:
            return spec.forward_fn(mod, variables, h)
        return _apply_layer(mod, variables, h, extras)

    # ------------------------------------------------------------ parity API

    def topology(self):
        from ...comm.mesh import BATCH_AXES, axis_size
        from .topology import PipeDataParallelTopology
        mesh = get_global_mesh()
        # dp counts only the batch-splitting axes (data, expert) — tensor/seq
        # are model-parallel degrees (matches config._resolve_dp_world_size)
        return PipeDataParallelTopology(self.num_stages, axis_size(mesh, *BATCH_AXES))

    def num_pipeline_stages(self):
        return self.num_stages


def _accepts_extras(mod, h, extras, init: bool) -> bool:
    if not extras:
        return False
    try:
        import inspect
        sig = inspect.signature(mod.__call__)
        pos = [p for p in sig.parameters.values()
               if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD) and p.name != "self"]
        has_varargs = any(p.kind == p.VAR_POSITIONAL for p in sig.parameters.values())
        return has_varargs or len(pos) >= 1 + len(extras)
    except (TypeError, ValueError):
        return False
