"""Pipeline instruction schedules (parity/introspection layer).

Reference: ``deepspeed/runtime/pipe/schedule.py`` — ``PipeSchedule:51``,
``InferenceSchedule:135``, ``TrainSchedule:189`` and the instruction
classes ``:327-489``.  There these drive per-rank MPMD execution; here the
compiled SPMD pipeline (pipeline.py) IS the schedule, so these generators
exist for (a) API/test parity, (b) documentation of the tick↔microbatch
mapping, (c) cost modelling (`num_ticks`, bubble fraction) used by the
autotuner.
"""

from dataclasses import dataclass


@dataclass(frozen=True)
class PipeInstruction:
    buffer_id: int = -1

    def __repr__(self):
        if self.buffer_id >= 0:
            return f"{type(self).__name__}(buffer_id={self.buffer_id})"
        return type(self).__name__


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass


class ForwardPass(PipeInstruction):
    pass


class BackwardPass(PipeInstruction):
    pass


class SendActivation(PipeInstruction):
    pass


class RecvActivation(PipeInstruction):
    pass


class SendGrad(PipeInstruction):
    pass


class RecvGrad(PipeInstruction):
    pass


class PipeSchedule:
    """Base: iterate over per-step instruction lists for one (stage,
    micro_batches, stages) coordinate (ref: schedule.py:51)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self):
        raise NotImplementedError

    def num_pipe_buffers(self):
        raise NotImplementedError

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, mb: int) -> bool:
        return 0 <= mb < self.micro_batches

    def _valid_stage(self, stage: int) -> bool:
        return 0 <= stage < self.stages

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (ref: schedule.py:135)."""

    def num_pipe_buffers(self):
        return 2

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for step_id in range(total):
            mb = step_id - self.stage_id
            cmds = []
            buf = step_id % 2
            if self._valid_micro_batch(mb):
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buf))
                else:
                    cmds.append(RecvActivation(buf))
            if self._valid_micro_batch(mb - 1) and self._valid_stage(self.next_stage) \
                    and not self.is_last_stage:
                cmds.append(SendActivation((step_id - 1) % 2))
            if self._valid_micro_batch(mb):
                cmds.append(ForwardPass(buf))
            yield cmds


class TrainSchedule(PipeSchedule):
    """Synchronous 1F1B (ref: schedule.py:189 TrainSchedule).  Produces, per
    stage, an alternating forward/backward step stream with warmup/cooldown;
    total length 2*(micro_batches + stages - 1)."""

    def num_pipe_buffers(self):
        return max(2, min(self.stages - self.stage_id, self.micro_batches))

    def _step_to_micro_batch(self, step_id):
        """Map a schedule step to (micro_batch_id, is_forward).  Even steps
        are forwards on even stages; parity alternates per stage so that
        sends and recvs line up (same tick algebra as the reference)."""
        even_step = step_id % 2 == 0
        even_stage = self.stage_id % 2 == 0
        if even_step == even_stage:
            mb = (step_id - self.stage_id) // 2
            return mb, True
        mb = (step_id - 2 * self.stages + self.stage_id + 2) // 2
        return mb, False

    def steps(self):
        prev_mb = -1
        total = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total):
            mb, is_forward = self._step_to_micro_batch(step_id)
            cmds = []
            if is_forward:
                if self._valid_micro_batch(prev_mb) and self._valid_stage(self.prev_stage):
                    cmds.append(SendGrad(self._buffer_idx(prev_mb)))
                if self._valid_micro_batch(mb) and self._valid_stage(self.prev_stage):
                    cmds.append(RecvActivation(self._buffer_idx(mb)))
                if self._valid_micro_batch(mb) and (self.is_first_stage or self.is_last_stage):
                    cmds.append(LoadMicroBatch(self._buffer_idx(mb)))
                if self._valid_micro_batch(mb):
                    cmds.append(ForwardPass(self._buffer_idx(mb)))
            else:
                if self._valid_micro_batch(mb) and self._valid_stage(self.next_stage):
                    cmds.append(RecvGrad(self._buffer_idx(mb)))
                if self._valid_micro_batch(prev_mb) and self._valid_stage(self.next_stage):
                    cmds.append(SendActivation(self._buffer_idx(prev_mb)))
                if self._valid_micro_batch(mb):
                    cmds.append(BackwardPass(self._buffer_idx(mb)))
            if step_id == total - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            prev_mb = mb
            yield cmds

    def _buffer_idx(self, mb):
        assert self._valid_micro_batch(mb)
        return mb % self.num_pipe_buffers()


def bubble_fraction(micro_batches: int, stages: int) -> float:
    """GPipe/1F1B bubble overhead — used by the autotuner cost model."""
    return (stages - 1) / (micro_batches + stages - 1)
