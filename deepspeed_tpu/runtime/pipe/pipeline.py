"""SPMD pipeline-parallel executor.

TPU-native replacement for the reference's instruction-driven pipeline
(``deepspeed/runtime/pipe/engine.py:61 PipelineEngine`` executing
``schedule.py:189 TrainSchedule`` with p2p send/recv between stage
processes, ``runtime/pipe/p2p.py``).  There, each rank runs a different
instruction stream (MPMD) and overlap comes from hand-managed buffers and
streams.  Here the whole pipeline is ONE compiled SPMD program:

* block weights are stacked with a leading layer axis sharded over the
  ``pipe`` mesh axis — each pipe device owns ``layers_per_stage`` layers;
* a ``lax.scan`` over "ticks" runs the GPipe schedule: at tick ``t`` stage
  ``s`` computes microbatch ``t - s``; activations rotate stage→stage+1 via
  ``lax.ppermute`` on ICI (the p2p.send/recv analog);
* reverse-mode AD through ``ppermute`` yields the reverse pipeline — the
  backward schedule the reference encodes as SendGrad/RecvGrad instructions
  falls out of the transpose rule;
* the driver loop costs ``M + S - 1`` ticks for M microbatches on S stages,
  i.e. the classic GPipe bubble ``(S-1)/(M+S-1)`` — same pipeline
  efficiency as the reference's 1F1B for equal M (1F1B improves *memory*,
  which remat already bounds here).

The per-microbatch extras (positions, segment ids, ...) travel with the
activation through the rotation, since stage ``s`` needs microbatch
``t - s``'s extras at tick ``t``.
"""

from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...comm.mesh import PIPE_AXIS

# Logical name for the stacked-layer leading axis of pipelined blocks;
# mapped to the ``pipe`` mesh axis by module_inject/tp_rules.py.
STAGE_LAYERS = "stage_layers"


def num_pipeline_ticks(micro_batches: int, stages: int) -> int:
    """Total schedule length (fwd ticks; ref: schedule.py total_steps is
    2*(M+S-1) counting fwd+bwd separately — AD supplies the factor 2)."""
    return micro_batches + stages - 1


def _microbatch(tree, num_micro):
    """[B, ...] → [M, B/M, ...] on every array leaf."""

    def split(x):
        if np.ndim(x) == 0:
            return x
        b = x.shape[0]
        assert b % num_micro == 0, (f"batch dim {b} not divisible by micro_batches={num_micro}")
        return x.reshape((num_micro, b // num_micro) + x.shape[1:])

    return jax.tree.map(split, tree)


def _unmicrobatch(tree):
    def join(x):
        if np.ndim(x) < 2:
            return x
        return x.reshape((x.shape[0] * x.shape[1], ) + x.shape[2:])

    return jax.tree.map(join, tree)


def _float0_like(tree):
    """Cotangents for integer/bool leaves (jax requires float0 there)."""
    import numpy as onp

    def z(t):
        if jnp.issubdtype(jnp.asarray(t).dtype, jnp.inexact):
            return jnp.zeros_like(t)
        return onp.zeros(onp.shape(t), jax.dtypes.float0)

    return jax.tree.map(z, tree)


def make_pipelined_1f1b(body_fn: Callable,
                        head_fn: Callable,
                        *,
                        mesh,
                        num_stages: int,
                        micro_batches: int,
                        remat: bool = True):
    """Build a TRUE 1F1B pipeline executor: one scan interleaving forward and
    backward microbatch work per tick (ref: pipe/schedule.py:189
    TrainSchedule and its executor pipe/engine.py:1409 _exec_schedule).

    Unlike ``pipelined_apply`` (GPipe: AD transposes the forward scan, so
    every in-flight tick carry — O(M) microbatch activations per stage — is
    saved for the backward), this executor computes gradients ITSELF inside
    the tick loop: each stage keeps a stash of at most ``min(S+1, M)`` saved
    stage inputs, runs its backward as soon as the matching cotangent
    arrives, and retires the stash slot — the 1F1B live-activation profile.
    The loss head runs inside the loop on the last stage (the reference puts
    the loss in the PipelineModule for the same reason), so the backward is
    seeded per microbatch without leaving the schedule.

    The result is exposed to autodiff as a ``jax.custom_vjp``: the primal
    computes (loss, grads) in one pass and saves the grads as residuals; the
    bwd rule scales them by the upstream cotangent (valid because gradients
    are linear in the scalar loss cotangent).  Upstream (embedding) layers
    stay differentiable through the returned dx.

    Args:
      body_fn: ``(layer_params, h, *extras_mb) -> h`` — one block.
      head_fn: ``(head_params, h_mb, mb_batch) -> scalar`` — the post-stack
        (final norm / lm head / loss) for ONE microbatch.  ``head_params``
        may be any pytree (it also flows through the caller's own forward,
        e.g. tied embeddings; cotangents from both paths sum).
    Returns:
      ``f(body_params, head_params, x, extras, batch) -> loss`` with a
      custom VJP.  ``batch`` is the per-microbatch-sliceable data pytree
      (labels etc.); its cotangent is zero.
    """
    S, M = num_stages, micro_batches
    T = 2 * (M + S - 1)
    NB = min(S + 1, M)  # stash depth: the 1F1B bound (ref: num_pipe_buffers)
    fwd_rotate = [(i, (i + 1) % S) for i in range(S)]
    bwd_rotate = [(i, (i - 1) % S) for i in range(S)]
    block = jax.checkpoint(body_fn) if remat else body_fn

    def _value_and_grads(body_params, head_params, x, extras, batch):
        for leaf in jax.tree.leaves(extras):
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                raise NotImplementedError(
                    "1f1b: float extras would receive ZERO gradient through the custom VJP "
                    "(unlike gpipe, which differentiates extras); pass only integer/bool "
                    "extras (positions, segment ids) or use schedule='gpipe'")
        mbs = _microbatch(x, M)
        extras_mb = tuple(_microbatch(e, M) for e in extras)
        batch_mb = _microbatch(batch, M)
        x_dtype = x.dtype
        upcast_wire = jax.default_backend() == "cpu"

        def _wire32(t):
            if not upcast_wire:
                return t
            return jax.tree.map(lambda a: a.astype(jnp.float32)
                                if jnp.issubdtype(a.dtype, jnp.floating) else a, t)

        extras_dtypes = jax.tree.map(lambda a: a.dtype, extras_mb)
        mbs32 = _wire32(mbs)
        extras_mb32 = _wire32(extras_mb)

        @partial(jax.shard_map,
                 mesh=mesh,
                 axis_names={PIPE_AXIS},
                 in_specs=(P(PIPE_AXIS), P(), P(), P(), P()),
                 out_specs=(P(), P(PIPE_AXIS), P(), P()),
                 check_vma=False)
        def run(params, head_params, mbs32, extras_mb32, batch_mb):
            stage = jax.lax.axis_index(PIPE_AXIS)
            mb_shape = mbs32.shape[1:]

            def stage_fwd_with(p, h, ex):
                def body(h, lp):
                    return block(lp, h, *ex), None

                h, _ = jax.lax.scan(body, h, p)
                return h

            def take_mb(tree, idx):
                return jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(a, idx, 0, keepdims=False), tree)

            carry0 = dict(
                fwd_msg=jnp.zeros(mb_shape, jnp.float32 if upcast_wire else x_dtype),
                fwd_msg_ex=jax.tree.map(lambda a: jnp.zeros(a.shape[1:], a.dtype), extras_mb32),
                bwd_msg=jnp.zeros(mb_shape, jnp.float32),
                stash_h=jnp.zeros((NB, ) + mb_shape, x_dtype),
                stash_ex=jax.tree.map(lambda a: jnp.zeros((NB, ) + a.shape[1:], a.dtype),
                                      jax.tree.map(lambda a, dt: jnp.zeros(a.shape[1:], dt),
                                                   extras_mb32, extras_dtypes)),
                seed=jnp.zeros((NB, ) + mb_shape, jnp.float32),
                body_grads=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
                head_grads=jax.tree.map(lambda p: jnp.zeros(jnp.shape(p), jnp.float32), head_params),
                dx=jnp.zeros((M, ) + mb_shape, jnp.float32),
                loss=jnp.zeros((), jnp.float32),
            )

            def tick(carry, t):
                parity_match = (t % 2) == (stage % 2)
                mb_f = (t - stage) // 2
                mb_b = (t - 2 * S + stage + 2) // 2
                do_fwd = parity_match & (mb_f >= 0) & (mb_f < M)
                do_bwd = (~parity_match) & (mb_b >= 0) & (mb_b < M)
                is_first = stage == 0
                is_last = stage == S - 1

                def fwd_branch(carry):
                    idx = jnp.maximum(mb_f, 0)
                    x_in = take_mb(mbs32, idx).astype(x_dtype)
                    ex_in = take_mb(extras_mb32, idx)
                    h_in = jnp.where(is_first, x_in, carry["fwd_msg"].astype(x_dtype))
                    ex_use = jax.tree.map(lambda new, old: jnp.where(is_first, new, old),
                                          ex_in, carry["fwd_msg_ex"])
                    ex_typed = jax.tree.map(lambda a, dt: a.astype(dt), ex_use, extras_dtypes)
                    slot = idx % NB
                    stash_h = jax.lax.dynamic_update_index_in_dim(carry["stash_h"], h_in, slot, 0)
                    stash_ex = jax.tree.map(
                        lambda buf, v: jax.lax.dynamic_update_index_in_dim(buf, v, slot, 0),
                        carry["stash_ex"], ex_use)
                    h_out = stage_fwd_with(params, h_in, ex_typed)
                    # last stage ONLY: per-microbatch loss + backward seed
                    # (runtime cond — other stages skip the head entirely)
                    mb_data = take_mb(batch_mb, idx)

                    def compute_head(_):
                        def head_loss(hp, h):
                            return head_fn(hp, h.astype(x_dtype), mb_data)

                        return jax.value_and_grad(head_loss, argnums=(0, 1))(head_params, h_out)

                    def skip_head(_):
                        zero_hg = jax.tree.map(lambda pp: jnp.zeros(jnp.shape(pp), jnp.asarray(pp).dtype),
                                               head_params)
                        return jnp.zeros((), jnp.float32), (zero_hg, jnp.zeros_like(h_out))

                    loss_mb, (dhead, dh) = jax.lax.cond(is_last, compute_head, skip_head, None)
                    head_grads = jax.tree.map(lambda g, acc: acc + g.astype(jnp.float32),
                                              dhead, carry["head_grads"])
                    seed = jax.lax.dynamic_update_index_in_dim(
                        carry["seed"], dh.astype(jnp.float32), slot, 0)
                    return {**carry,
                            "fwd_msg": _wire32(h_out) if upcast_wire else h_out,
                            "fwd_msg_ex": ex_use,
                            "stash_h": stash_h, "stash_ex": stash_ex,
                            "seed": seed,
                            "head_grads": head_grads,
                            "loss": carry["loss"] + loss_mb.astype(jnp.float32)}

                def bwd_branch(carry):
                    idx = jnp.maximum(mb_b, 0)
                    slot = idx % NB
                    h_in = jax.lax.dynamic_index_in_dim(carry["stash_h"], slot, 0, keepdims=False)
                    ex_in = jax.tree.map(
                        lambda buf: jax.lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False),
                        carry["stash_ex"])
                    ex_typed = jax.tree.map(lambda a, dt: a.astype(dt), ex_in, extras_dtypes)
                    dh_seed = jax.lax.dynamic_index_in_dim(carry["seed"], slot, 0, keepdims=False)
                    dh_out = jnp.where(is_last, dh_seed, carry["bwd_msg"]).astype(x_dtype)

                    def f(p, h):
                        return stage_fwd_with(p, h, ex_typed)

                    _, vjp = jax.vjp(f, params, h_in)
                    dparams, dh_in = vjp(dh_out)
                    body_grads = jax.tree.map(lambda g, acc: acc + g.astype(jnp.float32),
                                              dparams, carry["body_grads"])
                    dx = jax.lax.dynamic_update_index_in_dim(
                        carry["dx"], jnp.where(is_first, dh_in.astype(jnp.float32), 0.0), idx, 0)
                    return {**carry,
                            "bwd_msg": dh_in.astype(jnp.float32),
                            "body_grads": body_grads,
                            "dx": dx}

                carry = jax.lax.cond(do_fwd, fwd_branch, lambda c: c, carry)
                carry = jax.lax.cond(do_bwd, bwd_branch, lambda c: c, carry)
                # rotate every tick: activations forward, cotangents backward
                # (the SendActivation/SendGrad pair, ref: pipe/p2p.py:45);
                # garbage rotations in warmup/cooldown are never consumed —
                # validity is re-derived from the tick algebra at the consumer
                carry = {**carry,
                         "fwd_msg": jax.lax.ppermute(carry["fwd_msg"], PIPE_AXIS, fwd_rotate),
                         "fwd_msg_ex": jax.tree.map(
                             lambda a: jax.lax.ppermute(a, PIPE_AXIS, fwd_rotate),
                             carry["fwd_msg_ex"]),
                         "bwd_msg": jax.lax.ppermute(carry["bwd_msg"], PIPE_AXIS, bwd_rotate)}
                return carry, None

            carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T))
            # loss/head_grads live on the last stage, dx on the first —
            # psum with zero elsewhere broadcasts them pipe-wide
            loss = jax.lax.psum(carry["loss"], PIPE_AXIS) / M
            head_grads = jax.tree.map(
                lambda g: jax.lax.psum(g, PIPE_AXIS) / M, carry["head_grads"])
            dx = jax.lax.psum(carry["dx"], PIPE_AXIS) / M
            body_grads = jax.tree.map(lambda g: g / M, carry["body_grads"])
            return loss, body_grads, head_grads, dx

        loss, body_grads, head_grads, dx = run(body_params, head_params, mbs32,
                                               extras_mb32, batch_mb)
        dx = _unmicrobatch(dx).astype(jnp.float32)
        return loss, (body_grads, head_grads, dx)

    @jax.custom_vjp
    def pipelined_1f1b(body_params, head_params, x, extras, batch):
        # loss-only primal (eval_batch etc.): forward fill-drain + per-mb
        # head — no vjp work, no grad accumulators.  Differentiated calls go
        # through the fwd rule instead, which runs the interleaved 1F1B pass.
        h = pipelined_apply(body_fn, body_params, x, extras,
                            mesh=mesh, num_stages=S, micro_batches=M, remat=remat)
        h_mb = _microbatch(h, M)
        batch_mb = _microbatch(batch, M)

        def one(i):
            take = lambda tree: jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False), tree)
            return head_fn(head_params, take(h_mb), take(batch_mb))

        losses = jax.lax.map(one, jnp.arange(M))
        return jnp.mean(losses)

    def fwd(body_params, head_params, x, extras, batch):
        loss, grads = _value_and_grads(body_params, head_params, x, extras, batch)
        return loss, (grads, extras, batch)

    def bwd(res, ct):
        (body_grads, head_grads, dx), extras, batch = res
        scale = lambda t: jax.tree.map(lambda g: g * ct, t)
        return (scale(body_grads), scale(head_grads), (dx * ct).astype(jnp.float32),
                _float0_like(extras), _float0_like(batch))

    pipelined_1f1b.defvjp(fwd, bwd)
    return pipelined_1f1b


def pipelined_apply(body_fn: Callable,
                    body_params: Any,
                    x: jnp.ndarray,
                    extras: Sequence[Any],
                    *,
                    mesh,
                    num_stages: int,
                    micro_batches: int,
                    remat: bool = True):
    """Run ``x`` through the stacked pipelined blocks.

    Args:
      body_fn: ``(layer_params, h, *extras_mb) -> h`` — applies ONE block.
        Output must have the same shape/dtype as ``h`` (residual stream).
      body_params: pytree whose leaves are stacked ``[L, ...]`` with the
        leading axis sharded over the ``pipe`` mesh axis.
      x: ``[B, ...]`` activations entering the first block.
      extras: per-batch auxiliary inputs (``[B, ...]`` leading dim each,
        e.g. positions/segment_ids) consumed by every block.
      num_stages: pipeline depth S (== mesh.shape['pipe']).
      micro_batches: M — the reference's gradient_accumulation_steps
        (ref: pipe/engine.py micro_batches = gas).
    """
    S, M = num_stages, micro_batches
    if S == 1:
        # degenerate path: plain scan over layers, no pipeline overhead
        fn = jax.checkpoint(body_fn) if remat else body_fn

        def body(h, p):
            return fn(p, h, *extras), None

        out, _ = jax.lax.scan(body, x, body_params)
        return out

    mbs = _microbatch(x, M)
    extras_mb = tuple(_microbatch(e, M) for e in extras)
    fn = jax.checkpoint(body_fn) if remat else body_fn
    rotate = [(i, (i + 1) % S) for i in range(S)]

    # CPU only: keep pipe-replicated inputs fp32 at the shard_map boundary —
    # their backward transpose is a psum over ``pipe``, and *bf16* psum trips
    # an XLA-CPU check failure ("invalid binary instruction opcode copy").
    # On TPU bf16 collectives are native; no upcast, no extra HBM traffic.
    x_dtype = x.dtype
    upcast_wire = jax.default_backend() == "cpu"

    def _wire32(t):
        if not upcast_wire:
            return t
        return jax.tree.map(lambda a: a.astype(jnp.float32) if jnp.issubdtype(a.dtype, jnp.floating) else a, t)

    extras_dtypes = jax.tree.map(lambda a: a.dtype, extras_mb)
    mbs = _wire32(mbs)
    extras_mb = _wire32(extras_mb)

    @partial(jax.shard_map,
             mesh=mesh,
             axis_names={PIPE_AXIS},
             in_specs=(P(PIPE_AXIS), P(), P()),
             out_specs=P(),
             check_vma=False)
    def run(params, mbs, extras_mb):
        stage = jax.lax.axis_index(PIPE_AXIS)

        def stage_layers(h, ex):
            def body(h, p):
                return fn(p, h, *ex), None

            h, _ = jax.lax.scan(body, h, params)
            return h

        def tick(carry, t):
            state, state_ex, outputs = carry
            mb_idx = jnp.minimum(t, M - 1)
            x_in = jax.lax.dynamic_index_in_dim(mbs, mb_idx, 0, keepdims=False).astype(x_dtype)
            ex_in = jax.tree.map(lambda a: jax.lax.dynamic_index_in_dim(a, mb_idx, 0, keepdims=False), extras_mb)
            ex_in = jax.tree.map(lambda a, dt: a.astype(dt), ex_in, extras_dtypes)
            first = stage == 0
            state = jnp.where(first, x_in, state)
            state_ex = jax.tree.map(lambda new, old: jnp.where(first, new, old), ex_in, state_ex)
            h = stage_layers(state, state_ex)
            out_idx = t - (S - 1)
            write = jnp.logical_and(stage == S - 1, out_idx >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(outputs, h, jnp.maximum(out_idx, 0), axis=0)
            outputs = jnp.where(write, updated, outputs)
            # rotate activation + its extras to the next stage (the
            # SendActivation/RecvActivation pair, ref: pipe/p2p.py:45)
            state = jax.lax.ppermute(h, PIPE_AXIS, rotate)
            state_ex = jax.tree.map(lambda a: jax.lax.ppermute(a, PIPE_AXIS, rotate), state_ex)
            return (state, state_ex, outputs), None

        zero_state = jnp.zeros(mbs.shape[1:], x_dtype)
        zero_ex = jax.tree.map(lambda a, dt: jnp.zeros(a.shape[1:], dt), extras_mb, extras_dtypes)
        outputs0 = jnp.zeros(mbs.shape, x_dtype)
        (_, _, outputs), _ = jax.lax.scan(tick, (zero_state, zero_ex, outputs0),
                                          jnp.arange(num_pipeline_ticks(M, S)))
        # only the last stage holds real outputs; masked psum broadcasts them
        # to the whole pipe group (the _aggregate_total_loss broadcast analog,
        # ref: pipe/engine.py:584 — generalised to the full activation so the
        # replicated post-stage (norm/head/loss) can run everywhere)
        # fp32 for the wire: bf16 psum trips an XLA-CPU check failure
        # ("invalid binary instruction opcode copy"), and fp32 accumulation
        # is numerically safer on the real reduction anyway
        masked = jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)).astype(jnp.float32)
        return jax.lax.psum(masked, PIPE_AXIS).astype(outputs.dtype)

    return _unmicrobatch(run(body_params, mbs, extras_mb))
