"""SPMD pipeline-parallel executor.

TPU-native replacement for the reference's instruction-driven pipeline
(``deepspeed/runtime/pipe/engine.py:61 PipelineEngine`` executing
``schedule.py:189 TrainSchedule`` with p2p send/recv between stage
processes, ``runtime/pipe/p2p.py``).  There, each rank runs a different
instruction stream (MPMD) and overlap comes from hand-managed buffers and
streams.  Here the whole pipeline is ONE compiled SPMD program:

* block weights are stacked with a leading layer axis sharded over the
  ``pipe`` mesh axis — each pipe device owns ``layers_per_stage`` layers;
* a ``lax.scan`` over "ticks" runs the GPipe schedule: at tick ``t`` stage
  ``s`` computes microbatch ``t - s``; activations rotate stage→stage+1 via
  ``lax.ppermute`` on ICI (the p2p.send/recv analog);
* reverse-mode AD through ``ppermute`` yields the reverse pipeline — the
  backward schedule the reference encodes as SendGrad/RecvGrad instructions
  falls out of the transpose rule;
* the driver loop costs ``M + S - 1`` ticks for M microbatches on S stages,
  i.e. the classic GPipe bubble ``(S-1)/(M+S-1)`` — same pipeline
  efficiency as the reference's 1F1B for equal M (1F1B improves *memory*,
  which remat already bounds here).

The per-microbatch extras (positions, segment ids, ...) travel with the
activation through the rotation, since stage ``s`` needs microbatch
``t - s``'s extras at tick ``t``.
"""

from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...comm.mesh import PIPE_AXIS

# Logical name for the stacked-layer leading axis of pipelined blocks;
# mapped to the ``pipe`` mesh axis by module_inject/tp_rules.py.
STAGE_LAYERS = "stage_layers"


def num_pipeline_ticks(micro_batches: int, stages: int) -> int:
    """Total schedule length (fwd ticks; ref: schedule.py total_steps is
    2*(M+S-1) counting fwd+bwd separately — AD supplies the factor 2)."""
    return micro_batches + stages - 1


def _microbatch(tree, num_micro):
    """[B, ...] → [M, B/M, ...] on every array leaf."""

    def split(x):
        if np.ndim(x) == 0:
            return x
        b = x.shape[0]
        assert b % num_micro == 0, (f"batch dim {b} not divisible by micro_batches={num_micro}")
        return x.reshape((num_micro, b // num_micro) + x.shape[1:])

    return jax.tree.map(split, tree)


def _unmicrobatch(tree):
    def join(x):
        if np.ndim(x) < 2:
            return x
        return x.reshape((x.shape[0] * x.shape[1], ) + x.shape[2:])

    return jax.tree.map(join, tree)


def pipelined_apply(body_fn: Callable,
                    body_params: Any,
                    x: jnp.ndarray,
                    extras: Sequence[Any],
                    *,
                    mesh,
                    num_stages: int,
                    micro_batches: int,
                    remat: bool = True):
    """Run ``x`` through the stacked pipelined blocks.

    Args:
      body_fn: ``(layer_params, h, *extras_mb) -> h`` — applies ONE block.
        Output must have the same shape/dtype as ``h`` (residual stream).
      body_params: pytree whose leaves are stacked ``[L, ...]`` with the
        leading axis sharded over the ``pipe`` mesh axis.
      x: ``[B, ...]`` activations entering the first block.
      extras: per-batch auxiliary inputs (``[B, ...]`` leading dim each,
        e.g. positions/segment_ids) consumed by every block.
      num_stages: pipeline depth S (== mesh.shape['pipe']).
      micro_batches: M — the reference's gradient_accumulation_steps
        (ref: pipe/engine.py micro_batches = gas).
    """
    S, M = num_stages, micro_batches
    if S == 1:
        # degenerate path: plain scan over layers, no pipeline overhead
        fn = jax.checkpoint(body_fn) if remat else body_fn

        def body(h, p):
            return fn(p, h, *extras), None

        out, _ = jax.lax.scan(body, x, body_params)
        return out

    mbs = _microbatch(x, M)
    extras_mb = tuple(_microbatch(e, M) for e in extras)
    fn = jax.checkpoint(body_fn) if remat else body_fn
    rotate = [(i, (i + 1) % S) for i in range(S)]

    # CPU only: keep pipe-replicated inputs fp32 at the shard_map boundary —
    # their backward transpose is a psum over ``pipe``, and *bf16* psum trips
    # an XLA-CPU check failure ("invalid binary instruction opcode copy").
    # On TPU bf16 collectives are native; no upcast, no extra HBM traffic.
    x_dtype = x.dtype
    upcast_wire = jax.default_backend() == "cpu"

    def _wire32(t):
        if not upcast_wire:
            return t
        return jax.tree.map(lambda a: a.astype(jnp.float32) if jnp.issubdtype(a.dtype, jnp.floating) else a, t)

    extras_dtypes = jax.tree.map(lambda a: a.dtype, extras_mb)
    mbs = _wire32(mbs)
    extras_mb = _wire32(extras_mb)

    @partial(jax.shard_map,
             mesh=mesh,
             axis_names={PIPE_AXIS},
             in_specs=(P(PIPE_AXIS), P(), P()),
             out_specs=P(),
             check_vma=False)
    def run(params, mbs, extras_mb):
        stage = jax.lax.axis_index(PIPE_AXIS)

        def stage_layers(h, ex):
            def body(h, p):
                return fn(p, h, *ex), None

            h, _ = jax.lax.scan(body, h, params)
            return h

        def tick(carry, t):
            state, state_ex, outputs = carry
            mb_idx = jnp.minimum(t, M - 1)
            x_in = jax.lax.dynamic_index_in_dim(mbs, mb_idx, 0, keepdims=False).astype(x_dtype)
            ex_in = jax.tree.map(lambda a: jax.lax.dynamic_index_in_dim(a, mb_idx, 0, keepdims=False), extras_mb)
            ex_in = jax.tree.map(lambda a, dt: a.astype(dt), ex_in, extras_dtypes)
            first = stage == 0
            state = jnp.where(first, x_in, state)
            state_ex = jax.tree.map(lambda new, old: jnp.where(first, new, old), ex_in, state_ex)
            h = stage_layers(state, state_ex)
            out_idx = t - (S - 1)
            write = jnp.logical_and(stage == S - 1, out_idx >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(outputs, h, jnp.maximum(out_idx, 0), axis=0)
            outputs = jnp.where(write, updated, outputs)
            # rotate activation + its extras to the next stage (the
            # SendActivation/RecvActivation pair, ref: pipe/p2p.py:45)
            state = jax.lax.ppermute(h, PIPE_AXIS, rotate)
            state_ex = jax.tree.map(lambda a: jax.lax.ppermute(a, PIPE_AXIS, rotate), state_ex)
            return (state, state_ex, outputs), None

        zero_state = jnp.zeros(mbs.shape[1:], x_dtype)
        zero_ex = jax.tree.map(lambda a, dt: jnp.zeros(a.shape[1:], dt), extras_mb, extras_dtypes)
        outputs0 = jnp.zeros(mbs.shape, x_dtype)
        (_, _, outputs), _ = jax.lax.scan(tick, (zero_state, zero_ex, outputs0),
                                          jnp.arange(num_pipeline_ticks(M, S)))
        # only the last stage holds real outputs; masked psum broadcasts them
        # to the whole pipe group (the _aggregate_total_loss broadcast analog,
        # ref: pipe/engine.py:584 — generalised to the full activation so the
        # replicated post-stage (norm/head/loss) can run everywhere)
        # fp32 for the wire: bf16 psum trips an XLA-CPU check failure
        # ("invalid binary instruction opcode copy"), and fp32 accumulation
        # is numerically safer on the real reduction anyway
        masked = jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)).astype(jnp.float32)
        return jax.lax.psum(masked, PIPE_AXIS).astype(outputs.dtype)

    return _unmicrobatch(run(body_params, mbs, extras_mb))
