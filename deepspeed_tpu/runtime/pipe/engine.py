"""PipelineEngine — training engine for PipelineModule models.

Reference: ``deepspeed/runtime/pipe/engine.py:61 PipelineEngine`` — a
1,400-LoC subclass that executes instruction schedules with p2p comms,
pipeline buffers and per-stage optimizers.  Here pipelining happens inside
the compiled train step (the PipelineModule's apply lowers to the
shard_map/ppermute program in pipeline.py), so this subclass only:

* folds ``gradient_accumulation_steps`` into the pipeline's micro-batch
  count (ref: pipe/engine.py:338 ``train_batch`` consumes gas microbatches),
* exposes the stage-query parity surface (``is_first_stage`` …) — in SPMD
  every process participates in every stage, so these reflect the logical
  schedule rather than a rank's position,
* keeps ``forward``/``backward`` blocked like the reference (pipeline
  training must go through ``train_batch``/``eval_batch``,
  ref: pipe/engine.py:1345 _disabled docstrings).
"""

from ...utils.logging import log_dist
from ..engine import DeepSpeedEngine
from .module import PipelineModule
from .schedule import TrainSchedule, bubble_fraction


class PipelineEngine(DeepSpeedEngine):

    def __init__(self, model, config, **kwargs):
        assert isinstance(model, PipelineModule), "PipelineEngine requires a PipelineModule"
        if config.pipeline.stages != model.num_stages:
            # the module is authoritative (ref: PipelineModule carries the
            # topology); re-resolve batch sizing for the new dp degree
            config.pipeline.stages = model.num_stages
            config._configure_train_batch_size()
        # microbatches = gradient accumulation steps (ref: pipe/engine.py:81)
        self.micro_batches = config.gradient_accumulation_steps
        model.micro_batches = self.micro_batches
        # the pipeline consumes the full batch in one compiled call; the
        # outer GAS scan must not re-split it
        config.gradient_accumulation_steps = 1
        super().__init__(model=model, config=config, **kwargs)
        config.gradient_accumulation_steps = self.micro_batches
        self.num_stages = model.num_stages
        log_dist(
            f"PipelineEngine: stages={self.num_stages} micro_batches={self.micro_batches} "
            f"bubble={bubble_fraction(self.micro_batches, self.num_stages):.2%}",
            ranks=[0])

    def _assemble_batch(self, data_iter):
        """Concatenate ``micro_batches`` loader micro-batches into the full
        batch the compiled pipeline consumes (ref: pipe/engine.py train_batch
        and eval_batch both pull gas micro-batches from the iterator)."""
        import jax
        import numpy as np
        micro = [next(data_iter) for _ in range(self.micro_batches)]
        return jax.tree.map(lambda *xs: np.concatenate(xs, axis=0), *micro) \
            if self.micro_batches > 1 else micro[0]

    def train_batch(self, data_iter=None, batch=None):
        """The outer engine runs gas=1; micro-batching happens inside the
        compiled pipeline program."""
        if batch is None:
            assert data_iter is not None, "provide data_iter or batch"
            batch = self._assemble_batch(data_iter)
        return super().train_batch(batch=batch)

    def gradient_accumulation_steps(self):
        return self.micro_batches

    # ------------------------------------------------------- parity queries

    def is_first_stage(self):
        return True  # SPMD: this process computes every stage

    def is_last_stage(self):
        return True

    def is_pipe_parallel(self):
        return self.num_stages > 1

    def num_pipeline_stages(self):
        return self.num_stages

    def train_schedule(self, stage_id: int = 0) -> TrainSchedule:
        """The logical instruction schedule this step executes (for
        inspection/tests; ref: pipe/engine.py _exec_schedule)."""
        return TrainSchedule(micro_batches=self.micro_batches, stages=self.num_stages, stage_id=stage_id)

    # the reference blocks these for pipeline engines (pipe/engine.py:1345)
    def forward(self, *args, **kwargs):
        raise RuntimeError("Only train_batch() / eval_batch() are accessible when using pipeline parallelism "
                           "(parity with reference PipelineEngine).")

    def backward(self, *args, **kwargs):
        raise RuntimeError("Only train_batch() / eval_batch() are accessible when using pipeline parallelism "
                           "(parity with reference PipelineEngine).")

    def step(self, *args, **kwargs):
        raise RuntimeError("Only train_batch() / eval_batch() are accessible when using pipeline parallelism "
                           "(parity with reference PipelineEngine).")

    def eval_batch(self, data_iter=None, batch=None):
        """Forward-only over the pipeline (InferenceSchedule semantics).
        Pulls ``micro_batches`` micro-batches like train_batch — the compiled
        pipeline splits its input batch by the same factor."""
        if batch is None:
            batch = self._assemble_batch(data_iter)
        self._ensure_ready(batch)
        return self._build_eval_fn()(self.state, batch)
