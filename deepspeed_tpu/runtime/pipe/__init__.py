from .engine import PipelineEngine  # noqa: F401
from .module import LayerSpec, PipelineError, PipelineModule, TiedLayerSpec  # noqa: F401
from .pipeline import pipelined_apply  # noqa: F401
from .schedule import InferenceSchedule, TrainSchedule  # noqa: F401
from .topology import PipeDataParallelTopology, PipelineParallelGrid, ProcessTopology  # noqa: F401
