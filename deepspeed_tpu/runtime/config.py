"""DeepSpeed-style JSON config → typed config tree.

TPU-native analog of ``deepspeed/runtime/config.py`` (``DeepSpeedConfig``)
plus the feature sub-configs that live next to their subsystems in the
reference (``runtime/zero/config.py``, ``runtime/fp16``, ``monitor/config.py``,
``profiling/config.py``, ``comm/config.py``, ``runtime/activation_checkpointing
/checkpointing.py:1029``).  The JSON key surface mirrors the reference so a
DeepSpeed user's ``ds_config.json`` parses unchanged; values that only make
sense on CUDA (e.g. ``overlap_comm`` stream knobs) are accepted and recorded
but have no effect — XLA's latency-hiding scheduler owns overlap on TPU.
"""

import json
import os
from enum import Enum
from typing import Any, Dict, List, Optional, Union

from pydantic import Field, model_validator

from ..utils.logging import logger
from .config_utils import DeepSpeedConfigModel, dict_raise_error_on_duplicate_keys
from .constants import *  # noqa: F401,F403


class DtypeEnum(str, Enum):
    fp32 = "fp32"
    fp16 = "fp16"
    bf16 = "bf16"
    fp8 = "fp8"
    int8 = "int8"


def _to_jnp_dtype(d):
    import jax.numpy as jnp
    return {
        DtypeEnum.fp32: jnp.float32,
        DtypeEnum.fp16: jnp.float16,
        DtypeEnum.bf16: jnp.bfloat16,
        DtypeEnum.int8: jnp.int8,
    }[DtypeEnum(d)]


#############################################
# Precision
#############################################


class FP16Config(DeepSpeedConfigModel):
    """ref: runtime/config.py get_fp16_* readers + runtime/fp16/loss_scaler.py."""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0
    fp16_master_weights_and_grads: bool = False


class BF16Config(DeepSpeedConfigModel):
    """ref: runtime/config.py get_bfloat16_enabled; bf16 is the TPU default."""
    enabled: bool = False
    immediate_grad_update: bool = True


class TorchAutocastConfig(DeepSpeedConfigModel):
    enabled: bool = False
    dtype: Optional[str] = None
    lower_precision_safe_modules: Optional[List[str]] = None


class DataTypesConfig(DeepSpeedConfigModel):
    grad_accum_dtype: Optional[DtypeEnum] = None


#############################################
# ZeRO
#############################################


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """ref: runtime/zero/offload_config.py OffloadParamConfig."""
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(100_000_000, ge=0)
    max_in_cpu: int = Field(1_000_000_000, ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    """ref: runtime/zero/offload_config.py OffloadOptimizerConfig."""
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = Field(1.0, ge=0.0, le=1.0)


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    """ZeRO knobs (ref: runtime/zero/config.py DeepSpeedZeroConfig).

    On TPU the stages are realised as sharding policies over the combined
    data-parallel mesh axes (see runtime/zero/partition.py) rather than
    hook-driven gather/release, so several CUDA-era knobs (overlap_comm,
    bucket sizes) are accepted for compatibility and used only as hints.
    """
    stage: int = Field(0, ge=0, le=3)
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(500_000_000, ge=0)
    use_multi_rank_bucket_allreduce: bool = True
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(500_000_000, ge=0)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = Field(1_000_000_000, ge=0)
    cpu_offload_param: Optional[bool] = Field(None, json_schema_extra={"deprecated": True})
    cpu_offload_use_pin_memory: Optional[bool] = Field(None, json_schema_extra={"deprecated": True})
    cpu_offload: Optional[bool] = Field(None, json_schema_extra={"deprecated": True})
    prefetch_bucket_size: int = Field(50_000_000, ge=0, alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(100_000, ge=0, alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(int(1e30), ge=0, alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(1_000_000_000, ge=0, alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(1_000_000_000, ge=0, alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(False, alias="stage3_gather_16bit_weights_on_model_save")
    module_granularity_threshold: int = Field(0, alias="stage3_module_granularity_threshold")
    use_all_reduce_for_fetch_params: bool = Field(False, alias="stage3_use_all_reduce_for_fetch_params")
    stage3_gather_fp16_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    zero_hpz_partition_size: int = Field(1, ge=0)
    zero_quantized_weights: bool = False
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False
    zeropp_loco_param: Optional[Dict[str, Any]] = None
    mics_shard_size: int = Field(-1)
    mics_hierarchical_params_gather: bool = False
    memory_efficient_linear: bool = True
    pipeline_loading_checkpoint: bool = False
    override_module_apply: bool = True
    log_trace_cache_warnings: bool = False

    @model_validator(mode="after")
    def offload_ratio_check(self):
        offload_config = self.offload_optimizer
        if offload_config and offload_config.ratio < 1.0 and self.stage != 3:
            raise ValueError("Partial offloading only supported for ZeRO Stage 3.")
        return self


#############################################
# Optimizer / scheduler
#############################################


class OptimizerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = {}
    legacy_fusion: bool = False


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = {}


#############################################
# Aux feature blocks
#############################################


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """ref: runtime/activation_checkpointing/checkpointing.py:1029.

    ``partition_activations`` maps to sharding the remat residuals over the
    tensor axis; cpu_checkpointing maps to a host-offload remat policy.
    """
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


class FlopsProfilerConfig(DeepSpeedConfigModel):
    """ref: profiling/config.py."""
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class CommsLoggerConfig(DeepSpeedConfigModel):
    """ref: comm/config.py DeepSpeedCommsConfig."""
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = []


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class CometConfig(DeepSpeedConfigModel):
    enabled: bool = False
    samples_log_interval: int = 100
    project: Optional[str] = None
    workspace: Optional[str] = None
    api_key: Optional[str] = None
    experiment_name: Optional[str] = None
    experiment_key: Optional[str] = None
    online: Optional[bool] = None
    mode: Optional[str] = None


class DeepSpeedMonitorConfig(DeepSpeedConfigModel):
    """ref: monitor/config.py DeepSpeedMonitorConfig."""
    tensorboard: TensorBoardConfig = TensorBoardConfig()
    comet: CometConfig = CometConfig()
    wandb: WandbConfig = WandbConfig()
    csv_monitor: CSVConfig = CSVConfig()
    # MonitorMaster caps total buffered/forwarded events at this count and
    # drops the rest (counted in ``monitor/dropped_events``).  Fleet sims
    # emit an order of magnitude more events than a single engine; an
    # unbounded CSV/TB stream would grow without limit.  0 = unbounded.
    max_events: int = 0


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = {}
    writer: Optional[Dict[str, Any]] = None
    # pluggable engine: "orbax" sync / "async"-"nebula" background stream
    checkpoint_engine: str = "orbax"
    # keep-last-K retention: prune oldest (and invalid/torn) tags after each
    # successful publish; None/0 keeps everything (docs/RESILIENCE.md)
    keep_last_n: Optional[int] = None
    # crc32-verify the WHOLE tag (orbax state tree included) before restore;
    # detection of silent state rot costs one extra read of the checkpoint —
    # very large deployments may opt out and keep manifest checks for
    # metadata/npz only (docs/RESILIENCE.md durability contract)
    verify_checksums_on_load: bool = True

    @model_validator(mode="after")
    def _check_tag(self):
        if str(self.tag_validation).capitalize() not in CHECKPOINT_TAG_VALIDATION_MODES:
            raise ValueError(f"tag_validation must be one of {CHECKPOINT_TAG_VALIDATION_MODES}")
        return self


class AIOConfig(DeepSpeedConfigModel):
    """ref: runtime/swap_tensor/aio_config.py."""
    block_size: int = 1048576
    queue_depth: int = 8
    intra_op_parallelism: int = 1
    single_submit: bool = False
    overlap_events: bool = True
    use_gds: bool = False


class TensorParallelConfig(DeepSpeedConfigModel):
    """ref: runtime/tensor_parallel/config.py TPTrainingConfig (autotp_size)."""
    autotp_size: int = Field(1, ge=1)
    tensor_parallel: Dict[str, Any] = {}
    injection_policy_tuple: Optional[Any] = None
    tp_grain_size: int = 1


class HybridEngineConfig(DeepSpeedConfigModel):
    """RLHF train+generate engine block (ref: runtime/config.py:548)."""
    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8


class PipelineConfig(DeepSpeedConfigModel):
    """Pipeline engine knobs (ref: runtime/pipe/module.py + engine)."""
    stages: int = Field(1, ge=1)
    partition_method: str = "parameters"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    pipe_partitioned: bool = True
    grad_partitioned: bool = True
    use_reentrant: bool = False
    micro_batches_per_stage: Optional[int] = None


class MoEConfig(DeepSpeedConfigModel):
    """Expert-parallel sizing; in the reference EP degree comes from the MoE
    layer (deepspeed/moe/layer.py) — here it also shapes the mesh."""
    enabled: bool = False
    expert_parallel_size: int = Field(1, ge=1)
    num_experts: int = 1
    top_k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    drop_tokens: bool = True
    use_rts: bool = True
    noisy_gate_policy: Optional[str] = None


class ElasticityConfig(DeepSpeedConfigModel):
    """ref: elasticity/config.py (v0.1/0.2 compatible-batch-size search)."""
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = [2, 4, 6]
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True


class CompressionConfig(DeepSpeedConfigModel):
    """Compression-training block; scheduling handled by compression/ module."""
    weight_quantization: Dict[str, Any] = {}
    activation_quantization: Dict[str, Any] = {}
    sparse_pruning: Dict[str, Any] = {}
    row_pruning: Dict[str, Any] = {}
    head_pruning: Dict[str, Any] = {}
    channel_pruning: Dict[str, Any] = {}
    layer_reduction: Dict[str, Any] = {}


#############################################
# Top-level config
#############################################


class DeepSpeedConfigError(Exception):
    pass


class DeepSpeedConfig:
    """Parse + validate the full training config.

    Mirrors ``deepspeed/runtime/config.py DeepSpeedConfig``: resolves the
    (train_batch_size, micro_batch_per_device, gradient_accumulation_steps)
    triad against the data-parallel world size, instantiates every feature
    sub-config, and exposes flat attributes the engine reads.
    """

    def __init__(self, config: Union[str, Dict], mpu=None, mesh_device=None, dp_world_size: Optional[int] = None):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(f"Expected a valid json file path, got {config}")
            with open(config) as f:
                self._param_dict = json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        else:
            raise DeepSpeedConfigError(f"Expected a string path or dict, got: {type(config)}")

        pd = self._param_dict
        self.mpu = mpu
        self.mesh_device = mesh_device

        # ---- parallel degrees (shape the mesh; resolved before batch sizes)
        tp_block = pd.get(TENSOR_PARALLEL, {})
        self.tensor_parallel_config = TensorParallelConfig(**tp_block) if isinstance(tp_block, dict) \
            else TensorParallelConfig()
        self.sequence_parallel_size = pd.get(SEQUENCE_PARALLEL_SIZE, 1)
        self.pipeline = PipelineConfig(**pd.get(PIPELINE, {}))
        self.moe = MoEConfig(**pd.get(MOE, {}))

        # ---- feature blocks
        self.zero_config = DeepSpeedZeroConfig(**pd.get(ZERO_OPTIMIZATION, {}))
        self.fp16_config = FP16Config(**pd.get(FP16, {}))
        bf16_block = pd.get(BFLOAT16, pd.get(BFLOAT16_OLD, {}))
        self.bf16_config = BF16Config(**bf16_block)
        self.torch_autocast = TorchAutocastConfig(**pd.get(TORCH_AUTOCAST, {}))
        self.data_types = DataTypesConfig(**pd.get(DATA_TYPES, {}))
        self.optimizer_config = OptimizerConfig(**pd[OPTIMIZER]) if OPTIMIZER in pd else None
        self.scheduler_config = SchedulerConfig(**pd[SCHEDULER]) if SCHEDULER in pd else None
        self.activation_checkpointing_config = ActivationCheckpointingConfig(**pd.get(ACTIVATION_CHECKPOINTING, {}))
        self.flops_profiler_config = FlopsProfilerConfig(**pd.get(FLOPS_PROFILER, {}))
        self.comms_config = CommsLoggerConfig(**pd.get(COMMS_LOGGER, {}))
        self.monitor_config = DeepSpeedMonitorConfig(
            tensorboard=TensorBoardConfig(**pd.get(TENSORBOARD, {})),
            wandb=WandbConfig(**pd.get(WANDB, {})),
            csv_monitor=CSVConfig(**pd.get(CSV_MONITOR, {})),
            comet=CometConfig(**pd.get(COMET, {})),
        )
        self.checkpoint_config = CheckpointConfig(**pd.get(CHECKPOINT, {}))
        self.hybrid_engine = HybridEngineConfig(**pd.get("hybrid_engine", {}))
        self.aio_config = AIOConfig(**pd.get(AIO, {}))
        self.elasticity_config = ElasticityConfig(**pd.get(ELASTICITY, {}))
        self.compression_config = CompressionConfig(**pd.get(COMPRESSION_TRAINING, {}))
        self.data_efficiency_config = pd.get(DATA_EFFICIENCY, {})
        self.curriculum_learning_legacy = pd.get(CURRICULUM_LEARNING_LEGACY, {})

        # ---- scalars
        self.gradient_clipping = pd.get(GRADIENT_CLIPPING, GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients = pd.get(PRESCALE_GRADIENTS, PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = pd.get(GRADIENT_PREDIVIDE_FACTOR, GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = pd.get(SPARSE_GRADIENTS, SPARSE_GRADIENTS_DEFAULT)
        self.communication_data_type = pd.get(COMMUNICATION_DATA_TYPE, COMMUNICATION_DATA_TYPE_DEFAULT)
        self.seq_parallel_communication_data_type = pd.get(SEQ_PARALLEL_COMMUNICATION_DATA_TYPE,
                                                           SEQ_PARALLEL_COMMUNICATION_DATA_TYPE_DEFAULT)
        self.steps_per_print = pd.get(STEPS_PER_PRINT, STEPS_PER_PRINT_DEFAULT)
        self.wall_clock_breakdown = pd.get(WALL_CLOCK_BREAKDOWN, WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = pd.get(MEMORY_BREAKDOWN, MEMORY_BREAKDOWN_DEFAULT)
        self.dump_state = pd.get(DUMP_STATE, DUMP_STATE_DEFAULT)
        self.disable_allgather = pd.get(DISABLE_ALLGATHER, DISABLE_ALLGATHER_DEFAULT)
        self.zero_allow_untested_optimizer = pd.get(ZERO_ALLOW_UNTESTED_OPTIMIZER,
                                                    ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)
        self.graph_harvesting = pd.get(GRAPH_HARVESTING, GRAPH_HARVESTING_DEFAULT)
        self.eigenvalue_config = pd.get(EIGENVALUE, {})
        self.sparse_attention = pd.get(SPARSE_ATTENTION, None)
        self.autotuning_config = pd.get(AUTOTUNING, {})

        # ---- batch-size triad
        self.train_batch_size = pd.get(TRAIN_BATCH_SIZE, TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = pd.get(TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                                                     TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = pd.get(GRADIENT_ACCUMULATION_STEPS, GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self._dp_world_size_hint = dp_world_size
        self._configure_train_batch_size()

        self._do_sanity_check()

    # -- batch sizing (ref: runtime/config.py _configure_train_batch_size) ----

    def _resolve_dp_world_size(self):
        if self._dp_world_size_hint is not None:
            return self._dp_world_size_hint
        try:
            import jax
            world = jax.device_count()
        except Exception:
            world = 1
        denom = (self.pipeline.stages * self.tensor_parallel_config.autotp_size * self.sequence_parallel_size)
        return max(1, world // max(1, denom))

    def _configure_train_batch_size(self):
        dp = self._resolve_dp_world_size()
        self.dp_world_size_at_config = dp
        tb, mb, gas = self.train_batch_size, self.train_micro_batch_size_per_gpu, self.gradient_accumulation_steps

        if all(x is None for x in (tb, mb, gas)):
            raise DeepSpeedConfigError(
                "At least one of train_batch_size, train_micro_batch_size_per_gpu, "
                "gradient_accumulation_steps must be set")
        if tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp:
                raise DeepSpeedConfigError(
                    f"Check batch related parameters. train_batch_size is not equal to micro_batch_per_gpu * "
                    f"gradient_acc_step * world_size {tb} != {mb} * {gas} * {dp}")
        elif tb is not None and mb is not None:
            gas = tb // (mb * dp)
            if gas * mb * dp != tb:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} not divisible by micro_batch {mb} * dp {dp}")
        elif tb is not None and gas is not None:
            mb = tb // (gas * dp)
            if mb * gas * dp != tb:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} not divisible by gas {gas} * dp {dp}")
        elif tb is not None:
            gas = 1
            mb = tb // dp
            if mb * dp != tb:
                raise DeepSpeedConfigError(f"train_batch_size {tb} not divisible by dp {dp}")
        elif mb is not None:
            gas = gas if gas is not None else 1
            tb = mb * gas * dp
        else:  # only gas
            raise DeepSpeedConfigError(
                "gradient_accumulation_steps alone is insufficient; also set micro or global batch size")

        self.train_batch_size = tb
        self.train_micro_batch_size_per_gpu = mb
        self.gradient_accumulation_steps = gas

    def _do_sanity_check(self):
        if self.fp16_config.enabled and self.bf16_config.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 modes cannot both be enabled")
        if self.zero_config.stage > 0 and self.optimizer_config is None:
            logger.debug("ZeRO enabled with client/default optimizer")

    # -- convenience ----------------------------------------------------------

    @property
    def zero_enabled(self):
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self):
        return self.zero_config.stage

    @property
    def precision_dtype(self):
        """Compute dtype for params/activations."""
        import jax.numpy as jnp
        if self.fp16_config.enabled:
            return jnp.float16
        if self.bf16_config.enabled:
            return jnp.bfloat16
        return jnp.float32

    def print_user_config(self):
        logger.info("  json = {}".format(json.dumps(self._param_dict, sort_keys=True, indent=4, default=repr)))

    def print(self, name):
        logger.info("{}:".format(name))
        for arg in sorted(vars(self)):
            if arg != "_param_dict":
                logger.info("  {} {} {}".format(arg, "." * (29 - len(arg)), getattr(self, arg)))
        self.print_user_config()
