"""Runtime utilities.

ref: deepspeed/runtime/utils.py (~1,100 LoC): flatten/unflatten,
clip_grad_norm_, get_global_norm, see_memory_usage, partition helpers.
The math lives in jnp; memory introspection reads the JAX device stats.
"""

import gc
import math
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.optimizer import clip_by_global_norm, global_norm  # re-export  # noqa: F401
from ..utils.logging import log_dist, logger


def flatten_dense_tensors(tensors: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """ref: csrc/utils/flatten_unflatten.cpp (torch _flatten_dense_tensors);
    jnp concatenation — XLA fuses it away inside jit."""
    return jnp.concatenate([jnp.ravel(t) for t in tensors]) if tensors else jnp.zeros((0, ))


def unflatten_dense_tensors(flat: jnp.ndarray, tensors: Sequence[jnp.ndarray]) -> List[jnp.ndarray]:
    """Inverse of flatten_dense_tensors, shaped like ``tensors``."""
    outs, off = [], 0
    for t in tensors:
        n = int(np.prod(t.shape)) if t.shape else 1
        outs.append(jax.lax.dynamic_slice_in_dim(flat, off, n).reshape(t.shape))
        off += n
    return outs


def get_global_norm(norm_list: Sequence[float]) -> float:
    """ref: runtime/utils.py get_global_norm — combine per-group norms."""
    return math.sqrt(sum(n**2 for n in norm_list))


def clip_grad_norm_(gradients, max_norm: float, mpu=None, norm_type: int = 2):
    """Functional clip-by-global-norm (ref: runtime/utils.py
    clip_grad_norm_ — which psums the squared norm over model-parallel
    ranks; under pjit the norm is computed on global logical arrays, so the
    cross-rank reduction is implicit).  Returns (clipped, total_norm)."""
    if norm_type != 2:
        raise NotImplementedError("only L2 clipping is supported (parity: reference default)")
    clipped, norm = clip_by_global_norm(gradients, max_norm)
    return clipped, float(norm)


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """ref: runtime/utils.py partition_uniform — boundaries [p0..pN]."""
    parts = [0] * (num_parts + 1)
    chunk, residual = divmod(num_items, num_parts)
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < residual else 0)
    return parts


def prefix_sum_inc(weights: Sequence[float]) -> List[float]:
    out, acc = [], 0.0
    for w in weights:
        acc += w
        out.append(acc)
    return out


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Weighted balanced partition via binary search over bottleneck cost
    (ref: runtime/utils.py partition_balanced)."""
    n = len(weights)
    if num_parts >= n:
        return partition_uniform(n, num_parts)
    prefix = [0.0] + prefix_sum_inc(weights)

    def parts_needed(cap):
        parts, start = 0, 0
        while start < n:
            # furthest end with sum <= cap
            end = start
            while end < n and prefix[end + 1] - prefix[start] <= cap:
                end += 1
            if end == start:
                return float("inf")
            parts += 1
            start = end
        return parts

    lo = max(weights)
    hi = prefix[-1]
    for _ in range(64):
        mid = (lo + hi) / 2
        if parts_needed(mid) <= num_parts:
            hi = mid
        else:
            lo = mid
    # materialize boundaries at capacity hi
    bounds, start = [0], 0
    for _ in range(num_parts):
        end = start
        while end < n and prefix[end + 1] - prefix[start] <= hi:
            end += 1
        bounds.append(end)
        start = end
    bounds[-1] = n
    return bounds


def see_memory_usage(message: str, force: bool = False, ranks=(0, )):
    """Log live device + host memory (ref: runtime/utils.py
    see_memory_usage — MA/CA/psutil lines)."""
    if not force:
        return
    lines = [message]
    for d in jax.local_devices():
        try:
            stats = d.memory_stats() or {}
            in_use = stats.get("bytes_in_use", 0) / 2**30
            peak = stats.get("peak_bytes_in_use", 0) / 2**30
            limit = stats.get("bytes_limit", 0) / 2**30
            lines.append(f"  {d}: in_use {in_use:.2f} GB | peak {peak:.2f} GB | limit {limit:.2f} GB")
        except Exception:
            lines.append(f"  {d}: memory stats unavailable")
    try:
        import psutil
        vm = psutil.virtual_memory()
        lines.append(f"  CPU Virtual Memory: used = {vm.used / 2**30:.2f} GB, percent = {vm.percent}%")
    except Exception:
        pass
    log_dist("\n".join(lines), ranks=list(ranks))
    gc.collect()


def call_to_str(base: str, *args, **kwargs) -> str:
    """ref: runtime/utils.py call_to_str — debug formatting."""
    name = f"{base}("
    if args:
        name += ", ".join(repr(a) for a in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{k}={v!r}" for k, v in kwargs.items())
    return name + ")"


def empty_cache():
    """ref: accelerator empty_cache — jax analog frees donated buffers."""
    gc.collect()
    try:
        jax.clear_caches()
    except Exception:
        pass
