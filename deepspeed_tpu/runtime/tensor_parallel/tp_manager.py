"""AutoTP *training* manager.

ref: runtime/tensor_parallel/tp_manager.py:12 TpTrainingManager +
tensor_parallel/config.py:38 TPTrainingConfig, engine hook
engine.py:431 _configure_tensor_parallel.

The reference walks a torch module, slices Linear weights across TP ranks
and wraps rows/cols with allreduce layers so an HF model *trains* tensor-
parallel.  Here the same outcome is a sharding plan: given the flax params
tree, the manager classifies each kernel as column-parallel (output dim
sharded), row-parallel (input dim sharded, GSPMD inserts the allreduce) or
replicated, by the module-name heuristics AutoTP uses
(ref: module_inject/auto_tp.py:193 tp_parser — attention out-proj and MLP
down-proj are row-parallel, everything else wide is column-parallel).
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...comm.mesh import TENSOR_AXIS
from ...utils.logging import log_dist

# module-name suffixes that are ROW-parallel (contraction dim sharded →
# forward ends in the TP allreduce) — the reference's auto_tp "allreduce
# linears" list
ROW_PARALLEL_PATTERNS = ("o_proj", "out_proj", "down_proj", "dense_4h_to_h", "attention.dense", "fc2", "wo")


@dataclass
class TPTrainingConfig:
    """ref: tensor_parallel/config.py:38."""
    autotp_size: int = 1
    tensor_parallel: Optional[Dict] = None
    injection_policy_tuple: Optional[Tuple] = None
    keep_module_on_host: bool = False
    tp_grain_size: int = 1


class TpTrainingManager:
    """ref: tp_manager.py:12 — builds and applies the TP sharding plan."""

    def __init__(self, model=None, tp_size: int = 1, dtype=None, config: Optional[TPTrainingConfig] = None):
        self.module = model
        self.tp_size = config.autotp_size if config and config.autotp_size > 1 else tp_size
        self.config = config or TPTrainingConfig(autotp_size=self.tp_size)

    def plan(self, abs_params, mesh: Mesh) -> Dict[str, P]:
        """path → PartitionSpec for every kernel leaf."""
        tp = mesh.shape.get(TENSOR_AXIS, 1)
        out: Dict[str, P] = {}

        import re

        def is_row(path):
            # word-boundary match on dotted segments ('wo' must not hit
            # 'word_embeddings')
            return any(re.search(rf"(^|\.){re.escape(p)}(\.|$)", path) for p in ROW_PARALLEL_PATTERNS)

        def walk(tree, prefix=()):
            if isinstance(tree, dict):
                for k, v in tree.items():
                    walk(v, prefix + (str(k), ))
                return
            path = ".".join(prefix)
            shape = tree.shape if hasattr(tree, "shape") else ()
            # scan-over-layers trees stack a leading layer axis — never
            # shard it (converted HF trees: q_proj [L,E,H,D], o_proj [L,H,D,E])
            stacked = "layers" in prefix and len(shape) >= 3
            base = 1 if stacked else 0
            eff = shape[base:]
            if tp <= 1 or len(eff) < 2:
                out[path] = P()
            elif is_row(path):
                # row-parallel: shard the first contraction dim (heads for
                # [H, D, E]-style attention-out kernels)
                spec = [None] * len(shape)
                if eff[0] % tp == 0:
                    spec[base] = TENSOR_AXIS
                out[path] = P(*spec)
            else:
                # column-parallel: shard the output heads dim for
                # [E, H, D]-style kernels, else the last dim
                spec = [None] * len(shape)
                tgt = base + 1 if len(eff) >= 3 else len(shape) - 1
                if shape[tgt] % tp == 0:
                    spec[tgt] = TENSOR_AXIS
                elif shape[-1] % tp == 0:
                    spec[-1] = TENSOR_AXIS
                out[path] = P(*spec)

        walk(abs_params)
        n_row = sum(1 for s in out.values() if s and s[0] == TENSOR_AXIS)
        log_dist(f"TpTrainingManager: tp={tp}, {n_row} row-parallel / "
                 f"{len(out) - n_row} col-or-replicated params", ranks=[0])
        return out

    def shardings(self, abs_params, mesh: Mesh):
        import jax
        plan = self.plan(abs_params, mesh)

        def to_sh(tree, prefix=()):
            if isinstance(tree, dict):
                return {k: to_sh(v, prefix + (str(k), )) for k, v in tree.items()}
            return NamedSharding(mesh, plan[".".join(prefix)])

        return to_sh(abs_params)
