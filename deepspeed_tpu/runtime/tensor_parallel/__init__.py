"""Tensor-parallel training manager (ref: deepspeed/runtime/tensor_parallel/)."""

from .tp_manager import TpTrainingManager, TPTrainingConfig
