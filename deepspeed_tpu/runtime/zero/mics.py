"""MiCS (Minimize Communication Scale) + hpZ hierarchical partitioning.

ref: runtime/zero/mics.py (MiCS_Optimizer, MiCS_Init) and
partition_parameters.py:1673 _partition_param_sec (ZeRO++ hpZ).

Both features answer the same question — "shard over how many ranks?" —
because all-gathering a ZeRO-3 param from every rank crosses slow links.
* MiCS: shard params+grads+optimizer within a sub-group of ``shard_size``
  ranks, replicate across sub-groups; all-gathers stay inside the group.
* hpZ (ZeRO++): keep optimizer/grad sharding global, but hold a SECONDARY
  param partition within the node so backward all-gathers are intra-node.

On a TPU mesh this maps to *which mesh axes the ZeRO sharding uses*.  Mesh
axes are ordered outer→inner with inner axes ICI-adjacent (comm/mesh.py), so
a sub-group of size N = the product of the innermost DP axes: sharding over
those axes makes GSPMD emit all-gathers that ride ICI, replication across
the remaining outer axes (DCN in multi-pod) — exactly the MiCS/hpZ
communication pattern, with zero bookkeeping.
"""

from typing import Tuple

from jax.sharding import Mesh

from ...comm.mesh import ZERO_AXES
from ...utils.logging import log_dist


def mics_zero_axes(mesh: Mesh, shard_size: int, zero_axes=ZERO_AXES) -> Tuple[str, ...]:
    """Innermost subset of the active ZeRO axes whose product equals
    ``shard_size`` (the MiCS sub-group / hpZ secondary-partition size)."""
    active = [a for a in zero_axes if mesh.shape.get(a, 1) > 1]
    total = 1
    for a in active:
        total *= mesh.shape[a]
    if shard_size >= total:
        return tuple(active)
    chosen = []
    acc = 1
    for a in reversed(active):  # innermost first
        if acc == shard_size:
            break
        acc *= mesh.shape[a]
        chosen.append(a)
    if acc != shard_size:
        raise ValueError(
            f"mics/hpz shard size {shard_size} must equal the product of innermost "
            f"data-parallel mesh axes; available suffix products from {dict(mesh.shape)}: "
            f"{_suffix_products(mesh, active)}")
    return tuple(reversed(chosen))


def _suffix_products(mesh, active):
    out, acc = [], 1
    for a in reversed(active):
        acc *= mesh.shape[a]
        out.append(acc)
    return out


def resolve_partition_axes(mesh: Mesh, zero_config, zero_stage: int):
    """(param_axes, state_axes) for the configured stage + MiCS/hpZ knobs.

    * mics_shard_size>0 (ref: mics.py MiCS_Init(shard_size)): everything
      shards within the sub-group.
    * zero_hpz_partition_size>1 (ref: DeepSpeedZeroConfig.zero_hpz_partition_size):
      params use the secondary (intra-node) partition; optimizer/grads stay
      on the full DP axes.
    """
    param_axes = state_axes = ZERO_AXES
    mics = getattr(zero_config, "mics_shard_size", -1) or -1
    hpz = getattr(zero_config, "zero_hpz_partition_size", 1) or 1
    if zero_stage == 3 and mics > 0:
        param_axes = state_axes = mics_zero_axes(mesh, mics)
        log_dist(f"MiCS: sharding over axes {param_axes} (shard_size={mics})", ranks=[0])
    elif zero_stage == 3 and hpz > 1:
        param_axes = mics_zero_axes(mesh, hpz)
        log_dist(f"ZeRO++ hpZ: secondary param partition over {param_axes} "
                 f"(partition_size={hpz})", ranks=[0])
    return param_axes, state_axes


class MiCS_Init:
    """API-parity context manager (ref: mics.py MiCS_Init).  Partitioned
    construction on TPU happens via jit out_shardings at first use; this
    context simply carries the config for symmetry with the reference."""

    def __init__(self, module=None, data_parallel_group=None, mem_efficient_linear=True,
                 remote_device=None, pin_memory=False, config_dict_or_path=None,
                 config=None, enabled=True, dtype=None, mpu=None):
        self.enabled = enabled

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
