"""ZeRO as sharding policies (ref: deepspeed/runtime/zero/)."""

from .mics import MiCS_Init, mics_zero_axes, resolve_partition_axes
from .partition_parameters import GatheredParameters, Init
from .partition import (estimate_partitioned_bytes, grad_shardings, master_and_optstate_shardings,
                        zero_shard_spec)
from .tiling import TiledLinear, copy_params_from_dense
