"""TiledLinear — split a large linear into tiles to bound live memory.

ref: runtime/zero/tiling.py (TiledLinear / TiledLinearReturnBias): splits a
Linear into in_splits × out_splits sub-linears so ZeRO-3 gathers one tile at
a time instead of the whole weight.  TPU-native: tiles are the leading axes
of ONE stacked param [in_splits, out_splits, in/i, out/o]; the contraction
runs as a lax.scan over input tiles, so XLA keeps at most one gathered
tile slab live at a time (remat-friendly), and each tile matmul is still a
dense MXU op.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


class TiledLinear(nn.Module):
    """y = x @ W + b computed tile-by-tile (ref: tiling.py TiledLinear)."""
    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        assert in_dim % self.in_splits == 0, f"in_dim {in_dim} % in_splits {self.in_splits}"
        assert self.features % self.out_splits == 0, f"features {self.features} % out_splits {self.out_splits}"
        ti, to = in_dim // self.in_splits, self.features // self.out_splits

        # one stacked param; per-(i,j) tiles initialized independently like
        # the reference's sub-linears (fan-in of a tile, matching its copy)
        def init(rng, shape, dtype):
            rngs = jax.random.split(rng, self.in_splits * self.out_splits)
            tiles = [self.kernel_init(r, (ti, to), dtype) for r in rngs]
            return jnp.stack(tiles).reshape(self.in_splits, self.out_splits, ti, to)

        w = self.param("kernel", init, (self.in_splits, self.out_splits, ti, to), self.dtype)

        xt = x.reshape(x.shape[:-1] + (self.in_splits, ti))

        def body(acc, i):
            # one input tile against all its output tiles: [*, ti] @ [O, ti, to]
            xi = jnp.take(xt, i, axis=-2)
            wi = jax.lax.dynamic_index_in_dim(w, i, axis=0, keepdims=False)  # [O, ti, to]
            contrib = jnp.einsum("...i,oij->...oj", xi, wi)
            return acc + contrib, None

        acc0 = jnp.zeros(x.shape[:-1] + (self.out_splits, to), self.dtype)
        acc, _ = jax.lax.scan(body, acc0, jnp.arange(self.in_splits))
        y = acc.reshape(x.shape[:-1] + (self.features, ))
        if self.use_bias:
            b = self.param("bias", self.bias_init, (self.features, ), self.dtype)
            y = y + b
        return y


def copy_params_from_dense(tiled_params, dense_kernel, dense_bias=None):
    """Load a dense (in, out) kernel into the tiled layout (ref:
    tiling.py TiledLinear.copy_params_from)."""
    in_splits, out_splits, ti, to = tiled_params["kernel"].shape
    w = jnp.asarray(dense_kernel).reshape(in_splits, ti, out_splits, to).transpose(0, 2, 1, 3)
    out = dict(tiled_params)
    out["kernel"] = w.astype(tiled_params["kernel"].dtype)
    if dense_bias is not None and "bias" in tiled_params:
        out["bias"] = jnp.asarray(dense_bias).astype(tiled_params["bias"].dtype)
    return out
