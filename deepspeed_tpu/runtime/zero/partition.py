"""ZeRO stages as sharding policies.

This module replaces the gradient/optimizer partitioning machinery of the
reference (``runtime/zero/stage_1_and_2.py:98 DeepSpeedZeroOptimizer`` — IPG
buckets, round-robin partitioning, ``average_tensor:1057`` reduce-scatter —
and ``runtime/zero/stage3.py`` optimizer sub-groups) with declarative
shardings over the combined data-parallel mesh axes:

  stage 0 — params, grads, optimizer state replicated over DP; grads are
            psum'd by GSPMD (the bucketed-allreduce path,
            ref: runtime/engine.py:2547 allreduce_bucket).
  stage 1 — optimizer state (fp32 master + moments) sharded over DP.
  stage 2 — additionally gradients reduce-scattered: we constrain the grad
            pytree to the optimizer-state sharding so XLA lowers the backward
            reduction directly to reduce-scatter (the IPG-bucket path).
  stage 3 — params themselves sharded (see module_inject/tp_rules.py);
            optimizer state/grads inherit the param sharding, and the
            per-layer all-gather/free behaviour comes from scan-over-layers.

The "partition along the largest divisible dim" choice plays the role of the
reference's flatten-then-split-by-rank layout — but keeps tensors in their
natural shape so the MXU layouts stay intact.
"""

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...comm.mesh import ZERO_AXES, axis_size


def _spec_tuple(spec: Optional[P], ndim: int) -> Tuple:
    entries = tuple(spec) if spec is not None else ()
    return entries + (None, ) * (ndim - len(entries))


def zero_shard_spec(spec: Optional[P], shape: Tuple[int, ...], mesh: Mesh,
                    zero_axes=ZERO_AXES) -> P:
    """Add DP-axis sharding to an (possibly already TP-sharded) spec.

    Finds the first dimension that is unsharded and divisible by the DP world
    size and shards it there; if none divides, the tensor stays replicated
    (small norm/bias vectors — the reference similarly keeps sub-partition
    padding local)."""
    axes = tuple(a for a in zero_axes if mesh.shape.get(a, 1) > 1)
    if not axes:
        return spec if spec is not None else P()
    entries = list(_spec_tuple(spec, len(shape)))

    # if a dim already carries SOME of the zero axes (e.g. hpZ params sharded
    # over the intra-node subgroup only), extend that dim with the missing
    # axes so optimizer state/grads shard over the FULL group
    # (ref: hpZ — secondary param partition, primary optimizer partition)
    used_anywhere = set()
    for e in entries:
        used_anywhere.update(tuple(e) if isinstance(e, tuple) else ((e, ) if e is not None else ()))
    for d, e in enumerate(entries):
        cur = tuple(e) if isinstance(e, tuple) else ((e, ) if e is not None else ())
        present = [a for a in cur if a in axes]
        if not present:
            continue
        # extend with zero axes not used on ANY dim (e.g. expert params carry
        # the 'expert' mesh axis on their expert dim — it must not be added
        # to the ZeRO dim again)
        missing = tuple(a for a in axes if a not in used_anywhere)
        if not missing:
            return P(*entries)
        full = cur + missing
        total = int(np.prod([mesh.shape.get(a, 1) for a in full]))
        if shape[d] % total == 0:
            entries[d] = full
            return P(*entries)
        # can't extend this dim; try placing the missing axes on another dim
        msize = int(np.prod([mesh.shape.get(a, 1) for a in missing]))
        for d2, dim in enumerate(shape):
            if entries[d2] is None and dim % msize == 0 and dim >= msize:
                entries[d2] = missing if len(missing) > 1 else missing[0]
                return P(*entries)
        return P(*entries)

    zsize = axis_size(mesh, *axes)
    for d, dim in enumerate(shape):
        if entries[d] is None and dim % zsize == 0 and dim >= zsize:
            entries[d] = axes if len(axes) > 1 else axes[0]
            return P(*entries)
    return P(*entries)


def _shard_like(shardings_tree, shapes_tree, mesh, add_zero: bool, zero_axes=ZERO_AXES):
    def convert(sh, shape_struct):
        spec = sh.spec if isinstance(sh, NamedSharding) else sh
        shape = shape_struct.shape if hasattr(shape_struct, "shape") else tuple(shape_struct)
        if add_zero:
            spec = zero_shard_spec(spec, shape, mesh, zero_axes=zero_axes)
        return NamedSharding(mesh, spec)

    return jax.tree.map(convert, shardings_tree, shapes_tree)


def master_and_optstate_shardings(param_shardings, param_shapes, mesh: Mesh, stage: int, zero_axes=ZERO_AXES):
    """Sharding for fp32 master weights and per-param optimizer moments.

    stage >= 1: shard over DP axes (ref: stage_1_and_2.py partitioned fp32
    groups); stage 3: params already DP-sharded so this is a no-op add.
    ``zero_axes`` restricts the partition group (MiCS, see zero/mics.py).
    """
    add_zero = stage >= 1
    return _shard_like(param_shardings, param_shapes, mesh, add_zero, zero_axes)


def grad_shardings(param_shardings, param_shapes, mesh: Mesh, stage: int, zero_axes=ZERO_AXES):
    """Sharding constraint applied to gradients inside the compiled step.

    stage <= 1: grads replicated over DP (plain allreduce); stage >= 2:
    grads land reduce-scattered onto the optimizer partitioning.
    """
    add_zero = stage >= 2
    return _shard_like(param_shardings, param_shapes, mesh, add_zero, zero_axes)


def estimate_partitioned_bytes(param_shapes, shardings, dtype_bytes=4):
    """Debug helper: per-device bytes after partitioning."""
    total = 0
    for shape_struct, sh in zip(jax.tree.leaves(param_shapes), jax.tree.leaves(shardings)):
        shape = shape_struct.shape if hasattr(shape_struct, "shape") else tuple(shape_struct)
        n = int(np.prod(shape)) if shape else 1
        total += n * dtype_bytes // max(1, sh.num_devices if hasattr(sh, "num_devices") else 1)
    return total
