"""zero.Init / GatheredParameters — API-parity param-partitioning contexts.

ref: runtime/zero/partition_parameters.py (Init:825 — patches module
construction so params materialize pre-partitioned; GatheredParameters:2120
— temporarily all-gathers partitioned params for host-side access).

On TPU the heavy machinery is unnecessary: the engine initializes params
directly INTO their partitioned layout (jit with out_shardings,
engine._materialize_state), so ``Init`` is a thin context that records
construction-time intent.  ``GatheredParameters`` has a real job though:
user code (checkpoint surgery, stats, weight tying checks) sometimes needs
the full array of a ZeRO-3-sharded param on host — that is a device_get of
the global logical array, with optional write-back on exit (the reference's
``modifier_rank`` semantics).
"""

from typing import Any, Optional

import jax
import numpy as np

from ...utils.logging import logger


class Init:
    """ref: partition_parameters.py:825.  Accepts the reference's kwargs for
    drop-in compatibility; partitioned materialization happens at
    engine-init (see engine.py _materialize_state docstring)."""

    def __init__(self, module=None, data_parallel_group=None, mem_efficient_linear: bool = True,
                 remote_device: Optional[str] = None, pin_memory: bool = False,
                 config_dict_or_path=None, config=None, enabled: bool = True,
                 dtype=None, mpu=None, zero_param_parallel_group=None,
                 zero_quantized_weights: bool = False, zero_quantized_nontrainable_weights: bool = False,
                 sequence_data_parallel_group=None, param_swapper=None):
        self.enabled = enabled
        if enabled:
            logger.debug("zero.Init: params will materialize directly into their "
                         "partitioned layout at engine init")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class GatheredParameters:
    """ref: partition_parameters.py:2120.

    with GatheredParameters(engine, ["model.layers"], modifier_rank=0) as g:
        full = g["model.layers.mlp.down_proj.kernel"]   # host numpy
        g["model.layers.mlp.down_proj.kernel"] = full * 2   # written back

    Pass an engine (gathers from/writes back to engine.state.params) or a
    raw param tree (read-only gather).
    """

    def __init__(self, params_or_engine, names=None, modifier_rank: Optional[int] = None,
                 fwd_module=None, enabled: bool = True):
        self.enabled = enabled
        self._engine = None
        if hasattr(params_or_engine, "state") and hasattr(params_or_engine, "state_shardings"):
            self._engine = params_or_engine
            self._tree = params_or_engine.state.params
        else:
            self._tree = params_or_engine
        self.names = names
        self.modifier_rank = modifier_rank
        self._gathered = {}
        self._dirty = set()

    def _flatten(self):
        out = {}

        def walk(t, p=()):
            if isinstance(t, dict):
                for k, v in t.items():
                    walk(v, p + (str(k), ))
            else:
                out[".".join(p)] = t

        walk(self._tree)
        return out

    def __enter__(self):
        if not self.enabled:
            return self
        flat = self._flatten()
        wanted = flat if self.names is None else \
            {k: v for k, v in flat.items() if any(k.startswith(n) or n in k for n in self.names)}
        # device_get of the GLOBAL logical array = the all-gather
        self._gathered = {k: np.asarray(jax.device_get(v)) for k, v in wanted.items()}
        return self

    def __getitem__(self, name):
        return self._gathered[name]

    def keys(self):
        return self._gathered.keys()

    def __setitem__(self, name, value):
        assert self.modifier_rank is not None, \
            "writes require modifier_rank (parity with the reference's contract)"
        self._gathered[name] = np.asarray(value)
        self._dirty.add(name)

    def __exit__(self, *exc):
        if self._dirty and self._engine is not None:
            def walk(t, sh, p=()):
                if isinstance(t, dict):
                    return {k: walk(v, sh[k], p + (str(k), )) for k, v in t.items()}
                name = ".".join(p)
                if name in self._dirty:
                    return jax.device_put(self._gathered[name].astype(t.dtype), sh)
                return t

            new = walk(self._engine.state.params, self._engine.state_shardings.params)
            self._engine.state = self._engine.state._replace(params=new)
        elif self._dirty:
            logger.warning("GatheredParameters writes dropped: constructed from a raw tree, "
                           "pass the engine to persist modifications")
        return False
