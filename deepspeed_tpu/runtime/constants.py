"""Config keys and defaults (TPU-native analog of deepspeed/runtime/constants.py)."""

#############################################
# Routes
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer and lr scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

# Optimizer names (ref: runtime/config.py ADAM_OPTIMIZER etc.)
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM_OPTIMIZER = "fusedadam"
CPU_ADAM_OPTIMIZER = "cpuadam"
LAMB_OPTIMIZER = "lamb"
FUSED_LAMB_OPTIMIZER = "fusedlamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
MUADAM_OPTIMIZER = "muadam"
MUADAMW_OPTIMIZER = "muadamw"
MUSGD_OPTIMIZER = "musgd"
LION_OPTIMIZER = "lion"
MUON_OPTIMIZER = "muon"
ADAGRAD_OPTIMIZER = "adagrad"
SGD_OPTIMIZER = "sgd"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, CPU_ADAM_OPTIMIZER, LAMB_OPTIMIZER, FUSED_LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, LION_OPTIMIZER, ADAGRAD_OPTIMIZER,
    SGD_OPTIMIZER, MUON_OPTIMIZER
]

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_LOSS_SCALE = "loss_scale"
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_HYSTERESIS = "hysteresis"
FP16_CONSECUTIVE_HYSTERESIS = "consecutive_hysteresis"
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_AUTO_CAST = "auto_cast"
BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"
BFLOAT16_ENABLED = "enabled"

#############################################
# Gradient handling / misc training knobs
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0
PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0
SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False
COMMUNICATION_DATA_TYPE = "communication_data_type"
COMMUNICATION_DATA_TYPE_DEFAULT = None
SEQ_PARALLEL_COMMUNICATION_DATA_TYPE = "seq_parallel_communication_data_type"
SEQ_PARALLEL_COMMUNICATION_DATA_TYPE_DEFAULT = "fp32"
DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = None
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False
MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

#############################################
# ZeRO
#############################################
ZERO_OPTIMIZATION = "zero_optimization"
ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False
ZERO_FORCE_DS_CPU_OPTIMIZER = "zero_force_ds_cpu_optimizer"
ZERO_FORCE_DS_CPU_OPTIMIZER_DEFAULT = True

#############################################
# Parallelism
#############################################
TENSOR_PARALLEL = "tensor_parallel"
AUTOTP_SIZE = "autotp_size"
SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
PIPELINE = "pipeline"
PIPELINE_PARALLEL_SIZE = "pipeline_parallel_size"
EXPERT_PARALLEL_SIZE = "expert_parallel_size"
MOE = "moe"

#############################################
# Data types
#############################################
DATA_TYPES = "data_types"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"
GRAD_ACCUM_DTYPE_DEFAULT = None

#############################################
# Checkpoint
#############################################
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False
USE_NODE_LOCAL_STORAGE_CHECKPOINT = "use_node_local_storage"
USE_NODE_LOCAL_STORAGE_CHECKPOINT_DEFAULT = False

#############################################
# Misc feature blocks
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
FLOPS_PROFILER = "flops_profiler"
COMMS_LOGGER = "comms_logger"
MONITOR_CONFIG = "monitor_config"
TENSORBOARD = "tensorboard"
WANDB = "wandb"
CSV_MONITOR = "csv_monitor"
COMET = "comet"
AIO = "aio"
ELASTICITY = "elasticity"
AUTOTUNING = "autotuning"
COMPRESSION_TRAINING = "compression_training"
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
EIGENVALUE = "eigenvalue"
SPARSE_ATTENTION = "sparse_attention"
GRAPH_HARVESTING = "graph_harvesting"
GRAPH_HARVESTING_DEFAULT = False
TORCH_AUTOCAST = "torch_autocast"

DEFAULT_MESH_AXES = ("pipe", "data", "expert", "seq", "tensor")
