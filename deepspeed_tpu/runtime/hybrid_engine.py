"""Hybrid engine: RLHF train + generate sharing one set of weights.

TPU-native analog of ``deepspeed/runtime/hybrid_engine.py:30
DeepSpeedHybridEngine`` (generate:168, _zero3_forward:362).  The reference's
complexity — swapping ZeRO-3 partitioned training params into inference
kernel containers, gathering them layer-by-layer with
``GatheredParameters``, LoRA fuse/unfuse per container — exists because
train and inference use *different module objects over the same storage*.

Here both phases are jitted programs over the SAME TrainState.params pytree:
* ``train_batch``: inherited from DeepSpeedEngine (compiled train step).
* ``generate``: a compiled decode loop that closes over nothing — it takes
  ``state.params`` as an argument, so generation always sees the latest
  weights with zero copies or re-sharding (XLA re-gathers ZeRO-sharded
  params per step exactly like the train step does).
* LoRA fuse/unfuse (ref: hybrid_engine.py:135 fuse_lora_weight /
  :142 unfuse_lora_weight): pure tree transforms from deepspeed_tpu.linear,
  applied around a generation phase so decode matmuls hit one fused kernel.

The inference_tp_size / tp_gather_partition_size knobs are honored by
resharding params to the generate-phase sharding when they differ from the
training mesh (ref: hybrid_engine's inference TP groups).
"""

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist
from .engine import DeepSpeedEngine


class DeepSpeedHybridEngine(DeepSpeedEngine):

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._gen_fns = {}
        self._t_gen = 0.0
        self._gen_tokens = 0
        self._lora_fused = False
        self._in_eval = False
        cfg = self._config.hybrid_engine
        log_dist(f"DeepSpeedHybridEngine: max_out_tokens={cfg.max_out_tokens} "
                 f"inference_tp_size={cfg.inference_tp_size}", ranks=[0])

    # ------------------------------------------------------------- modes

    def eval(self):
        """Enter generation phase (ref: hybrid_engine.py eval())."""
        self._in_eval = True
        return self

    def train(self, mode: bool = True):
        """Back to training; unfuse LoRA if a generate phase fused it."""
        self._in_eval = not mode
        if mode and self._lora_fused:
            self.unfuse_lora_weight()
        return self

    # ------------------------------------------------------------- LoRA

    def fuse_lora_weight(self):
        """ref: hybrid_engine.py:135.

        Quantized-base LoRA models keep their base in the 'quant' variable
        collection, which is not part of TrainState — fusing is skipped (with
        a warning) rather than raising, so generate(..., fuse_lora=True)
        still runs with the unfused adapter path for them."""
        from ..linear import fuse_lora
        from ..utils.logging import logger
        assert not self._lora_fused, "LoRA already fused"
        try:
            self.state = self.state._replace(params=fuse_lora(self.state.params))
            self._lora_fused = True
        except ValueError as e:
            logger.warning(f"fuse_lora skipped: {e}")

    def unfuse_lora_weight(self):
        """ref: hybrid_engine.py:142."""
        from ..linear import unfuse_lora
        assert self._lora_fused, "LoRA not fused"
        self.state = self.state._replace(params=unfuse_lora(self.state.params))
        self._lora_fused = False

    # ---------------------------------------------------------- generate

    def generate(self, input_ids, max_new_tokens: Optional[int] = None, do_sample: bool = False,
                 temperature: float = 1.0, eos_token_id: Optional[int] = None, rng=None,
                 fuse_lora: bool = False):
        """Decode continuation of ``input_ids`` with the CURRENT training
        weights (ref: hybrid_engine.py:168 generate).

        One compiled program per (B, S_in, max_new, do_sample) signature;
        the full decode loop runs on-device via lax.scan — no per-token
        host round-trips (the analog of the reference's cuda-graph'd
        inference containers).
        """
        if self.state is None:
            # RLHF loops may roll out before the first update: materialize
            # the (sharded) state from the prompt shapes
            self._materialize_state(batch={"input_ids": np.asarray(input_ids)})
        he = self._config.hybrid_engine
        max_new = max_new_tokens or he.max_out_tokens
        ids = jnp.asarray(input_ids)
        b, s0 = ids.shape

        if fuse_lora and not self._lora_fused:
            self.fuse_lora_weight()

        key = (b, s0, max_new, do_sample, float(temperature))
        if key not in self._gen_fns:
            module = self.module

            def decode(params, ids, rng):
                buf = jnp.zeros((b, s0 + max_new), ids.dtype).at[:, :s0].set(ids)

                def body(carry, t):
                    buf, rng = carry
                    out = module.apply({"params": params}, buf)
                    logits = out[0] if isinstance(out, tuple) else out
                    cur = s0 + t
                    last = jnp.take_along_axis(logits, jnp.full((b, 1, 1), cur - 1), axis=1)[:, 0]
                    rng, sub = jax.random.split(rng)
                    if do_sample:
                        nxt = jax.random.categorical(sub, last / temperature, axis=-1)
                    else:
                        nxt = jnp.argmax(last, axis=-1)
                    buf = jax.lax.dynamic_update_slice_in_dim(buf, nxt.astype(buf.dtype)[:, None], cur, axis=1)
                    return (buf, rng), None

                (buf, _), _ = jax.lax.scan(body, (buf, rng), jnp.arange(max_new))
                return buf

            self._gen_fns[key] = jax.jit(decode)

        # per-call nonce: repeated sampled rollouts between train steps must
        # not reuse a key (RLHF collects many generations per step)
        self._gen_nonce = getattr(self, "_gen_nonce", 0) + 1
        rng = rng if rng is not None else jax.random.fold_in(
            jax.random.PRNGKey(int(self.global_steps)), self._gen_nonce)
        t0 = time.time()  # dslint-ok(determinism): hybrid engine reports real generate-phase wall time
        with self.mesh:
            buf = self._gen_fns[key](self.state.params, ids, rng)
        out = np.asarray(buf)
        self._t_gen += time.time() - t0  # dslint-ok(determinism): hybrid engine reports real generate-phase wall time
        self._gen_tokens += b * max_new

        if eos_token_id is not None:
            gen = out[:, s0:]
            hit = gen == eos_token_id
            first = np.where(hit.any(1), hit.argmax(1), max_new)
            cols = np.arange(max_new)[None, :]
            gen = np.where(cols <= first[:, None], gen, eos_token_id)
            out = np.concatenate([out[:, :s0], gen], axis=1)
        return out

    # ------------------------------------------------------------ metrics

    def generate_throughput(self):
        """tokens/sec over all generate() calls (ref: hybrid_engine latency
        accounting in _generate)."""
        return self._gen_tokens / self._t_gen if self._t_gen > 0 else 0.0
