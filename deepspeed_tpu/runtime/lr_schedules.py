"""LR schedules (ref: deepspeed/runtime/lr_schedules.py).

The reference implements LRRangeTest(:273), OneCycle(:371), WarmupLR(:633),
WarmupDecayLR(:723), WarmupCosineLR(:774) as stateful torch schedulers.  Here
each schedule is a pure function ``step -> lr`` (jit-traceable, so the lr
computation lives inside the compiled train step), wrapped in a thin stateful
shim exposing the torch-style ``step()/get_last_lr()/state_dict()`` surface
for API parity.
"""

import math
from typing import Callable

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]


def lr_range_test(lr_range_test_min_lr=1e-3,
                  lr_range_test_step_size=2000,
                  lr_range_test_step_rate=1.0,
                  lr_range_test_staircase=False,
                  **_) -> Callable:
    """ref: lr_schedules.py:273 LRRangeTest."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        interval = jnp.floor(step / lr_range_test_step_size) if lr_range_test_staircase \
            else step / lr_range_test_step_size
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


def one_cycle(cycle_min_lr=0.0,
              cycle_max_lr=1e-3,
              decay_lr_rate=0.0,
              cycle_first_step_size=2000,
              cycle_second_step_size=None,
              cycle_first_stair_count=0,
              cycle_second_stair_count=None,
              decay_step_size=0,
              **_) -> Callable:
    """ref: lr_schedules.py:371 OneCycle (lr triangle then decay)."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        up = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down = jnp.clip((step - cycle_first_step_size) / second, 0.0, 1.0)
        in_cycle_lr = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * jnp.where(step <= cycle_first_step_size, up,
                                                                               1.0 - down)
        post = jnp.maximum(step - total_cycle, 0.0)
        if decay_step_size > 0:
            decay = (1.0 + decay_lr_rate)**(-(jnp.floor(post / decay_step_size)))
        else:
            decay = 1.0
        return jnp.where(step <= total_cycle, in_cycle_lr, cycle_min_lr * decay)

    return schedule


def warmup_lr(warmup_min_lr=0.0, warmup_max_lr=1e-3, warmup_num_steps=1000, warmup_type="log", **_) -> Callable:
    """ref: lr_schedules.py:633 WarmupLR (log or linear warmup, then flat)."""
    warmup_num_steps = max(2, warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(step / warmup_num_steps, 0.0, 1.0)
        if warmup_type == "log":
            # log-warmup: lr rises like log(step)/log(N)
            gamma = jnp.log(jnp.maximum(step, 1.0)) / math.log(warmup_num_steps)
            gamma = jnp.clip(gamma, 0.0, 1.0)
        else:
            gamma = frac
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma

    return schedule


def warmup_decay_lr(total_num_steps,
                    warmup_min_lr=0.0,
                    warmup_max_lr=1e-3,
                    warmup_num_steps=1000,
                    warmup_type="log",
                    **_) -> Callable:
    """ref: lr_schedules.py:723 WarmupDecayLR (warmup then linear decay to 0)."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
    warmup_num_steps_ = max(2, warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        decay = jnp.clip(
            (total_num_steps - step) / jnp.maximum(float(total_num_steps - warmup_num_steps_), 1.0), 0.0, 1.0)
        return jnp.where(step < warmup_num_steps_, base(step), warmup_max_lr * decay)

    return schedule


def warmup_cosine_lr(total_num_steps,
                     warmup_min_ratio=0.0,
                     warmup_num_steps=1000,
                     cos_min_ratio=1e-4,
                     warmup_type="log",
                     lr=1e-3,
                     **_) -> Callable:
    """ref: lr_schedules.py:774 WarmupCosineLR (ratios of the base optimizer lr)."""
    warmup_num_steps_ = max(2, warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        if warmup_type == "log":
            g = jnp.clip(jnp.log(jnp.maximum(step, 1.0)) / math.log(warmup_num_steps_), 0.0, 1.0)
        else:
            g = jnp.clip(step / warmup_num_steps_, 0.0, 1.0)
        warm_ratio = warmup_min_ratio + (1.0 - warmup_min_ratio) * g
        progress = jnp.clip((step - warmup_num_steps_) / max(1.0, total_num_steps - warmup_num_steps_), 0.0, 1.0)
        cos_ratio = cos_min_ratio + (1.0 - cos_min_ratio) * 0.5 * (1.0 + jnp.cos(jnp.pi * progress))
        return lr * jnp.where(step < warmup_num_steps_, warm_ratio, cos_ratio)

    return schedule


SCHEDULE_BUILDERS = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    WARMUP_COSINE_LR: warmup_cosine_lr,
}


def get_lr_schedule(name: str, params: dict, base_lr: float = 1e-3) -> Callable:
    if name not in SCHEDULE_BUILDERS:
        raise ValueError(f"Unknown scheduler {name}; valid: {VALID_LR_SCHEDULES}")
    params = dict(params)
    if name == WARMUP_COSINE_LR:
        params.setdefault("lr", base_lr)
    return SCHEDULE_BUILDERS[name](**params)


class LRSchedulerShim:
    """torch-style scheduler facade over a pure schedule fn (API parity with
    the reference's scheduler objects returned from deepspeed.initialize)."""

    def __init__(self, schedule_fn: Callable, optimizer=None):
        self.schedule_fn = schedule_fn
        self.optimizer = optimizer
        self.last_batch_iteration = -1

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_last_lr(self):
        return [float(self.schedule_fn(max(0, self.last_batch_iteration)))]

    def get_lr(self):
        return self.get_last_lr()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]
