"""Power-iteration curvature (Hessian top-eigenvalue) estimation.

ref: runtime/eigenvalue.py (Eigenvalue.compute_eigenvalue — per-block power
iteration using double backward; consumed by the quantizer's eigenvalue-
aware schedule, engine config ``eigenvalue:{enabled,...}``).

JAX-native: Hessian-vector products via forward-over-reverse
(jvp of grad) — one jit'd HVP per iteration, no graph retention tricks.
"""

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import logger


class Eigenvalue:

    def __init__(self, verbose: bool = False, max_iter: int = 100, tol: float = 1e-2,
                 stability: float = 1e-6, gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def _normalize(self, tree):
        norm = jnp.sqrt(sum(jnp.vdot(x, x).real for x in jax.tree.leaves(tree)))
        return jax.tree.map(lambda x: x / (norm + self.stability), tree), norm

    def compute_eigenvalue(self, loss_fn: Callable, params, rng=None,
                           block_filter: Optional[Callable[[str], bool]] = None) -> Dict[str, float]:
        """Top Hessian eigenvalue per top-level param block.

        ``loss_fn(params) -> scalar``.  Returns {block_name: eigenvalue}
        (ref: eigenvalue.py compute_eigenvalue returning per-layer values,
        post-processed so zero/failed estimates get the max seen — same
        convention here).
        """
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        # cache the compiled HVP per loss_fn: compute_eigenvalue runs at
        # every gas boundary and must not recompile the double backward
        cache = getattr(self, "_hvp_cache", None)
        if cache is None:
            cache = self._hvp_cache = {}
        hvp = cache.get(loss_fn)
        if hvp is None:
            grad_fn = jax.grad(loss_fn)
            hvp = cache[loss_fn] = jax.jit(lambda p, v: jax.jvp(grad_fn, (p, ), (v, ))[1])

        results = {}
        blocks = list(params.keys()) if isinstance(params, dict) else [None]
        for bi, name in enumerate(blocks):
            if block_filter is not None and name is not None and not block_filter(str(name)):
                continue
            sub = params[name] if name is not None else params
            k = jax.random.fold_in(rng, bi)  # deterministic across processes
            leaves, treedef = jax.tree.flatten(sub)
            v_leaves = [jax.random.normal(jax.random.fold_in(k, li), x.shape, x.dtype)
                        if jnp.issubdtype(x.dtype, jnp.floating) else jnp.zeros_like(x)
                        for li, x in enumerate(leaves)]
            v = jax.tree.unflatten(treedef, v_leaves)
            v, _ = self._normalize(v)
            eig = 0.0
            for i in range(self.max_iter):
                # embed the block vector into a full-tree tangent
                full_v = jax.tree.map(jnp.zeros_like, params)
                if name is not None:
                    full_v = {**full_v, name: v}
                else:
                    full_v = v
                hv_full = hvp(params, full_v)
                hv = hv_full[name] if name is not None else hv_full
                v_new, norm = self._normalize(hv)
                new_eig = float(norm)
                if abs(new_eig - eig) < self.tol * max(abs(eig), 1e-12):
                    eig = new_eig
                    break
                eig, v = new_eig, v_new
            results[str(name)] = eig
            if self.verbose:
                logger.info(f"eigenvalue[{name}] = {eig:.4e} ({i + 1} iters)")

        # replace zero/failed estimates with the max (ref: eigenvalue.py
        # post-process "set to max of other layers")
        mx = max(results.values(), default=0.0)
        return {k: (val if val > 0 else mx) for k, val in results.items()}
