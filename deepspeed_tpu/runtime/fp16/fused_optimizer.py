"""FP16_Optimizer — fp32-master mixed precision as a gradient transform.

ref: runtime/fp16/fused_optimizer.py:33 FP16_Optimizer (and
unfused_optimizer.py:24 FP16_UnfusedOptimizer — the fused/unfused split is a
CUDA kernel detail with no TPU analog; both map here).

The DeepSpeedEngine implements this logic inline in its compiled step
(scaled loss → unscale → overflow-skip → fp32 master update → recast,
engine.py _apply_grads).  This class packages the same math as a standalone
optax-style GradientTransformation for client code that builds its own
training loops: state = (inner_state, master fp32 params, loss-scaler
state); update consumes SCALED fp16/bf16 grads and emits parameter DELTAS
in compute dtype, skipping on overflow exactly like the reference's
``overflow`` path.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from ...ops.optimizer import GradientTransformation
from .loss_scaler import DynamicLossScaler, LossScalerState, create_loss_scaler, found_inf_or_nan


class FP16OptimizerState(NamedTuple):
    inner: Any
    master: Any          # fp32 copies of params
    scaler: LossScalerState
    skipped: jnp.ndarray


class FP16_Optimizer:
    """Wrap ``inner`` with loss scaling + fp32 master weights.  Duck-typed
    to the optax-style (init, update) contract the engine accepts for
    client optimizers."""

    def __init__(self, inner: GradientTransformation, fp16_config=None, compute_dtype=jnp.float16,
                 clip_grad: float = 0.0):
        self.inner = inner
        self.scaler = create_loss_scaler(fp16_config, compute_dtype)
        self.clip_grad = clip_grad
        self.compute_dtype = compute_dtype
        self.init = self._init
        self.update = self._update

    def _init(self, params):
        master = jax.tree.map(lambda p: p.astype(jnp.float32), params)
        return FP16OptimizerState(inner=self.inner.init(master), master=master,
                                  scaler=self.scaler.init_state(),
                                  skipped=jnp.zeros((), jnp.int32))

    def _update(self, scaled_grads, state: FP16OptimizerState, params=None):
        inv = 1.0 / state.scaler.cur_scale
        grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, scaled_grads)
        found_inf = found_inf_or_nan(grads)
        if self.clip_grad and self.clip_grad > 0:
            from ...ops.optimizer import clip_by_global_norm
            grads, _ = clip_by_global_norm(grads, self.clip_grad)
        updates, new_inner = self.inner.update(grads, state.inner, state.master)
        new_master = jax.tree.map(lambda m, u: m + u, state.master, updates)

        def pick(new, old):
            return jax.tree.map(lambda n, o: jnp.where(found_inf, o, n), new, old)

        new_master = pick(new_master, state.master)
        new_inner = pick(new_inner, state.inner)
        # emit deltas in compute dtype: new_param - old_param
        deltas = jax.tree.map(lambda m, p: (m.astype(self.compute_dtype) - p), new_master, params) \
            if params is not None else jax.tree.map(lambda m: m.astype(self.compute_dtype), new_master)
        new_state = FP16OptimizerState(inner=new_inner, master=new_master,
                                       scaler=self.scaler.update(state.scaler, found_inf),
                                       skipped=state.skipped + found_inf.astype(jnp.int32))
        return deltas, new_state

    @property
    def loss_scale(self):
        """ref: fused_optimizer.py loss_scale property (static value needs
        the live state — read state.scaler.cur_scale instead)."""
        return None


# the reference's unfused variant differs only in CUDA kernel choice
FP16_UnfusedOptimizer = FP16_Optimizer
