"""Static / dynamic loss scaling as jit-compatible state.

TPU-native analog of ``deepspeed/runtime/fp16/loss_scaler.py``
(``LossScaler:67``, ``DynamicLossScaler:91``).  The reference mutates Python
state after a device→host sync of the overflow flag; here the scaler is a
small pytree threaded through the compiled train step so the
scale-adjust/skip decision happens on-device with no sync
(``lax.cond``-free: pure ``jnp.where`` arithmetic).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
CONSECUTIVE_HYSTERESIS = "consecutive_hysteresis"
MIN_LOSS_SCALE = "min_scale"


class LossScalerState(NamedTuple):
    cur_scale: jnp.ndarray  # f32 scalar
    cur_hysteresis: jnp.ndarray  # i32 scalar
    last_overflow_iter: jnp.ndarray  # i32 scalar
    iteration: jnp.ndarray  # i32 scalar


class DynamicLossScaler:
    """Functional loss scaler.  ``update(state, found_inf)`` returns the new
    state; ``should_skip`` is simply ``found_inf``."""

    def __init__(self,
                 init_scale=2**16,
                 scale_factor=2.0,
                 scale_window=1000,
                 min_scale=1.0,
                 delayed_shift=1,
                 consecutive_hysteresis=False,
                 dynamic=True):
        self.init_scale = float(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self.delayed_shift = int(delayed_shift)
        self.consecutive_hysteresis = consecutive_hysteresis
        self.dynamic = dynamic

    def init_state(self) -> LossScalerState:
        return LossScalerState(cur_scale=jnp.asarray(self.init_scale, jnp.float32),
                               cur_hysteresis=jnp.asarray(self.delayed_shift, jnp.int32),
                               last_overflow_iter=jnp.asarray(-1, jnp.int32),
                               iteration=jnp.asarray(0, jnp.int32))

    def update(self, state: LossScalerState, found_inf) -> LossScalerState:
        if not self.dynamic:
            return state._replace(iteration=state.iteration + 1)
        it = state.iteration
        overflow = found_inf.astype(jnp.bool_)
        # hysteresis: only halve the scale after `delayed_shift` consecutive overflows
        hyst_exhausted = state.cur_hysteresis <= 1
        new_scale_on_overflow = jnp.where(hyst_exhausted,
                                          jnp.maximum(state.cur_scale / self.scale_factor, self.min_scale),
                                          state.cur_scale)
        new_hyst_on_overflow = jnp.where(hyst_exhausted, state.cur_hysteresis, state.cur_hysteresis - 1)
        # growth: double after scale_window clean iterations
        window_ok = ((it - state.last_overflow_iter) % self.scale_window) == (self.scale_window - 1)
        new_scale_clean = jnp.where(window_ok, state.cur_scale * self.scale_factor, state.cur_scale)
        reset_hyst = jnp.asarray(self.delayed_shift, jnp.int32)
        new_hyst_clean = reset_hyst if self.consecutive_hysteresis else state.cur_hysteresis

        return LossScalerState(
            cur_scale=jnp.where(overflow, new_scale_on_overflow, new_scale_clean),
            cur_hysteresis=jnp.where(overflow, new_hyst_on_overflow, new_hyst_clean),
            last_overflow_iter=jnp.where(overflow, it, state.last_overflow_iter),
            iteration=it + 1,
        )


class StaticLossScaler(DynamicLossScaler):

    def __init__(self, scale=1.0):
        super().__init__(init_scale=scale, dynamic=False)


def found_inf_or_nan(grads):
    """Global finite-check across a grad pytree (ref: stage3.py:2027 overflow
    check — there an allreduce of found-inf; here grads are already global)."""
    leaves = [jnp.sum(~jnp.isfinite(g.astype(jnp.float32))) for g in jax.tree.leaves(grads)]
    if not leaves:
        return jnp.asarray(False)
    return sum(leaves) > 0


def create_loss_scaler(fp16_config=None, dtype=None):
    import jax.numpy as jnp_
    if fp16_config is None or dtype != jnp_.float16 or not getattr(fp16_config, "enabled", False):
        return StaticLossScaler(1.0)
    if fp16_config.loss_scale and fp16_config.loss_scale > 0:
        return StaticLossScaler(fp16_config.loss_scale)
    return DynamicLossScaler(init_scale=2.0**fp16_config.initial_scale_power,
                             scale_window=fp16_config.loss_scale_window,
                             min_scale=fp16_config.min_loss_scale,
                             delayed_shift=fp16_config.hysteresis,
                             consecutive_hysteresis=fp16_config.consecutive_hysteresis)
