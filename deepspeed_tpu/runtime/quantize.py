"""MoQ — Mixture-of-Quantization training quantizer.

ref: runtime/quantize.py (Quantizer.quantize — gradual bit reduction with a
mixed-fp16 blend ratio, optionally scheduled by per-layer Hessian
eigenvalues; engine hook engine.py:1532 _configure_quantization).

Functional port: ``MoQQuantizer.apply(params, step, eigenvalues=None)``
quantize-dequantizes weight leaves at the current bit-width with an
fp16-mix ratio that decays from 1→0 (``quantize_real_ratio`` in the
reference), so early training sees mostly-full-precision weights.  When
eigenvalues are provided (runtime/eigenvalue.py), layers with larger
curvature keep higher precision longer — the reference's
eigenvalue-adjusted period.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..compression.utils import asym_quantize, sym_quantize
from ..utils.logging import logger


class MoQQuantizer:

    def __init__(self, q_groups: int = 1, q_mixed_fp16: bool = False, q_change_ratio: float = 0.01,
                 q_type: int = 0, q_rounding: int = 0, q_verbose: bool = False,
                 q_eigenvalue: bool = False, start_bits: int = 16, target_bits: int = 8,
                 period: int = 100):
        self.q_groups = q_groups
        self.q_mixed_fp16 = q_mixed_fp16
        self.q_change_ratio = q_change_ratio
        self.q_type = q_type  # 0: symmetric, 1: asymmetric
        self.q_rounding = q_rounding
        self.q_eigenvalue = q_eigenvalue
        self.start_bits = start_bits
        self.target_bits = target_bits
        self.period = period
        if q_verbose:
            logger.info(f"MoQ: {start_bits}→{target_bits} bits, period={period}, "
                        f"mixed_fp16={q_mixed_fp16}, eigenvalue={q_eigenvalue}")

    def bits_at(self, step, scale: float = 1.0):
        """Halve from start→target every doubling period (ref:
        quantize.py:136 q_period <<= 1).  ``scale`` stretches the period for
        high-curvature layers (eigenvalue scheduling)."""
        s = jnp.maximum(0.0, step.astype(jnp.float32))
        p = jnp.maximum(1.0, self.period * scale)
        k = jnp.floor(jnp.log2(s / p + 1.0))
        return jnp.maximum(float(self.target_bits), jnp.floor(self.start_bits * jnp.exp2(-k)))

    def mix_ratio(self, step):
        """quantize_real_ratio: fp16-blend weight decaying 1→0
        (ref: quantize.py update_fp16_ratio)."""
        if not self.q_mixed_fp16:
            return jnp.asarray(0.0, jnp.float32)
        return jnp.clip(1.0 - self.q_change_ratio * step.astype(jnp.float32), 0.0, 1.0)

    def apply(self, params, step, eigenvalues: Optional[Dict[str, float]] = None):
        """Quantize-dequantize every ≥2-D float leaf (STE inside)."""
        step = jnp.asarray(step)
        mix = self.mix_ratio(step)
        eigs = eigenvalues or {}
        max_eig = max(eigs.values(), default=1.0) or 1.0

        def walk(tree, path=()):
            if isinstance(tree, dict):
                return {k: walk(v, path + (str(k), )) for k, v in tree.items()}
            if not hasattr(tree, "ndim") or tree.ndim < 2 or not jnp.issubdtype(tree.dtype, jnp.floating):
                return tree
            scale = 1.0
            if self.q_eigenvalue and eigs:
                block = path[0] if path else ""
                # higher curvature → longer period → later quantization
                scale = 1.0 + eigs.get(str(block), 0.0) / max_eig
            bits = self.bits_at(step, scale)
            qfn = sym_quantize if self.q_type == 0 else asym_quantize
            q = qfn(tree, bits, num_groups=self.q_groups)
            return (mix * tree + (1.0 - mix) * q).astype(tree.dtype)

        return walk(params)


# API-parity alias (ref: runtime/quantize.py class Quantizer)
Quantizer = MoQQuantizer
