"""Pluggable checkpoint engines.

ref: runtime/checkpoint_engine/{checkpoint_engine.py CheckpointEngine ABC,
torch_checkpoint_engine.py TorchCheckpointEngine,
nebula_checkpoint_engine.py NebulaCheckpointEngine} + deepspeed/nebula/.

* OrbaxCheckpointEngine — synchronous sharded save/restore (the
  TorchCheckpointEngine analog; resharding-on-restore included).
* AsyncCheckpointEngine — orbax AsyncCheckpointer: save returns while the
  write streams in the background (the Nebula tiered/async service's role;
  ``commit()`` waits for durability like Nebula's commit).
"""

import os
from typing import Any, Optional

from ..resilience.retry import RetryPolicy, retry_call
from ..utils.logging import log_dist, logger

# transient-I/O absorption for the sharded tree writes (NFS hiccups, EIO);
# InjectedCrash — simulated process death — is NOT an OSError and passes
# straight through (resilience/retry.py)
_IO_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
                        budget_s=5.0)


class CheckpointEngine:
    """ref: checkpoint_engine.py CheckpointEngine ABC."""

    def __init__(self, config_params=None):
        self.config = config_params

    def create(self, tag):
        log_dist(f"checkpoint tag {tag}", ranks=[0])

    def save(self, state_dict, path: str):
        raise NotImplementedError

    def load(self, path: str, target=None, map_location=None):
        raise NotImplementedError

    def commit(self, tag):
        return True

    def makedirs(self, path, exist_ok=True):
        os.makedirs(path, exist_ok=exist_ok)


class OrbaxCheckpointEngine(CheckpointEngine):
    """Synchronous orbax save/restore (ref: torch_checkpoint_engine.py)."""

    def save(self, state_dict, path: str):
        import orbax.checkpoint as ocp

        def _save():
            from ..resilience import fault_injection as fi
            fi.check("ckpt.state_save")
            with ocp.StandardCheckpointer() as c:
                c.save(path, state_dict, force=True)

        retry_call(_save, _IO_RETRY, site="ckpt.state_save")
        return path

    def load(self, path: str, target=None, map_location=None):
        import orbax.checkpoint as ocp

        def _load():
            with ocp.StandardCheckpointer() as c:
                return c.restore(path, target) if target is not None else c.restore(path)

        return retry_call(_load, _IO_RETRY, site="ckpt.state_restore")


class AsyncCheckpointEngine(CheckpointEngine):
    """Async background save (ref: nebula_checkpoint_engine.py — Nebula's
    async/tiered persistence; commit() == Nebula commit barrier)."""

    def __init__(self, config_params=None):
        super().__init__(config_params)
        self._ckptr = None

    def _ensure(self):
        if self._ckptr is None:
            import orbax.checkpoint as ocp
            self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        return self._ckptr

    def save(self, state_dict, path: str):
        import orbax.checkpoint as ocp

        def _issue():
            from ..resilience import fault_injection as fi
            fi.check("ckpt.state_save")
            self._ensure().save(path, args=ocp.args.StandardSave(state_dict), force=True)

        retry_call(_issue, _IO_RETRY, site="ckpt.state_save")
        return path  # returns immediately; write streams in background

    def load(self, path: str, target=None, map_location=None):
        import orbax.checkpoint as ocp
        c = self._ensure()
        c.wait_until_finished()
        return c.restore(path, args=ocp.args.StandardRestore(target)) if target is not None \
            else c.restore(path)

    def commit(self, tag):
        if self._ckptr is not None:
            self._ckptr.wait_until_finished()
        log_dist(f"async checkpoint {tag} committed", ranks=[0])
        return True


_ASYNC_SINGLETON: Optional[AsyncCheckpointEngine] = None


def make_checkpoint_engine(name: Optional[str] = None, config_params=None) -> CheckpointEngine:
    """'orbax'/'torch' → sync; 'async'/'nebula' → async.  The async engine is
    a process-wide singleton: orbax's AsyncCheckpointer owns a background
    thread pool, and successive saves must serialize through one instance
    (a fresh checkpointer per save would leak threads and lose the pending-
    write barrier)."""
    global _ASYNC_SINGLETON
    name = (name or "orbax").lower()
    if name in ("async", "nebula"):
        if _ASYNC_SINGLETON is None:
            _ASYNC_SINGLETON = AsyncCheckpointEngine(config_params)
        return _ASYNC_SINGLETON
    return OrbaxCheckpointEngine(config_params)


def wait_for_pending_saves():
    """Barrier for any in-flight async save (call before restoring or at
    process exit — the Nebula commit fence)."""
    if _ASYNC_SINGLETON is not None:
        _ASYNC_SINGLETON.commit("pending")
