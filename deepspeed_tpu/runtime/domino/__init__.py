"""Domino TP comm-overlap transformer (ref: deepspeed/runtime/domino/)."""

from .transformer import DominoTransformer, DominoTransformerLayer
