"""Domino: tensor-parallel transformer with compute/communication overlap.

ref: runtime/domino/transformer.py:411 DominoTransformer +
domino/async_linear.py:47 DominoAsyncColumnParallelLinear.  The reference
splits each batch into µ-batches and launches the TP allreduce of µ-batch i
asynchronously while computing µ-batch i+1, hiding TP communication behind
compute.

TPU-native: the layer processes µ-batch chunks as independent dataflow
chains inside one jitted program.  Each chain's row-parallel matmul ends in
a GSPMD-inserted allreduce, and since chain i+1's matmuls have no data
dependency on chain i's allreduce, XLA's latency-hiding scheduler overlaps
them — the async-handle choreography becomes a property of the schedule.
The µ-batch count (ref: tag_micro_batches) controls the overlap depth.

Layer structure matches the reference (Megatron block): LN → col-parallel
QKV → attention → row-parallel proj [+allreduce] → residual → LN →
col-parallel MLP-in → gelu → row-parallel MLP-out [+allreduce] → residual.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from ...comm.mesh import TENSOR_AXIS

# logical axis vocabulary shared with the model zoo (module_inject/tp_rules)
EMBED = "embed"
HEADS = "heads"
HEAD_DIM = "head_dim"
MLP = "mlp"


def _logical(init, names):
    return nn.with_logical_partitioning(init, names)


class DominoTransformerLayer(nn.Module):
    """One TP transformer block over µ-batch chunks
    (ref: transformer.py:DominoTransformerLayer.forward)."""
    hidden_size: int
    num_attention_heads: int
    ffn_hidden_size: int
    micro_batches: int = 2  # ref: Domino's µ-batch split degree
    causal: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        H = self.num_attention_heads
        D = self.hidden_size // H
        dt = self.dtype

        ln1_scale = self.param("input_layernorm", _logical(nn.initializers.ones_init(), (EMBED, )),
                               (self.hidden_size, ), jnp.float32)
        ln2_scale = self.param("post_attention_layernorm", _logical(nn.initializers.ones_init(), (EMBED, )),
                               (self.hidden_size, ), jnp.float32)
        wqkv = self.param("qkv", _logical(nn.initializers.lecun_normal(), (EMBED, HEADS, HEAD_DIM)),
                          (self.hidden_size, H, 3 * D), jnp.float32)
        wo = self.param("dense", _logical(nn.initializers.lecun_normal(), (HEADS, HEAD_DIM, EMBED)),
                        (H, D, self.hidden_size), jnp.float32)
        w1 = self.param("mlp_h_to_4h", _logical(nn.initializers.lecun_normal(), (EMBED, MLP)),
                        (self.hidden_size, self.ffn_hidden_size), jnp.float32)
        w2 = self.param("mlp_4h_to_h", _logical(nn.initializers.lecun_normal(), (MLP, EMBED)),
                        (self.ffn_hidden_size, self.hidden_size), jnp.float32)

        def ln(v, scale):
            m = jnp.mean(v.astype(jnp.float32), -1, keepdims=True)
            var = jnp.var(v.astype(jnp.float32), -1, keepdims=True)
            return ((v - m) * jax.lax.rsqrt(var + 1e-5) * scale).astype(dt)

        def one_chunk(xc):
            # attention: col-parallel QKV (sharded over heads), row-parallel out
            h = ln(xc, ln1_scale)
            qkv = jnp.einsum("bse,ehd->bshd", h, wqkv.astype(dt))
            q, k, v = jnp.split(qkv, 3, axis=-1)
            scores = jnp.einsum("bshd,bthd->bhst", q, k) / np.sqrt(D)
            if self.causal:
                S = xc.shape[1]
                mask = jnp.tril(jnp.ones((S, S), bool))
                scores = jnp.where(mask[None, None], scores, jnp.finfo(scores.dtype).min)
            probs = jax.nn.softmax(scores, -1)
            ctx = jnp.einsum("bhst,bthd->bshd", probs, v)
            # row-parallel projection: contraction over the TP-sharded head
            # axis ⇒ GSPMD inserts the TP allreduce here (the async_linear
            # allreduce in the reference)
            attn_out = jnp.einsum("bshd,hde->bse", ctx, wo.astype(dt))
            xc = xc + attn_out
            # MLP col→row parallel; second matmul again ends in TP allreduce
            h2 = ln(xc, ln2_scale)
            inter = jax.nn.gelu(jnp.einsum("bse,ef->bsf", h2, w1.astype(dt)))
            mlp_out = jnp.einsum("bsf,fe->bse", inter, w2.astype(dt))
            return xc + mlp_out

        B = x.shape[0]
        n = min(self.micro_batches, B)
        if n <= 1 or B % n != 0:
            return one_chunk(x)
        # independent µ-batch chains: XLA overlaps chunk i's trailing
        # allreduce with chunk i+1's matmuls (Domino's async pipeline)
        chunks = jnp.split(x, n, axis=0)
        outs = [one_chunk(c) for c in chunks]
        return jnp.concatenate(outs, axis=0)


class DominoTransformer(nn.Module):
    """Stack of Domino layers (ref: transformer.py:411 DominoTransformer)."""
    num_layers: int
    hidden_size: int
    num_attention_heads: int
    ffn_hidden_size: int
    micro_batches: int = 2
    causal: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i in range(self.num_layers):
            x = DominoTransformerLayer(hidden_size=self.hidden_size,
                                       num_attention_heads=self.num_attention_heads,
                                       ffn_hidden_size=self.ffn_hidden_size,
                                       micro_batches=self.micro_batches,
                                       causal=self.causal,
                                       dtype=self.dtype,
                                       name=f"layer_{i}")(x)
        return x
