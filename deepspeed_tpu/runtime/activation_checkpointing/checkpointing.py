"""Activation checkpointing — remat policies over ``jax.checkpoint``.

TPU-native analog of ``deepspeed/runtime/activation_checkpointing/
checkpointing.py`` (1,150 LoC: Megatron-compatible ``checkpoint():948``,
``CheckpointFunction:488`` with ``partition_activations:377`` /
``gather_partitioned_activations:266``, CPU checkpointing, contiguous
buffers, ``CudaRNGStatesTracker:124``).

The mapping (SURVEY §5 "Activation checkpointing"):

* ``checkpoint(fn, *args)``      → ``jax.checkpoint`` (rematerialise in bwd)
* ``partition_activations``      → a sharding constraint on saved residuals
  over the tensor axis: each TP rank stores 1/tp of every checkpoint, the
  backward gather is an XLA all-gather the scheduler overlaps — same memory
  maths as the reference's explicit partition/gather pair.
* ``cpu_checkpointing``          → ``save_and_offload_only_these_names``
  policy offloading named residuals to ``pinned_host`` memory.
* contiguous_memory_optimization → no-op on TPU (XLA owns allocation; noted
  in config for parity).
* ``CudaRNGStatesTracker``       → ``RNGStatesTracker`` over threaded PRNG
  keys (functional, fork-on-use; no global device RNG state exists in JAX).
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ...utils.logging import logger

_CONFIG = None
_MPU = None

# names used with jax.ad_checkpoint.checkpoint_name inside model code to
# mark offloadable/saveable residuals
CHECKPOINT_NAME = "ds_act_ckpt"


# --------------------------------------------------------------------- RNG


class RNGStatesTracker:
    """Functional analog of ``CudaRNGStatesTracker`` (ref:
    checkpointing.py:124): named PRNG streams; ``fork(name)`` yields a fresh
    subkey deterministically, so remat replays identical randomness (the
    problem the reference's RNG state juggling solves — JAX solves it by
    construction, keys being values)."""

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if name in self.states_:
            raise Exception(f"rng state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    def fork(self, name="model-parallel-rng"):
        if name not in self.states_:
            raise Exception(f"rng state {name} is not added")
        self.states_[name], sub = jax.random.split(self.states_[name])
        return sub


_RNG_TRACKER = RNGStatesTracker()


def get_cuda_rng_tracker():
    """Name kept for API parity (ref: checkpointing.py get_cuda_rng_tracker)."""
    return _RNG_TRACKER


def model_parallel_cuda_manual_seed(seed):
    """Seed DP-common and TP-distinct streams (ref: checkpointing.py:
    model_parallel_cuda_manual_seed).  On TPU the 'tp-distinct' stream is
    folded per axis index inside the traced program via fold_in."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("model-parallel-rng", seed + 2718)
    _RNG_TRACKER.add("data-parallel-rng", seed)
    return _RNG_TRACKER


def model_parallel_rng_key(seed, axis_name="tensor"):
    """Traced helper: per-TP-rank key (use inside shard_map/jit)."""
    key = jax.random.PRNGKey(seed)
    try:
        idx = jax.lax.axis_index(axis_name)
        return jax.random.fold_in(key, idx)
    except NameError:
        return key


# ------------------------------------------------------------------ policies


def _policy_from_config(cfg):
    """Build a jax.checkpoint policy from the DS config block."""
    pol = jax.checkpoint_policies
    if cfg is None:
        return None  # rematerialise everything (DeepSpeed default)
    if getattr(cfg, "cpu_checkpointing", False):
        # offload the marked residuals to host RAM instead of recomputing
        return pol.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=[CHECKPOINT_NAME],
            offload_src="device",
            offload_dst="pinned_host")
    if getattr(cfg, "number_checkpoints", None):
        # keep matmul outputs; close analog of "checkpoint every N layers"
        return pol.dots_with_no_batch_dims_saveable
    return None


def checkpoint_name(x, name=CHECKPOINT_NAME):
    """Tag a residual for the offload/save policies
    (wraps jax.ad_checkpoint.checkpoint_name)."""
    from jax.ad_checkpoint import checkpoint_name as _cn
    return _cn(x, name)


# ------------------------------------------------------------------- config


def configure(mpu_=None,
              deepspeed_config=None,
              partition_activations=None,
              contiguous_checkpointing=None,
              num_checkpoints=None,
              checkpoint_in_cpu=None,
              synchronize=None,
              profile=None):
    """ref: checkpointing.py configure — record the policy knobs."""
    global _CONFIG, _MPU
    from ..config import ActivationCheckpointingConfig

    if deepspeed_config is not None and hasattr(deepspeed_config, "activation_checkpointing_config"):
        _CONFIG = deepspeed_config.activation_checkpointing_config
    else:
        _CONFIG = ActivationCheckpointingConfig(
            partition_activations=bool(partition_activations),
            contiguous_memory_optimization=bool(contiguous_checkpointing),
            cpu_checkpointing=bool(checkpoint_in_cpu),
            number_checkpoints=num_checkpoints,
            synchronize_checkpoint_boundary=bool(synchronize),
            profile=bool(profile),
        )
    _MPU = mpu_
    if _CONFIG.contiguous_memory_optimization:
        logger.debug("contiguous_memory_optimization is a no-op on TPU (XLA owns allocation)")


def is_configured():
    """ref: checkpointing.py is_configured."""
    return _CONFIG is not None


def reset():
    """ref: checkpointing.py reset."""
    global _CONFIG
    _CONFIG = None


# --------------------------------------------------------------- checkpoint


def _partition_constraint(tree):
    """Shard saved residuals across the tensor axis (the reference's
    partition_activations:377 splits each activation across TP ranks; here
    the same layout is a with_sharding_constraint on the LAST dim, and the
    bwd all-gather is compiler-inserted)."""
    from jax.sharding import PartitionSpec as P

    from ...comm.mesh import TENSOR_AXIS, get_global_mesh, has_global_mesh
    if not has_global_mesh():
        return tree
    mesh = get_global_mesh()
    if mesh.shape.get(TENSOR_AXIS, 1) <= 1:
        return tree

    def constrain(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[-1] % mesh.shape[TENSOR_AXIS] == 0:
            spec = P(*([None] * (x.ndim - 1) + [TENSOR_AXIS]))
            return jax.lax.with_sharding_constraint(x, jax.sharding.NamedSharding(mesh, spec))
        return x

    return jax.tree.map(constrain, tree)


def checkpoint(function: Callable, *args, **kwargs) -> Any:
    """Megatron-compatible activation checkpointing (ref:
    checkpointing.py:948 checkpoint): runs ``function(*args)`` under remat.

    Unlike the reference there is no CheckpointFunction autograd.Function —
    ``jax.checkpoint`` handles saving/recomputing, and RNG replay is free
    because keys are arguments.
    """
    cfg = _CONFIG
    policy = _policy_from_config(cfg)

    wrapped = jax.checkpoint(function, policy=policy) if policy is not None else jax.checkpoint(function)

    if cfg is not None and cfg.partition_activations:
        def with_partition(*a, **k):
            a = _partition_constraint(a)
            return wrapped(*a, **k)
        return with_partition(*args, **kwargs)
    return wrapped(*args, **kwargs)


def checkpoint_wrapper(function: Callable) -> Callable:
    """Decorator form: ``layer = checkpoint_wrapper(layer)``."""
    def inner(*args, **kwargs):
        return checkpoint(function, *args, **kwargs)
    return inner


def non_reentrant_checkpoint(function, *args, **kwargs):
    """ref: checkpointing.py:704 — reentrancy is meaningless under tracing;
    same implementation, kept for API parity."""
    return checkpoint(function, *args, **kwargs)


# ---------------------------------------------------- parity helper exports


def partition_activations_in_checkpoint(partition_activation):
    global _CONFIG
    if _CONFIG is None:
        configure(partition_activations=partition_activation)
    else:
        _CONFIG.partition_activations = partition_activation
    logger.info(f"**************Partition Activations {partition_activation}************")
