"""BF16_Optimizer — bf16 params with fp32 master + fp32 grad accumulation.

ref: runtime/bf16_optimizer.py:35 BF16_Optimizer (bf16 model weights, fp32
flat master partitions in ZeRO-1 layout, fp32 gradient accumulation).

The engine implements exactly this when ``bf16.enabled`` (TrainState.master
fp32 + zero-stage sharding of master/moments).  The standalone transform
here is FP16_Optimizer minus the loss scaler — bf16's range makes scaling
unnecessary (the reference likewise has no scaler on the bf16 path).
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from ..ops.optimizer import GradientTransformation


class BF16OptimizerState(NamedTuple):
    inner: Any
    master: Any  # fp32


class BF16_Optimizer:
    """Duck-typed (init, update) like the engine's client-optimizer contract."""

    def __init__(self, inner: GradientTransformation, clip_grad: float = 0.0):
        self.inner = inner
        self.clip_grad = clip_grad
        self.init = self._init
        self.update = self._update

    def _init(self, params):
        master = jax.tree.map(lambda p: p.astype(jnp.float32), params)
        return BF16OptimizerState(inner=self.inner.init(master), master=master)

    def _update(self, grads, state: BF16OptimizerState, params=None):
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        if self.clip_grad and self.clip_grad > 0:
            from ..ops.optimizer import clip_by_global_norm
            grads, _ = clip_by_global_norm(grads, self.clip_grad)
        updates, new_inner = self.inner.update(grads, state.inner, state.master)
        new_master = jax.tree.map(lambda m, u: m + u, state.master, updates)
        deltas = jax.tree.map(lambda m, p: m.astype(p.dtype) - p, new_master, params) \
            if params is not None else jax.tree.map(lambda m: m.astype(jnp.bfloat16), new_master)
        return deltas, BF16OptimizerState(inner=new_inner, master=new_master)
