from .swapper import (AioSwapConfig, PartitionedOptimizerSwapper, SwapInHandle,  # noqa: F401
                      TensorSwapper)
