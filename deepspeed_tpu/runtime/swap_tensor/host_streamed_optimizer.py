"""Grouped host-streamed optimizer states (ZeRO-Infinity CPU tier).

Reference: ``deepspeed/runtime/zero/stage_1_and_2.py`` CPU offload +
``csrc/adam/cpu_adam_impl.cpp`` — fp32 master/moments live in host memory
and the update touches them in bounded pieces, never materializing the
whole state beside the model.

TPU-native problem this solves (r4's receipts, docs/PERF.md): XLA will not
bound HBM staging for host-resident state inside ONE program — a
whole-tree update against ``pinned_host`` gets every host→HBM pull
hoisted to the program top, ``optimization_barrier`` chains are ignored
by buffer assignment, and ``compute_on("device_host")`` still stages its
I/O through HBM.  So the bounding is done at the DISPATCH level instead:
the fp32 master + Adam moments are partitioned into byte-balanced leaf
groups held as ``pinned_host`` jax Arrays (resident in the TPU host's
RAM — transfers never cross a client tunnel), and each training step runs
one small jitted update program per group with the host buffers donated.
Per-dispatch HBM staging is bounded by the group's bytes; dispatches are
async, so group g+1's host→HBM pull overlaps group g's compute (the
pipelined-swapper overlap, with XLA's transfer engine in place of aio
threads).

Interface-compatible with ``PipelinedNVMeOptimizer`` so the engine's
``_nvme_train_step`` orchestration (fwd/bwd program + grouped update loop)
drives either storage tier.  Selected by
``offload_optimizer: {device: cpu, pipeline_read: true}`` on a
single-device mesh (the multi-chip answer is ZeRO sharding, not offload).
"""

from collections import deque
from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.logging import log_dist


class _NoopSwapper:
    """Duck-typed stand-in for the NVMe swapper's flush surface: host
    arrays are always durable (nothing is in flight on aio threads)."""

    def flush_writes(self):
        pass

    def teardown(self):
        pass


class HostStreamedOptimizer:
    """fp32 master + Adam moments in TPU-host pinned memory, updated by
    per-group dispatches with donated host buffers."""

    def __init__(self, opt, param_leaves, n_groups: int = 8,
                 compute_dtype=jnp.bfloat16, mesh=None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ...comm.mesh import get_global_mesh
        self.opt = opt
        self.compute_dtype = compute_dtype
        mesh = mesh if mesh is not None else get_global_mesh()
        self._dev_sh = NamedSharding(mesh, P())
        self._host_sh = self._dev_sh.with_memory_kind("pinned_host")
        try:  # same probe as the engine's try_host_offload: CPU test
            # backends have no pinned_host memory kind — the grouped
            # dispatch structure (and its numerics) is identical, the
            # state just stays in device space there
            jax.jit(lambda x: x, out_shardings=self._host_sh) \
                .lower(jax.ShapeDtypeStruct((1, ), jnp.float32)).compile()
        except Exception:
            log_dist("HostStreamedOptimizer: pinned_host unsupported on this "
                     "backend; grouped state stays in device memory", ranks=[0])
            self._host_sh = self._dev_sh
        self.swapper = _NoopSwapper()
        self.events = deque(maxlen=512)
        self._update_fns: Dict[int, Callable] = {}

        # byte-balanced contiguous leaf partition (same policy as the NVMe
        # swapper so group sizes, and therefore the HBM staging bound, are
        # predictable: ~total_fp32_bytes x 3 / n_groups per dispatch)
        sizes = [int(np.prod(l.shape)) * 4 for l in param_leaves]
        target = max(1, sum(sizes) // max(1, n_groups))
        self.groups: List[List[int]] = []
        cur, acc = [], 0
        for i, s in enumerate(sizes):
            cur.append(i)
            acc += s
            if acc >= target and len(self.groups) < n_groups - 1:
                self.groups.append(cur)
                cur, acc = [], 0
        if cur:
            self.groups.append(cur)
        self.n_groups = len(self.groups)

        # initialize host-resident state leaf-by-leaf: the fp32 master is
        # cast on device and streamed out (one leaf of HBM at a time, never
        # the whole fp32 tree); moments are born in host space
        to_host_f32 = jax.jit(lambda p: p.astype(jnp.float32), out_shardings=self._host_sh)
        self._master: List[List[Any]] = []
        self._mu: List[List[Any]] = []
        self._nu: List[List[Any]] = []
        for idxs in self.groups:
            ms, mus, nus = [], [], []
            for i in idxs:
                p = param_leaves[i]
                ms.append(to_host_f32(p))
                zeros = jax.jit(lambda p=p: jnp.zeros(p.shape, jnp.float32),
                                out_shardings=self._host_sh)()
                mus.append(zeros)
                nus.append(jax.jit(lambda p=p: jnp.zeros(p.shape, jnp.float32),
                                   out_shardings=self._host_sh)())
            self._master.append(ms)
            self._mu.append(mus)
            self._nu.append(nus)
        jax.block_until_ready(self._master[-1][-1])
        gb = sum(sizes) * 3 / 1e9
        log_dist(f"HostStreamedOptimizer: {len(param_leaves)} leaves in "
                 f"{self.n_groups} groups, {gb:.1f} GB fp32 state in host memory, "
                 f"~{gb / self.n_groups:.1f} GB HBM staging per dispatch", ranks=[0])

    def _group_update(self, g: int):
        if g not in self._update_fns:
            from ...ops.adam import AdamState
            n = len(self.groups[g])
            host, dev = self._host_sh, self._dev_sh

            def upd(master, mu, nu, grads, count, scale):
                # explicit host→HBM pulls INSIDE the program (mixed host/
                # device operands are rejected by the compute ops); bounded
                # to this group's bytes — the whole point of the dispatch
                # split
                pull = lambda xs: [jax.device_put(x, dev) for x in xs]
                master, mu, nu = pull(master), pull(mu), pull(nu)
                g32 = [x.astype(jnp.float32) * scale for x in grads]
                updates, st = self.opt.update(g32, AdamState(count, mu, nu), master)
                new_master = [m + u for m, u in zip(master, updates)]
                new_params = [m.astype(self.compute_dtype) for m in new_master]
                return new_master, st.exp_avg, st.exp_avg_sq, new_params

            self._update_fns[g] = jax.jit(
                upd,
                donate_argnums=(0, 1, 2),
                in_shardings=([host] * n, [host] * n, [host] * n, [dev] * n, dev, dev),
                out_shardings=([host] * n, [host] * n, [host] * n, [dev] * n))
        return self._update_fns[g]

    def pending_writes(self) -> int:
        return 0  # host buffers: nothing in flight past dispatch

    def step(self, grad_leaves, count, clip_scale):
        """Per-group update sweep.  Returns new compute-dtype param leaves
        (device), original leaf order.  Dispatches are async: group g+1's
        host pulls overlap group g's compute on the transfer engine."""
        new_params: List[Any] = [None] * sum(len(g) for g in self.groups)
        for g, idxs in enumerate(self.groups):
            self.events.append(("prefetch_issue", g))  # dispatch == prefetch here
            nm, nmu, nnu, np_leaves = self._group_update(g)(
                self._master[g], self._mu[g], self._nu[g],
                [grad_leaves[i] for i in idxs], count, clip_scale)
            self.events.append(("update_done", g))
            self._master[g], self._mu[g], self._nu[g] = nm, nmu, nnu
            self.events.append(("writeback_issue", g))
            for i, p in zip(idxs, np_leaves):
                new_params[i] = p
        return new_params

    # ------------------------------------------------- checkpoint surface

    def master_matches_params(self, param_leaves, compute_dtype) -> bool:
        """One representative leaf per group, compared in compute dtype
        (params were cast from exactly this master on a true resume)."""
        for g, idxs in enumerate(self.groups):
            disk = np.asarray(jax.device_get(self._master[g][0]),
                              np.float32).astype(compute_dtype)
            live = np.asarray(jax.device_get(param_leaves[idxs[0]]))
            if disk.shape != live.shape or not np.array_equal(disk, live):
                return False
        return True

    def resync_master_from_params(self, param_leaves):
        to_host_f32 = jax.jit(lambda p: p.astype(jnp.float32), out_shardings=self._host_sh)
        zeros_like_host = jax.jit(lambda p: jnp.zeros_like(p, jnp.float32),
                                  out_shardings=self._host_sh)
        for g, idxs in enumerate(self.groups):
            self._master[g] = [to_host_f32(param_leaves[i]) for i in idxs]
            self._mu[g] = [zeros_like_host(param_leaves[i]) for i in idxs]
            self._nu[g] = [zeros_like_host(param_leaves[i]) for i in idxs]

    def state_dict_host(self):
        out = []
        for g in range(self.n_groups):
            out.append({"master": [np.asarray(jax.device_get(x)) for x in self._master[g]],
                        "mu": [np.asarray(jax.device_get(x)) for x in self._mu[g]],
                        "nu": [np.asarray(jax.device_get(x)) for x in self._nu[g]]})
        return out

    # checkpoint persistence: UNLIKE the NVMe tier (whose swap files are
    # already durable on disk), host-tier state lives in process RAM — the
    # engine persists it into the checkpoint tag directory
    def save_state(self, directory: str):
        import os
        for g in range(self.n_groups):
            arrs = {}
            for name, store in (("master", self._master), ("mu", self._mu), ("nu", self._nu)):
                for i, x in enumerate(store[g]):
                    arrs[f"{name}_{i}"] = np.asarray(jax.device_get(x))
            np.savez(os.path.join(directory, f"host_opt_group{g}.npz"), **arrs)

    def load_state(self, directory: str) -> bool:
        """Restore group state saved by ``save_state``; False when the files
        are absent or shaped for a different partitioning."""
        import os
        loads = []
        for g in range(self.n_groups):
            path = os.path.join(directory, f"host_opt_group{g}.npz")
            if not os.path.exists(path):
                return False
            with np.load(path) as z:
                grp = {name: [z[f"{name}_{i}"] for i in range(len(self.groups[g]))]
                       for name in ("master", "mu", "nu")}
            if any(g_arr.shape != np.asarray(jax.device_get(cur)).shape
                   for g_arr, cur in zip(grp["master"], self._master[g])):
                return False
            loads.append(grp)
        for g, grp in enumerate(loads):
            self._master[g] = [jax.device_put(x, self._host_sh) for x in grp["master"]]
            self._mu[g] = [jax.device_put(x, self._host_sh) for x in grp["mu"]]
            self._nu[g] = [jax.device_put(x, self._host_sh) for x in grp["nu"]]
        return True

    def teardown(self):
        self._master = self._mu = self._nu = []
