"""Grouped host-streamed optimizer states (ZeRO-Infinity CPU tier).

Reference: ``deepspeed/runtime/zero/stage_1_and_2.py`` CPU offload +
``csrc/adam/cpu_adam_impl.cpp`` — fp32 master/moments live in host memory
and the update touches them in bounded pieces, never materializing the
whole state beside the model.

TPU-native problem this solves (r4's receipts, docs/PERF.md): XLA will not
bound HBM staging for host-resident state inside ONE program — a
whole-tree update against ``pinned_host`` gets every host→HBM pull
hoisted to the program top, ``optimization_barrier`` chains are ignored
by buffer assignment, and ``compute_on("device_host")`` still stages its
I/O through HBM.  So the bounding is done at the DISPATCH level: fp32
master + Adam moments are partitioned into byte-balanced leaf groups held
as ``pinned_host`` jax Arrays, and each group runs three SEPARATE
dispatches through a double-buffered HBM staging arena:

  upload(g)   host→HBM ``device_put`` of master/mu/nu (the staging slot);
  compute(g)  fused Adam over the staged buffers, which are DONATED —
              the slot's HBM is reused for the outputs;
  download(g) HBM→host ``device_put`` of the updated state (async).

The pipeline keeps at most ``max_staged`` (default 2) groups staged but
unconsumed: upload(g+1) is issued before compute(g) is even dispatched, so
it rides the transfer engine under compute(g); download(g) is issued right
after compute(g) and drains under compute(g+1); the host thread fences one
group BEHIND the dispatch front (on compute(g-1) before leaving iteration
g), which both enforces the staging bound and yields per-group completion
timestamps.  The engine additionally calls ``prefetch(0)``/``prefetch(1)``
right after dispatching the fwd/bwd program, so the first uploads overlap
the BACKWARD of the same step rather than starting at the step boundary.

Unlike the pre-r6 single-dispatch-per-group form (host pulls inside the
update program), the overlap here is measured, not asserted:
``instrumentation`` (overlap_instrumentation.py) records timestamped
events every step, ``step(..., serialize=True)`` runs a fenced probe sweep
attributing per-group upload/compute/download seconds, and
``overlap_report()`` combines them into the overlap fraction and the
transfer-/compute-bound floor emitted to ``BENCH_SCALE.json``.

Interface-compatible with ``PipelinedNVMeOptimizer`` so the engine's
``_nvme_train_step`` orchestration (fwd/bwd program + grouped update loop)
drives either storage tier.  Selected by
``offload_optimizer: {device: cpu, pipeline_read: true}`` on a
single-device mesh (the multi-chip answer is ZeRO sharding, not offload —
asserted by the multichip dryrun).
"""

from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.logging import log_dist
from .overlap_instrumentation import OverlapInstrumentation, now


class _NoopSwapper:
    """Duck-typed stand-in for the NVMe swapper's flush surface: host
    arrays are always durable (nothing is in flight on aio threads)."""

    def flush_writes(self):
        pass

    def teardown(self):
        pass


class HostStreamedOptimizer:
    """fp32 master + Adam moments in TPU-host pinned memory, updated by a
    double-buffered upload/compute/download pipeline of per-group
    dispatches with donated staging buffers."""

    def __init__(self, opt, param_leaves, n_groups: int = 8,
                 compute_dtype=jnp.bfloat16, mesh=None, max_staged: int = 2):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ...comm.mesh import get_global_mesh
        self.opt = opt
        self.compute_dtype = compute_dtype
        mesh = mesh if mesh is not None else get_global_mesh()
        self._dev_sh = NamedSharding(mesh, P())
        try:  # same probe as the engine's try_host_offload: CPU test
            # backends have no pinned_host memory kind — the grouped
            # dispatch structure (and its numerics) is identical, the
            # state just stays in device space there
            self._host_sh = self._dev_sh.with_memory_kind("pinned_host")
            jax.jit(lambda x: x, out_shardings=self._host_sh) \
                .lower(jax.ShapeDtypeStruct((1, ), jnp.float32)).compile()
        except Exception:
            log_dist("HostStreamedOptimizer: pinned_host unsupported on this "
                     "backend; grouped state stays in device memory", ranks=[0])
            self._host_sh = self._dev_sh
        # True when host and device are genuinely distinct memory spaces
        # (on CPU fallback uploads are zero-copy aliases)
        self.host_tier_distinct = self._host_sh is not self._dev_sh
        self.swapper = _NoopSwapper()
        self.events = deque(maxlen=512)
        self.instrumentation = OverlapInstrumentation()
        self.max_staged = max(1, int(max_staged))
        # staging arena: group id -> (master, mu, nu) device-resident lists;
        # a slot is consumed (and its buffers donated) exactly once
        self._staged: Dict[int, Tuple[List[Any], List[Any], List[Any]]] = {}
        self._update_fns: Dict[int, Callable] = {}

        # byte-balanced contiguous leaf partition (same policy as the NVMe
        # swapper so group sizes, and therefore the HBM staging bound, are
        # predictable: ~total_fp32_bytes x 3 x max_staged / n_groups live)
        sizes = [int(np.prod(l.shape)) * 4 for l in param_leaves]
        target = max(1, sum(sizes) // max(1, n_groups))
        self.groups: List[List[int]] = []
        cur, acc = [], 0
        for i, s in enumerate(sizes):
            cur.append(i)
            acc += s
            if acc >= target and len(self.groups) < n_groups - 1:
                self.groups.append(cur)
                cur, acc = [], 0
        if cur:
            self.groups.append(cur)
        self.n_groups = len(self.groups)

        # initialize host-resident state leaf-by-leaf: the fp32 master is
        # cast on device and streamed out (one leaf of HBM at a time, never
        # the whole fp32 tree); moments are born in host space
        to_host_f32 = jax.jit(lambda p: p.astype(jnp.float32), out_shardings=self._host_sh)
        self._master: List[List[Any]] = []
        self._mu: List[List[Any]] = []
        self._nu: List[List[Any]] = []
        for idxs in self.groups:
            ms, mus, nus = [], [], []
            for i in idxs:
                p = param_leaves[i]
                ms.append(to_host_f32(p))
                zeros = jax.jit(lambda p=p: jnp.zeros(p.shape, jnp.float32),
                                out_shardings=self._host_sh)()
                mus.append(zeros)
                nus.append(jax.jit(lambda p=p: jnp.zeros(p.shape, jnp.float32),
                                   out_shardings=self._host_sh)())
            self._master.append(ms)
            self._mu.append(mus)
            self._nu.append(nus)
        jax.block_until_ready(self._master[-1][-1])
        gb = sum(sizes) * 3 / 1e9
        log_dist(f"HostStreamedOptimizer: {len(param_leaves)} leaves in "
                 f"{self.n_groups} groups, {gb:.1f} GB fp32 state in host memory, "
                 f"~{gb / self.n_groups:.1f} GB HBM staging per slot "
                 f"(x{self.max_staged} slots)", ranks=[0])

    # ---------------------------------------------------------- update prog

    def _group_update(self, g: int):
        """Jitted per-group fused-Adam program over DEVICE-resident staged
        buffers.  The staged master/moments are donated: the staging slot's
        HBM is reused for the outputs, so one slot's bytes never count
        twice against the arena bound."""
        if g not in self._update_fns:
            from ...ops.adam import AdamState
            n = len(self.groups[g])
            dev = self._dev_sh

            def upd(master, mu, nu, grads, count, scale):
                g32 = [x.astype(jnp.float32) * scale for x in grads]
                updates, st = self.opt.update(g32, AdamState(count, mu, nu), master)
                new_master = [m + u for m, u in zip(master, updates)]
                new_params = [m.astype(self.compute_dtype) for m in new_master]
                return new_master, st.exp_avg, st.exp_avg_sq, new_params

            self._update_fns[g] = jax.jit(
                upd,
                donate_argnums=(0, 1, 2),
                in_shardings=([dev] * n, [dev] * n, [dev] * n, [dev] * n, dev, dev),
                out_shardings=([dev] * n, [dev] * n, [dev] * n, [dev] * n))
        return self._update_fns[g]

    # ------------------------------------------------------------- pipeline

    def prefetch(self, g: int) -> bool:
        """Issue group ``g``'s host→HBM upload (async ``device_put`` into a
        staging slot).  Bounded: refuses when ``max_staged`` slots are
        already live, so a caller racing ahead cannot blow the HBM arena.
        Idempotent per live slot.  Called by the engine right after the
        fwd/bwd dispatch so the first uploads overlap the backward."""
        if not (0 <= g < self.n_groups) or g in self._staged:
            return False
        if len(self._staged) >= self.max_staged:
            return False
        self.events.append(("upload_issue", g))
        self.instrumentation.record("upload_issue", g)
        self._staged[g] = jax.device_put(
            (self._master[g], self._mu[g], self._nu[g]), self._dev_sh)
        return True

    def _take_staged(self, g: int):
        """Consume group ``g``'s staging slot for the compute dispatch.
        The slot is removed BEFORE its buffers are donated: a second take
        (which would read donated buffers) fails loudly instead of
        returning deleted arrays."""
        staged = self._staged.pop(g, None)
        if staged is None:
            raise RuntimeError(
                f"HostStreamedOptimizer: staging slot for group {g} was never "
                "uploaded or was already consumed (donated) — double-consume "
                "would read a donated buffer")
        return staged

    def pending_writes(self) -> int:
        return 0  # host buffers: durable once their d2h device_put drains

    def step(self, grad_leaves, count, clip_scale, serialize: bool = False,
             flush: bool = False):
        """Per-group update sweep.  Returns new compute-dtype param leaves
        (device), original leaf order.

        Default (pipelined): upload(g+1) is issued before compute(g) is
        dispatched, download(g) right after — transfers ride under compute.
        The host fences one group behind the front; the LAST group's
        compute and all downloads are left in flight so they drain under
        the next step's fwd/bwd (``flush=True`` fences them and records
        the full pipelined wall time for measurement).

        ``serialize=True`` runs the instrumentation probe: a hard fence
        after every phase, recording honest per-group phase seconds into
        ``instrumentation.probe`` (numerics identical — same programs, same
        order, just fenced)."""
        if serialize:
            return self._step_serialized(grad_leaves, count, clip_scale)
        t_entry = now()
        # fence on the grads: compute cannot start before them anyway, and
        # everything already issued (incl. the backward-phase prefetches)
        # keeps running while the host waits here
        if grad_leaves:
            jax.block_until_ready(grad_leaves)
        t0 = now()
        bwd_wait_s = t0 - t_entry
        prefetch_wait_s = None
        self.prefetch(0)
        if 0 in self._staged:
            tw = now()
            jax.block_until_ready(self._staged[0])
            prefetch_wait_s = now() - tw  # ~0 when the upload hid behind bwd
        new_params: List[Optional[Any]] = [None] * sum(len(g) for g in self.groups)
        compute_done_ts: List[float] = []
        prev_probe = None  # (group, first param leaf) fencing one behind
        for g, idxs in enumerate(self.groups):
            # next group's upload rides the transfer engine WHILE this
            # group's compute runs (the double buffer)
            self.prefetch(g + 1)
            m, mu, nu = self._take_staged(g)
            # slot g is consumed: a refused prefetch above (max_staged=1)
            # gets its second chance now that the slot is free
            self.prefetch(g + 1)
            self.events.append(("compute_issue", g))
            self.instrumentation.record("compute_issue", g)
            nm, nmu, nnu, np_leaves = self._group_update(g)(
                m, mu, nu, [grad_leaves[i] for i in idxs], count, clip_scale)
            # async write-back: group g's d2h drains while g+1 computes —
            # and the LAST groups' downloads drain under the next fwd/bwd
            self.events.append(("download_issue", g))
            self.instrumentation.record("download_issue", g)
            self._master[g], self._mu[g], self._nu[g] = jax.device_put(
                (nm, nmu, nnu), self._host_sh)
            for i, p in zip(idxs, np_leaves):
                new_params[i] = p
            if prev_probe is not None:
                # fence ONE group behind the dispatch front: compute(g) and
                # upload(g+1) are already enqueued, so the device stays busy
                # while the host waits; this bounds live staging slots and
                # timestamps compute completion per group
                pg, leaf = prev_probe
                jax.block_until_ready(leaf)
                self.events.append(("update_done", pg))
                compute_done_ts.append(self.instrumentation.record("compute_done", pg))
            prev_probe = (g, np_leaves[0] if np_leaves else None)
        if flush and prev_probe is not None:
            pg, leaf = prev_probe
            jax.block_until_ready(leaf)
            self.events.append(("update_done", pg))
            compute_done_ts.append(self.instrumentation.record("compute_done", pg))
            jax.block_until_ready(self._master)  # all d2h write-backs landed
            self.instrumentation.set_step(now() - t0, bwd_wait_s=bwd_wait_s,
                                          prefetch_wait_s=prefetch_wait_s,
                                          compute_done_ts=compute_done_ts)
        return new_params

    def _step_serialized(self, grad_leaves, count, clip_scale):
        """Instrumentation probe sweep: same programs and issue order as the
        pipelined step, but with a hard fence after every phase so each
        group's upload/compute/download seconds are attributed exactly."""
        if grad_leaves:
            jax.block_until_ready(grad_leaves)
        # any slots staged by a backward-phase prefetch would blur the
        # upload attribution — drain and drop them (re-uploaded fenced)
        if self._staged:
            jax.block_until_ready(self._staged)
            self._staged.clear()
        t_sweep0 = now()
        new_params: List[Optional[Any]] = [None] * sum(len(g) for g in self.groups)
        per_group = []
        for g, idxs in enumerate(self.groups):
            t0 = now()
            self.prefetch(g)
            jax.block_until_ready(self._staged[g])
            t1 = self.instrumentation.record("upload_done", g)
            m, mu, nu = self._take_staged(g)
            self.events.append(("compute_issue", g))
            self.instrumentation.record("compute_issue", g)
            nm, nmu, nnu, np_leaves = self._group_update(g)(
                m, mu, nu, [grad_leaves[i] for i in idxs], count, clip_scale)
            jax.block_until_ready(np_leaves)
            self.events.append(("update_done", g))
            t2 = self.instrumentation.record("compute_done", g)
            self.events.append(("download_issue", g))
            self.instrumentation.record("download_issue", g)
            self._master[g], self._mu[g], self._nu[g] = jax.device_put(
                (nm, nmu, nnu), self._host_sh)
            jax.block_until_ready((self._master[g], self._mu[g], self._nu[g]))
            t3 = self.instrumentation.record("download_done", g)
            per_group.append({"upload_s": t1 - t0, "compute_s": t2 - t1,
                              "download_s": t3 - t2})
            for i, p in zip(idxs, np_leaves):
                new_params[i] = p
        self.instrumentation.set_probe(per_group, wall_s=now() - t_sweep0)
        return new_params

    def overlap_report(self):
        """Measured-overlap artifact (see overlap_instrumentation.report);
        None until a ``serialize=True`` probe sweep has run."""
        rep = self.instrumentation.report()
        if rep is not None:
            rep["host_tier_distinct"] = self.host_tier_distinct
            rep["max_staged"] = self.max_staged
        return rep

    # ------------------------------------------------- checkpoint surface

    def master_matches_params(self, param_leaves, compute_dtype) -> bool:
        """One representative leaf per group, compared in compute dtype
        (params were cast from exactly this master on a true resume)."""
        for g, idxs in enumerate(self.groups):
            disk = np.asarray(jax.device_get(self._master[g][0]),
                              np.float32).astype(compute_dtype)
            live = np.asarray(jax.device_get(param_leaves[idxs[0]]))
            if disk.shape != live.shape or not np.array_equal(disk, live):
                return False
        return True

    def resync_master_from_params(self, param_leaves):
        self._staged.clear()
        to_host_f32 = jax.jit(lambda p: p.astype(jnp.float32), out_shardings=self._host_sh)
        zeros_like_host = jax.jit(lambda p: jnp.zeros_like(p, jnp.float32),
                                  out_shardings=self._host_sh)
        for g, idxs in enumerate(self.groups):
            self._master[g] = [to_host_f32(param_leaves[i]) for i in idxs]
            self._mu[g] = [zeros_like_host(param_leaves[i]) for i in idxs]
            self._nu[g] = [zeros_like_host(param_leaves[i]) for i in idxs]

    def state_dict_host(self):
        out = []
        for g in range(self.n_groups):
            out.append({"master": [np.asarray(jax.device_get(x)) for x in self._master[g]],
                        "mu": [np.asarray(jax.device_get(x)) for x in self._mu[g]],
                        "nu": [np.asarray(jax.device_get(x)) for x in self._nu[g]]})
        return out

    # checkpoint persistence: UNLIKE the NVMe tier (whose swap files are
    # already durable on disk), host-tier state lives in process RAM — the
    # engine persists it into the checkpoint tag directory (as the
    # extra-state callback inside save_checkpoint's durability fence, so
    # the npz files are covered by the tag's crc32 manifest and written
    # BEFORE `latest` is published)
    def save_state(self, directory: str):
        import os

        from ...resilience.atomic_io import atomic_savez
        for g in range(self.n_groups):
            arrs = {}
            for name, store in (("master", self._master), ("mu", self._mu), ("nu", self._nu)):
                for i, x in enumerate(store[g]):
                    arrs[f"{name}_{i}"] = np.asarray(jax.device_get(x))
            atomic_savez(os.path.join(directory, f"host_opt_group{g}.npz"), arrs,
                         site="host_opt.save")

    def load_state(self, directory: str) -> bool:
        """Restore group state saved by ``save_state``; False when the files
        are absent, torn/corrupt (checksum manifest or archive read fails —
        rejected up front, never mid-restore), or shaped for a different
        partitioning.  The live state is only replaced once EVERY group
        verified and loaded."""
        import os
        import zipfile

        from ...resilience import events
        from ...resilience import fault_injection as fi
        from ...resilience.atomic_io import verify_manifest
        from ...resilience.retry import RetryPolicy, retry_call
        from ...utils.logging import logger
        # transient read errors at the load entry are retryable (the
        # os_error taxonomy contract); archive-level failures below degrade
        # to a False return instead
        retry_call(lambda: fi.check("host_opt.load"),
                   RetryPolicy(max_attempts=3, base_delay_s=0.01, max_delay_s=0.25,
                               budget_s=2.0),
                   site="host_opt.load")
        if not all(os.path.exists(os.path.join(directory, f"host_opt_group{g}.npz"))
                   for g in range(self.n_groups)):
            return False
        # the tag-level resilience manifest (written post-fence by
        # save_checkpoint) pins every npz to its crc32; a tag saved before
        # the manifest existed falls through to the archive-read guard
        errors = verify_manifest(directory,
                                 match=lambda rel: rel.startswith("host_opt_group"))
        if errors:
            logger.warning("host-streamed offload: rejecting host_opt_group*.npz "
                           f"state at {directory} — checksum manifest failed: "
                           f"{errors[0]}")
            events.emit("resilience/host_opt_reject")
            return False
        loads = []
        for g in range(self.n_groups):
            path = os.path.join(directory, f"host_opt_group{g}.npz")
            try:
                with np.load(path) as z:
                    grp = {name: [z[f"{name}_{i}"] for i in range(len(self.groups[g]))]
                           for name in ("master", "mu", "nu")}
            except (OSError, ValueError, KeyError, zipfile.BadZipFile, EOFError) as e:
                logger.warning(f"host-streamed offload: rejecting truncated/corrupt "
                               f"{path}: {e}")
                events.emit("resilience/host_opt_reject")
                return False
            if any(g_arr.shape != np.asarray(jax.device_get(cur)).shape
                   for g_arr, cur in zip(grp["master"], self._master[g])):
                return False
            loads.append(grp)
        self._staged.clear()  # staged slots would upload pre-restore state
        for g, grp in enumerate(loads):
            self._master[g] = [jax.device_put(x, self._host_sh) for x in grp["master"]]
            self._mu[g] = [jax.device_put(x, self._host_sh) for x in grp["mu"]]
            self._nu[g] = [jax.device_put(x, self._host_sh) for x in grp["nu"]]
        return True

    def teardown(self):
        self._master = self._mu = self._nu = []
        self._staged.clear()
