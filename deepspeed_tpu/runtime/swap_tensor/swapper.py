"""Tensor swapping to NVMe/disk via the native async-IO engine.

Reference: ``deepspeed/runtime/swap_tensor/`` —
  ``AsyncPartitionedParameterSwapper`` (partitioned_param_swapper.py),
  ``partitioned_optimizer_swapper.py``, ``async_swapper.py``,
  ``aio_config.py`` — asynchronous O_DIRECT NVMe swap of params and
  optimizer state, overlapped with the step via pipelined read/write.

TPU-native realisation: pytrees of (numpy/jax) arrays are flattened, each
leaf streamed to its own file region through ``ops/aio`` (C++ thread-pool
engine).  ``swap_out_async``/``swap_in_async`` return handles so the engine
can overlap swap traffic of sub-group *i±1* with the optimizer step of
sub-group *i* (ref: pipelined_optimizer_swapper.py double buffering —
here the overlap is host-thread concurrency against device compute).
"""

import dataclasses
import json
import os
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ...resilience import fault_injection as fi
from ...resilience.retry import RetryPolicy, retry_call
from ...utils.logging import logger

# swap I/O sits on the training critical path: retries are short and few —
# a persistently failing NVMe should surface fast, not stall the step
_SWAP_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.01, max_delay_s=0.25,
                          budget_s=2.0)


@dataclasses.dataclass(frozen=True)
class AioSwapConfig:
    """ref: runtime/swap_tensor/aio_config.py (block_size/queue_depth/
    thread_count/single_submit/overlap_events)."""
    block_size: int = 1 << 20
    queue_depth: int = 32
    thread_count: int = 4
    use_o_direct: bool = False


class SwapInHandle:
    """Pending swap-in; ``wait()`` returns the reconstructed pytree."""

    def __init__(self, aio, buffers: List[np.ndarray], treedef, shapes, dtypes):
        self._aio = aio
        self._buffers = buffers
        self._treedef = treedef
        self._shapes = shapes
        self._dtypes = dtypes
        self._result = None

    def wait(self):
        if self._result is None:
            self._aio.wait()
            leaves = [b.reshape(s) for b, s in zip(self._buffers, self._shapes)]
            self._result = jax.tree.unflatten(self._treedef, leaves)
            self._buffers = []
        return self._result


class SwapOutHandle:
    def __init__(self, aio):
        self._aio = aio
        self._done = False

    def wait(self):
        if not self._done:
            self._aio.wait()
            self._done = True


class TensorSwapper:
    """Pytree↔disk swapper (one file per key, leaves concatenated at
    block-aligned offsets; manifest json carries shapes/dtypes)."""

    def __init__(self, swap_dir: str, config: AioSwapConfig = AioSwapConfig()):
        from ...ops.aio import AsyncIOHandle
        self.dir = Path(swap_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.config = config
        self._aio_factory = lambda: AsyncIOHandle(config.block_size, config.queue_depth,
                                                  config.thread_count, config.use_o_direct)
        self._manifests: Dict[str, dict] = {}

    def _path(self, key: str) -> Path:
        return self.dir / f"{key}.swp"

    def _align(self, n: int) -> int:
        a = 4096
        return -(-n // a) * a

    def swap_out_async(self, key: str, tree, _retry: bool = True) -> SwapOutHandle:
        leaves = jax.tree.leaves(tree)
        treedef = jax.tree.structure(tree)
        np_leaves = [np.ascontiguousarray(jax.device_get(l)) for l in leaves]
        offsets, off = [], 0
        for l in np_leaves:
            offsets.append(off)
            off += self._align(l.nbytes)
        self._manifests[key] = {
            "treedef": treedef,
            "shapes": [l.shape for l in np_leaves],
            "dtypes": [str(l.dtype) for l in np_leaves],
            "offsets": offsets,
        }
        path = self._path(key)

        def _issue_writes():
            # ISSUE-time transients retried with backoff (the re-issue
            # rewrites every leaf region, so a half-issued first attempt is
            # harmless).  Failures surfacing later in the handle's wait()
            # propagate on the ASYNC path — the blocking swap_out wrapper
            # retries the whole issue+wait cycle instead
            fi.check("swap.write")
            aio = self._aio_factory()
            for l, o in zip(np_leaves, offsets):
                aio.async_pwrite(l.reshape(-1), path, o)
            return aio

        aio = retry_call(_issue_writes, _SWAP_RETRY, site="swap.write") if _retry \
            else _issue_writes()
        return SwapOutHandle(aio)

    def swap_out(self, key: str, tree) -> None:
        # blocking path: transient wait-side failures (EIO surfaced at
        # completion) are absorbed by re-running the WHOLE issue+wait
        # cycle — every leaf region is rewritten, so it is idempotent.
        # The inner issue retry is disabled here: ONE policy governs the
        # attempt count (nested retries would multiply to 3x3 and make
        # chaos-plan hit counts unpredictable)
        retry_call(lambda: self.swap_out_async(key, tree, _retry=False).wait(),
                   _SWAP_RETRY, site="swap.write")

    def swap_in_async(self, key: str, _retry: bool = True) -> SwapInHandle:
        m = self._manifests[key]
        path = self._path(key)

        def _issue_reads():
            # issue-time transients only (see _issue_writes); fresh buffers
            # per attempt so a torn first attempt cannot leak into the
            # returned handle
            fi.check("swap.read")
            aio = self._aio_factory()
            buffers = []
            for shape, dtype, off in zip(m["shapes"], m["dtypes"], m["offsets"]):
                buf = np.empty(int(np.prod(shape)) if shape else 1, dtype=np.dtype(dtype))
                aio.async_pread(buf, path, off)
                buffers.append(buf)
            return aio, buffers

        aio, buffers = retry_call(_issue_reads, _SWAP_RETRY, site="swap.read") if _retry \
            else _issue_reads()
        return SwapInHandle(aio, buffers, m["treedef"], m["shapes"], m["dtypes"])

    def swap_in(self, key: str):
        # blocking path: issue+wait retried end-to-end (fresh handle and
        # buffers per attempt; inner issue retry disabled — one policy
        # governs the attempt count)
        return retry_call(lambda: self.swap_in_async(key, _retry=False).wait(),
                          _SWAP_RETRY, site="swap.read")

    def release(self, key: str) -> None:
        self._manifests.pop(key, None)
        p = self._path(key)
        if p.exists():
            p.unlink()

    def swapped_keys(self):
        return list(self._manifests)

    def teardown(self):
        for k in list(self._manifests):
            self.release(k)


class PartitionedOptimizerSwapper:
    """Optimizer-state sub-group swapping (ref: partitioned_optimizer_swapper
    .py + pipelined_optimizer_swapper.py).  The engine steps sub-groups
    sequentially; ``prefetch`` overlaps the next group's read with the
    current group's compute."""

    def __init__(self, swap_dir: str, config: AioSwapConfig = AioSwapConfig()):
        self.swapper = TensorSwapper(swap_dir, config)
        self._pending_in: Dict[int, SwapInHandle] = {}
        self._pending_out: Dict[int, SwapOutHandle] = {}

    def swap_out_group(self, group_id: int, state_tree, blocking: bool = False):
        h = self.swapper.swap_out_async(f"optgroup_{group_id}", state_tree)
        if blocking:
            h.wait()
        else:
            self._pending_out[group_id] = h

    def prefetch_group(self, group_id: int):
        if group_id not in self._pending_in:
            if group_id in self._pending_out:  # write must land before read
                self._pending_out.pop(group_id).wait()
            self._pending_in[group_id] = self.swapper.swap_in_async(f"optgroup_{group_id}")

    def swap_in_group(self, group_id: int):
        self.prefetch_group(group_id)
        return self._pending_in.pop(group_id).wait()

    def flush_writes(self):
        for h in self._pending_out.values():
            h.wait()
        self._pending_out.clear()
