"""Measured overlap for the streamed-optimizer group pipelines.

The streamed tiers (``HostStreamedOptimizer``, ``PipelinedNVMeOptimizer``)
claim that group *g+1*'s state transfer hides behind group *g*'s fused
Adam dispatch.  This module turns that claim into numbers instead of a
docstring: each pipeline records timestamped per-group phase events here,
plus two kinds of timing sweeps —

* **serialized probe** (``set_probe``): one update sweep run with a hard
  fence after every phase, yielding honest per-group ``upload_s`` /
  ``compute_s`` / ``download_s`` durations (no overlap possible, so the
  phase attribution is exact);
* **pipelined step** (``set_step``): the normal double-buffered sweep,
  fenced only at entry (gradients ready) and exit (all outputs + host
  write-backs ready), yielding the achieved wall time and per-group
  compute-completion timestamps.

``report()`` combines the two into the artifact fields
(``BENCH_SCALE.json`` host-streamed leg, docs/PERF.md):

  serialized_s     = Σ(upload + compute + download)      -- no-overlap cost
  transfer_s       = Σ(upload + download)
  ideal_pipelined_s= max(compute_s, transfer_s)          -- perfect-overlap
                     floor, conservatively assuming ONE transfer engine
                     serves both directions
  overlap_fraction = (serialized_s - pipelined_wall_s)
                     / (serialized_s - ideal_pipelined_s)   in [0, 1]
  bound            = "transfer" | "compute" -- which floor binds; a
                     transfer-bound pipeline CANNOT reach compute-limited
                     throughput no matter how good the scheduling, and the
                     floor value is the receipt.

Per-group device-idle gaps come from the pipelined step's compute
completion timestamps minus the probe's compute durations at the same
shapes.
"""

import time
from collections import deque
from typing import Any, Dict, List, Optional

PHASES = ("upload", "compute", "download")


def now() -> float:
    return time.perf_counter()  # dslint-ok(determinism): the pipeline perf-clock primitive itself; lifted into the tracer domain by anchor offset


class OverlapInstrumentation:
    """Timestamped event ring + probe/step records for one pipeline."""

    def __init__(self, maxlen: int = 4096):
        self.events = deque(maxlen=maxlen)
        self.probe: Optional[Dict[str, Any]] = None
        self.last_step: Optional[Dict[str, Any]] = None
        # bumped on every probe/step record so consumers (monitor) can emit
        # a report once per fresh measurement instead of every step
        self.version = 0

    # ------------------------------------------------------------- events

    def record(self, kind: str, group: int) -> float:
        t = now()
        self.events.append((kind, group, t))
        return t

    def events_of(self, kind: str) -> Dict[int, float]:
        """Latest timestamp per group for ``kind``."""
        out: Dict[int, float] = {}
        for k, g, t in self.events:
            if k == kind:
                out[g] = t
        return out

    # ------------------------------------------------------------- sweeps

    def set_probe(self, per_group: List[Dict[str, float]], wall_s: float):
        totals = {f"{ph}_s": sum(g[f"{ph}_s"] for g in per_group) for ph in PHASES}
        self.probe = {
            "per_group": per_group,
            "wall_s": wall_s,
            "serialized_s": sum(totals.values()),
            **totals,
        }
        self.version += 1

    def set_step(self, wall_s: float, bwd_wait_s: Optional[float] = None,
                 prefetch_wait_s: Optional[float] = None,
                 compute_done_ts: Optional[List[float]] = None):
        self.last_step = {
            "pipelined_wall_s": wall_s,
            "bwd_wait_s": bwd_wait_s,
            "prefetch_wait_s": prefetch_wait_s,
            "compute_done_ts": compute_done_ts,
        }
        self.version += 1

    # ----------------------------------------------------------- telemetry

    def lift_spans(self, tracer, parent, track: str = "stream",
                   since_ts: float = 0.0, offset: float = 0.0) -> int:
        """Lift the phase events recorded since ``since_ts`` (perf-counter
        domain) into trace spans under ``parent`` (a telemetry Span).

        Paired ``<phase>_issue``/``<phase>_done`` events for a group
        become a real child span ``<phase> g<N>``; an unpaired issue (an
        async transfer left in flight by the pipelined sweep — by design)
        becomes a point event on ``parent``, so the trace never claims a
        duration nobody measured.  ``offset`` maps perf-counter timestamps
        into the tracer's clock domain.  Returns how many spans were
        materialized."""
        pairs: Dict[tuple, float] = {}   # (phase, group) -> issue ts
        made = 0
        for kind, g, t in self.events:
            if t < since_ts or "_" not in kind:
                continue
            phase, _, edge = kind.rpartition("_")
            if phase not in PHASES:
                continue
            if edge == "issue":
                pairs[(phase, g)] = t
            elif edge == "done":
                t0 = pairs.pop((phase, g), None)
                if t0 is None:
                    parent.event(f"{phase}_done g{g}", t + offset)
                    continue
                tracer.add_span(f"{phase} g{g}", parent.trace_id,
                                t0 + offset, t + offset,
                                parent_id=parent.span_id, track=track,
                                attrs={"group": g, "phase": phase})
                made += 1
        for (phase, g), t0 in sorted(pairs.items()):
            parent.event(f"{phase}_issue g{g}", t0 + offset, {"in_flight": True})
        return made

    # ------------------------------------------------------------- report

    def report(self) -> Optional[Dict[str, Any]]:
        """Combine the latest serialized probe and pipelined step into the
        overlap artifact.  None until a probe has run."""
        if self.probe is None:
            return None
        p = self.probe
        transfer_s = p["upload_s"] + p["download_s"]
        ideal = max(p["compute_s"], transfer_s)
        rep: Dict[str, Any] = {
            "n_groups": len(p["per_group"]),
            "per_group": [dict(g) for g in p["per_group"]],
            "upload_s": round(p["upload_s"], 6),
            "compute_s": round(p["compute_s"], 6),
            "download_s": round(p["download_s"], 6),
            "serialized_s": round(p["serialized_s"], 6),
            "transfer_s": round(transfer_s, 6),
            "ideal_pipelined_s": round(ideal, 6),
            "bound": "transfer" if transfer_s > p["compute_s"] else "compute",
        }
        step = self.last_step
        if step is not None:
            wall = step["pipelined_wall_s"]
            rep["pipelined_wall_s"] = round(wall, 6)
            hideable = p["serialized_s"] - ideal
            if hideable > 1e-9:
                frac = (p["serialized_s"] - wall) / hideable
            else:
                # nothing to hide (e.g. CPU fallback: zero-copy transfers)
                frac = 1.0
            rep["overlap_fraction"] = round(min(1.0, max(0.0, frac)), 4)
            rep["speedup_vs_serialized"] = round(p["serialized_s"] / max(wall, 1e-9), 4)
            if step.get("bwd_wait_s") is not None:
                rep["bwd_wait_s"] = round(step["bwd_wait_s"], 6)
            if step.get("prefetch_wait_s") is not None:
                # ~0 when the backward-phase prefetch really hid the first
                # uploads behind the fwd/bwd program
                rep["prefetch_wait_after_bwd_s"] = round(step["prefetch_wait_s"], 6)
            ts = step.get("compute_done_ts")
            if ts and len(ts) >= 2:
                gaps = []
                for g in range(1, len(ts)):
                    span = ts[g] - ts[g - 1]
                    comp = p["per_group"][g]["compute_s"] if g < len(p["per_group"]) else 0.0
                    gaps.append(max(0.0, span - comp))
                rep["device_idle_gap_s_per_group"] = [round(x, 6) for x in gaps]
                rep["device_idle_gap_s"] = round(sum(gaps), 6)
        return rep
