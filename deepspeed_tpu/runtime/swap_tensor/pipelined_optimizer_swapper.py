"""Pipelined NVMe optimizer-state swapping (ZeRO-Infinity's in-step path).

Reference: ``deepspeed/runtime/swap_tensor/pipelined_optimizer_swapper.py``
— optimizer sub-states live on NVMe and are double-buffered around the
update: while sub-group *g* updates, group *g+1*'s read and group *g-1*'s
write are in flight on the aio threads, and the tail writes drain while
the NEXT step's forward/backward runs on the device.

TPU-native realisation: the fwd/bwd stays ONE jitted device program
(grads + loss + grad-norm out); the optimizer update runs per sub-group
in a small jitted program whose fp32 master/moments stream
disk → host → HBM → disk through ``PartitionedOptimizerSwapper``
(ops/aio C++ thread pool underneath).  Configured by
``zero_optimization.offload_optimizer: {device: nvme, nvme_path: ...}``.
"""

import dataclasses
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.logging import log_dist
from .overlap_instrumentation import OverlapInstrumentation, now
from .swapper import AioSwapConfig, PartitionedOptimizerSwapper


class PipelinedNVMeOptimizer:
    """Owns the fp32 master + Adam moments on NVMe, partitioned into
    byte-balanced sub-groups of parameter leaves; ``step`` runs the
    double-buffered update loop.  ``events`` records the issue order
    (prefetch/update/writeback) so tests can assert the overlap structure
    without depending on disk timing; ``instrumentation`` additionally
    timestamps every phase and ``step(serialize=True)`` runs the fenced
    probe sweep that turns the overlap claim into measured per-group
    read/compute/write seconds (same surface as HostStreamedOptimizer)."""

    def __init__(self, opt, param_leaves, nvme_path: str, n_groups: int = 4,
                 compute_dtype=jnp.bfloat16, aio: AioSwapConfig = AioSwapConfig()):
        self.opt = opt
        self.compute_dtype = compute_dtype
        self.swapper = PartitionedOptimizerSwapper(nvme_path, aio)
        # bounded instrumentation ring (tests assert the double-buffer issue
        # order; production steps must not accumulate host memory)
        self.events = deque(maxlen=512)
        self.instrumentation = OverlapInstrumentation()
        self._update_fns: Dict[int, Callable] = {}

        # byte-balanced contiguous leaf partition
        sizes = [int(np.prod(l.shape)) * 4 for l in param_leaves]
        target = max(1, sum(sizes) // max(1, n_groups))
        self.groups: List[List[int]] = []
        cur, acc = [], 0
        for i, s in enumerate(sizes):
            cur.append(i)
            acc += s
            if acc >= target and len(self.groups) < n_groups - 1:
                self.groups.append(cur)
                cur, acc = [], 0
        if cur:
            self.groups.append(cur)
        self.n_groups = len(self.groups)

        # resume: matching swap files from a previous run are REUSED (the
        # checkpoint stores params+step; the moments live here — see
        # engine.save_checkpoint); otherwise initialize fp32 master from
        # the params + zero moments, written straight to disk, never
        # resident in full
        shapes = [[list(param_leaves[i].shape) for i in idxs] for idxs in self.groups]
        meta_path = self.swapper.swapper.dir / "pipelined_meta.json"
        if self._try_resume(meta_path, shapes):
            log_dist(f"PipelinedNVMeOptimizer: resumed {self.n_groups} sub-groups "
                     f"from {nvme_path}", ranks=[0])
            return
        for g, idxs in enumerate(self.groups):
            master = [np.asarray(jax.device_get(param_leaves[i]), np.float32) for i in idxs]
            sub = {"master": master,
                   "mu": [np.zeros_like(m) for m in master],
                   "nu": [np.zeros_like(m) for m in master]}
            self.swapper.swap_out_group(g, sub, blocking=True)
        # atomic: resume must never see a half-written partitioning manifest
        from ...resilience.atomic_io import atomic_write_json
        atomic_write_json(str(meta_path), {"groups": shapes})
        log_dist(f"PipelinedNVMeOptimizer: {len(param_leaves)} leaves in "
                 f"{self.n_groups} sub-groups on {nvme_path}", ranks=[0])

    def _try_resume(self, meta_path, shapes) -> bool:
        """Rebuild the swapper manifests from persisted metadata when the
        on-disk sub-states match this model's partitioning exactly."""
        import json
        if not meta_path.exists():
            return False
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return False
        if meta.get("groups") != shapes:
            log_dist(f"PipelinedNVMeOptimizer: swap files at {meta_path.parent} "
                     "belong to a different model partitioning — reinitializing",
                     ranks=[0])
            return False
        ts = self.swapper.swapper
        for g, group_shapes in enumerate(shapes):
            key = f"optgroup_{g}"
            if not ts._path(key).exists():
                return False
            # leaf order of {"master": [...], "mu": [...], "nu": [...]} is
            # alphabetical keys → master, mu, nu; offsets re-derived with the
            # same alignment rule the writer used
            template = {"master": [np.empty(0)] * len(group_shapes),
                        "mu": [np.empty(0)] * len(group_shapes),
                        "nu": [np.empty(0)] * len(group_shapes)}
            all_shapes = [tuple(s) for s in group_shapes] * 3
            offsets, off = [], 0
            for s in all_shapes:
                offsets.append(off)
                off += ts._align(int(np.prod(s)) * 4 if s else 4)
            ts._manifests[key] = {
                "treedef": jax.tree.structure(template),
                "shapes": all_shapes,
                "dtypes": ["float32"] * len(all_shapes),
                "offsets": offsets,
            }
        return True

    def _group_update(self, g: int):
        """Jitted per-group update: AdamState math over this group's leaves
        (the generic GradientTransformation applied to a sub-tree)."""
        if g not in self._update_fns:
            from ...ops.adam import AdamState

            def upd(master, mu, nu, grads, count, scale):
                g32 = [x.astype(jnp.float32) * scale for x in grads]
                updates, st = self.opt.update(g32, AdamState(count, mu, nu), master)
                new_master = [m + u for m, u in zip(master, updates)]
                new_params = [m.astype(self.compute_dtype) for m in new_master]
                return new_master, st.exp_avg, st.exp_avg_sq, new_params

            self._update_fns[g] = jax.jit(upd, donate_argnums=(0, 1, 2))
        return self._update_fns[g]

    def pending_writes(self) -> int:
        return len(self.swapper._pending_out)

    def resync_master_from_params(self, param_leaves):
        """Rewrite the disk master from freshly-loaded params (zeroing the
        moments): called by load_checkpoint when the swap files do NOT
        belong to the loaded training state."""
        self.swapper.flush_writes()
        for g, idxs in enumerate(self.groups):
            master = [np.asarray(jax.device_get(param_leaves[i]), np.float32) for i in idxs]
            sub = {"master": master,
                   "mu": [np.zeros_like(m) for m in master],
                   "nu": [np.zeros_like(m) for m in master]}
            self.swapper.swap_out_group(g, sub, blocking=True)

    def master_matches_params(self, param_leaves, compute_dtype) -> bool:
        """True when the disk master corresponds to ``param_leaves`` (the
        true-resume case: params were cast from exactly this master).
        Checks one representative leaf per group."""
        self.swapper.flush_writes()
        for g, idxs in enumerate(self.groups):
            sub = self.swapper.swap_in_group(g)
            disk = np.asarray(sub["master"][0], np.float32).astype(compute_dtype)
            live = np.asarray(jax.device_get(param_leaves[idxs[0]]))
            if disk.shape != live.shape or not np.allclose(disk, live, atol=0, rtol=0):
                return False
        return True

    def prefetch(self, g: int) -> bool:
        """Issue group ``g``'s disk read on the aio threads (idempotent —
        the swapper tracks pending reads).  The engine calls this right
        after dispatching the fwd/bwd program so the first groups' reads
        overlap the BACKWARD instead of starting at the step boundary."""
        if not (0 <= g < self.n_groups) or g in self.swapper._pending_in:
            return False
        self.events.append(("prefetch_issue", g))
        self.instrumentation.record("upload_issue", g)
        self.swapper.prefetch_group(g)
        return True

    def step(self, grad_leaves, count, clip_scale, serialize: bool = False,
             flush: bool = False):
        """Double-buffered update sweep.  Returns the new compute-dtype
        param leaves (device), in original leaf order.

        ``serialize=True`` runs the instrumentation probe (fence after
        every phase, blocking writes) attributing per-group read/compute/
        write seconds; ``flush=True`` drains the tail writes and records
        the pipelined wall time for measurement."""
        if grad_leaves and (serialize or flush):
            jax.block_until_ready(grad_leaves)
        t0 = now()
        new_params: List[Optional[Any]] = [None] * sum(len(g) for g in self.groups)
        per_group = []
        if not serialize:  # probe mode keeps reads sequential for attribution
            self.prefetch(0)
        for g, idxs in enumerate(self.groups):
            if not serialize:
                # next group's disk read rides the aio threads WHILE this
                # group's update computes (the double buffer)
                self.prefetch(g + 1)
            tg0 = now()
            # read stall: time the host actually waits on the aio threads —
            # ~0 when the prefetch fully hid the read behind prior compute
            sub = self.swapper.swap_in_group(g)
            tg1 = self.instrumentation.record("upload_done", g)
            self.instrumentation.record("compute_issue", g)
            nm, nmu, nnu, np_leaves = self._group_update(g)(
                sub["master"], sub["mu"], sub["nu"],
                [grad_leaves[i] for i in idxs], count, clip_scale)
            if serialize:
                jax.block_until_ready(np_leaves)
                tg2 = self.instrumentation.record("compute_done", g)
            for i, p in zip(idxs, np_leaves):
                new_params[i] = p
            # the device_get is this tier's natural compute fence (outputs
            # stream d2h for the disk write)
            host_sub = {"master": [np.asarray(x) for x in jax.device_get(nm)],
                        "mu": [np.asarray(x) for x in jax.device_get(nmu)],
                        "nu": [np.asarray(x) for x in jax.device_get(nnu)]}
            if not serialize:
                tg2 = self.instrumentation.record("compute_done", g)
            self.events.append(("update_done", g))
            # async write-back: drains while group g+1 updates — and the
            # LAST groups' writes drain while the next step's fwd/bwd runs
            self.swapper.swap_out_group(g, host_sub, blocking=serialize)
            self.events.append(("writeback_issue", g))
            tg3 = self.instrumentation.record("download_issue", g)
            if serialize:
                per_group.append({"upload_s": tg1 - tg0, "compute_s": tg2 - tg1,
                                  "download_s": tg3 - tg2})
        if serialize:
            self.instrumentation.set_probe(per_group, wall_s=now() - t0)
        elif flush:
            self.swapper.flush_writes()
            done = self.instrumentation.events_of("compute_done")
            self.instrumentation.set_step(
                now() - t0,
                compute_done_ts=[done[g] for g in range(self.n_groups) if g in done])
        return new_params

    def overlap_report(self):
        """Measured-overlap artifact; None until a ``serialize=True`` probe
        sweep has run."""
        return self.instrumentation.report()

    def state_dict_host(self):
        """Materialize the full optimizer state on host (checkpointing)."""
        self.swapper.flush_writes()
        out = []
        for g in range(self.n_groups):
            out.append(self.swapper.swap_in_group(g))
            # reading consumed the pending-in handle; re-register nothing —
            # the on-disk copy is still valid
        return out

    def teardown(self):
        self.swapper.flush_writes()
        self.swapper.swapper.teardown()
