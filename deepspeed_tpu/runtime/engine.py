"""DeepSpeedEngine — the training engine.

TPU-native analog of ``deepspeed/runtime/engine.py:189 DeepSpeedEngine``
(3,990 LoC).  The reference wraps an eager torch ``nn.Module`` and
orchestrates fwd/bwd/step with hook-driven ZeRO machinery; here the whole
train step — gradient accumulation scan, loss scaling, grad sharding
constraints (reduce-scatter), clipping, optimizer update, master-weight
recast — is ONE jitted program whose in/out shardings realise the configured
ZeRO stage (see runtime/zero/partition.py).  What the reference does with
streams, hooks and buckets, XLA's scheduler does from the program structure.

API parity map (reference → here):
  engine.forward(batch)            → forward()            (engine.py:2041)
  engine.backward(loss)            → backward()           (engine.py:2204)
  engine.step()                    → step()               (engine.py:2338)
  engine.train_batch(...)          → train_batch()        (pipe/engine.py:338;
        promoted here to the primary fused path for all configs)
  engine.eval_batch                → eval_batch
  engine.save_checkpoint/load_...  → save_checkpoint/load_checkpoint
        (engine.py:3274/2928; implemented over orbax in checkpoint/engine.py)
  engine.no_sync                   → no_sync (engine.py:2184; no-op — grad
        sync placement is compiled, accumulation already local)
"""

import os
from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm import mesh as mesh_lib
from ..comm.mesh import BATCH_AXES, SEQ_AXIS, MeshSpec, create_mesh, set_global_mesh
from ..ops import optimizer as opt_lib
from ..ops.adam import adam, adamw, fused_adam
from ..ops.adagrad import adagrad, sgd
from ..ops.lamb import fused_lamb
from ..ops.lion import fused_lion
from ..ops.onebit import onebit_adam, onebit_lamb, zero_one_adam
from ..utils.logging import log_dist, logger
from ..utils.timer import (BACKWARD_GLOBAL_TIMER, FORWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER, TRAIN_BATCH_TIMER,
                           NoopTimer, SynchronizedWallClockTimer, ThroughputTimer)
from .config import DeepSpeedConfig
from .constants import (ADAGRAD_OPTIMIZER, ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER,
                        FUSED_LAMB_OPTIMIZER, LAMB_OPTIMIZER, LION_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
                        ONEBIT_LAMB_OPTIMIZER, SGD_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER)
from .fp16.loss_scaler import DynamicLossScaler, LossScalerState, create_loss_scaler, found_inf_or_nan
from .lr_schedules import LRSchedulerShim, get_lr_schedule
from .zero.partition import grad_shardings as make_grad_shardings
from .zero.partition import master_and_optstate_shardings

OPTIMIZER_FACTORIES = {
    ADAM_OPTIMIZER: adam,
    ADAMW_OPTIMIZER: adamw,
    FUSED_ADAM_OPTIMIZER: fused_adam,
    "cpuadam": fused_adam,  # offload handled by sharding/memory-kind, same math
    LAMB_OPTIMIZER: fused_lamb,
    FUSED_LAMB_OPTIMIZER: fused_lamb,
    LION_OPTIMIZER: fused_lion,
    ADAGRAD_OPTIMIZER: adagrad,
    SGD_OPTIMIZER: sgd,
    ONEBIT_ADAM_OPTIMIZER: onebit_adam,
    ONEBIT_LAMB_OPTIMIZER: onebit_lamb,
    ZERO_ONE_ADAM_OPTIMIZER: zero_one_adam,
}


class TrainState(NamedTuple):
    """Everything the compiled step reads+writes.  ``master`` is the fp32
    copy (ref: runtime/bf16_optimizer.py fp32 groups); when training in fp32
    it is aliased conceptually to params (stored once, params is the master).
    """
    step: jnp.ndarray
    params: Any  # compute dtype
    master: Any  # fp32 master (or () when compute dtype is fp32)
    opt_state: Any
    scaler: LossScalerState
    skipped_steps: jnp.ndarray


class StepMetrics(NamedTuple):
    loss: jnp.ndarray
    grad_norm: jnp.ndarray
    found_inf: jnp.ndarray
    lr: jnp.ndarray
    loss_scale: jnp.ndarray


def _default_model_inputs(batch):
    kw = {}
    for k in ("positions", "segment_ids"):
        if k in batch:
            kw[k] = batch[k]
    return (batch["input_ids"], ), kw


def _default_loss_fn(outputs, batch):
    from ..models.llama import causal_lm_loss
    if "labels" not in batch:
        raise KeyError("batch must contain 'labels' for the default causal-LM loss; "
                       "pass loss_fn= to initialize() for custom losses")
    return causal_lm_loss(outputs, batch["labels"], batch.get("loss_mask"))


class DeepSpeedEngine:

    def __init__(self,
                 model,
                 config: DeepSpeedConfig,
                 optimizer=None,
                 lr_scheduler=None,
                 loss_fn: Optional[Callable] = None,
                 model_inputs_fn: Optional[Callable] = None,
                 mesh=None,
                 params=None,
                 init_rng=None,
                 dont_change_device=False):
        self.module = model
        self._config = config
        self.loss_fn = loss_fn or _default_loss_fn
        self.model_inputs_fn = model_inputs_fn or _default_model_inputs
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.init_rng = init_rng if init_rng is not None else jax.random.PRNGKey(0)

        # ---- mesh (ref: groups.py group creation + initialize_mesh_device)
        if mesh is None:
            spec = MeshSpec(pipe=config.pipeline.stages,
                            data=-1,
                            expert=config.moe.expert_parallel_size,
                            seq=config.sequence_parallel_size,
                            tensor=config.tensor_parallel_config.autotp_size)
            mesh = create_mesh(spec)
        self.mesh = mesh
        set_global_mesh(mesh)

        self.compute_dtype = config.precision_dtype
        self.zero_stage = config.zero_optimization_stage
        self.gas = config.gradient_accumulation_steps

        # ---- loss scaling (ref: runtime/fp16/loss_scaler.py)
        self.loss_scaler = create_loss_scaler(config.fp16_config, self.compute_dtype)

        # ---- optimizer transform + lr schedule
        self.lr_base, self._base_lr_schedule = self._build_lr_schedule()
        # variable-batch LR scaling (ref: data_sampling/variable_batch_size_
        # and_lr.py scale_lr): _lr_scale is a python float read at TRACE time
        # — each batch-size bucket compiles its own step with its own scale
        # (the jit cache is keyed on it via _ensure_ready)
        self._lr_scale = 1.0
        self._vblr = None  # (ref_batch_size, method) when enabled
        self.lr_schedule = lambda step: self._base_lr_schedule(step) * self._lr_scale
        self.opt = self._build_optimizer_transform()
        if lr_scheduler is None or callable(lr_scheduler) and not hasattr(lr_scheduler, "step"):
            self.lr_scheduler = LRSchedulerShim(self.lr_schedule)
        else:
            self.lr_scheduler = lr_scheduler

        # ---- timers/monitor (ref: engine.py:154 EngineTimers, monitor hookup)
        self.timers = SynchronizedWallClockTimer() if config.wall_clock_breakdown else NoopTimer()
        self.tput_timer = ThroughputTimer(batch_size=config.train_batch_size,
                                          steps_per_output=config.steps_per_print)
        self.monitor = self._build_monitor()

        # ---- flops profiler (ref: engine.py:300-304 construction,
        # :2411-2424 step trigger)
        self.flops_profiler = None
        if config.flops_profiler_config.enabled:
            from ..profiling.flops_profiler import FlopsProfiler
            self.flops_profiler = FlopsProfiler(model=self.module, ds_engine=self,
                                                recompute_fwd_factor=config.flops_profiler_config.recompute_fwd_factor)

        # ---- telemetry (deepspeed_tpu/telemetry, docs/OBSERVABILITY.md):
        # per-step traces (engine/step -> fwd_bwd/optim, plus the streamed
        # optimizer's upload/compute/download child spans) and a metrics
        # registry; disabled (null, allocation-free) until set_telemetry()
        from ..telemetry.trace import NULL_TRACER
        self.tracer = NULL_TRACER
        self.metrics_registry = None

        # ---- compression-aware training (ref: compression/compress.py
        # init_compression; applied as a param transform inside the loss)
        self._compression_fn = None
        self._compression_requested = bool(config._param_dict.get("compression_training"))

        # ---- progressive layer drop (ref: engine.py progressive_layer_drop
        # config + runtime/progressive_layer_drop.py)
        self.progressive_layer_drop = None
        pld_cfg = config._param_dict.get("progressive_layer_drop", {})
        if pld_cfg.get("enabled", False):
            from .progressive_layer_drop import ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(theta=pld_cfg.get("theta", 0.5),
                                                               gamma=pld_cfg.get("gamma", 0.001))

        # ---- state (lazy until first batch unless params given)
        self.state: Optional[TrainState] = None
        self.state_shardings = None
        self._grad_shardings = None
        self._train_step_fn = None
        self._eval_fn = None
        self._accum_fn = None
        self._apply_fn = None
        self._pending_grads = None
        self._pending_loss = None
        self._micro_step_count = 0
        self.global_steps = 0
        self.global_samples = 0
        if params is not None:
            self._materialize_state(params=params)

        log_dist(f"DeepSpeedEngine: mesh={dict(self.mesh.shape)} zero_stage={self.zero_stage} "
                 f"dtype={self.compute_dtype.__name__} gas={self.gas}", ranks=[0])

    # ------------------------------------------------------------------ build

    def _build_lr_schedule(self):
        base_lr = 1e-3
        if self._config.optimizer_config is not None:
            base_lr = self._config.optimizer_config.params.get("lr", 1e-3)
        if self.client_lr_scheduler is not None and callable(self.client_lr_scheduler):
            return base_lr, self.client_lr_scheduler
        if self._config.scheduler_config is not None and self._config.scheduler_config.type:
            fn = get_lr_schedule(self._config.scheduler_config.type, self._config.scheduler_config.params, base_lr)
            return base_lr, fn
        return base_lr, (lambda step: jnp.asarray(base_lr, jnp.float32))

    def _build_optimizer_transform(self):
        if self.client_optimizer is not None:
            opt = self.client_optimizer
            if hasattr(opt, "init") and hasattr(opt, "update"):
                return opt
            raise TypeError("client optimizer must be an optax-style GradientTransformation")
        cfg = self._config.optimizer_config
        if cfg is None or cfg.type is None:
            return self._maybe_loco_wrap(adamw(lr=self.lr_schedule))
        name = cfg.type.lower()
        if name not in OPTIMIZER_FACTORIES:
            raise ValueError(f"Unknown optimizer {cfg.type}; known: {sorted(OPTIMIZER_FACTORIES)}")
        params = dict(cfg.params)
        params.pop("lr", None)
        params.pop("torch_adam", None)
        # 1-bit family: "comm_backend_name" (ref: runtime/fp16/onebit/adam.py
        # comm_backend_name nccl/mpi/compressed) routes the momentum exchange
        # through the REAL compressed wire (runtime/comm/compressed.py) inside
        # a shard_map training step — see _build_compressed_train_step
        backend = params.pop("comm_backend_name", None)
        if name in (ONEBIT_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER):
            self._onebit_comm_backend = backend
            if name == ZERO_ONE_ADAM_OPTIMIZER:
                # 0/1 Adam has NO warmup — the momentum rides the compressed
                # wire from step 0 (ref: zoadam.py), and on var-interval
                # steps exp_avg_sq updates from the UNCOMPRESSED allreduced
                # grad like the reference (var_allreduce_fn below, cond-gated
                # to the rare due steps; see ops/onebit.zero_one_adam)
                self._onebit_freeze_step = 0
            else:
                self._onebit_freeze_step = int(params.get("freeze_step", 100))
            if self._compressed_transport_active():
                from .comm.compressed import compressed_allreduce
                from ..comm.mesh import DATA_AXIS

                def exchange(tensor, error):
                    avg, e_new = compressed_allreduce(tensor, error, DATA_AXIS)
                    # single-stage error feedback on the AVERAGED tensor:
                    # pmean(local - compressed) == global momentum minus the
                    # transmitted average — the server-side EF of the
                    # reference's two-stage scheme (nccl.py:16 steps 3-4);
                    # keeping per-worker error would make the opt state
                    # worker-varying, which the replicated TrainState can't
                    # represent
                    return avg, jax.lax.pmean(e_new, DATA_AXIS)

                params["compress_fn"] = exchange
                if name == ZERO_ONE_ADAM_OPTIMIZER:
                    # reference variance source (zoadam.py): var-due steps
                    # exchange the raw fp32 grad; lax.cond in the optimizer
                    # keeps it off the wire on every other step
                    params["var_allreduce_fn"] = \
                        lambda g: jax.lax.pmean(g, DATA_AXIS)
                # warmup-phase twin WITHOUT the exchange: its compressed
                # result is discarded anyway (frozen=False selects the exact
                # momentum), so tracing the collectives into the warmup
                # program would be pure wasted wire every pre-freeze step
                self._opt_warmup = OPTIMIZER_FACTORIES[name](
                    lr=self.lr_schedule, **{k: v for k, v in params.items()
                                            if k not in ("compress_fn",
                                                         "var_allreduce_fn")})
        if name in (ADAM_OPTIMIZER, FUSED_ADAM_OPTIMIZER, "cpuadam"):
            # the reference's adam_w_mode flag (ops/adam/fused_adam.py)
            adam_w = params.pop("adam_w_mode", True)
            opt = fused_adam(lr=self.lr_schedule, adam_w_mode=adam_w, **params)
        else:
            opt = OPTIMIZER_FACTORIES[name](lr=self.lr_schedule, **params)
        return self._maybe_loco_wrap(opt)

    def _maybe_loco_wrap(self, opt):
        """ZeRO++ LoCo (``zeropp_loco_param`` + ``zero_quantized_gradients``):
        the qgZ gradient wire WITH error feedback — the previous round's
        quantization error folds back into the gradient before quantizing
        (ref: runtime/comm/coalesced_collectives.py:81
        all_to_all_loco_quant_reduce; config key zero/config.py:315).

        Implemented as a state-carrying GradientTransformation so the error
        tree rides opt_state (sharded/checkpointed like any moment); the
        update runs INSIDE the manual-DDP shard_map step.  The error is
        server-side (pmean'd) — replicated state cannot hold per-worker
        residuals."""
        loco_cfg = getattr(self._config.zero_config, "zeropp_loco_param", None)
        qgz_flag = getattr(self._config.zero_config, "zero_quantized_gradients", False)
        # the 1-bit transport owns the wire (and its unwrapped warmup twin
        # could not carry the (inner, err) state) — LoCo stands down
        self._loco_active = bool(loco_cfg is not None and qgz_flag
                                 and not getattr(self, "_onebit_comm_backend", None)
                                 and self._manual_ddp_eligible())
        if not self._loco_active:
            if loco_cfg is not None:
                logger.warning("zeropp_loco_param set but LoCo transport needs "
                               "zero_quantized_gradients plus the manual-DDP "
                               "requirements (pure-DP mesh, stage 0, gas=1, "
                               "non-fp16) — ignored")
            return opt

        from ..comm.mesh import DATA_AXIS
        from ..ops.optimizer import GradientTransformation, tree_zeros_like
        from .comm.compressed import padded_quant_allreduce
        beta = float((loco_cfg or {}).get("err_beta", 0.8))
        world = self.mesh.shape[DATA_AXIS]
        clip = self._config.gradient_clipping

        def red(g, e):
            full, new_err = padded_quant_allreduce(g, DATA_AXIS, world, error=e,
                                                   err_beta=beta)
            return full, jax.lax.pmean(new_err, DATA_AXIS)

        def init(params):
            return (opt.init(params), tree_zeros_like(params, jnp.float32))

        def update(grads, state, params=None):
            inner, err = state
            pairs = jax.tree.map(red, grads, err)
            reduced = jax.tree.map(lambda t: t[0], pairs,
                                   is_leaf=lambda x: isinstance(x, tuple))
            new_err = jax.tree.map(lambda t: t[1], pairs,
                                   is_leaf=lambda x: isinstance(x, tuple))
            if clip and clip > 0:
                # clipping belongs to the REDUCED gradient — the engine's
                # pre-update clip is skipped in loco mode (its local-grad
                # norm would over-clip by up to sqrt(world) on noisy grads)
                norm = opt_lib.global_norm(reduced)
                cs = jnp.minimum(1.0, clip / (norm + 1e-6))
                reduced = jax.tree.map(lambda g: g * cs, reduced)
            updates, new_inner = opt.update(reduced, inner, params)
            return updates, (new_inner, new_err)

        log_dist(f"ZeRO++ LoCo gradient transport active (err_beta={beta})", ranks=[0])
        return GradientTransformation(init, update)

    def _streamed_offload_ok(self, what: str) -> bool:
        """Shared eligibility for the DISPATCH-streamed offload tiers
        (NVMe swap / host grouped): single-device mesh, Adam-family
        optimizer, non-fp16 static-unity scaling — the per-group update
        orchestration owns the step; the sharded multi-chip answer is ZeRO."""
        from .fp16.loss_scaler import StaticLossScaler
        name = (self._config.optimizer_config.type or "").lower() \
            if self._config.optimizer_config else "adamw"
        ok = (self.mesh.size == 1
              and name in (ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, "cpuadam")
              and isinstance(self.loss_scaler, StaticLossScaler)
              and float(self.loss_scaler.init_scale) == 1.0
              and self.compute_dtype != jnp.float16)
        if not ok:
            logger.warning(f"offload_optimizer {what}: the streamed update needs a "
                           "single-device mesh, Adam-family optimizer and non-fp16 "
                           "static-unity scaling — falling back to host memory-kind "
                           "offload")
        return ok

    def _nvme_pipelined_active(self) -> bool:
        """True when optimizer states should live on NVMe with the pipelined
        double-buffered swap (ref: swap_tensor/pipelined_optimizer_swapper.py):
        offload_optimizer device=nvme + nvme_path."""
        off = self._config.zero_config.offload_optimizer
        if off is None or str(getattr(off, "device", "")) != "nvme" \
                or not getattr(off, "nvme_path", None):
            return False
        return self._streamed_offload_ok("device=nvme")

    def _host_streamed_active(self) -> bool:
        """True when optimizer states should live in TPU-host pinned memory
        with the GROUPED multi-dispatch update (swap_tensor/
        host_streamed_optimizer.py).  Selected by device=cpu +
        pipeline_read/pipeline_write (the reference's pipelined-offload
        knobs, ref: runtime/zero/offload_config.py:78) — the plain
        device=cpu path keeps the single-program compute_on update, whose
        HBM staging XLA does not bound (docs/PERF.md r4 receipts)."""
        off = self._config.zero_config.offload_optimizer
        if off is None or str(getattr(off, "device", "")) != "cpu" \
                or not (getattr(off, "pipeline_read", False)
                        or getattr(off, "pipeline_write", False)):
            return False
        return self._streamed_offload_ok("device=cpu pipelined")

    def _compressed_transport_active(self) -> bool:
        """True when the 1-bit momentum exchange should ride the compressed
        wire: a comm backend was requested, there is a >1 data axis to
        exchange over, and the state layout is the replicated one the
        manual-collective step requires (ref constraint: the 1-bit
        optimizers require ZeRO stage <= 1; here stage 0 + gas 1)."""
        if getattr(self, "_onebit_comm_backend", None) is None:
            return False
        ok = self._manual_ddp_eligible()
        if not ok:
            logger.warning(
                "onebit comm_backend_name set but compressed transport needs a pure-DP "
                "mesh (>1 'data' axis, all others 1 — the manual step reduces over "
                "'data' only), zero stage 0, gas=1 and non-fp16 compute — falling "
                "back to local compression numerics (no wire exchange)")
            self._onebit_comm_backend = None
        return ok

    def _build_monitor(self):
        try:
            from ..monitor.monitor import MonitorMaster
            monitor = MonitorMaster(self._config.monitor_config)
            if monitor.enabled:
                # resilience/* events (injected faults, retries, checkpoint
                # fallbacks, watchdog trips) ride the same writer surface
                from ..resilience import events as res_events
                res_events.attach_monitor(monitor)
            return monitor
        except Exception as e:  # monitor must never break training
            logger.debug(f"monitor disabled: {e}")
            return None

    # ---------------------------------------------------------- state init

    def _materialize_state(self, batch=None, params=None, abstract=False):
        """Create the sharded TrainState.

        Params are initialised directly into their partitioned layout
        (jit with out_shardings) — the analog of ``zero.Init``'s
        partition-at-construction (ref: runtime/zero/partition_parameters.py:825):
        no device ever holds the unsharded model.

        ``abstract=True`` builds only shapes + shardings (ShapeDtypeStructs,
        nothing allocated) — the AOT compile-only path behind
        ``compile_aot`` for memory-budget analysis of models far larger
        than the local host could hold.
        """
        from flax import linen as nn

        from ..module_inject.tp_rules import param_shardings as make_param_shardings
        from .zero.mics import resolve_partition_axes

        # MiCS / ZeRO++ hpZ: restrict which DP mesh axes the ZeRO partition
        # uses (ref: runtime/zero/mics.py, partition_parameters.py hpZ)
        param_axes, state_axes = resolve_partition_axes(self.mesh, self._config.zero_config, self.zero_stage)

        if params is None:
            args, kwargs = self.model_inputs_fn(batch)
            abs_args, abs_kwargs = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(np.shape(x), jnp.asarray(x).dtype), (args, kwargs))

            def boxed_init(rng):
                return self.module.init(rng, *abs_args, **abs_kwargs)

            abs_boxed = jax.eval_shape(boxed_init, self.init_rng)
            var_shardings = make_param_shardings(abs_boxed, self.mesh, self.zero_stage, fsdp_axes=param_axes)

            def unboxed_init(rng):
                return nn.meta.unbox(boxed_init(rng))

            if abstract:
                variables = nn.meta.unbox(abs_boxed)
            else:
                with self.mesh:
                    variables = jax.jit(unboxed_init, out_shardings=var_shardings)(self.init_rng)
        else:
            variables = params if isinstance(params, dict) and "params" in params else {"params": params}
            variables = nn.meta.unbox(variables)
            abs_vars = jax.eval_shape(lambda: variables)
            var_shardings = make_param_shardings(abs_vars, self.mesh, self.zero_stage, fsdp_axes=param_axes)
            variables = jax.device_put(variables, var_shardings)

        raw_params = variables["params"]
        param_sh = var_shardings["params"]

        # cast params to compute dtype; master keeps fp32
        use_master = self.compute_dtype != jnp.float32
        cast = partial(jax.tree.map, lambda x: x.astype(self.compute_dtype)
                       if jnp.issubdtype(x.dtype, jnp.floating) else x)

        abs_params = jax.eval_shape(lambda: raw_params)
        master_sh = master_and_optstate_shardings(param_sh, abs_params, self.mesh, self.zero_stage,
                                                  zero_axes=state_axes)
        self._grad_shardings = make_grad_shardings(param_sh, abs_params, self.mesh, self.zero_stage,
                                                   zero_axes=state_axes)

        nvme_pipe_early = self._nvme_pipelined_active()
        host_stream_early = self._host_streamed_active() and not nvme_pipe_early

        @partial(jax.jit, out_shardings=None)
        def build_state(p):
            if nvme_pipe_early or host_stream_early:
                # dispatch-streamed offload: master + moments live on DISK
                # (PipelinedNVMeOptimizer) or in host pinned memory
                # (HostStreamedOptimizer); the device state is params-only
                master, opt_state = (), ()
            else:
                master = jax.tree.map(lambda x: x.astype(jnp.float32), p) if use_master else ()
                opt_state = self.opt.init(master if use_master else p)
            return TrainState(step=jnp.zeros((), jnp.int32),
                              params=cast(p),
                              master=master,
                              opt_state=opt_state,
                              scaler=self.loss_scaler.init_state(),
                              skipped_steps=jnp.zeros((), jnp.int32))

        # compute output shardings for the state
        abs_state = jax.eval_shape(build_state, abs_params)
        opt_sh = self._optstate_shardings(abs_state.opt_state, param_sh, master_sh)
        repl = NamedSharding(self.mesh, P())
        # offload_optimizer device=cpu → optimizer/master live in host memory
        # (memory_kind pinned_host); XLA streams them through the update
        # (ref: runtime/zero/offload_config.py + cpu_adam — same math, the
        # host residency is a sharding property, not a different optimizer)
        host_kind_ok = [None]  # probe result shared by both offload blocks

        def try_host_offload(name, *sharding_trees):
            """Move shardings to host memory kind if the backend supports it
            (one probe-compile, cached); returns the trees (possibly unchanged)."""
            to_host = lambda s: s.with_memory_kind("pinned_host") \
                if isinstance(s, NamedSharding) else s
            if host_kind_ok[0] is None:
                try:
                    probe = NamedSharding(self.mesh, P())  # rank-agnostic probe
                    jax.jit(lambda x: x, out_shardings=to_host(probe)) \
                        .lower(jax.ShapeDtypeStruct((1, ), jnp.float32)).compile()
                    host_kind_ok[0] = True
                except Exception as e:
                    host_kind_ok[0] = False
                    logger.warning(f"host memory kinds unsupported on this backend; "
                                   f"offload stays on device ({e})")
            if not host_kind_ok[0]:
                return sharding_trees
            out = tuple(jax.tree.map(to_host, t) for t in sharding_trees)
            log_dist(f"{name}: resident in host memory (streamed through HBM)", ranks=[0])
            return out

        offload = self._config.zero_config.offload_optimizer
        nvme_pipe = nvme_pipe_early  # computed once above (warns on fallback)
        streamed = nvme_pipe or host_stream_early
        if offload is not None and offload.device in ("cpu", "nvme") and not streamed:
            if use_master:
                master_sh, opt_sh = try_host_offload("offload_optimizer", master_sh, opt_sh)
            else:
                (opt_sh, ) = try_host_offload("offload_optimizer", opt_sh)
        # offload_param (ZeRO-Infinity): compute-dtype params themselves live
        # in host memory and stream through HBM per use — with scan-over-
        # layers XLA prefetches one layer's slab at a time (the analog of the
        # reference's AsyncPartitionedParameterSwapper double buffering,
        # ref: runtime/zero/partition_parameters.py remote_device="cpu")
        p_offload = self._config.zero_config.offload_param
        if p_offload is not None and getattr(p_offload, "device", None) in ("cpu", "nvme"):
            (param_sh, ) = try_host_offload("offload_param", param_sh)
        self.state_shardings = TrainState(
            step=repl,
            params=param_sh,
            master=master_sh if use_master and not streamed else (),
            opt_state=opt_sh,
            scaler=jax.tree.map(lambda _: repl, abs_state.scaler),
            skipped_steps=repl,
        )
        if abstract:
            # shape+sharding skeleton only: leaves are ShapeDtypeStructs
            # carrying their NamedSharding — exactly what jit.lower accepts
            self.state = jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s)
                if isinstance(s, NamedSharding) else a, abs_state, self.state_shardings)
        else:
            with self.mesh:
                self.state = jax.jit(build_state, out_shardings=self.state_shardings)(raw_params)
        if nvme_pipe and not abstract and getattr(self, "_nvme_opt", None) is None:
            from .swap_tensor.pipelined_optimizer_swapper import PipelinedNVMeOptimizer
            self._nvme_opt = PipelinedNVMeOptimizer(
                self.opt, jax.tree.leaves(self.state.params),
                self._config.zero_config.offload_optimizer.nvme_path,
                compute_dtype=self.compute_dtype)
        elif host_stream_early and not abstract and getattr(self, "_nvme_opt", None) is None:
            # same orchestration (_nvme_train_step), host-memory storage tier;
            # buffer_count sizes the partition exactly as it does for the
            # NVMe tier (ref: offload_config.py buffer_count) — more groups
            # = smaller HBM staging per dispatch
            from .swap_tensor.host_streamed_optimizer import HostStreamedOptimizer
            self._nvme_opt = HostStreamedOptimizer(
                self.opt, jax.tree.leaves(self.state.params),
                n_groups=max(1, self._config.zero_config.offload_optimizer.buffer_count),
                compute_dtype=self.compute_dtype, mesh=self.mesh)
        n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(abs_params))
        log_dist(f"Initialized TrainState: {n_params/1e6:.1f}M params, zero_stage={self.zero_stage}"
                 f"{' (abstract)' if abstract else ''}", ranks=[0])

    def _optstate_shardings(self, abs_opt_state, param_sh, master_sh):
        """Match each per-param moment tree inside opt_state to the master
        sharding; scalars replicated."""
        repl = NamedSharding(self.mesh, P())
        param_leaves = jax.tree.structure(master_sh if master_sh != () else param_sh)

        def assign(subtree):
            # if subtree matches the param tree structure, use master shardings
            # — but only for leaves whose rank fits the spec (e.g. OnebitLamb
            # keeps per-param SCALAR trust ratios in a param-shaped tree)
            try:
                if jax.tree.structure(subtree) == param_leaves:
                    sh_tree = master_sh if master_sh != () else param_sh

                    def fit(aval, sh):
                        ok = isinstance(sh, NamedSharding) and \
                            getattr(aval, "ndim", 0) >= len(sh.spec)
                        return sh if ok else repl

                    return jax.tree.map(fit, subtree, sh_tree)
            except Exception:
                pass
            return None

        def walk(node):
            matched = assign(node)
            if matched is not None:
                return matched
            if hasattr(node, "_fields"):  # NamedTuple
                return type(node)(*[walk(getattr(node, f)) for f in node._fields])
            if isinstance(node, tuple):
                return tuple(walk(x) for x in node)
            if isinstance(node, list):
                return [walk(x) for x in node]
            if isinstance(node, dict):
                return {k: walk(v) for k, v in node.items()}
            return repl

        return walk(abs_opt_state)

    # ---------------------------------------------------------- step builder

    def _batch_sharding_tree(self, batch):
        seq_ax = SEQ_AXIS if self.mesh.shape.get(SEQ_AXIS, 1) > 1 else None

        def one(x):
            nd = np.ndim(x)
            if nd == 0:
                return NamedSharding(self.mesh, P())
            spec = [BATCH_AXES] + ([seq_ax] if nd > 1 else []) + [None] * (nd - 2)
            return NamedSharding(self.mesh, P(*spec))

        return jax.tree.map(one, batch)

    def _microbatch_loss(self, params, mb, step=None, training=False):
        if self._compression_fn is not None and step is not None:
            params = self._compression_fn(params, step)
        args, kwargs = self.model_inputs_fn(mb)
        if training and step is not None and self.progressive_layer_drop is not None \
                and getattr(self.module, "supports_pld", False):
            # traced PLD schedule: theta(t) = (1-p)·e^{-γt} + p, per-layer
            # keep mask drawn from a step-derived key (ref:
            # runtime/progressive_layer_drop.py; one compiled program, the
            # schedule advances via the step input)
            from .progressive_layer_drop import pld_layer_mask
            pld = self.progressive_layer_drop
            theta = (1.0 - pld.theta) * jnp.exp(-pld.gamma * step.astype(jnp.float32)) + pld.theta
            rng = jax.random.fold_in(jax.random.PRNGKey(17), step)
            mask, inv = pld_layer_mask(rng, self.module.cfg.num_hidden_layers, theta)
            kwargs["pld_scale"] = mask * inv
        # TRUE-1F1B pipeline modules compute the loss INSIDE the schedule
        # (post-stack per microbatch, interleaved backward); the engine's
        # jax.grad then consumes the custom-VJP grads
        if getattr(self.module, "schedule", None) == "1f1b":
            if kwargs:
                from .pipe.module import PipelineError
                raise PipelineError(
                    f"PipelineModule does not accept keyword model inputs {sorted(kwargs)} "
                    "(same contract as the gpipe schedule)")
            return self.module.apply_loss_1f1b({"params": params}, self.loss_fn, mb, *args)
        outputs = self.module.apply({"params": params}, *args, **kwargs)
        return self.loss_fn(outputs, mb)

    def enable_compression(self):
        """Build the compression transform from config (ref:
        compression/compress.py:100 init_compression)."""
        self._compression_requested = True
        self._step_key = None  # force step rebuild
        self._step_cache = {}  # cached programs were traced without the transform
        if self.state is not None:
            self._build_compression()

    def _build_compression(self):
        from ..compression.compress import build_compression_fn
        comp_dict = self._config._param_dict.get("compression_training", {})
        abs_params = jax.eval_shape(lambda: self.state.params)
        self._compression_fn = build_compression_fn(comp_dict, abs_params)

    def _grads_for_batch(self, state, batch):
        """Accumulated (summed) scaled grads + mean loss over the GAS axis.

        Gradient accumulation = lax.scan over microbatches (ref: the
        micro-step loop around engine.backward, engine.py:2204), computed in
        grad_accum_dtype fp32 (ref: runtime/config.py data_types).
        """
        params = state.params
        scale = state.scaler.cur_scale

        def scaled_loss(p, mb):
            loss = self._microbatch_loss(p, mb, step=state.step, training=True)
            return (loss * scale).astype(jnp.float32), loss

        grad_fn = jax.grad(scaled_loss, has_aux=True)

        if self.gas == 1:
            grads, loss = grad_fn(params, batch)
            return grads, loss

        def reshape_gas(x):
            if np.ndim(x) == 0:
                return x
            b = x.shape[0]
            return x.reshape((self.gas, b // self.gas) + x.shape[1:])

        batch_g = jax.tree.map(reshape_gas, batch)

        def body(carry, mb):
            acc, loss_acc = carry
            g, loss = grad_fn(params, mb)
            acc = jax.tree.map(lambda a, b_: a + b_.astype(jnp.float32), acc, g)
            return (acc, loss_acc + loss), None

        zero_grads = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (grads, loss_sum), _ = jax.lax.scan(body, (zero_grads, jnp.zeros((), jnp.float32)),
                                            batch_g, length=self.gas)
        return grads, loss_sum / self.gas

    def _apply_grads(self, state: TrainState, grads, loss):
        """Unscale, constrain sharding, clip, update, recast — with on-device
        overflow skip (ref: stage3.py:2082 step + loss-scaler adjust).

        bf16/fp32 fast path: with a static unity scaler there is nothing to
        unscale and no overflow-skip (ref: bf16_optimizer.py has no scaler),
        so the finite-check reduction and the 3× whole-tree ``where`` passes
        are elided from the compiled step entirely.
        """
        cfg = self._config
        from .fp16.loss_scaler import StaticLossScaler
        # fp16 is excluded from the fast path even at loss_scale=1: non-finite
        # grads are real in half precision and the step must still be skipped
        # on overflow (ref: fused_optimizer.py keeps the overflow check for
        # static scales)
        static_unity = isinstance(self.loss_scaler, StaticLossScaler) and \
            float(self.loss_scaler.init_scale) == 1.0 and \
            self.compute_dtype != jnp.float16
        inv = (1.0 / self.gas) if static_unity else 1.0 / (state.scaler.cur_scale * self.gas)
        if cfg.gradient_predivide_factor != 1.0:
            inv = inv / cfg.gradient_predivide_factor

        use_master = self.compute_dtype != jnp.float32
        from ..ops.adam import AdamState
        # use_master required: the fp32-compute variant would feed
        # device-resident params into the host-compute region
        stream_offload = (static_unity and use_master and self._host_offloaded_opt()
                          and isinstance(state.opt_state, AdamState))
        if stream_offload:
            # leaf-streamed path: never materialize the fp32 grad tree — the
            # norm reduces each leaf with an f32 accumulator (XLA fuses the
            # cast into the reduction) and the cast happens per leaf inside
            # the sequenced update
            norm2 = sum(jnp.sum(jnp.square(g.astype(jnp.float32) * inv))
                        for g in jax.tree.leaves(grads))
            grad_norm = jnp.sqrt(norm2)
            found_inf = jnp.asarray(False)
            clip_scale = jnp.asarray(1.0, jnp.float32)
            if cfg.gradient_clipping and cfg.gradient_clipping > 0:
                clip_scale = jnp.minimum(1.0, cfg.gradient_clipping / (grad_norm + 1e-6))
            new_params, new_master, new_opt_state = self._offload_streamed_update(
                grads, state, inv, clip_scale)
        else:
            grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
            from ..comm.mesh import DATA_AXIS, in_manual_mesh
            manual = in_manual_mesh()
            if not manual:  # inside shard_map (compressed transport path)
                # the grads are per-device values; GSPMD constraints don't
                # apply
                grads = jax.lax.with_sharding_constraint(grads, self._grad_shardings)

            found_inf = jnp.asarray(False) if static_unity else found_inf_or_nan(grads)
            grad_norm = opt_lib.global_norm(grads)
            if manual:
                # per-device grads: reduce so every worker clips with the
                # same scale and the metrics are well-defined under the
                # replicated out-spec
                grad_norm = jnp.sqrt(jax.lax.pmean(jnp.square(grad_norm), DATA_AXIS))
                if not static_unity:
                    found_inf = jax.lax.pmax(found_inf.astype(jnp.int32),
                                             DATA_AXIS).astype(jnp.bool_)
            if cfg.gradient_clipping and cfg.gradient_clipping > 0 \
                    and not (manual and getattr(self, "_loco_active", False)):
                # LoCo clips inside its optimizer wrapper on the REDUCED
                # grads; clipping the local grads here against the (noise-
                # inflated) local norm would over-clip
                clip_scale = jnp.minimum(1.0, cfg.gradient_clipping / (grad_norm + 1e-6))
                grads = jax.tree.map(lambda g: g * clip_scale, grads)

            master = state.master if use_master else state.params
            # host-offloaded (pinned_host) states: memory-space typing
            # requires the update's compute operands in device space —
            # explicit transfers in; out_shardings stream the results back
            master = self._from_host(master,
                                     self.state_shardings.master if use_master
                                     else self.state_shardings.params)
            opt_in = self._from_host(state.opt_state, self.state_shardings.opt_state)
            updates, new_opt_state = self.opt.update(grads, opt_in, master)
            new_master = opt_lib.apply_updates(master, updates)

            if not static_unity:
                # skip the update entirely on overflow (ref: fused_optimizer.py)
                def pick(new, old):
                    return jax.tree.map(lambda n, o: jnp.where(found_inf, o, n), new, old)

                new_master = pick(new_master, master)
                # compare against the device-pulled opt_in, not the (possibly
                # pinned_host) state.opt_state — mixing memory spaces in the
                # where() fails to lower (advisor r4)
                new_opt_state = pick(new_opt_state, opt_in)
            new_params = jax.tree.map(lambda m: m.astype(self.compute_dtype),
                                      new_master) if use_master else new_master
        new_scaler = self.loss_scaler.update(state.scaler, found_inf)
        lr_val = jnp.asarray(self.lr_schedule(state.step + 1), jnp.float32)

        new_state = TrainState(step=state.step + 1,
                               params=new_params,
                               master=new_master if use_master else (),
                               opt_state=new_opt_state,
                               scaler=new_scaler,
                               skipped_steps=state.skipped_steps + found_inf.astype(jnp.int32))
        metrics = StepMetrics(loss=loss.astype(jnp.float32),
                              grad_norm=grad_norm,
                              found_inf=found_inf,
                              lr=lr_val,
                              loss_scale=state.scaler.cur_scale)
        return new_state, metrics

    def _host_offloaded_opt(self):
        """True when master/optimizer shardings live in pinned_host."""
        sh = (self.state_shardings.master, self.state_shardings.opt_state)
        return any(isinstance(s, NamedSharding) and s.memory_kind == "pinned_host"
                   for s in jax.tree.leaves(sh))

    def _offload_streamed_update(self, grads, state, inv, clip_scale):
        """CPU-Adam: the optimizer step executes as XLA HOST compute, on the
        TPU host where the offloaded fp32 master/moments live.

        Same division of labor as the reference (ref:
        csrc/adam/cpu_adam_impl.cpp + runtime/zero/stage_1_and_2.py CPU
        offload): device does fwd/bwd, the host applies Adam.  Grads cross
        to the host; fresh compute-dtype params cross back.  Verified on
        chip: loss parity with the on-device update to ~1e-3.

        Honest limits (measured): a device-side whole-tree update hoists
        every host→HBM pull to the program top (whole fp32 state on device
        at once); this host-execute path still stages its I/O buffers
        through HBM for layout conversion, so the single-chip capacity win
        over no-offload is partial — at true 7B+ scale the answer is ZeRO
        sharding across chips (see MEMBUDGET.json), not single-chip
        offload.
        """
        from jax.experimental.compute_on import compute_on

        use_master = self.compute_dtype != jnp.float32
        master = state.master if use_master else state.params
        opt_state = state.opt_state
        host = NamedSharding(self.mesh, P()).with_memory_kind("pinned_host")

        # grads keep their ZeRO sharding, only the memory kind changes — a
        # replicated host spec would all-gather every leaf into each host
        g_host = jax.tree.map(
            lambda g, s: jax.device_put(
                g, s.with_memory_kind("pinned_host") if isinstance(s, NamedSharding) else host),
            grads, self._grad_shardings)
        scal = jax.device_put(clip_scale * inv, host)
        with compute_on("device_host"):
            g32 = jax.tree.map(lambda g: g.astype(jnp.float32) * scal, g_host)
            updates, new_opt_state = self.opt.update(g32, opt_state, master)
            new_master = jax.tree.map(lambda m, u: m + u, master, updates)
            new_params_h = jax.tree.map(lambda m: m.astype(self.compute_dtype),
                                        new_master) if use_master else new_master
        param_sh = self.state_shardings.params
        new_params = jax.tree.map(
            lambda x, s: jax.device_put(x, s if isinstance(s, NamedSharding) else None),
            new_params_h, param_sh)
        return new_params, new_master, new_opt_state

    def _from_host(self, tree, sh_tree):
        """Pull host-offloaded (pinned_host) state into device space for the
        update (ZeRO-Infinity streaming: XLA schedules the transfers leaf by
        leaf, so only the leaves currently being updated occupy HBM)."""
        leaves = [s for s in jax.tree.leaves(sh_tree) if isinstance(s, NamedSharding)]
        if not any(s.memory_kind == "pinned_host" for s in leaves):
            return tree

        def pull(x, s):
            if isinstance(s, NamedSharding) and s.memory_kind == "pinned_host":
                return jax.device_put(x, s.with_memory_kind("device"))
            return x

        return jax.tree.map(pull, tree, sh_tree)

    def _manual_ddp_eligible(self) -> bool:
        """Shared eligibility for the manual-DDP compressed transports
        (1-bit momentum wire, qgZ gradient wire): a >1 pure-DP data axis,
        replicated state (stage 0), gas=1 and non-fp16 compute."""
        from ..comm.mesh import DATA_AXIS
        pure_dp = all(size == 1 for ax, size in self.mesh.shape.items() if ax != DATA_AXIS)
        return (self.mesh.shape.get(DATA_AXIS, 1) > 1 and pure_dp and self.zero_stage == 0
                and self.gas == 1 and self.compute_dtype != jnp.float16)

    def _qgz_active(self) -> bool:
        """ZeRO++ qgZ gradient transport (zero_quantized_gradients): the
        step's grad reduction rides int8 — quantized all-to-all
        reduce-scatter + quantized all-gather (ref:
        runtime/comm/coalesced_collectives.py:31).  Decision latched (and
        the fallback warned) ONCE — step-program rebuilds must not re-warn,
        and a 1-bit run with the flag also set must not claim the fp32
        wire is in use."""
        if getattr(self, "_qgz_decided", None) is None:
            if not getattr(self._config.zero_config, "zero_quantized_gradients", False) \
                    or getattr(self, "_onebit_comm_backend", None):
                self._qgz_decided = False
            else:
                self._qgz_decided = self._manual_ddp_eligible()
                if not self._qgz_decided:
                    logger.warning("zero_quantized_gradients needs a pure-DP mesh, zero "
                                   "stage 0, gas=1 and non-fp16 compute — gradients stay "
                                   "on the fp32 wire")
        return self._qgz_decided

    def _build_compressed_train_step(self, batch, warmup: bool):
        """Manual-DDP step with the grad/momentum exchange on the
        COMPRESSED wire (r3 verdict item 2: the pieces existed but no
        config path routed the training step through them).

        Per-device gradients are computed WITHOUT a GSPMD mean — each
        worker differentiates only its batch shard.  Two transports:

        * 1-bit family (comm_backend_name): the reference flow
          (fp16/onebit/adam.py — local momentum update, then
          compressed_allreduce of the momentum): n/8 sign bytes + one
          fp32 scale per tensor instead of 4n
          (ref: runtime/comm/nccl.py:16).
        * qgZ (zero_quantized_gradients): int8 quantized all-to-all
          reduce-scatter + quantized all-gather of the GRADS before a
          normal optimizer update
          (ref: runtime/comm/coalesced_collectives.py:31).
        """
        from ..comm.mesh import DATA_AXIS
        qgz = self._qgz_active()
        batch_sh = self._batch_sharding_tree(batch)
        repl = NamedSharding(self.mesh, P())
        metrics_sh = StepMetrics(*([repl] * 5))
        state_specs = jax.tree.map(lambda _: P(), self.state)
        batch_specs = jax.tree.map(lambda s: s.spec, batch_sh)
        metric_specs = StepMetrics(*([P()] * 5))

        opt_for_phase = self._opt_warmup if warmup else self.opt

        def sharded_step(state, b):
            scale = state.scaler.cur_scale

            def scaled_loss(p, mb):
                loss = self._microbatch_loss(p, mb, step=state.step, training=True)
                return (loss * scale).astype(jnp.float32), loss

            grads, loss = jax.grad(scaled_loss, has_aux=True)(state.params, b)
            if qgz and not getattr(self, "_loco_active", False):
                # (LoCo reduces inside the optimizer update — the error
                # state rides opt_state)
                from .comm.compressed import padded_quant_allreduce
                world = self.mesh.shape[DATA_AXIS]
                grads = jax.tree.map(
                    lambda g: padded_quant_allreduce(g, DATA_AXIS, world), grads)
            elif warmup:
                # warmup stage: full-precision gradient allreduce, exactly
                # the reference backend pre-freeze (fp16/onebit/adam.py) —
                # without it worker params fork (local grads, no exchange
                # until the momentum compression kicks in)
                grads = jax.tree.map(lambda g: jax.lax.pmean(g, DATA_AXIS), grads)
            loss = jax.lax.pmean(loss, DATA_AXIS)
            # phase-bound optimizer (tracing happens on the first call,
            # synchronously after this build — the swap is trace-local)
            prev, self.opt = self.opt, opt_for_phase
            try:
                return self._apply_grads(state, grads, loss)
            finally:
                self.opt = prev

        step_fn = jax.shard_map(sharded_step, mesh=self.mesh,
                                in_specs=(state_specs, batch_specs),
                                out_specs=(state_specs, metric_specs),
                                check_vma=False)
        self._train_step_fn = jax.jit(step_fn,
                                      in_shardings=(self.state_shardings, batch_sh),
                                      out_shardings=(self.state_shardings, metrics_sh),
                                      donate_argnums=(0, ))
        self._batch_shardings = batch_sh

        # wire accounting for CommsLogger, vs 4n fp32 transport:
        # 1-bit → signs (n/8) + one fp32 scale per tensor; qgZ → int8 both
        # directions (n + n/256 scale bytes each way)
        if qgz:
            # per direction: padded int8 payload + one fp32 scale per 256-block
            # (the padding to world*256 is real wire traffic)
            unit = self.mesh.shape[DATA_AXIS] * 256

            def leaf_bytes(n):
                padded = -(-n // unit) * unit
                return 2 * (padded + 4 * (padded // 256))

            self._compressed_wire_bytes = sum(
                leaf_bytes(int(np.prod(l.shape))) for l in jax.tree.leaves(self.state.params))
        else:
            self._compressed_wire_bytes = sum(
                (int(np.prod(l.shape)) + 7) // 8 + 4 for l in jax.tree.leaves(self.state.params))
        self._compressed_wire_name = "all_to_all_quant_reduce" if qgz else "compressed_allreduce"

        def unsupported(*a, **k):
            raise RuntimeError("the imperative forward/backward/step path does not support "
                               "compressed gradient/momentum transport; use train_batch()")

        self._accum_fn = unsupported
        self._apply_step_fn = unsupported

    def _build_nvme_train_step(self, batch):
        """Device program for the pipelined-NVMe mode: fwd/bwd only — grads,
        loss and the grad norm come OUT; the optimizer update runs per
        sub-group against disk-resident states (PipelinedNVMeOptimizer)."""
        batch_sh = self._batch_sharding_tree(batch)
        repl = NamedSharding(self.mesh, P())
        inv = 1.0 / self.gas
        if self._config.gradient_predivide_factor != 1.0:
            inv = inv / self._config.gradient_predivide_factor
        if getattr(self, "_nvme_opt", None) is not None:
            # lr/phase inputs are baked at trace time (e.g. variable-batch
            # _lr_scale rides self.lr_schedule): a step rebuild must retrace
            # the per-group update programs too
            self._nvme_opt._update_fns.clear()

        def grad_step(state, b):
            grads, loss = self._grads_for_batch(state, b)
            norm2 = sum(jnp.sum(jnp.square(g.astype(jnp.float32) * inv))
                        for g in jax.tree.leaves(grads))
            return grads, loss, jnp.sqrt(norm2)

        self._train_step_fn = jax.jit(grad_step, in_shardings=(self.state_shardings, batch_sh))
        self._batch_shardings = batch_sh

        def unsupported(*a, **k):
            raise RuntimeError("the imperative forward/backward/step path does not support "
                               "pipelined NVMe optimizer offload; use train_batch()")

        self._accum_fn = unsupported
        self._apply_step_fn = unsupported

    def _nvme_train_step(self, batch):
        """Host-orchestrated step: device fwd/bwd (async), then the
        double-buffered per-group update.  Step N's tail write-backs drain
        while step N+1's fwd/bwd dispatches (the overlap the reference gets
        from its swap pipeline), and the FIRST groups' state uploads/reads
        are issued here, right after the fwd/bwd dispatch, so they ride the
        transfer engine (or aio threads) under the backward itself."""
        nv = self._nvme_opt
        nv.events.append(("step_entry_pending_writes", nv.pending_writes()))
        state = self.state
        step_span = getattr(self, "_step_span", None)
        with self.tracer.span("engine/fwd_bwd", parent=step_span, track="engine"):
            # span covers the DISPATCH; the async program keeps running —
            # the wait for grads shows up inside the optim span (bwd_wait)
            grads, loss, gnorm = self._train_step_fn(state, batch)
        # backward-phase prefetch: fwd/bwd is dispatched but (async) still
        # running — stage the first groups now instead of at step boundary
        mode = getattr(self, "_nvme_step_mode", None)
        if mode != "serialize":
            nv.prefetch(0)
            nv.prefetch(1)
        inv = 1.0 / self.gas
        cfg = self._config
        if cfg.gradient_predivide_factor != 1.0:
            inv = inv / cfg.gradient_predivide_factor
        scale = jnp.asarray(inv, jnp.float32)
        if cfg.gradient_clipping and cfg.gradient_clipping > 0:
            scale = scale * jnp.minimum(1.0, cfg.gradient_clipping / (gnorm + 1e-6))
        self.timers(STEP_GLOBAL_TIMER).start()
        opt_span = self.tracer.start_span("engine/optim", parent=step_span,
                                          track="engine")
        if self.tracer.enabled:
            # clock-domain anchor: instrumentation timestamps are absolute
            # perf_counter; map them into the tracer's clock by offset
            from ..runtime.swap_tensor.overlap_instrumentation import now as _perf_now
            anchor_perf, anchor_trace = _perf_now(), self.tracer.now()
        try:
            new_leaves = nv.step(jax.tree.leaves(grads), jnp.asarray(self.global_steps, jnp.int32),
                                 scale, serialize=(mode == "serialize"),
                                 flush=(mode == "flush"))
        except Exception as e:
            # the failed steps are exactly the ones an operator reads the
            # trace for — tag and close instead of dropping the open span
            opt_span.set(error=f"{type(e).__name__}: {e}")
            raise
        finally:
            if self.tracer.enabled and getattr(nv, "instrumentation", None) is not None:
                # lift this step's upload/compute/download pipeline events
                # into real child spans of the optim span (paired
                # issue->done become spans; unpaired issues — async tails
                # left in flight — become span events on the optim span)
                nv.instrumentation.lift_spans(
                    self.tracer, opt_span, track="stream",
                    since_ts=anchor_perf, offset=anchor_trace - anchor_perf)
            self.tracer.end(opt_span)
        self.timers(STEP_GLOBAL_TIMER).stop()
        tdef = jax.tree.structure(state.params)
        new_state = state._replace(params=jax.tree.unflatten(tdef, new_leaves),
                                   step=state.step + 1)
        metrics = StepMetrics(loss=loss.astype(jnp.float32),
                              grad_norm=gnorm,
                              found_inf=jnp.asarray(False),
                              lr=jnp.asarray(self.lr_schedule(state.step + 1), jnp.float32),
                              loss_scale=jnp.asarray(1.0, jnp.float32))
        return new_state, metrics

    def _build_train_step(self, batch):
        if getattr(self, "_nvme_opt", None) is not None or \
                (getattr(self, "_abstract_state", False)
                 and (self._nvme_pipelined_active() or self._host_streamed_active())):
            # abstract (compile_aot) engines build the nvme grad-step program
            # too: the normal path would feed the () opt_state to opt.update
            return self._build_nvme_train_step(batch)
        if getattr(self, "_onebit_comm_backend", None):
            return self._build_compressed_train_step(
                batch, warmup=self.global_steps < self._onebit_freeze_step)
        if self._qgz_active():
            return self._build_compressed_train_step(batch, warmup=False)
        batch_sh = self._batch_sharding_tree(batch)
        repl = NamedSharding(self.mesh, P())

        def train_step(state, b):
            grads, loss = self._grads_for_batch(state, b)
            return self._apply_grads(state, grads, loss)

        metrics_sh = StepMetrics(*([repl] * 5))
        self._train_step_fn = jax.jit(train_step,
                                      in_shardings=(self.state_shardings, batch_sh),
                                      out_shardings=(self.state_shardings, metrics_sh),
                                      donate_argnums=(0, ))
        self._batch_shardings = batch_sh

        def accum(state, b):
            # one micro-batch per call — NO gas re-split here: the imperative
            # forward/backward/step path calls backward() once per micro-batch
            # and step() divides the summed grads by gas
            scale = state.scaler.cur_scale

            def scaled_loss(p, mb):
                loss = self._microbatch_loss(p, mb, step=state.step, training=True)
                return (loss * scale).astype(jnp.float32), loss

            grads, loss = jax.grad(scaled_loss, has_aux=True)(state.params, b)
            return grads, loss

        micro_batch_sh = self._batch_sharding_tree(batch)
        self._accum_fn = jax.jit(accum, in_shardings=(self.state_shardings, micro_batch_sh))
        self._apply_step_fn = jax.jit(self._apply_grads,
                                      in_shardings=(self.state_shardings, None, repl),
                                      out_shardings=(self.state_shardings, metrics_sh),
                                      donate_argnums=(0, ))

    @staticmethod
    def _batch_key(batch):
        import numpy as _np
        leaves, treedef = jax.tree.flatten(batch)
        return (str(treedef),
                tuple((_np.shape(l), str(getattr(l, "dtype", type(l)))) for l in leaves))

    def _ensure_ready(self, batch):
        if getattr(self, "_abstract_state", False):
            raise RuntimeError(
                "this engine was AOT-compiled abstractly (compile_aot) and holds "
                "no real state; create a fresh engine to train")
        if self.state is None:
            self._materialize_state(batch=batch)
        if self._compression_requested and self._compression_fn is None:
            self._build_compression()
            self._compression_requested = False
        if self._vblr is not None:
            from .data_pipeline.data_sampling.variable_batch_size_and_lr import scale_lr
            ref_bs, method = self._vblr
            if isinstance(batch, dict) and batch.get("loss_mask") is not None:
                # bucketed loaders pad with all-masked rows; the EFFECTIVE
                # batch size (real sequences) drives the LR scale
                bs = int(np.asarray(batch["loss_mask"]).any(axis=-1).sum())
            else:
                bs = int(np.shape(jax.tree.leaves(batch)[0])[0])
            self._lr_scale = scale_lr(ref_bs, bs, method=method)
        # compiled fns are keyed by batch structure: a malformed batch fails
        # cleanly without poisoning the cache, and changing batch shapes
        # (e.g. curriculum seq-len growth) triggers a fresh compile
        key = self._batch_key(batch) + (self._lr_scale, )
        self._rebuilt_this_step = False
        if getattr(self, "_onebit_comm_backend", None):
            # compressed transport compiles distinct warmup (fp32 grad
            # allreduce) and compression (momentum-wire) phase programs,
            # switched host-side at freeze_step like the reference backend
            key = key + (self.global_steps < self._onebit_freeze_step, )
        if getattr(self, "_step_key", None) != key:
            # memoize built programs per key: alternating batch buckets
            # (variable batch size, curriculum flips) must NOT retrace on
            # every switch — steady state reuses the compiled set
            cache = getattr(self, "_step_cache", None)
            if cache is None:
                cache = self._step_cache = {}
            if key in cache:
                (self._train_step_fn, self._accum_fn, self._apply_step_fn,
                 self._batch_shardings, self._eval_fn) = cache[key]
            else:
                self._build_train_step(batch)
                self._rebuilt_this_step = True  # first call pays compilation
                self._eval_fn = None
                cache[key] = (self._train_step_fn, self._accum_fn, self._apply_step_fn,
                              self._batch_shardings, self._eval_fn)
            self._step_key = key

    # ------------------------------------------------------------- public API

    def set_variable_batch_lr(self, ref_batch_size: int, method: str = "linear"):
        """Enable variable-batch LR scaling (ref: data_sampling/
        variable_batch_size_and_lr.py lr_scheduler_for_variable_batch_size):
        every train_batch's LR is multiplied by scale_lr(ref_batch_size,
        actual_batch_size, method).  Pairs with VariableBatchDataLoader."""
        self._vblr = (int(ref_batch_size), method)

    def compile_aot(self, batch):
        """AOT-compile the full train step WITHOUT allocating any state.

        The TPU-native answer to the reference's ZeRO memory estimators
        (ref: runtime/zero/stage3.py estimate_zero3_model_states_mem_needs_
        all_live and the autotuner's memory model): instead of closed-form
        approximations, the REAL compiled program's memory analysis — exact
        per-device bytes for arguments (state), outputs, and XLA temp/peak
        (activations, collectives) — at full model scale on any mesh,
        including a virtual CPU mesh standing in for a pod slice.

        Returns the ``jax`` Compiled object: ``.memory_analysis()`` for the
        HBM budget, ``.cost_analysis()`` for FLOPs.  The engine holds only
        ShapeDtypeStructs afterwards — training on it raises; build a fresh
        engine to actually train.
        """
        assert self.state is None, (
            "compile_aot requires a fresh engine: this one already holds real "
            "training state, which abstract materialization would destroy")
        self._materialize_state(batch=batch, abstract=True)
        self._abstract_state = True
        self._build_train_step(batch)
        abs_batch = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype, sharding=s),
            batch, self._batch_shardings)
        with mesh_lib.trace_mesh(self.mesh):
            return self._train_step_fn.lower(self.state, abs_batch).compile()

    def train_batch(self, data_iter=None, batch=None):
        """Run one full training step = gas micro-batches (ref:
        pipe/engine.py:338 train_batch; for non-pipeline configs this fuses
        what forward/backward/step do imperatively)."""
        if batch is None:
            assert data_iter is not None, "provide data_iter or batch"
            micro = [next(data_iter) for _ in range(self.gas)]
            batch = jax.tree.map(lambda *xs: np.concatenate(xs, axis=0), *micro) if self.gas > 1 else micro[0]
        # shape donor for elastic re-materialization after a membership change
        # (elasticity/elastic_agent.py) — host arrays, one batch, cheap
        self.last_batch = batch
        self._ensure_ready(batch)
        # named chaos site around the step dispatch: injects device loss
        # (drives DSElasticAgent recovery), stragglers (drives the step
        # watchdog) or transient errors; a single `is None` test when unarmed
        from ..resilience import fault_injection as _fi
        _fi.check("engine.step")
        prof_cfg = self._config.flops_profiler_config
        profiling_now = (self.flops_profiler is not None and self.global_steps == prof_cfg.profile_step)
        if profiling_now:
            self.flops_profiler.start_profile(example_batch=batch)
        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        import time as _time
        _step_t0 = _time.time()  # dslint-ok(determinism): 1-bit wire latency proxy is real dispatch wall time (see comment below)
        # one trace per training step; phases land as child spans (the
        # null tracer makes this whole block allocation-free when off)
        self._step_span = self.tracer.start_span(
            "engine/step", track="engine",
            attrs={"global_step": self.global_steps} if self.tracer.enabled else None)
        try:
            with mesh_lib.trace_mesh(self.mesh):  # first call traces model code
                if getattr(self, "_nvme_opt", None) is not None:
                    self.state, metrics = self._nvme_train_step(batch)
                else:
                    with self.tracer.span("engine/fused_step",
                                          parent=self._step_span, track="engine"):
                        self.state, metrics = self._train_step_fn(self.state, batch)
        finally:
            self.tracer.end(self._step_span)
            self._step_span = None
        if getattr(self, "_compressed_wire_bytes", None) \
                and self.global_steps >= getattr(self, "_onebit_freeze_step", 0) \
                and not self._rebuilt_this_step:
            # only compression-phase steps carry the 1-bit wire (warmup's
            # traffic is the fp32 grad pmean); latency = dispatch wall time,
            # the closest host-side proxy for the async step.  Steps that
            # just (re)built the program are skipped — their wall time is
            # dominated by compilation, not the wire
            from ..comm import comm as dist
            dist._record(self._compressed_wire_name, _step_t0, self._compressed_wire_bytes)
        self.timers(TRAIN_BATCH_TIMER).stop()
        self.tput_timer.stop(global_step=True)
        if profiling_now:
            jax.block_until_ready(metrics.loss)
            self.flops_profiler.stop_profile()
            self.flops_profiler.print_model_profile(profile_step=self.global_steps,
                                                    module_depth=prof_cfg.module_depth,
                                                    top_modules=prof_cfg.top_modules,
                                                    detailed=prof_cfg.detailed,
                                                    output_file=prof_cfg.output_file)
            self.flops_profiler.end_profile()
        self.global_steps += 1
        self.global_samples += self._config.train_batch_size
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        self._write_monitor(metrics)
        self._maybe_print(metrics)
        return metrics.loss

    def measure_stream_overlap(self, batch, pipelined_steps: int = 1):
        """Measure the streamed-optimizer pipeline's transfer/compute
        overlap on real steps and return the artifact dict (see
        overlap_instrumentation.report): per-group upload/compute/download
        seconds, the aggregate overlap fraction, and the transfer-/compute-
        bound floor.  Runs ``pipelined_steps`` normal (flushed) steps plus
        one serialized probe step — these are REAL training steps (state
        advances).  Requires an active streamed offload tier."""
        assert getattr(self, "_nvme_opt", None) is not None, (
            "measure_stream_overlap needs an active streamed optimizer tier "
            "(offload_optimizer device=cpu+pipeline_read or device=nvme)")
        try:
            self._nvme_step_mode = "flush"
            for _ in range(max(1, pipelined_steps)):
                self.train_batch(batch=batch)
            self._nvme_step_mode = "serialize"
            self.train_batch(batch=batch)
        finally:
            self._nvme_step_mode = None
        return self._nvme_opt.overlap_report()

    def _build_eval_fn(self):
        if self._eval_fn is None:
            def eval_loss(state, b):
                return self._microbatch_loss(state.params, b, step=state.step)
            self._eval_fn = jax.jit(eval_loss, in_shardings=(self.state_shardings, self._batch_shardings))
            # refresh the per-bucket step cache: its entry was created with
            # _eval_fn=None at train-step build time, and restoring that
            # stale None on a bucket switch-and-back would force an eval
            # retrace (advisor r2)
            cache = getattr(self, "_step_cache", None)
            key = getattr(self, "_step_key", None)
            if cache is not None and key in cache:
                cache[key] = (self._train_step_fn, self._accum_fn, self._apply_step_fn,
                              self._batch_shardings, self._eval_fn)
        return self._eval_fn

    def forward(self, batch):
        """Compute loss for a micro-batch (eval path shares the jitted fn)."""
        self._ensure_ready(batch)
        self._last_batch = batch
        fn = self._build_eval_fn()
        self.timers(FORWARD_GLOBAL_TIMER).start()
        with mesh_lib.trace_mesh(self.mesh):
            loss = fn(self.state, batch)
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        return loss

    __call__ = forward

    def backward(self, loss=None, batch=None):
        """Accumulate gradients for the last forwarded batch (ref:
        engine.py:2204 backward).  The ``loss`` argument is accepted for API
        parity; gradients are recomputed functionally."""
        batch = batch if batch is not None else getattr(self, "_last_batch", None)
        assert batch is not None, "call forward(batch) first or pass batch="
        self._ensure_ready(batch)
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        with mesh_lib.trace_mesh(self.mesh):
            grads, loss_v = self._accum_fn(self.state, batch)
        if self._pending_grads is None:
            self._pending_grads, self._pending_loss = grads, loss_v
        else:
            self._pending_grads = jax.tree.map(jnp.add, self._pending_grads, grads)
            self._pending_loss = self._pending_loss + loss_v
        self.timers(BACKWARD_GLOBAL_TIMER).stop()
        self._micro_step_count += 1
        return loss_v

    def is_gradient_accumulation_boundary(self):
        """ref: engine.py:2124."""
        return self._micro_step_count % self.gas == 0

    def step(self):
        """Apply the optimizer once per GAS boundary (ref: engine.py:2338)."""
        assert self._pending_grads is not None, "backward() must run before step()"
        if not self.is_gradient_accumulation_boundary():
            return
        self.timers(STEP_GLOBAL_TIMER).start()
        # note: _apply_grads divides by gas via the scaler path; pending grads
        # are summed over backward() calls which matches
        loss = self._pending_loss / self._micro_step_count
        with mesh_lib.trace_mesh(self.mesh):
            self.state, metrics = self._apply_step_fn(self.state, self._pending_grads, loss)
        self.timers(STEP_GLOBAL_TIMER).stop()
        self._pending_grads, self._pending_loss = None, None
        self._micro_step_count = 0
        self.global_steps += 1
        self.global_samples += self._config.train_batch_size
        self._write_monitor(metrics)
        self._maybe_print(metrics)
        self.lr_scheduler.step()
        return metrics

    def eval_batch(self, data_iter=None, batch=None):
        if batch is None:
            batch = next(data_iter)
        return self.forward(batch)

    def no_sync(self):
        """Grad-sync control is compiled into the step on TPU; context kept
        for API parity (ref: engine.py:2184)."""
        import contextlib
        return contextlib.nullcontext()

    def zero_grad(self):
        self._pending_grads, self._pending_loss = None, None
        self._micro_step_count = 0

    # ------------------------------------------------------------- monitoring

    def set_telemetry(self, tracer=None, metrics=None):
        """Attach a telemetry ``Tracer`` and/or ``MetricsRegistry``
        (deepspeed_tpu/telemetry).  Every subsequent ``train_batch`` emits
        one ``engine/step`` trace with ``fwd_bwd``/``optim`` child spans
        (streamed-optimizer tiers additionally lift their per-group
        upload/compute/download phases into child spans), and the flops
        profiler — when enabled — publishes its per-step flops/params
        gauges into the registry."""
        from ..telemetry.trace import NULL_TRACER
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics_registry = metrics
        if self.flops_profiler is not None:
            # always propagate — set_telemetry() with no registry must
            # DETACH a previously attached one, or the profiler keeps
            # publishing into (and pinning) a registry the caller dropped
            self.flops_profiler.attach_metrics(metrics)
        return self

    def _write_monitor(self, metrics):
        if self.monitor is not None and self.monitor.enabled:
            events = [
                ("Train/Samples/train_loss", float(metrics.loss), self.global_samples),
                ("Train/Samples/lr", float(metrics.lr), self.global_samples),
                ("Train/Samples/loss_scale", float(metrics.loss_scale), self.global_samples),
            ]
            nv = getattr(self, "_nvme_opt", None)
            ver = getattr(getattr(nv, "instrumentation", None), "version", 0)
            if nv is not None and hasattr(nv, "overlap_report") \
                    and ver != getattr(self, "_stream_report_ver", 0):
                # streamed-optimizer overlap metrics: emitted once per FRESH
                # measurement (probe/flushed step), not re-sent every step
                rep = nv.overlap_report()
                if rep is not None:
                    for key in ("upload_s", "compute_s", "download_s",
                                "overlap_fraction", "pipelined_wall_s"):
                        if rep.get(key) is not None:
                            events.append((f"Train/Samples/stream_{key}",
                                           float(rep[key]), self.global_samples))
                self._stream_report_ver = ver
            self.monitor.write_events(events)

    def _maybe_print(self, metrics):
        spp = self._config.steps_per_print
        if spp and self.global_steps % spp == 0:
            log_dist(
                f"step={self.global_steps} loss={float(metrics.loss):.4f} "
                f"lr={float(metrics.lr):.3e} gnorm={float(metrics.grad_norm):.3f} "
                f"scale={float(metrics.loss_scale):.0f} skipped={int(self.state.skipped_steps)}",
                ranks=[0])

    # ------------------------------------------------------------ checkpoints

    # --------------------------------------------------------- state offload

    def offload_states(self, include=None, device: str = "cpu", nvme_path=None,
                       pin_memory: bool = True, non_blocking: bool = False):
        """Evict optimizer state / fp32 master weights from device memory
        (ref: runtime/zero/offload_states.py + engine.offload_states — used
        e.g. between RLHF train and generate phases).

        device='cpu'  → host numpy copies (HBM freed; ``reload_states``
                        or the next train_batch re-uploads them).
        device='nvme' → streamed to ``nvme_path`` via the native aio engine
                        (ops/aio); ``reload_states`` REQUIRED before training.
        """
        assert self.state is not None, "no state materialized yet"
        include = set(include or ("optimizer_states", "master_weights"))
        self._offloaded = getattr(self, "_offloaded", {})

        def take(name, tree):
            if name not in include or tree == ():
                return tree
            if device == "nvme":
                from .swap_tensor import AioSwapConfig, TensorSwapper
                if getattr(self, "_nvme_swapper", None) is None:
                    assert nvme_path is not None, "offload_states(device='nvme') needs nvme_path"
                    self._nvme_swapper = TensorSwapper(nvme_path, AioSwapConfig())
                self._nvme_swapper.swap_out(name, tree)
                self._offloaded[name] = "nvme"
                # zero-length host placeholders keep the pytree structure
                return jax.tree.map(lambda x: np.empty((0, ), np.dtype(x.dtype)), tree)
            self._offloaded[name] = "cpu"
            return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)

        new_opt = take("optimizer_states", self.state.opt_state)
        new_master = take("master_weights", self.state.master)
        self.state = self.state._replace(opt_state=new_opt, master=new_master)
        log_dist(f"offload_states: {sorted(include)} → {device}", ranks=[0])

    def reload_states(self, non_blocking: bool = False):
        """Restore previously offloaded states to their device shardings
        (ref: engine.reload_states)."""
        offloaded = getattr(self, "_offloaded", {})
        if not offloaded:
            return

        def put(name, tree, shardings):
            if name not in offloaded or tree == ():
                return tree
            if offloaded[name] == "nvme":
                tree = self._nvme_swapper.swap_in(name)
            return jax.device_put(tree, shardings)

        self.state = self.state._replace(
            opt_state=put("optimizer_states", self.state.opt_state, self.state_shardings.opt_state),
            master=put("master_weights", self.state.master, self.state_shardings.master))
        self._offloaded = {}

    def save_checkpoint(self, save_dir, tag=None, client_state=None, save_latest=True, exclude_frozen_parameters=False):
        from .swap_tensor.host_streamed_optimizer import HostStreamedOptimizer
        nv = getattr(self, "_nvme_opt", None)
        if nv is not None and not isinstance(nv, HostStreamedOptimizer):
            # NVMe tier: optimizer state lives on disk already; the
            # checkpoint captures params + step, and resume re-reads the
            # swap files at nvme_path (they are flushed durable here)
            nv.swapper.flush_writes()
            logger.warning("save_checkpoint with pipelined NVMe offload: optimizer "
                           "moments stay in the nvme_path swap files — keep that "
                           "directory alongside the checkpoint to resume exactly")
        from ..checkpoint.engine import save_checkpoint as _save
        # host tier: state is process RAM — persist it INTO the tag dir
        # (unlike NVMe swap files, nothing else makes it durable).  Passed
        # as the extra-state callback so the npz files land INSIDE the
        # durability fence: covered by the tag manifest and written before
        # `latest` is published (a crash mid-npz leaves the previous
        # checkpoint published, not a half-restorable new one)
        extra = nv.save_state if isinstance(nv, HostStreamedOptimizer) else None
        return _save(self, save_dir, tag=tag, client_state=client_state or {},
                     save_latest=save_latest, extra_state_cb=extra)

    def load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False):
        from ..checkpoint.engine import load_checkpoint as _load
        out = _load(self, load_dir, tag=tag, load_optimizer_states=load_optimizer_states,
                    load_module_only=load_module_only)
        if getattr(self, "_nvme_opt", None) is not None and self.state is not None:
            from .swap_tensor.host_streamed_optimizer import HostStreamedOptimizer
            nv = self._nvme_opt
            loaded_path = out[0] if isinstance(out, tuple) else None
            if isinstance(nv, HostStreamedOptimizer) and load_optimizer_states \
                    and loaded_path is not None:
                # host tier: restore the group state persisted into the tag
                # dir by save_checkpoint.  The tag dir is the PATH THE LOAD
                # RESOLVED (returned above) — re-reading `latest` here would
                # point at the corrupt tag the loader just fell back FROM
                tag_dir = loaded_path
                if nv.load_state(tag_dir):
                    # a same-shaped host_opt_group*.npz from a DIFFERENT run
                    # loads cleanly but its master would silently revert the
                    # restored params on the first step — probe one leaf per
                    # group and resync (moments reset, warned) on mismatch
                    leaves = jax.tree.leaves(self.state.params)
                    if not nv.master_matches_params(leaves, self.compute_dtype):
                        logger.warning(
                            "host-streamed offload: restored host_opt_group*.npz "
                            "state does not correspond to the loaded checkpoint's "
                            "params (same shapes, different run?) — reinitializing "
                            "master from the restored weights (Adam moments reset)")
                        nv.resync_master_from_params(leaves)
                    return out
            # the offloaded fp32 master must correspond to the restored
            # params — otherwise the first step would silently revert the
            # loaded weights to whatever the store held (e.g. the random
            # init written at materialization)
            leaves = jax.tree.leaves(self.state.params)
            if not nv.master_matches_params(leaves, self.compute_dtype):
                logger.warning("streamed optimizer offload: stored state does not match "
                               "the loaded checkpoint — reinitializing master from the "
                               "restored weights (Adam moments reset to zero)")
                nv.resync_master_from_params(leaves)
        return out

    # ------------------------------------------------------------- properties

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def train_batch_size(self):
        return self._config.train_batch_size

    def gradient_accumulation_steps(self):
        return self.gas

    def get_global_grad_norm(self):
        return None  # populated in metrics per step

    def zero_optimization(self):
        return self.zero_stage > 0

    def zero_optimization_stage(self):
        return self.zero_stage

    @property
    def loss_scale(self):
        return float(self.state.scaler.cur_scale) if self.state is not None else None

    @property
    def skipped_steps(self):
        return int(self.state.skipped_steps) if self.state is not None else 0

    def get_lr(self):
        return [float(self.lr_schedule(self.state.step if self.state is not None else 0))]

    def module_state_dict(self):
        return self.state.params if self.state is not None else None
