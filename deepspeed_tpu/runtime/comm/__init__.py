from .compressed import (all_to_all_quant_reduce, compressed_allreduce,  # noqa: F401
                         quantized_all_gather)
