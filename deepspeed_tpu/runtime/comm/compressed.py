"""Compressed collectives: 1-bit error-feedback allreduce and ZeRO++
quantized reductions.

Reference:
  * ``deepspeed/runtime/comm/compressed.py:13 CompressedBackend`` /
    ``nccl.py:16 NcclBackend`` — the error-feedback sign-compressed
    allreduce behind OnebitAdam/OnebitLamb/ZeroOneAdam;
  * ``runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce``
    (qgZ: quantized gradient all-to-all reduction) and the quantized weight
    all-gather (qwZ) of ZeRO++.

All functions are designed for use INSIDE ``shard_map`` bodies (explicit
``jax.lax`` collectives over a named axis), which is where TPU programs
spell out comm that GSPMD would otherwise insert at full precision.  The
wire format is real packed bits/int8 — the ICI/DCN traffic is genuinely
1/4–1/32 of fp32, not a simulation.
"""

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ...ops.quantizer import pack_signs, unpack_signs
# Pallas-fused quant/dequant on TPU, jnp fallback elsewhere (ref:
# csrc/quantization swizzled_quantize.cu — the wire-format pack kernels)
from ...ops.quant_kernels import (dequantize_int4_pallas as dequantize_int4,
                                  dequantize_int8_pallas as dequantize_int8,
                                  quantize_int4_pallas as quantize_int4,
                                  quantize_int8_pallas as quantize_int8)


def compressed_allreduce(x, error, axis_name: str) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Error-feedback 1-bit allreduce (ref: compressed.py
    ``compressed_allreduce`` — steps 1/2 with worker error feedback).

    x:     local tensor shard-identical shape on every rank
    error: carried compression residual (same shape; init zeros)
    Returns (averaged tensor, new error).  Wire traffic per rank:
    n/8 bytes of signs + one f32 scale, all-gathered over the axis.
    """
    shape = x.shape
    n = x.size
    local = x.astype(jnp.float32) + error.astype(jnp.float32)
    flat = local.reshape(-1)
    # per-tensor scale: mean |x| of the corrected tensor (ref uses
    # norm/sqrt(n) — mean-abs is the sign-quantization MSE optimum)
    scale = jnp.mean(jnp.abs(flat))
    signs = jnp.sign(flat)
    signs = jnp.where(signs == 0, 1.0, signs)
    compressed = scale * signs
    new_error = (flat - compressed).reshape(shape)

    packed = pack_signs(flat)                                  # uint8[n/8]
    all_packed = jax.lax.all_gather(packed, axis_name)         # [P, n/8]
    all_scales = jax.lax.all_gather(scale, axis_name)          # [P]
    world = all_scales.shape[0]
    decoded = jax.vmap(lambda p, s: unpack_signs(p, n) * s)(all_packed, all_scales)
    avg = jnp.mean(decoded, axis=0).reshape(shape)
    return avg.astype(x.dtype), new_error.astype(error.dtype)


def all_to_all_quant_reduce(x, axis_name: str, bits: int = 8, block: int = 256,
                            return_local_dequant: bool = False):
    """qgZ: quantized gradient reduce-scatter (ref: coalesced_collectives.py
    :31 all_to_all_quant_reduce — quantize → all-to-all → dequant-reduce).

    x: [n] local gradient with n divisible by the axis size.  Each rank
    receives everyone's quantized copy of ITS output shard and reduces in
    fp32.  Returns the rank's averaged shard [n/P].  Wire: int8 (or packed
    int4) instead of fp32.  ``return_local_dequant`` additionally returns
    the dequantized copy of THIS rank's full input exactly as the wire
    carried it (the LoCo error-feedback residual source — computed here so
    the codec exists in exactly one place).
    """
    world = jax.lax.axis_size(axis_name)
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.size
    assert n % world == 0
    shard = n // world
    chunks = flat.reshape(world, shard)
    if bits == 8:
        q, s = quantize_int8(chunks.reshape(-1), block)
        local_deq = dequantize_int8(q, s, (n, )) if return_local_dequant else None
        nblocks = q.shape[0] // world
        q = q.reshape(world, nblocks, block)
        s = s.reshape(world, nblocks)
    else:
        q, s = quantize_int4(chunks.reshape(-1), block)
        local_deq = dequantize_int4(q, s, (n, )) if return_local_dequant else None
        nblocks = q.shape[0] // world
        q = q.reshape(world, nblocks, block // 2)
        s = s.reshape(world, nblocks)
    # all_to_all: rank r sends chunk d to rank d, receives [P, ...] copies of
    # its own chunk index
    q_recv = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=False)
    s_recv = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0, tiled=False)
    if bits == 8:
        deq = jax.vmap(lambda qq, ss: dequantize_int8(qq, ss, (shard, )))(q_recv, s_recv)
    else:
        deq = jax.vmap(lambda qq, ss: dequantize_int4(qq, ss, (shard, )))(q_recv, s_recv)
    reduced = jnp.mean(deq, axis=0)  # [shard] fp32
    if return_local_dequant:
        return reduced, local_deq
    return reduced


def quantized_all_gather(shard, axis_name: str, bits: int = 8, block: int = 256):
    """qwZ: quantized weight all-gather (ref: ZeRO++ quantized param
    all_gather_coalesced, partition_parameters.py quantized path).

    shard: this rank's parameter shard [m].  Returns the dequantized full
    tensor [P*m] (fp32).  Wire: int8/int4 + per-block scales.
    """
    flat = shard.reshape(-1).astype(jnp.float32)
    m = flat.size
    if bits == 8:
        q, s = quantize_int8(flat, block)
        all_q = jax.lax.all_gather(q, axis_name)      # [P, m/block, block]
        all_s = jax.lax.all_gather(s, axis_name)
        deq = jax.vmap(lambda qq, ss: dequantize_int8(qq, ss, (m, )))(all_q, all_s)
    else:
        q, s = quantize_int4(flat, block)
        all_q = jax.lax.all_gather(q, axis_name)
        all_s = jax.lax.all_gather(s, axis_name)
        deq = jax.vmap(lambda qq, ss: dequantize_int4(qq, ss, (m, )))(all_q, all_s)
    return deq.reshape(-1)


def padded_quant_allreduce(x, axis_name: str, world: int, bits: int = 8, block: int = 256,
                           error=None, err_beta: float = 0.8):
    """Whole-tensor quantized allreduce on the qgZ wire: pad to a
    world×block multiple (zero padding is exact under the mean), quantized
    all-to-all reduce-scatter, quantized all-gather, truncate back.

    With ``error`` (same shape as ``x``): the LoCo variant — the previous
    round's quantization error folds back pre-quantization and the new
    residual is returned alongside.  Returns ``reduced`` or
    ``(reduced, new_error)``.  The single codec home for both the engine's
    qgZ step and the LoCo optimizer wrapper."""
    flat = x.reshape(-1).astype(jnp.float32)
    unit = world * block
    pad = (-flat.size) % unit
    if error is None:
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad, ), flat.dtype)])
        shard = all_to_all_quant_reduce(flat, axis_name, bits=bits, block=block)
        full = quantized_all_gather(shard, axis_name, bits=bits, block=block)
        return full[:x.size].reshape(x.shape).astype(x.dtype)
    ef = error.reshape(-1).astype(jnp.float32)
    if pad:
        z = jnp.zeros((pad, ), jnp.float32)
        flat, ef = jnp.concatenate([flat, z]), jnp.concatenate([ef, z])
    shard, new_err = loco_all_to_all_quant_reduce(flat, ef, axis_name, bits=bits,
                                                  block=block, err_beta=err_beta)
    full = quantized_all_gather(shard, axis_name, bits=bits, block=block)
    return (full[:x.size].reshape(x.shape).astype(x.dtype),
            new_err[:x.size].reshape(x.shape))


def loco_all_to_all_quant_reduce(x, error, axis_name: str, bits: int = 8, block: int = 256,
                                 err_beta: float = 0.8):
    """LoCo-qgZ: quantized gradient reduction WITH local error feedback
    (ref: coalesced_collectives.py:81 all_to_all_loco_quant_reduce — the
    LoCo variant folds the previous round's quantization error back into
    the gradient before quantizing, removing the bias of plain qgZ).

    x: [n] local grad; error: [n] running error state (same shape).
    Returns (reduced_shard [n/P] fp32, new_error [n]).
    """
    flat = x.reshape(-1).astype(jnp.float32)
    fed = flat + err_beta * error.reshape(-1).astype(jnp.float32)
    reduced, deq = all_to_all_quant_reduce(fed, axis_name, bits=bits, block=block,
                                           return_local_dequant=True)
    new_error = (fed - deq).reshape(x.shape)
    return reduced, new_error.astype(error.dtype)
