"""Data-efficiency pipeline (ref: deepspeed/runtime/data_pipeline/):
curriculum learning, data sampling, random-LTD token dropping."""

from .curriculum_scheduler import CurriculumScheduler  # noqa: F401
