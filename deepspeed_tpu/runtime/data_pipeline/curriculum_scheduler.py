"""Curriculum-learning difficulty scheduler.

ref: ``deepspeed/runtime/data_pipeline/curriculum_scheduler.py:11
CurriculumScheduler`` — maps global step → difficulty (e.g. sequence
length) under fixed_linear / fixed_root / fixed_discrete / custom
schedules.  Pure host-side control logic; on TPU a difficulty change
means new batch shapes, which triggers a cached recompile of the train
step (engine keys compiled fns by batch shape).
"""

import math

from ...utils.logging import logger
from .constants import *  # noqa: F401,F403


class CurriculumScheduler:

    def __init__(self, config):
        self.state = {}
        assert CURRICULUM_LEARNING_MIN_DIFFICULTY in config, \
            f"Curriculum learning requires the config '{CURRICULUM_LEARNING_MIN_DIFFICULTY}'"
        assert CURRICULUM_LEARNING_MAX_DIFFICULTY in config, \
            f"Curriculum learning requires the config '{CURRICULUM_LEARNING_MAX_DIFFICULTY}'"
        assert CURRICULUM_LEARNING_SCHEDULE_TYPE in config, \
            f"Curriculum learning requires the config '{CURRICULUM_LEARNING_SCHEDULE_TYPE}'"
        self.state[CURRICULUM_LEARNING_MIN_DIFFICULTY] = config[CURRICULUM_LEARNING_MIN_DIFFICULTY]
        self.state[CURRICULUM_LEARNING_MAX_DIFFICULTY] = config[CURRICULUM_LEARNING_MAX_DIFFICULTY]
        self.state[CURRICULUM_LEARNING_CURRENT_DIFFICULTY] = config[CURRICULUM_LEARNING_MIN_DIFFICULTY]
        self.state[CURRICULUM_LEARNING_SCHEDULE_TYPE] = config[CURRICULUM_LEARNING_SCHEDULE_TYPE]
        self.first_step = True
        self.custom_get_difficulty = None

        schedule_type = config[CURRICULUM_LEARNING_SCHEDULE_TYPE]
        schedule_config = config.get(CURRICULUM_LEARNING_SCHEDULE_CONFIG, {})
        if schedule_type == CURRICULUM_LEARNING_SCHEDULE_FIXED_DISCRETE:
            # {"difficulty": [1,2,3], "max_step": [5,10]}
            assert len(schedule_config[CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY]) > 0
            assert len(schedule_config[CURRICULUM_LEARNING_SCHEDULE_MAX_STEP]) > 0
            assert len(schedule_config[CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY]) == \
                len(schedule_config[CURRICULUM_LEARNING_SCHEDULE_MAX_STEP]) + 1
        elif schedule_type in (CURRICULUM_LEARNING_SCHEDULE_FIXED_LINEAR, CURRICULUM_LEARNING_SCHEDULE_FIXED_ROOT):
            assert schedule_config[CURRICULUM_LEARNING_SCHEDULE_TOTAL_STEP] > 0
            assert schedule_config[CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY_STEP] > 0
            if schedule_config[CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY_STEP] % 8 != 0:
                logger.warning("Curriculum learning difficulty_step that is not a multiple of 8 "
                               "hurts MXU tiling (prefer seq-len multiples of 8/128 on TPU)")
            if schedule_type == CURRICULUM_LEARNING_SCHEDULE_FIXED_ROOT:
                assert schedule_config[CURRICULUM_LEARNING_SCHEDULE_ROOT_DEGREE] > 0
        elif schedule_type == CURRICULUM_LEARNING_SCHEDULE_CUSTOM:
            pass
        else:
            raise RuntimeError(f"Unsupported curriculum schedule type {schedule_type}")
        self.state[CURRICULUM_LEARNING_SCHEDULE_CONFIG] = schedule_config

    def get_current_difficulty(self):
        return self.state[CURRICULUM_LEARNING_CURRENT_DIFFICULTY]

    def set_current_difficulty(self, difficulty):
        self.state[CURRICULUM_LEARNING_CURRENT_DIFFICULTY] = difficulty

    def set_custom_get_difficulty(self, schedule_function):
        self.custom_get_difficulty = schedule_function

    def get_state(self):
        return self.state

    def set_state(self, state):
        self.state = state

    def __fixed_discrete_get_difficulty(self, global_steps):
        s_state = self.state[CURRICULUM_LEARNING_SCHEDULE_CONFIG]
        max_steps = s_state[CURRICULUM_LEARNING_SCHEDULE_MAX_STEP]
        difficulties = s_state[CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY]
        for i, cap in enumerate(max_steps):
            if global_steps <= cap:
                return difficulties[i]
        return difficulties[-1]

    def __fixed_root_get_difficulty(self, global_steps, root_degree=None):
        s_state = self.state[CURRICULUM_LEARNING_SCHEDULE_CONFIG]
        if root_degree is None:
            root_degree = s_state[CURRICULUM_LEARNING_SCHEDULE_ROOT_DEGREE]
        next_difficulty = (float(global_steps) / s_state[CURRICULUM_LEARNING_SCHEDULE_TOTAL_STEP]) ** (1.0 /
                                                                                                       root_degree)
        next_difficulty = math.floor(
            next_difficulty *
            (self.state[CURRICULUM_LEARNING_MAX_DIFFICULTY] - self.state[CURRICULUM_LEARNING_MIN_DIFFICULTY]) +
            self.state[CURRICULUM_LEARNING_MIN_DIFFICULTY])
        next_difficulty -= next_difficulty % s_state[CURRICULUM_LEARNING_SCHEDULE_DIFFICULTY_STEP]
        next_difficulty = min(next_difficulty, self.state[CURRICULUM_LEARNING_MAX_DIFFICULTY])
        next_difficulty = max(next_difficulty, self.state[CURRICULUM_LEARNING_MIN_DIFFICULTY])
        return next_difficulty

    def get_difficulty(self, global_steps):
        stype = self.state[CURRICULUM_LEARNING_SCHEDULE_TYPE]
        if stype == CURRICULUM_LEARNING_SCHEDULE_FIXED_DISCRETE:
            return self.__fixed_discrete_get_difficulty(global_steps)
        if stype == CURRICULUM_LEARNING_SCHEDULE_FIXED_LINEAR:
            return self.__fixed_root_get_difficulty(global_steps, root_degree=1)
        if stype == CURRICULUM_LEARNING_SCHEDULE_FIXED_ROOT:
            return self.__fixed_root_get_difficulty(global_steps)
        if stype == CURRICULUM_LEARNING_SCHEDULE_CUSTOM:
            assert self.custom_get_difficulty is not None, "custom schedule needs set_custom_get_difficulty"
            return self.custom_get_difficulty(global_steps)
        raise RuntimeError(f"Unsupported curriculum schedule type {stype}")

    def update_difficulty(self, global_steps):
        if self.state[CURRICULUM_LEARNING_CURRENT_DIFFICULTY] < self.state[CURRICULUM_LEARNING_MAX_DIFFICULTY]:
            self.state[CURRICULUM_LEARNING_CURRENT_DIFFICULTY] = self.get_difficulty(global_steps)
        return self.state[CURRICULUM_LEARNING_CURRENT_DIFFICULTY]
