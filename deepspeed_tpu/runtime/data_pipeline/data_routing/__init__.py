from .basic_layer import RandomLayerTokenDrop  # noqa: F401
from .scheduler import RandomLTDScheduler  # noqa: F401
