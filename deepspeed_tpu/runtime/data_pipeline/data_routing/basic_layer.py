"""Random layerwise token dropping (random-LTD).

ref: ``deepspeed/runtime/data_pipeline/data_routing/basic_layer.py:14
RandomLayerTokenDrop`` + the CUDA token gather/scatter kernels in
``csrc/random_ltd`` (SURVEY §2.5 maps these to plain XLA gather/sort).

TPU-native design: the reserved length is STATIC per curriculum phase
(shape-stable → one compile per phase); index sampling uses threaded PRNG
keys via ``jax.random.permutation`` under vmap; gather/scatter lower to
one XLA gather / scatter each — no custom kernels needed.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


def _sample_sorted_indices(rng, n_layers, batch, seq_len, reserved):
    """(n_layers, batch, reserved) sorted random token indices."""
    keys = jax.random.split(rng, n_layers * batch).reshape(n_layers, batch, 2)

    def one(key):
        perm = jax.random.permutation(key, seq_len)[:reserved]
        return jnp.sort(perm)

    return jax.vmap(jax.vmap(one))(keys)


def gpt_sample_tokens(rng, reserved_length, seq_len, batch, n_layers, attn_mask=None):
    """Decoder-style sampling (ref: ops/random_ltd/dropping_utils.py
    gpt_sample_tokens): indices sorted so causal order is preserved; the
    causal mask for the short sequence is rebuilt by the attention op from
    positions, so no per-layer mask tensor is materialised."""
    idx = _sample_sorted_indices(rng, n_layers, batch, seq_len, reserved_length)
    return idx, None


def bert_sample_tokens(rng, reserved_length, seq_len, batch, n_layers, attn_mask=None):
    """Encoder-style sampling (ref: bert_sample_tokens): also slices the
    padding mask to the kept tokens (n_layers, batch, reserved)."""
    idx = _sample_sorted_indices(rng, n_layers, batch, seq_len, reserved_length)
    part_mask = None
    if attn_mask is not None:
        part_mask = jax.vmap(lambda layer_idx: jnp.take_along_axis(attn_mask, layer_idx, axis=1))(idx)
    return idx, part_mask


def gather_tokens(x, indices, batch_first=True):
    """Keep only the sampled tokens (ref: csrc/random_ltd gather →
    here one XLA gather).  x: (B,S,H) or (S,B,H); indices: (B, reserved).
    Returns (x, part) both in the input layout."""
    xb = x if batch_first else jnp.swapaxes(x, 0, 1)
    part = jnp.take_along_axis(xb, indices[:, :, None], axis=1)
    if not batch_first:
        part = jnp.swapaxes(part, 0, 1)
    return x, part


def scatter_tokens(full, part, indices, batch_first=True):
    """Write processed tokens back into the full sequence (ref: ScatterTokens)."""
    if not batch_first:
        full = jnp.swapaxes(full, 0, 1)
        part = jnp.swapaxes(part, 0, 1)
    out = full.at[jnp.arange(full.shape[0])[:, None], indices].set(part)
    if not batch_first:
        out = jnp.swapaxes(out, 0, 1)
    return out


class RandomLayerTokenDrop:
    """Functional wrapper around a transformer layer fn.

    Usage:
        ltd = RandomLayerTokenDrop(layer_fn, layer_id=i)
        ltd.init_config(config, scheduler, i)
        hidden = ltd(hidden, rng=key, training=True, **layer_kwargs)

    ``layer_fn(hidden, **kwargs)`` may return a tensor or a tuple whose
    first element is the hidden state (same contract as the reference).
    """

    def __init__(self, layer: Callable, layer_id: int = 0):
        self.random_ltd_layer = layer
        self.random_ltd_layer_id = layer_id
        self.random_ltd_scheduler = None
        self.mask_name = None
        self.batch_first = True
        self.model_type = "decoder"
        self.random_ltd_num_layer = 1

    def init_config(self, config, scheduler, random_ltd_layer_id):
        from ..constants import (RANDOM_LTD_MODEL_MASK_NAME, RANDOM_LTD_MODEL_TYPE, RANDOM_LTD_TOTAL_LAYER_NUM)
        self.random_ltd_scheduler = scheduler
        self.random_ltd_layer_id = random_ltd_layer_id
        self.mask_name = config.get(RANDOM_LTD_MODEL_MASK_NAME)
        self.model_type = config.get(RANDOM_LTD_MODEL_TYPE, "decoder")
        self.random_ltd_num_layer = scheduler.random_ltd_layer_num

    def __call__(self, hidden_states, rng=None, training=True, **kwargs):
        sched = self.random_ltd_scheduler
        seq_len = hidden_states.shape[1] if self.batch_first else hidden_states.shape[0]
        batch = hidden_states.shape[0] if self.batch_first else hidden_states.shape[1]
        reserved = sched.get_current_seq() if sched is not None else seq_len

        if not training or sched is None or reserved >= seq_len:
            return self.random_ltd_layer(hidden_states, **kwargs)

        mask = kwargs.get(self.mask_name) if self.mask_name else None
        sampler = bert_sample_tokens if self.model_type == "encoder" else gpt_sample_tokens
        if rng is None:
            rng = jax.random.PRNGKey(sched.state.get("current_steps", 0))
        # one sampling per step, shared across wrapped layers (ref stores it
        # in scheduler state at layer 0)
        cache_key = "_sampled_cache"
        cached = sched.state.get(cache_key)
        if self.random_ltd_layer_id == 0 or cached is None or cached[0] != (int(reserved), int(seq_len), int(batch)):
            idx, part_mask = sampler(rng, int(reserved), seq_len, batch, self.random_ltd_num_layer, mask)
            sched.state[cache_key] = ((int(reserved), int(seq_len), int(batch)), idx, part_mask)
        else:
            _, idx, part_mask = cached

        layer_idx = idx[self.random_ltd_layer_id % idx.shape[0]]
        full, part = gather_tokens(hidden_states, layer_idx, self.batch_first)
        if self.mask_name and part_mask is not None:
            kwargs[self.mask_name] = part_mask[self.random_ltd_layer_id % part_mask.shape[0]]

        outputs = self.random_ltd_layer(part, **kwargs)
        if isinstance(outputs, tuple):
            merged = scatter_tokens(full, outputs[0], layer_idx, self.batch_first)
            return (merged, ) + tuple(outputs[1:])
        return scatter_tokens(full, outputs, layer_idx, self.batch_first)
