"""Random-LTD sequence-length scheduler.

ref: ``deepspeed/runtime/data_pipeline/data_routing/scheduler.py``
(BaseScheduler/RandomLTDScheduler) — grows the reserved token count from
min_value to max_value on a fixed_linear schedule and tracks consumed
layer-tokens.
"""

import math

from ..constants import *  # noqa: F401,F403

RANDOM_LTD_CONSUMED_LAYER_TOKENS = "consumed_layer_tokens"


class BaseScheduler:

    def __init__(self):
        self.state = {}

    def _fixed_root_get_value(self, global_steps, root_degree):
        s_cfg = self.state[RANDOM_LTD_SCHEDULE_CONFIG]
        frac = (float(global_steps) / s_cfg[RANDOM_LTD_REQUIRE_STEP]) ** (1.0 / root_degree)
        next_seq = math.floor(frac * (self.state[RANDOM_LTD_MAX_VALUE] - self.state[RANDOM_LTD_MIN_VALUE]) +
                              self.state[RANDOM_LTD_MIN_VALUE])
        next_seq -= next_seq % s_cfg[RANDOM_LTD_INCREASE_STEP]
        return min(next_seq, self.state[RANDOM_LTD_MAX_VALUE])

    def get_value(self, global_steps):
        if self.state[RANDOM_LTD_SCHEDULE_TYPE] == "fixed_linear":
            return self._fixed_root_get_value(global_steps, 1)
        raise RuntimeError(f"Unsupported random-LTD schedule type {self.state[RANDOM_LTD_SCHEDULE_TYPE]}")


class RandomLTDScheduler(BaseScheduler):

    def __init__(self, config):
        super().__init__()
        self.model_layer_num = config[RANDOM_LTD_TOTAL_LAYER_NUM]
        self.random_ltd_layer_num = config[RANDOM_LTD_LAYER_NUM]
        self.config_schedule = config[RANDOM_LTD_SCHEDULER]
        self.global_batch_size = config[RANDOM_LTD_GLOBAL_BATCH_SIZE]
        self.reset_to_init()
        self.state[RANDOM_LTD_CONSUMED_LAYER_TOKENS] = 0

    def reset_to_init(self):
        self.state[RANDOM_LTD_MIN_VALUE] = self.config_schedule[RANDOM_LTD_MIN_VALUE]
        self.state[RANDOM_LTD_MAX_VALUE] = self.config_schedule[RANDOM_LTD_MAX_VALUE]
        self.state[RANDOM_LTD_CURRENT_VALUE] = self.config_schedule[RANDOM_LTD_MIN_VALUE]
        self.state[RANDOM_LTD_SCHEDULE_CONFIG] = self.config_schedule[RANDOM_LTD_SCHEDULE_CONFIG]
        self.state[RANDOM_LTD_SCHEDULE_TYPE] = self.config_schedule[RANDOM_LTD_SCHEDULE_TYPE]
        self.state[RANDOM_LTD_CURR_STEP] = -1

    def get_total_layer_tokens(self, train_iters):
        for step in range(train_iters):
            self.update_seq(step)
        return self.state[RANDOM_LTD_CONSUMED_LAYER_TOKENS]

    def get_current_seq(self):
        return self.state[RANDOM_LTD_CURRENT_VALUE]

    def set_current_seq(self, seq_length):
        self.state[RANDOM_LTD_CURRENT_VALUE] = seq_length

    def get_random_ltd_layer_num(self):
        return self.random_ltd_layer_num

    def update_seq(self, global_steps):
        if self.state[RANDOM_LTD_CURRENT_VALUE] < self.state[RANDOM_LTD_MAX_VALUE]:
            self.state[RANDOM_LTD_CURRENT_VALUE] = self.get_value(global_steps)
        if global_steps != self.state[RANDOM_LTD_CURR_STEP]:
            self.state[RANDOM_LTD_CONSUMED_LAYER_TOKENS] += self.global_batch_size * (
                self.state[RANDOM_LTD_CURRENT_VALUE] * self.random_ltd_layer_num +
                self.state[RANDOM_LTD_MAX_VALUE] * (self.model_layer_num - self.random_ltd_layer_num))
            self.state[RANDOM_LTD_CURR_STEP] = global_steps
        return self.state[RANDOM_LTD_CURRENT_VALUE]

    def state_dict(self):
        return {
            RANDOM_LTD_CONSUMED_LAYER_TOKENS: self.state[RANDOM_LTD_CONSUMED_LAYER_TOKENS],
            RANDOM_LTD_CURR_STEP: self.state[RANDOM_LTD_CURR_STEP],
            RANDOM_LTD_CURRENT_VALUE: self.state[RANDOM_LTD_CURRENT_VALUE],
            RANDOM_LTD_MIN_VALUE: self.state[RANDOM_LTD_MIN_VALUE],
            RANDOM_LTD_MAX_VALUE: self.state[RANDOM_LTD_MAX_VALUE],
        }

    def load_state_dict(self, state_dict):
        for k in (RANDOM_LTD_CONSUMED_LAYER_TOKENS, RANDOM_LTD_CURR_STEP, RANDOM_LTD_CURRENT_VALUE,
                  RANDOM_LTD_MIN_VALUE, RANDOM_LTD_MAX_VALUE):
            self.state[k] = state_dict[k]
