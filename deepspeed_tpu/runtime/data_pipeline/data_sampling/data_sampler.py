"""Curriculum-aware deterministic data sampler.

ref: ``deepspeed/runtime/data_pipeline/data_sampling/data_sampler.py:36
DeepSpeedDataSampler`` — yields per-rank index batches where the sample
pool grows with curriculum difficulty.  Each configured metric has an
``index_to_sample`` map and a difficulty schedule; at every step the
sampler takes the intersection of samples admitted by all metrics,
shuffles the new admissions into the pending cluster, and emits
deterministic global batches partitioned across data-parallel ranks.

Differences from the reference: single-controller JAX means ONE sampler
instance feeds the whole job (the reference runs one per rank and slices
by rank id; here ``get_next_global_batch`` returns the full batch and
``__iter__`` yields this process's shard).
"""

import numpy as np

from ....utils.logging import logger
from ..constants import *  # noqa: F401,F403
from ..curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:

    def __init__(self,
                 data_efficiency_config,
                 one_epoch_total_samples,
                 micro_batch_size,
                 data_parallel_rank,
                 data_parallel_size,
                 data_parallel_group=None,
                 gradient_accumulation_steps=1,
                 global_rank=0,
                 drop_last=True):
        self.data_efficiency_config = data_efficiency_config
        self.one_epoch_total_samples = one_epoch_total_samples
        self.index_dtype = np.int64
        self.total_samples = one_epoch_total_samples * data_efficiency_config[DATA_SAMPLING].get(
            DATA_SAMPLING_NUM_EPOCHS, DATA_SAMPLING_NUM_EPOCHS_DEFAULT)
        self.micro_batch_size = micro_batch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = micro_batch_size * data_parallel_size
        self.gradient_accumulation_steps = gradient_accumulation_steps
        self.global_batch_size = self.micro_batch_times_data_parallel_size * gradient_accumulation_steps
        self.global_rank = global_rank
        self.drop_last = drop_last
        self.np_rng = np.random.default_rng(data_efficiency_config.get(DATA_EFFICIENCY_SEED,
                                                                       DATA_EFFICIENCY_SEED_DEFAULT))
        self.state = {}
        self.batch = []
        self.consumed_samples = 0
        self.curriculum_step = 0
        self.current_difficulties = {}
        self.data_cluster = []  # admitted-but-unconsumed sample indices
        self.data_cluster_sizes = []
        # every sample id ever admitted (consumed or pending) — admission must
        # not re-admit consumed ids when difficulty grows, and epoch wrap-around
        # re-draws from exactly this pool
        self._ever_admitted = np.zeros(one_epoch_total_samples, dtype=bool)
        self.curriculum_schedulers = {}
        self.curriculum_index_to_sample = {}
        self.curriculum_index_to_metric = {}
        self.custom_get_difficulty = {}

        cl_cfg = data_efficiency_config[DATA_SAMPLING].get(CURRICULUM_LEARNING, {})
        self.curriculum_learning_enabled = cl_cfg.get(CURRICULUM_LEARNING_ENABLED, False)
        if self.curriculum_learning_enabled:
            for metric, metric_cfg in cl_cfg[CURRICULUM_LEARNING_METRICS].items():
                self.curriculum_schedulers[metric] = CurriculumScheduler(metric_cfg)
                if CURRICULUM_LEARNING_SAMPLE_PATH in metric_cfg:
                    self.curriculum_index_to_sample[metric] = np.load(
                        metric_cfg[CURRICULUM_LEARNING_SAMPLE_PATH], allow_pickle=True)
                if CURRICULUM_LEARNING_METRIC_PATH in metric_cfg:
                    self.curriculum_index_to_metric[metric] = np.load(
                        metric_cfg[CURRICULUM_LEARNING_METRIC_PATH], allow_pickle=True)
                if metric_cfg.get(CURRICULUM_LEARNING_DIFFICULTY_TYPE) == CURRICULUM_LEARNING_PERCENTILE_BASED:
                    assert metric in self.curriculum_index_to_metric, \
                        f"percentile-based metric {metric} needs {CURRICULUM_LEARNING_METRIC_PATH}"

    def __len__(self):
        return self.total_samples

    def set_custom_curriculum_learning_schedule(self, schedule_func_dict):
        """ref: data_sampler.py:117."""
        for metric, fn in schedule_func_dict.items():
            assert metric in self.curriculum_schedulers, f"unknown curriculum metric {metric}"
            self.curriculum_schedulers[metric].set_custom_get_difficulty(fn)

    # ---------------------------------------------------------- admission

    def get_sample_based_on_metric_value(self, metric, value_start, value_end):
        """Samples whose metric value ∈ (value_start, value_end]
        (ref: data_sampler.py:133)."""
        metric_values = self.curriculum_index_to_metric[metric]
        mask = (metric_values > value_start) & (metric_values <= value_end)
        return np.nonzero(mask)[0].astype(self.index_dtype)

    def get_sample_based_on_metric_percentile(self, metric, percentile_start, percentile_end):
        """Samples in the metric's (start, end] percentile band
        (ref: data_sampler.py:143)."""
        metric_values = self.curriculum_index_to_metric[metric]
        lo = np.quantile(metric_values, max(0.0, percentile_start / 100.0))
        hi = np.quantile(metric_values, min(1.0, percentile_end / 100.0))
        mask = (metric_values >= lo if percentile_start <= 0 else metric_values > lo) & (metric_values <= hi)
        return np.nonzero(mask)[0].astype(self.index_dtype)

    def _admitted_for(self, metric, difficulty, prev_difficulty):
        cl_cfg = self.data_efficiency_config[DATA_SAMPLING][CURRICULUM_LEARNING]
        metric_cfg = cl_cfg[CURRICULUM_LEARNING_METRICS][metric]
        dtype_ = metric_cfg.get(CURRICULUM_LEARNING_DIFFICULTY_TYPE, CURRICULUM_LEARNING_VALUE_BASED)
        if metric in self.curriculum_index_to_sample and dtype_ == CURRICULUM_LEARNING_VALUE_BASED \
                and metric not in self.curriculum_index_to_metric:
            # index_to_sample maps difficulty → sample ids
            table = self.curriculum_index_to_sample[metric]
            if isinstance(table, np.ndarray) and table.dtype == object:
                table = table.item() if table.shape == () else table
            out = []
            for d in (table.keys() if isinstance(table, dict) else range(len(table))):
                if prev_difficulty < d <= difficulty:
                    out.append(np.asarray(table[d], self.index_dtype))
            return np.concatenate(out) if out else np.empty((0, ), self.index_dtype)
        if dtype_ == CURRICULUM_LEARNING_VALUE_BASED:
            return self.get_sample_based_on_metric_value(metric, prev_difficulty, difficulty)
        return self.get_sample_based_on_metric_percentile(metric, prev_difficulty, difficulty)

    def get_new_cluster(self):
        """Admit newly-eligible samples: intersection over metrics of each
        metric's admission set, minus everything already admitted (pending OR
        consumed) (ref: data_sampler.py:171)."""
        new_samples = None
        for metric in self.curriculum_schedulers:
            difficulty = self.current_difficulties[metric]
            admitted = self._admitted_for(metric, difficulty, -float("inf"))
            new_samples = admitted if new_samples is None else np.intersect1d(new_samples, admitted)
        if new_samples is None:
            new_samples = np.arange(self.one_epoch_total_samples, dtype=self.index_dtype)
        fresh = new_samples[~self._ever_admitted[new_samples]]
        if fresh.size:
            fresh = fresh.copy()
            self._ever_admitted[fresh] = True
            self.np_rng.shuffle(fresh)
            self.data_cluster.append(fresh)
            self.data_cluster_sizes.append(fresh.size)
        logger.debug(f"curriculum step {self.curriculum_step}: admitted {fresh.size} new samples")

    # ------------------------------------------------------------ batching

    def get_start_end_idx(self, batch_len=None):
        """This DP rank's slice bounds within a global micro-batch
        (ref: data_sampler.py:122)."""
        n = batch_len if batch_len is not None else self.micro_batch_times_data_parallel_size
        per_rank = n // self.data_parallel_size
        start_idx = self.data_parallel_rank * per_rank
        return start_idx, start_idx + per_rank

    def sample_from_clusters(self):
        """Draw a global batch round-robin-proportionally from pending
        clusters (ref: data_sampler.py:232)."""
        return self.sample_from_clusters_n(self.global_batch_size)

    def sample_from_clusters_n(self, need):
        out = []
        while need > 0 and self.data_cluster:
            cluster = self.data_cluster[0]
            take = min(need, cluster.size)
            out.append(cluster[:take])
            rest = cluster[take:]
            if rest.size:
                self.data_cluster[0] = rest
            else:
                self.data_cluster.pop(0)
                self.data_cluster_sizes.pop(0)
            need -= take
        return np.concatenate(out) if out else np.empty((0, ), self.index_dtype)

    def get_next_global_batch(self):
        """ref: data_sampler.py:264."""
        if self.curriculum_learning_enabled:
            self.curriculum_step += 1
            previous = dict(self.current_difficulties)
            changed = False
            for metric, sched in self.curriculum_schedulers.items():
                d = sched.update_difficulty(self.curriculum_step)
                if previous.get(metric) != d:
                    changed = True
                self.current_difficulties[metric] = d
            if changed or not self.data_cluster:
                self.get_new_cluster()
            batch = self.sample_from_clusters()
            # epoch wrap-around: when the admitted pool can't fill a global
            # batch, re-draw (reshuffled) from the pool of already-admitted
            # samples — the curriculum restricts WHICH samples are eligible,
            # never the batch size (ref: data_sampler.py epoch reshuffle)
            while batch.size < self.global_batch_size:
                pool = np.nonzero(self._ever_admitted)[0].astype(self.index_dtype)
                if pool.size == 0:
                    break
                refill = pool.copy()
                self.np_rng.shuffle(refill)
                self.data_cluster.append(refill)
                self.data_cluster_sizes.append(refill.size)
                more = self.sample_from_clusters_n(self.global_batch_size - batch.size)
                batch = np.concatenate([batch, more])
        else:
            start = self.consumed_samples % self.one_epoch_total_samples
            idx = (np.arange(self.global_batch_size, dtype=self.index_dtype) + start) % self.one_epoch_total_samples
            batch = idx
        self.consumed_samples += batch.size
        return batch

    def __iter__(self):
        while self.consumed_samples <= self.total_samples:
            batch = self.get_next_global_batch()
            if batch.size == 0:
                return
            # yield per-micro-batch slices for this DP rank
            for i in range(self.gradient_accumulation_steps):
                micro = batch[i * self.micro_batch_times_data_parallel_size:(i + 1) *
                              self.micro_batch_times_data_parallel_size]
                if micro.size < self.micro_batch_times_data_parallel_size and self.drop_last:
                    return
                start_idx, end_idx = self.get_start_end_idx(micro.size)
                yield micro[start_idx:end_idx].tolist()

    # ---------------------------------------------------------- state io

    def state_dict(self):
        """ref: data_sampler.py:316."""
        return {
            CURRICULUM_LEARNING_BATCH: [c.tolist() for c in self.data_cluster],
            CURRICULUM_LEARNING_CONSUMED_SAMPLES: self.consumed_samples,
            CURRICULUM_LEARNING_STEP: self.curriculum_step,
            CURRICULUM_LEARNING_CURRENT_DIFFICULTIES: dict(self.current_difficulties),
            CURRICULUM_LEARNING_NP_RNG_STATE: self.np_rng.bit_generator.state,
            "ever_admitted": np.nonzero(self._ever_admitted)[0].tolist(),
        }

    def load_state_dict(self, state_dict):
        """ref: data_sampler.py:327."""
        self.data_cluster = [np.asarray(c, self.index_dtype) for c in state_dict[CURRICULUM_LEARNING_BATCH]]
        self.data_cluster_sizes = [c.size for c in self.data_cluster]
        self.consumed_samples = state_dict[CURRICULUM_LEARNING_CONSUMED_SAMPLES]
        self.curriculum_step = state_dict[CURRICULUM_LEARNING_STEP]
        self.current_difficulties = dict(state_dict[CURRICULUM_LEARNING_CURRENT_DIFFICULTIES])
        self.np_rng.bit_generator.state = state_dict[CURRICULUM_LEARNING_NP_RNG_STATE]
        self._ever_admitted = np.zeros(self.one_epoch_total_samples, dtype=bool)
        self._ever_admitted[np.asarray(state_dict.get("ever_admitted", []), dtype=np.int64)] = True
        for metric, sched in self.curriculum_schedulers.items():
            if metric in self.current_difficulties:
                sched.set_current_difficulty(self.current_difficulties[metric])
