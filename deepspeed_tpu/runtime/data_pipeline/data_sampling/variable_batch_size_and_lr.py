"""Variable batch size + LR scaling.

ref: deepspeed/runtime/data_pipeline/data_sampling/variable_batch_size_and_lr.py:1
(batch_by_seqlens, scale_lr, dataloader_for_variable_batch_size,
lr_scheduler_for_variable_batch_size) — pack sequences into batches by a
token budget ("Attention is all you need" §5.1 style), then scale the LR of
each batch by its size relative to a reference batch size.

TPU-native differences from the reference:
  * every distinct (batch_size, seq_len) pair is a fresh XLA compilation, so
    the packer QUANTIZES both: batch sizes land on ``batch_size_buckets``
    and each microbatch pads its sequences up to a power-of-two-ish seqlen
    bucket — steady state reuses a handful of compiled programs instead of
    one per shape (the engine's jit cache is keyed on batch structure,
    runtime/engine.py _ensure_ready);
  * the LR scale is a trace-time constant per bucket (engine.
    set_variable_batch_lr), not a per-step scheduler mutation — same math,
    compiled form.
"""

import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ....utils.logging import logger


def scale_lr(base_batch_size: int, batch_size: int, method: str = "linear", base_lr: float = 1.0) -> float:
    """LR multiplier for a batch of ``batch_size`` given the reference
    ``base_batch_size`` (ref: variable_batch_size_and_lr.py:149 scale_lr)."""
    if method == "linear":
        return base_lr * batch_size / base_batch_size
    if method == "sqrt":
        return base_lr * float(np.sqrt(batch_size / base_batch_size))
    if method in (None, "none"):
        return base_lr
    raise ValueError(f"unknown LR scaling method {method!r} (linear | sqrt | none)")


def batch_by_seqlens(seqlens: Sequence[int],
                     max_tokens: int,
                     sequence_ids_per_mb: Optional[Sequence[int]] = None,
                     min_batch_size: int = 1,
                     max_batch_size: Optional[int] = None,
                     sequence_picking_order: str = "dataloader",
                     effective_batch_size: int = 1,
                     required_microbatches_of_same_size: bool = False,
                     verbose: bool = False,
                     seed: Optional[int] = None):
    """Pack sample indices into microbatches under a token budget.

    Returns ``(microbatch_ids, batch_sizes, batch_max_seqlens)`` where
    ``microbatch_ids`` is a list of (batch_id, sample_ids) per microbatch,
    ``batch_sizes`` the number of sequences in each effective batch (for LR
    scaling), and ``batch_max_seqlens`` the max seqlen per effective batch
    (ref: variable_batch_size_and_lr.py:23 batch_by_seqlens — same contract,
    re-derived packing)."""
    assert sequence_picking_order in ("random", "seqlen", "dataloader")
    ids = list(range(len(seqlens))) if sequence_ids_per_mb is None else list(sequence_ids_per_mb)
    pairs = [(seqlens[i], i) for i in ids]

    long_ids = [i for l, i in pairs if l > max_tokens]
    if long_ids:
        logger.warning(f"batch_by_seqlens: {len(long_ids)} samples exceed max_tokens={max_tokens}; skipped")
        pairs = [(l, i) for l, i in pairs if l <= max_tokens]

    if sequence_picking_order == "random":
        random.Random(seed).shuffle(pairs)
    elif sequence_picking_order == "seqlen":
        pairs.sort()

    # greedy fill: a microbatch is padded to its max seqlen, so its token
    # cost is len(mb) * max_seqlen(mb)
    microbatches: List[List[int]] = []
    cur: List[int] = []
    cur_max = 0
    for l, i in pairs:
        new_max = max(cur_max, l)
        if cur and ((len(cur) + 1) * new_max > max_tokens or
                    (max_batch_size and len(cur) >= max_batch_size)):
            microbatches.append(cur)
            cur, cur_max = [], 0
            new_max = l
        cur.append(i)
        cur_max = new_max
    if cur:
        microbatches.append(cur)
    microbatches = [mb for mb in microbatches if len(mb) >= min_batch_size]

    # group microbatches into effective batches of `effective_batch_size`
    # microbatches each (the reference's gradient-accumulation grouping);
    # drop the ragged tail group
    n_groups = len(microbatches) // effective_batch_size
    microbatches = microbatches[:n_groups * effective_batch_size]

    if required_microbatches_of_same_size:
        # within each effective batch, trim every microbatch to the group min
        dropped = 0
        for g in range(n_groups):
            grp = microbatches[g * effective_batch_size:(g + 1) * effective_batch_size]
            size = min(len(mb) for mb in grp)
            for k, mb in enumerate(grp):
                dropped += len(mb) - size
                microbatches[g * effective_batch_size + k] = mb[:size]
        if dropped:
            logger.warning(f"batch_by_seqlens: same-size constraint dropped {dropped} samples "
                           f"this epoch (reshuffle or relax required_microbatches_of_same_size)")

    microbatch_ids = []
    batch_sizes, batch_max_seqlens = [], []
    for g in range(n_groups):
        grp = microbatches[g * effective_batch_size:(g + 1) * effective_batch_size]
        microbatch_ids.extend((g, mb) for mb in grp)
        batch_sizes.append(sum(len(mb) for mb in grp))
        batch_max_seqlens.append(max(max(seqlens[i] for i in mb) for mb in grp))
    if verbose:
        logger.info(f"batch_by_seqlens: {len(pairs)} samples -> {len(microbatches)} microbatches "
                    f"in {n_groups} batches; sizes={batch_sizes}")
    return microbatch_ids, batch_sizes, batch_max_seqlens


def _seqlen_bucket(n: int, buckets: Optional[Sequence[int]] = None) -> int:
    """Round n up to a compile-friendly bucket (next power of two by
    default).  Raises when n exceeds every explicit bucket — silently
    clamping would truncate data at _pad_rows."""
    if buckets:
        for b in sorted(buckets):
            if n <= b:
                return b
        raise ValueError(f"{n} exceeds the largest bucket {max(buckets)}; "
                         f"add a bigger bucket or cap the packer (max_tokens/max_batch_size)")
    b = 1
    while b < n:
        b *= 2
    return b


class VariableBatchDataLoader:
    """Iterate (padded) variable-size batches (ref:
    variable_batch_size_and_lr.py:165 dataloader_for_variable_batch_size).

    ``dataset[i]`` must return a dict of 1-D arrays (e.g. input_ids/labels);
    each microbatch pads its sequences to the bucketed max seqlen and stacks
    them.  Yields ``(batch_dict, batch_size)`` — feed batch_dict to
    engine.train_batch and let engine.set_variable_batch_lr handle the LR.
    """

    def __init__(self,
                 dataset,
                 microbatch_ids: List[Tuple[int, List[int]]],
                 seqlen_buckets: Optional[Sequence[int]] = None,
                 batch_size_buckets: Optional[Sequence[int]] = None,
                 round_batch_to: int = 1,
                 pad_token_id: int = 0,
                 pad_field: str = "input_ids"):
        self.dataset = dataset
        self.microbatch_ids = microbatch_ids
        self.seqlen_buckets = seqlen_buckets
        self.batch_size_buckets = batch_size_buckets
        # data-parallel sharding needs the (padded) batch dim divisible by
        # the dp world size — masked pad rows make up the difference
        self.round_batch_to = max(1, int(round_batch_to))
        self.pad_token_id = pad_token_id
        self.pad_field = pad_field

    def __len__(self):
        return len(self.microbatch_ids)

    def _pad_rows(self, rows: List[Dict[str, np.ndarray]]):
        target_len = _seqlen_bucket(max(len(r[self.pad_field]) for r in rows), self.seqlen_buckets)
        n = len(rows)
        if self.batch_size_buckets:
            n = _seqlen_bucket(n, self.batch_size_buckets)
        n = -(-n // self.round_batch_to) * self.round_batch_to
        out = {}
        for key in rows[0]:
            pad_val = self.pad_token_id if key == self.pad_field else 0
            arr = np.full((n, target_len), pad_val, dtype=np.asarray(rows[0][key]).dtype)
            for r_i, row in enumerate(rows):
                v = np.asarray(row[key])
                arr[r_i, :len(v)] = v
            out[key] = arr
        # padding rows contribute nothing: mask real tokens of real rows only
        mask = np.zeros((n, target_len), np.float32)
        for r_i, row in enumerate(rows):
            mask[r_i, :len(np.asarray(row[self.pad_field]))] = 1.0
        out.setdefault("loss_mask", mask)
        return out, len(rows)

    def __iter__(self):
        for _gid, sample_ids in self.microbatch_ids:
            rows = [self.dataset[i] for i in sample_ids]
            yield self._pad_rows(rows)


def get_dataloader_and_lr_scheduler_for_variable_batch_size_deepspeed(
        dataset,
        engine,
        seqlens: Optional[Sequence[int]] = None,
        max_tokens: int = 4096,
        ref_batch_size: Optional[int] = None,
        lr_scaling_method: str = "linear",
        sequence_picking_order: str = "dataloader",
        seqlen_buckets: Optional[Sequence[int]] = None,
        batch_size_buckets: Optional[Sequence[int]] = None,
        seed: Optional[int] = None,
        pad_token_id: int = 0):
    """One-call wiring (ref: variable_batch_size_and_lr.py:351): packs the
    dataset by token budget, enables LR scaling on the engine, returns the
    loader.  ``ref_batch_size`` defaults to the mean packed batch size."""
    if seqlens is None:
        seqlens = [len(np.asarray(dataset[i]["input_ids"])) for i in range(len(dataset))]
    microbatch_ids, batch_sizes, _ = batch_by_seqlens(
        seqlens, max_tokens, sequence_picking_order=sequence_picking_order, seed=seed)
    if ref_batch_size is None:
        ref_batch_size = max(1, int(round(float(np.mean(batch_sizes)))) if batch_sizes else 1)
    engine.set_variable_batch_lr(ref_batch_size, method=lr_scaling_method)
    # pad every batch to a multiple of the engine's data-parallel world so
    # the (data, expert)-sharded batch dim always divides
    from ....comm.mesh import BATCH_AXES, axis_size
    round_to = axis_size(engine.mesh, *BATCH_AXES)
    loader = VariableBatchDataLoader(dataset, microbatch_ids, seqlen_buckets=seqlen_buckets,
                                     batch_size_buckets=batch_size_buckets,
                                     round_batch_to=round_to, pad_token_id=pad_token_id)
    return loader, engine.lr_scheduler
