"""Memory-mapped indexed dataset.

ref: ``deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py:369
MMapIndexedDataset`` — variable-length token sequences stored contiguously
with an index of (offset, length) per sample, read zero-copy via mmap.

Own on-disk format (NOT the Megatron .bin/.idx layout):

``<path>.bin``   raw sample payloads, concatenated
``<path>.idx``   header: magic ``DSTPUIDX``, version u32, dtype-code u32,
                 count u64; then lengths  u32[count], then byte offsets
                 u64[count].

Reads return numpy views into the mmap (no copy) — feeding a host→device
transfer directly.  Suits TPU input pipelines: the loader slices fixed
shapes from the mmap and the engine's jit cache keys on shape.
"""

import os
import struct


import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {
    1: np.uint8,
    2: np.int8,
    3: np.int16,
    4: np.int32,
    5: np.int64,
    6: np.float32,
    7: np.float64,
    8: np.uint16,
}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix_path):
    return prefix_path + ".bin"


def index_file_path(prefix_path):
    return prefix_path + ".idx"


class MMapIndexedDatasetBuilder:
    """Append samples then ``finalize`` (ref: indexed_dataset.py
    MMapIndexedDatasetBuilder)."""

    def __init__(self, out_file, dtype=np.int32):
        self._path = out_file
        self._data_file = open(data_file_path(out_file), "wb")
        self._dtype = np.dtype(dtype)
        self._lengths = []
        self._offsets = []
        self._pos = 0

    def add_item(self, tokens):
        arr = np.asarray(tokens, dtype=self._dtype)
        self._offsets.append(self._pos)
        self._lengths.append(arr.size)
        b = arr.tobytes(order="C")
        self._data_file.write(b)
        self._pos += len(b)

    def add_doc(self, tokens, doc_ids=None):
        self.add_item(tokens)

    def merge_file_(self, another_file):
        other = MMapIndexedDataset(another_file)
        for i in range(len(other)):
            self.add_item(other[i])

    def finalize(self, index_file=None):
        self._data_file.close()
        path = index_file or index_file_path(self._path)
        with open(path, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<II", _VERSION, _DTYPE_CODES[self._dtype]))
            f.write(struct.pack("<Q", len(self._lengths)))
            f.write(np.asarray(self._lengths, np.uint32).tobytes())
            f.write(np.asarray(self._offsets, np.uint64).tobytes())


class MMapIndexedDataset:
    """Zero-copy reader (ref: indexed_dataset.py:369)."""

    def __init__(self, path, skip_warmup=True):
        self._path = path
        with open(index_file_path(path), "rb") as f:
            magic = f.read(len(_MAGIC))
            assert magic == _MAGIC, f"bad index magic in {path}: {magic}"
            version, dtype_code = struct.unpack("<II", f.read(8))
            assert version == _VERSION
            (count, ) = struct.unpack("<Q", f.read(8))
            self._dtype = np.dtype(_DTYPES[dtype_code])
            self._lengths = np.frombuffer(f.read(4 * count), np.uint32)
            self._offsets = np.frombuffer(f.read(8 * count), np.uint64)
        self._bin = np.memmap(data_file_path(path), mode="r", dtype=np.uint8)

    def __len__(self):
        return len(self._lengths)

    @property
    def sizes(self):
        return self._lengths

    @property
    def dtype(self):
        return self._dtype

    def __getstate__(self):
        return self._path

    def __setstate__(self, path):
        self.__init__(path)

    def get(self, idx, offset=0, length=None):
        n = int(self._lengths[idx]) - offset
        if length is not None:
            n = min(n, length)
        start = int(self._offsets[idx]) + offset * self._dtype.itemsize
        nbytes = n * self._dtype.itemsize
        return np.frombuffer(self._bin[start:start + nbytes], dtype=self._dtype)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self.get(i) for i in range(*idx.indices(len(self)))]
        return self.get(idx)

    @property
    def supports_prefetch(self):
        return False

    @staticmethod
    def exists(path):
        return os.path.exists(index_file_path(path)) and os.path.exists(data_file_path(path))
