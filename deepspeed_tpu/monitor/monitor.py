"""Experiment monitoring (ref: deepspeed/monitor/monitor.py:30 MonitorMaster).

Fans out ``write_events([(tag, value, step)])`` to the enabled backends:
TensorBoard (ref: monitor/tensorboard.py), WandB (monitor/wandb.py), CSV
(monitor/csv_monitor.py), Comet (monitor/comet.py).  Only process 0 writes.
"""

import csv
import os
from typing import List, Tuple

from ..utils.logging import logger


class Monitor:

    def __init__(self, monitor_config):
        self.monitor_config = monitor_config
        self.enabled = False

    def write_events(self, event_list: List[Tuple]):
        raise NotImplementedError


class TensorBoardMonitor(Monitor):

    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.summary_writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter
            out = os.path.join(tensorboard_config.output_path or "./runs", tensorboard_config.job_name)
            self.summary_writer = SummaryWriter(log_dir=out)
            self.enabled = True
        except Exception as e:
            logger.warning(f"TensorBoard monitor disabled: {e}")

    def write_events(self, event_list):
        if self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        self.summary_writer.flush()


class WandbMonitor(Monitor):

    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        try:
            import wandb
            wandb.init(project=wandb_config.project, group=wandb_config.group, entity=wandb_config.team)
            self.wandb = wandb
            self.enabled = True
        except Exception as e:
            logger.warning(f"WandB monitor disabled: {e}")
            self.wandb = None

    def write_events(self, event_list):
        if self.wandb is None:
            return
        for name, value, step in event_list:
            self.wandb.log({name: value}, step=step)


class csvMonitor(Monitor):

    def __init__(self, csv_config):
        super().__init__(csv_config)
        self.filenames = {}
        self.output_path = os.path.join(csv_config.output_path or "./csv_logs", csv_config.job_name)
        os.makedirs(self.output_path, exist_ok=True)
        self.enabled = True

    def write_events(self, event_list):
        for name, value, step in event_list:
            safe = name.replace("/", "_")
            path = os.path.join(self.output_path, f"{safe}.csv")
            new = not os.path.exists(path)
            with open(path, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, value])


class MonitorMaster(Monitor):
    """ref: monitor/monitor.py:30 — routes events to every enabled writer."""

    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        self.monitors = []
        try:
            import jax
            is_rank0 = jax.process_index() == 0
        except Exception:
            is_rank0 = True
        if not is_rank0:
            return
        if monitor_config.tensorboard.enabled:
            m = TensorBoardMonitor(monitor_config.tensorboard)
            if m.enabled:
                self.monitors.append(m)
        if monitor_config.wandb.enabled:
            m = WandbMonitor(monitor_config.wandb)
            if m.enabled:
                self.monitors.append(m)
        if monitor_config.csv_monitor.enabled:
            m = csvMonitor(monitor_config.csv_monitor)
            if m.enabled:
                self.monitors.append(m)
        self.enabled = bool(self.monitors)

    def write_events(self, event_list):
        for m in self.monitors:
            m.write_events(event_list)
