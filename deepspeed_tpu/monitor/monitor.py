"""Experiment monitoring (ref: deepspeed/monitor/monitor.py:30 MonitorMaster).

Fans out ``write_events([(tag, value, step)])`` to the enabled backends:
TensorBoard (ref: monitor/tensorboard.py), WandB (monitor/wandb.py), CSV
(monitor/csv_monitor.py), Comet (monitor/comet.py).  Only process 0 writes.
"""

import csv
import os
from typing import List, Tuple

from ..utils.logging import logger


class Monitor:

    def __init__(self, monitor_config):
        self.monitor_config = monitor_config
        self.enabled = False

    def write_events(self, event_list: List[Tuple]):
        raise NotImplementedError


class TensorBoardMonitor(Monitor):

    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.summary_writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter
            out = os.path.join(tensorboard_config.output_path or "./runs", tensorboard_config.job_name)
            self.summary_writer = SummaryWriter(log_dir=out)
            self.enabled = True
        except Exception as e:
            logger.warning(f"TensorBoard monitor disabled: {e}")

    def write_events(self, event_list):
        if self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        self.summary_writer.flush()


class WandbMonitor(Monitor):

    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        try:
            import wandb
            wandb.init(project=wandb_config.project, group=wandb_config.group, entity=wandb_config.team)
            self.wandb = wandb
            self.enabled = True
        except Exception as e:
            logger.warning(f"WandB monitor disabled: {e}")
            self.wandb = None

    def write_events(self, event_list):
        if self.wandb is None:
            return
        for name, value, step in event_list:
            self.wandb.log({name: value}, step=step)


class CometMonitor(Monitor):
    """ref: monitor/comet.py:23 CometMonitor — lazy comet_ml import, one
    experiment per run, per-sample throttling via samples_log_interval."""

    def __init__(self, comet_config):
        super().__init__(comet_config)
        self.sample_idx = 0
        self.interval = getattr(comet_config, "samples_log_interval", 100)
        if getattr(comet_config, "mode", None) == "disabled":
            # 'disabled' means OFF — not an offline experiment archive
            self.experiment = None
            return
        try:
            import comet_ml
            kwargs = {}
            if comet_config.api_key:
                kwargs["api_key"] = comet_config.api_key
            if comet_config.project:
                kwargs["project_name"] = comet_config.project
            if comet_config.workspace:
                kwargs["workspace"] = comet_config.workspace
            if comet_config.mode == "offline":
                kwargs["online"] = False
            elif comet_config.online is not None:
                kwargs["online"] = comet_config.online
            if comet_config.experiment_key:
                self.experiment = comet_ml.ExistingExperiment(
                    previous_experiment=comet_config.experiment_key, **kwargs)
            else:
                self.experiment = comet_ml.Experiment(**kwargs)
            if comet_config.experiment_name:
                self.experiment.set_name(comet_config.experiment_name)
            self.enabled = True
        except Exception as e:  # comet_ml not installed / auth failure
            logger.warning(f"Comet monitor disabled: {e}")
            self.experiment = None

    def write_events(self, event_list):
        if self.experiment is None:
            return
        self.sample_idx += 1
        if self.interval and (self.sample_idx - 1) % self.interval != 0:
            return
        for name, value, step in event_list:
            self.experiment.log_metric(name, value, step=step)


class csvMonitor(Monitor):

    def __init__(self, csv_config):
        super().__init__(csv_config)
        self.filenames = {}
        self.output_path = os.path.join(csv_config.output_path or "./csv_logs", csv_config.job_name)
        os.makedirs(self.output_path, exist_ok=True)
        self.enabled = True

    def write_events(self, event_list):
        for name, value, step in event_list:
            safe = name.replace("/", "_")
            path = os.path.join(self.output_path, f"{safe}.csv")
            new = not os.path.exists(path)
            with open(path, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, value])


class MonitorMaster(Monitor):
    """ref: monitor/monitor.py:30 — routes events to every enabled writer.

    Event volume is bounded: past ``monitor_config.max_events`` forwarded
    events (0 = unbounded), further events are DROPPED and counted in
    ``dropped_events`` — a fleet simulation fans N replicas' ``serving/*``
    streams plus ``fleet/*`` routing events through one master, an order of
    magnitude more than a single engine, and an unbounded CSV/TensorBoard
    stream would grow without limit.  Each time the drop count crosses a
    power of two, one ``monitor/dropped_events`` summary event is forwarded
    (O(log drops) overhead) so the loss is visible on the same surface."""

    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        self.max_events = int(getattr(monitor_config, "max_events", 0) or 0)
        self.events_written = 0
        self.dropped_events = 0
        self._next_drop_notice = 1
        self.monitors = []
        try:
            import jax
            is_rank0 = jax.process_index() == 0
        except Exception:
            is_rank0 = True
        if not is_rank0:
            return
        if monitor_config.tensorboard.enabled:
            m = TensorBoardMonitor(monitor_config.tensorboard)
            if m.enabled:
                self.monitors.append(m)
        if monitor_config.wandb.enabled:
            m = WandbMonitor(monitor_config.wandb)
            if m.enabled:
                self.monitors.append(m)
        if monitor_config.csv_monitor.enabled:
            m = csvMonitor(monitor_config.csv_monitor)
            if m.enabled:
                self.monitors.append(m)
        if monitor_config.comet.enabled:
            m = CometMonitor(monitor_config.comet)
            if m.enabled:
                self.monitors.append(m)
        self.enabled = bool(self.monitors)

    def write_events(self, event_list):
        if self.max_events > 0:
            room = self.max_events - self.events_written
            if room <= 0:
                self._drop(len(event_list))
                return
            if len(event_list) > room:
                self._drop(len(event_list) - room)
                event_list = event_list[:room]
        self.events_written += len(event_list)
        for m in self.monitors:
            m.write_events(event_list)

    def _drop(self, n: int) -> None:
        self.dropped_events += n
        if self.dropped_events >= self._next_drop_notice:
            while self._next_drop_notice <= self.dropped_events:
                self._next_drop_notice *= 2
            notice = [("monitor/dropped_events", float(self.dropped_events),
                       self.events_written + self.dropped_events)]
            for m in self.monitors:
                m.write_events(notice)
