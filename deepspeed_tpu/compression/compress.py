"""Compression-aware training (QAT, pruning, layer reduction).

TPU-native analog of ``deepspeed/compression/compress.py``
(init_compression:100, redundancy_clean:148, student_initialization:192)
and ``basic_layer.py`` (LinearLayer_Compress etc.).

The reference rewrites nn.Modules in place (LinearLayer_Compress wraps each
targeted Linear and mutates weights in forward, driven by
compression_scheduler ticking per step).  Functionally in JAX:

    fn = build_compression_fn(compression_dict, abs_params)
    compressed_params = fn(params, step)        # inside the jitted loss

``fn`` applies, per matched parameter leaf, quantize-dequantize with a
straight-through estimator and/or magnitude pruning masks.  The schedule
(enable at ``schedule_offset``, bit decay every doubling
``quantization_period`` — ref: runtime/quantize.py:136 where
``q_period <<= 1`` each precision drop) is computed from the traced ``step``
so no recompilation happens when the schedule advances.

``redundancy_clean`` bakes the masks/quantization permanently into the param
tree (the reference's fix_*_helpers), for export after compression training.
"""

import re
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import logger
from .constants import *  # noqa: F401,F403
from .utils import (asym_quantize, channel_mask_l1, head_mask_l1, row_mask_l1, sparse_mask_l1, ste,
                    stochastic_round_quantize, sym_quantize)


def _match(path: str, patterns: List[str]) -> bool:
    for pat in patterns:
        if pat == "*" or pat in path or re.search(pat, path):
            return True
    return False


def _param_paths(tree, prefix=()):
    out = []
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.extend(_param_paths(v, prefix + (str(k), )))
    else:
        out.append(".".join(prefix))
    return out


def _groups(method_block) -> List[Tuple[dict, List[str]]]:
    out = []
    for _name, g in (method_block.get(DIFFERENT_GROUPS) or {}).items():
        out.append((g.get(DIFFERENT_GROUPS_PARAMETERS, {}), g.get(DIFFERENT_GROUPS_MODULE_SCOPE, ["*"])))
    return out


def _bits_at(step, offset, start_bits, target_bits, period):
    """Traced bit schedule: start_bits until offset, then halve every
    doubling period until target_bits (ref: runtime/quantize.py:134-139)."""
    s = jnp.maximum(0.0, step.astype(jnp.float32) - offset)
    k = jnp.floor(jnp.log2(s / max(period, 1) + 1.0))
    bits = jnp.maximum(float(target_bits), jnp.floor(start_bits * jnp.exp2(-k)))
    return jnp.where(step >= offset, bits, float(start_bits))


class CompressionSpec:
    """Parsed compression_training dict → per-technique match lists."""

    def __init__(self, compression_dict: Dict[str, Any]):
        self.raw = compression_dict or {}

    def technique(self, name):
        blk = self.raw.get(name) or {}
        shared = blk.get(SHARED_PARAMETERS) or {}
        if not shared.get(TECHNIQUE_ENABLED, False):
            return None
        return shared, _groups(blk)

    @property
    def any_enabled(self):
        return any(self.technique(t) is not None
                   for t in (WEIGHT_QUANTIZATION, SPARSE_PRUNING, ROW_PRUNING, HEAD_PRUNING, CHANNEL_PRUNING))


def build_compression_fn(compression_dict: Dict[str, Any], abs_params) -> Any:
    """Return ``fn(params, step) -> params`` applying all enabled weight
    techniques, or None if nothing is enabled.  Activation quantization is
    separate (`QuantAct` module below) since it lives in model forward."""
    spec = CompressionSpec(compression_dict)
    if not spec.any_enabled:
        return None
    paths = _param_paths(abs_params)

    wq = spec.technique(WEIGHT_QUANTIZATION)
    sp = spec.technique(SPARSE_PRUNING)
    rp = spec.technique(ROW_PRUNING)
    hp = spec.technique(HEAD_PRUNING)
    cp = spec.technique(CHANNEL_PRUNING)

    # resolve per-path actions once (host side)
    actions = {}  # path -> list of (kind, cfg)
    for path in paths:
        acts = []
        leaf_name = path.rsplit(".", 1)[-1]
        is_weight = leaf_name in ("kernel", "embedding", "weight") or leaf_name.endswith("kernel")
        if not is_weight:
            continue
        if wq:
            shared, groups = wq
            if shared.get(WQ_QUANTIZE_IN_FORWARD, True):
                for params_cfg, mods in groups:
                    if _match(path, mods):
                        acts.append(("wq", {
                            "offset": shared.get(TECHNIQUE_SCHEDULE_OFFSET, 0),
                            "type": shared.get(WQ_QUANTIZATION_TYPE, "symmetric"),
                            "rounding": shared.get(WQ_ROUNDING, "nearest"),
                            "groups": shared.get(WQ_GROUPS, 1),
                            "start": params_cfg.get(WQ_START_BITS, 8),
                            "target": params_cfg.get(WQ_TARGET_BITS, 8),
                            "period": params_cfg.get(WQ_PERIOD, 1),
                        }))
                        break
        for kind, tech in (("sp", sp), ("rp", rp), ("cp", cp)):
            if tech:
                shared, groups = tech
                method = shared.get(PRUNE_METHOD, "l1")
                if method not in ("l1", "topk"):
                    raise ValueError(f"pruning method {method} not supported")
                if method == "topk":
                    logger.warning("topk (learnable-score) pruning approximated by l1 magnitude on TPU")
                for params_cfg, mods in groups:
                    if _match(path, mods):
                        acts.append((kind, {
                            "offset": shared.get(TECHNIQUE_SCHEDULE_OFFSET, 0),
                            "ratio": 1.0 - params_cfg.get(PRUNE_DENSE_RATIO, 1.0),
                        }))
                        break
        if hp:
            shared, groups = hp
            for params_cfg, mods in groups:
                if _match(path, mods):
                    acts.append(("hp", {
                        "offset": shared.get(TECHNIQUE_SCHEDULE_OFFSET, 0),
                        "ratio": 1.0 - params_cfg.get(PRUNE_DENSE_RATIO, 1.0),
                        "num_heads": shared.get(HP_NUM_HEADS, 1),
                    }))
                    break
        if acts:
            actions[path] = acts

    if not actions:
        return None
    logger.info(f"compression: {len(actions)} parameters matched "
                f"({[t for t in ('wq', 'sp', 'rp', 'hp', 'cp') if any(k == t for a in actions.values() for k, _ in a)]})")

    def _structured_mask(kind, w, cfg, stacked):
        """Pruning mask for a (possibly scan-stacked) kernel.

        The default model layout stacks per-layer kernels under a leading
        layer axis (``model.layers.*`` paths, shapes [L, ...]); masks must be
        computed per layer, not across the stack, so stacked kernels are
        vmapped over axis 0.  Per-layer DenseGeneral kernels are flattened to
        (in, out*) for row/channel pruning; head pruning handles the 2-D
        (H*D, out) and 3-D o_proj (H, D, out) layouts and refuses anything
        else loudly (ref: basic_layer.py head/row/channel pruning act on 2-D
        nn.Linear weights)."""
        if stacked and w.ndim > 2:
            return jax.vmap(lambda wl: _structured_mask(kind, wl, cfg, False))(w)
        if kind == "rp":
            w2 = w.reshape(w.shape[0], -1)
            return jnp.broadcast_to(row_mask_l1(w2, cfg["ratio"]), w2.shape).reshape(w.shape)
        if kind == "cp":
            w2 = w.reshape(w.shape[0], -1)
            return jnp.broadcast_to(channel_mask_l1(w2, cfg["ratio"]), w2.shape).reshape(w.shape)
        # head pruning
        num_heads = cfg["num_heads"]
        if w.ndim == 2:
            return jnp.broadcast_to(head_mask_l1(w, cfg["ratio"], num_heads), w.shape)
        if w.ndim == 3:
            if w.shape[0] != num_heads:
                raise ValueError(
                    f"head pruning: 3-D kernel leading axis {w.shape[0]} != num_heads {num_heads} "
                    f"(expected o_proj layout (H, D, out), got {w.shape})")
            norms = jnp.sum(jnp.abs(w), axis=(1, 2))
            from .utils import topk_mask
            return jnp.broadcast_to(topk_mask(norms, cfg["ratio"])[:, None, None], w.shape)
        raise ValueError(f"head pruning needs a 2-D (H*D, out) or 3-D (H, D, out) kernel, got shape {w.shape}")

    def apply_leaf(path, w, step):
        # scan-stacked collections are named 'layers' (llama-family) or 'h'
        # (falcon/gpt2); matching only 'layers' made falcon/gpt2 pruning
        # silently compute masks across the whole [L, in, out] stack
        stacked = any(seg in ("layers", "h") for seg in path.split("."))
        for kind, cfg in actions.get(path, ()):
            on = step >= cfg["offset"]
            if kind == "wq":
                bits = _bits_at(step, cfg["offset"], cfg["start"], cfg["target"], cfg["period"])
                if cfg.get("rounding") == "stochastic":
                    # per-step, per-param key derived from the traced step
                    import zlib
                    rng = jax.random.fold_in(jax.random.PRNGKey(zlib.crc32(path.encode()) & 0x7FFFFFFF), step)
                    wq_ = stochastic_round_quantize(w, bits, cfg["groups"], rng)
                else:
                    qfn = sym_quantize if cfg["type"] == "symmetric" else asym_quantize
                    wq_ = qfn(w, bits, num_groups=cfg["groups"])
                w = jnp.where(on, wq_, w)
            elif kind == "sp":
                w = jnp.where(on, w * jax.lax.stop_gradient(sparse_mask_l1(w, cfg["ratio"])), w)
            elif kind in ("rp", "cp", "hp"):
                m = _structured_mask(kind, w, cfg, stacked)
                w = jnp.where(on, w * jax.lax.stop_gradient(m), w)
        return w

    def fn(params, step):
        def walk(tree, prefix=()):
            if isinstance(tree, dict):
                return {k: walk(v, prefix + (str(k), )) for k, v in tree.items()}
            path = ".".join(prefix)
            return apply_leaf(path, tree, step) if path in actions else tree

        return walk(params)

    return fn


# ----------------------------------------------------------- public parity API


def init_compression(model_or_engine, deepspeed_config, teacher_model=None, mpu=None):
    """Attach compression to a live engine (ref: compress.py:100).  For raw
    flax models just validates the config; the engine picks the transform up
    from its DeepSpeedConfig at step-build time."""
    from ..runtime.engine import DeepSpeedEngine
    if isinstance(model_or_engine, DeepSpeedEngine):
        eng = model_or_engine
        eng.enable_compression()
        return eng
    return model_or_engine


def redundancy_clean(params, compression_dict: Dict[str, Any], final_step: int = 10**9):
    """Bake masks/quantization into the weights permanently
    (ref: compress.py:148 redundancy_clean → fix_compression)."""
    fn = build_compression_fn(compression_dict, jax.eval_shape(lambda: params))
    if fn is None:
        return params
    return jax.jit(fn)(params, jnp.asarray(final_step, jnp.int32))


def student_initialization(student_params, teacher_params, deepspeed_config):
    """Layer-reduction init: copy chosen teacher layers into the student
    (ref: compress.py:192; config keys layer_reduction.*).

    Works on scan-stacked layer params (leading layer axis, our models) by
    gathering ``teacher_layer`` indices, and copies ``other_module_name``
    subtrees verbatim.
    """
    from .constants import LR_MODULE_NAME_PREFIX, LR_OTHER_MODULE_NAME, LR_TEACHER_LAYER
    cfg = deepspeed_config if isinstance(deepspeed_config, dict) else {}
    lr = (cfg.get("compression_training") or {}).get(LAYER_REDUCTION) or cfg.get(LAYER_REDUCTION) or {}
    teacher_layer = lr.get(LR_TEACHER_LAYER)
    assert teacher_layer is not None, "layer_reduction.teacher_layer required"
    prefix = lr.get(LR_MODULE_NAME_PREFIX, "")
    other = lr.get(LR_OTHER_MODULE_NAME, [])
    idx = np.asarray(teacher_layer, np.int32)

    def walk(stu, tea, prefix_path=""):
        if isinstance(stu, dict):
            return {k: walk(v, tea[k], f"{prefix_path}.{k}".strip(".")) for k, v in stu.items()}
        in_layers = prefix == "" or prefix in prefix_path
        if in_layers and hasattr(tea, "shape") and tea.ndim >= 1 and tea.shape[0] >= idx.size \
                and stu.shape[0] == idx.size and stu.shape[1:] == tea.shape[1:]:
            return jnp.take(tea, idx, axis=0)  # stacked-layer gather
        if stu.shape == tea.shape and (in_layers or _match(prefix_path, other) or not other):
            return tea
        return stu

    return walk(student_params, teacher_params)
