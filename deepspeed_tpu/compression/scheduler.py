"""Compression scheduler — host-side progress reporting.

ref: deepspeed/compression/scheduler.py (compression_scheduler).  In the
reference this object mutates layer flags every step; here the schedule is
compiled into the train step (compress._bits_at / offset gates on the traced
step), so the scheduler only mirrors what the compiled schedule is doing —
for logging and for tests asserting schedule math.
"""

from .compress import CompressionSpec
from .constants import *  # noqa: F401,F403


class CompressionScheduler:

    def __init__(self, compression_dict):
        self.spec = CompressionSpec(compression_dict)
        self.training_steps = 0

    def step(self, n: int = 1):
        self.training_steps += n

    def bits_now(self, start_bits, target_bits, period, offset=0):
        """Python mirror of compress._bits_at for verification."""
        import math
        s = max(0, self.training_steps - offset)
        k = int(math.floor(math.log2(s / max(period, 1) + 1.0)))
        bits = max(target_bits, start_bits // (2**k))
        return bits if self.training_steps >= offset else start_bits

    def enabled(self, technique):
        t = self.spec.technique(technique)
        if t is None:
            return False
        shared, _ = t
        return self.training_steps >= shared.get(TECHNIQUE_SCHEDULE_OFFSET, 0)
