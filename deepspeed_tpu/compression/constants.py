"""Compression config keys (ref: deepspeed/compression/constants.py)."""

WEIGHT_QUANTIZATION = "weight_quantization"
ACTIVATION_QUANTIZATION = "activation_quantization"
SPARSE_PRUNING = "sparse_pruning"
ROW_PRUNING = "row_pruning"
HEAD_PRUNING = "head_pruning"
CHANNEL_PRUNING = "channel_pruning"
LAYER_REDUCTION = "layer_reduction"

SHARED_PARAMETERS = "shared_parameters"
DIFFERENT_GROUPS = "different_groups"

TECHNIQUE_ENABLED = "enabled"
TECHNIQUE_SCHEDULE_OFFSET = "schedule_offset"
TECHNIQUE_SCHEDULE_OFFSET_END = "schedule_offset_end"

DIFFERENT_GROUPS_PARAMETERS = "params"
DIFFERENT_GROUPS_MODULE_SCOPE = "modules"
DIFFERENT_GROUPS_RELATED_MODULE_SCOPE = "related_modules"

# weight quantization shared
WQ_QUANTIZE_IN_FORWARD = "quantize_weight_in_forward"
WQ_QUANTIZATION_TYPE = "quantization_type"   # symmetric | asymmetric
WQ_ROUNDING = "rounding"                     # nearest | stochastic
WQ_GROUPS = "quantize_groups"
# weight quantization per-group params
WQ_START_BITS = "start_bits"
WQ_TARGET_BITS = "target_bits"
WQ_PERIOD = "quantization_period"

# activation quantization per-group params
AQ_BITS = "bits"
AQ_TYPE = "quantization_type"
AQ_RANGE_CALIBRATION = "range_calibration"   # dynamic | static

# pruning per-group params
PRUNE_DENSE_RATIO = "dense_ratio"
PRUNE_METHOD = "method"                      # l1 | topk (l1 supported)
HP_NUM_HEADS = "num_heads"

# layer reduction
LR_KEEP_NUMBER_LAYER = "keep_number_layer"
LR_MODULE_NAME_PREFIX = "module_name_prefix"
LR_TEACHER_LAYER = "teacher_layer"
LR_OTHER_MODULE_NAME = "other_module_name"
